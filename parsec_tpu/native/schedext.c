/* Native scheduler hot path: the ready queue and the dep countdown in C.
 *
 * Rebuild of the reference's native scheduling core (reference:
 * parsec/mca/sched/* queue disciplines over parsec_list_item rings and
 * the atomic dep countdown of parsec_internal.h:355-366
 * update_deps_with_counter): the per-scheduling-event Python work —
 * status transition, Task.ready_at stamping, priority-ordered
 * push/pop, and the dep-counter decrement + ready-transition test —
 * collapses into ONE METH_FASTCALL crossing per event, the pinsext.c
 * pattern (tracer 5.0 -> 1.16 us/task) applied to the scheduler.
 *
 * Concurrency model: every entry point runs under the GIL and never
 * releases it (no callbacks into Python between state mutations except
 * where noted), so the GIL itself is the queue lock — the Python
 * fallback pays a threading.Lock round-trip per operation ON TOP of
 * the GIL; this pays neither.  The heap entries own strong references
 * to their tasks (the C-side twin of NativeDequeue's park/claim side
 * table, without the ctypes crossing or the id-keyed parking dict).
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

static inline double now_monotonic(void) {
    struct timespec t;
    clock_gettime(CLOCK_MONOTONIC, &t);
    return (double)t.tv_sec + (double)t.tv_nsec * 1e-9;
}

/* interned attribute names, created at module init */
static PyObject *s_status, *s_ready_at, *s_priority;

/* ------------------------------------------------------------------ */
/* ReadyQueue: binary max-heap of (priority, FIFO seq) -> task        */
/* ------------------------------------------------------------------ */

typedef struct {
    int64_t prio;       /* higher pops first */
    uint64_t seq;       /* FIFO among equal priorities */
    PyObject *task;     /* strong reference */
} rq_ent_t;

typedef struct {
    PyObject_HEAD
    rq_ent_t *heap;
    Py_ssize_t len, cap;
    uint64_t seq;
    /* stats (display_stats / metrics scrape) */
    uint64_t pushes, pops;
    Py_ssize_t max_len;
    PyObject *ready_status;   /* TaskStatus.READY, set at construction */
} RQObject;

static int rq_grow(RQObject *q) {
    Py_ssize_t ncap = q->cap ? q->cap * 2 : 1024;
    rq_ent_t *nh = (rq_ent_t *)realloc(q->heap,
                                       (size_t)ncap * sizeof(rq_ent_t));
    if (!nh) {
        PyErr_NoMemory();
        return -1;
    }
    q->heap = nh;
    q->cap = ncap;
    return 0;
}

/* entry a beats entry b (pops first)? */
static inline int rq_before(const rq_ent_t *a, const rq_ent_t *b) {
    if (a->prio != b->prio)
        return a->prio > b->prio;
    return a->seq < b->seq;
}

static void rq_sift_up(RQObject *q, Py_ssize_t i) {
    rq_ent_t e = q->heap[i];
    while (i > 0) {
        Py_ssize_t p = (i - 1) / 2;
        if (!rq_before(&e, &q->heap[p]))
            break;
        q->heap[i] = q->heap[p];
        i = p;
    }
    q->heap[i] = e;
}

static void rq_sift_down(RQObject *q, Py_ssize_t i) {
    rq_ent_t e = q->heap[i];
    Py_ssize_t n = q->len;
    for (;;) {
        Py_ssize_t c = 2 * i + 1;
        if (c >= n)
            break;
        if (c + 1 < n && rq_before(&q->heap[c + 1], &q->heap[c]))
            c++;
        if (!rq_before(&q->heap[c], &e))
            break;
        q->heap[i] = q->heap[c];
        i = c;
    }
    q->heap[i] = e;
}

/* push one task: read .priority, set .status (and .ready_at when
 * stamping), insert.  prio_override INT64_MIN means "back of the
 * queue" (the fairness contract for distance-rescheduled tasks). */
static int rq_push_one(RQObject *q, PyObject *task, int stamp,
                       int to_back, double now) {
    int64_t prio = 0;
    if (to_back) {
        prio = INT64_MIN;
    } else {
        PyObject *p = PyObject_GetAttr(task, s_priority);
        if (!p)
            return -1;
        prio = PyLong_AsLongLong(p);
        Py_DECREF(p);
        if (prio == -1 && PyErr_Occurred())
            return -1;
    }
    if (PyObject_SetAttr(task, s_status, q->ready_status) < 0)
        return -1;
    if (stamp) {
        PyObject *ts = PyFloat_FromDouble(now);
        if (!ts)
            return -1;
        int r = PyObject_SetAttr(task, s_ready_at, ts);
        Py_DECREF(ts);
        if (r < 0)
            return -1;
    }
    if (q->len >= q->cap && rq_grow(q) < 0)
        return -1;
    rq_ent_t *e = &q->heap[q->len++];
    e->prio = prio;
    e->seq = q->seq++;
    e->task = task;
    Py_INCREF(task);
    rq_sift_up(q, q->len - 1);
    q->pushes++;
    if (q->len > q->max_len)
        q->max_len = q->len;
    return 0;
}

/* push_batch(tasks, stamp, to_back=0) — ONE crossing per scheduling
 * event: the whole ready ring transitions to READY (ready_at stamped
 * from one clock read: the batch became ready at the same moment,
 * matching core/scheduling.schedule's Python fallback) and lands in
 * the heap. */
static PyObject *rq_push_batch(PyObject *self_, PyObject *const *args,
                               Py_ssize_t nargs) {
    RQObject *q = (RQObject *)self_;
    if (nargs < 2 || nargs > 3) {
        PyErr_SetString(PyExc_TypeError,
                        "push_batch(tasks, stamp[, to_back])");
        return NULL;
    }
    int stamp = PyObject_IsTrue(args[1]);
    if (stamp < 0)
        return NULL;
    int to_back = 0;
    if (nargs == 3) {
        to_back = PyObject_IsTrue(args[2]);
        if (to_back < 0)
            return NULL;
    }
    PyObject *fast = PySequence_Fast(args[0], "tasks must be a sequence");
    if (!fast)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    PyObject **items = PySequence_Fast_ITEMS(fast);
    double now = stamp ? now_monotonic() : 0.0;
    for (Py_ssize_t i = 0; i < n; i++) {
        if (rq_push_one(q, items[i], stamp, to_back, now) < 0) {
            Py_DECREF(fast);
            return NULL;
        }
    }
    Py_DECREF(fast);
    Py_RETURN_NONE;
}

static PyObject *rq_pop(PyObject *self_, PyObject *noargs) {
    (void)noargs;
    RQObject *q = (RQObject *)self_;
    if (q->len == 0)
        Py_RETURN_NONE;
    PyObject *task = q->heap[0].task;   /* ownership moves to caller */
    q->len--;
    if (q->len > 0) {
        q->heap[0] = q->heap[q->len];
        rq_sift_down(q, 0);
    }
    q->pops++;
    return task;
}

static PyObject *rq_stats(PyObject *self_, PyObject *noargs) {
    (void)noargs;
    RQObject *q = (RQObject *)self_;
    return Py_BuildValue("(KKnn)", (unsigned long long)q->pushes,
                         (unsigned long long)q->pops, q->max_len, q->len);
}

static Py_ssize_t rq_length(PyObject *self_) {
    return ((RQObject *)self_)->len;
}

static void rq_dealloc(PyObject *self_) {
    RQObject *q = (RQObject *)self_;
    for (Py_ssize_t i = 0; i < q->len; i++)
        Py_DECREF(q->heap[i].task);
    free(q->heap);
    Py_CLEAR(q->ready_status);
    Py_TYPE(self_)->tp_free(self_);
}

static int rq_init(PyObject *self_, PyObject *args, PyObject *kwds) {
    (void)kwds;
    RQObject *q = (RQObject *)self_;
    PyObject *ready;
    if (!PyArg_ParseTuple(args, "O", &ready))
        return -1;
    Py_INCREF(ready);
    Py_XSETREF(q->ready_status, ready);
    return 0;
}

static PyObject *rq_new(PyTypeObject *type, PyObject *args,
                        PyObject *kwds) {
    (void)args;
    (void)kwds;
    RQObject *q = (RQObject *)type->tp_alloc(type, 0);
    if (q) {
        q->heap = NULL;
        q->len = q->cap = 0;
        q->seq = 0;
        q->pushes = q->pops = 0;
        q->max_len = 0;
        q->ready_status = NULL;
    }
    return (PyObject *)q;
}

static PyMethodDef rq_methods[] = {
    {"push_batch", (PyCFunction)(void (*)(void))rq_push_batch,
     METH_FASTCALL,
     "push_batch(tasks, stamp[, to_back]): READY-transition + ready_at "
     "stamp + priority-ordered insert, one crossing per event"},
    {"pop", (PyCFunction)rq_pop, METH_NOARGS,
     "pop the highest-priority task (FIFO among equals), or None"},
    {"stats", (PyCFunction)rq_stats, METH_NOARGS,
     "(pushes, pops, max_len, len)"},
    {NULL, NULL, 0, NULL}};

static PySequenceMethods rq_as_sequence = {
    .sq_length = rq_length,
};

static PyTypeObject RQType = {
    PyVarObject_HEAD_INIT(NULL, 0).tp_name = "schedext.ReadyQueue",
    .tp_basicsize = sizeof(RQObject),
    .tp_dealloc = rq_dealloc,
    .tp_as_sequence = &rq_as_sequence,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_methods = rq_methods,
    .tp_init = rq_init,
    .tp_new = rq_new,
};

/* ------------------------------------------------------------------ */
/* DepTable: the dep-countdown record store (engine.deliver_dep)      */
/* ------------------------------------------------------------------ */

/* One pending record, a private heap type so records live as dict
 * values.  Mirrors engine.PendingRecord. */
typedef struct {
    PyObject_HEAD
    int64_t expected, arrivals;
    PyObject *locals;    /* dict */
    PyObject *inputs;    /* dict or NULL (lazily created) */
    PyObject *sources;   /* dict or NULL */
} DepRec;

static void deprec_dealloc(PyObject *self_) {
    DepRec *r = (DepRec *)self_;
    Py_CLEAR(r->locals);
    Py_CLEAR(r->inputs);
    Py_CLEAR(r->sources);
    Py_TYPE(self_)->tp_free(self_);
}

static PyTypeObject DepRecType = {
    PyVarObject_HEAD_INIT(NULL, 0).tp_name = "schedext._DepRec",
    .tp_basicsize = sizeof(DepRec),
    .tp_dealloc = deprec_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_new = NULL,   /* internal only */
};

typedef struct {
    PyObject_HEAD
    PyObject *table;    /* dict: key -> DepRec */
} DTObject;

/* install a fresh countdown record (called once per successor, on the
 * first arrival's MISS).  A record that appeared since the caller's
 * miss is KEPT — two workers racing the first two arrivals of one
 * successor both observe the miss, and the second create must not
 * wipe the first's recorded arrival.  Shared by the Python-visible
 * create() and the in-C delivery walk of the extended chain. */
static int dtc_create(DTObject *t, PyObject *key, long long expected,
                      PyObject *locals) {
    PyObject *existing = PyDict_GetItemWithError(t->table, key);
    if (existing)
        return 0;
    if (PyErr_Occurred())
        return -1;
    DepRec *r = (DepRec *)DepRecType.tp_alloc(&DepRecType, 0);
    if (!r)
        return -1;
    r->expected = expected;
    r->arrivals = 0;
    Py_INCREF(locals);
    r->locals = locals;
    r->inputs = NULL;
    r->sources = NULL;
    int rc = PyDict_SetItem(t->table, key, (PyObject *)r);
    Py_DECREF(r);
    return rc < 0 ? -1 : 0;
}

static PyObject *dt_create(PyObject *self_, PyObject *const *args,
                           Py_ssize_t nargs) {
    DTObject *t = (DTObject *)self_;
    if (nargs != 3) {
        PyErr_SetString(PyExc_TypeError, "create(key, expected, locals)");
        return NULL;
    }
    long long expected = PyLong_AsLongLong(args[1]);
    if (expected == -1 && PyErr_Occurred())
        return NULL;
    if (dtc_create(t, args[0], expected, args[2]) < 0)
        return NULL;
    Py_RETURN_NONE;
}

/* one arrival: 2 = ready (*out is the (locals, inputs_or_None,
 * sources_or_None) payload, record removed), 1 = not ready, 0 = miss
 * (caller create()s then re-arrives), -1 = error.  The JDF gather
 * rule is enforced here: a data flow receiving two copies raises
 * (range deps may only gather CTL). */
static int dtc_arrive(DTObject *t, PyObject *key, PyObject *flow,
                      PyObject *copy, PyObject *source, PyObject **out) {
    *out = NULL;
    PyObject *ent = PyDict_GetItemWithError(t->table, key);
    if (!ent)
        return PyErr_Occurred() ? -1 : 0;
    DepRec *r = (DepRec *)ent;
    r->arrivals++;
    /* record EVERY arrival's binding, None included — a CTL delivery
     * must land flow->None in task.data so prepare_input sees the
     * task-fed flow as bound (exact twin of the Python record path) */
    if (!r->inputs) {
        r->inputs = PyDict_New();
        if (!r->inputs)
            return -1;
    } else if (copy != Py_None) {
        PyObject *prev = PyDict_GetItemWithError(r->inputs, flow);
        if (!prev && PyErr_Occurred())
            return -1;
        if (prev && prev != Py_None) {
            /* ASCII only: PyErr_Format's format string must be */
            PyErr_Format(PyExc_RuntimeError,
                         "data flow %R received two copies - range "
                         "deps may only gather CTL", flow);
            return -1;
        }
    }
    {
        /* a gather's earlier real copy must survive a later None
         * arrival on the same flow (CTL range edges all carry None) */
        int has = PyDict_Contains(r->inputs, flow);
        if (has < 0)
            return -1;
        if (copy != Py_None || !has) {
            if (PyDict_SetItem(r->inputs, flow, copy) < 0)
                return -1;
        }
    }
    if (source != Py_None) {
        if (!r->sources) {
            r->sources = PyDict_New();
            if (!r->sources)
                return -1;
        }
        if (PyDict_SetItem(r->sources, flow, source) < 0)
            return -1;
    }
    if (r->arrivals < r->expected)
        return 1;
    /* ready transition: hand the record's contents to the caller and
     * drop the entry in the same crossing */
    PyObject *payload = PyTuple_New(3);
    if (!payload)
        return -1;
    Py_INCREF(r->locals);
    PyTuple_SET_ITEM(payload, 0, r->locals);
    PyObject *ins = r->inputs ? r->inputs : Py_None;
    Py_INCREF(ins);
    PyTuple_SET_ITEM(payload, 1, ins);
    PyObject *srcs = r->sources ? r->sources : Py_None;
    Py_INCREF(srcs);
    PyTuple_SET_ITEM(payload, 2, srcs);
    if (PyDict_DelItem(t->table, key) < 0) {
        Py_DECREF(payload);
        return -1;
    }
    *out = payload;
    return 2;
}

/* arrive(key, flow, copy, source) -> None (not ready), False (no
 * record: caller must create() then re-arrive), or the ready payload
 * (locals, inputs_or_None, sources_or_None) with the record removed. */
static PyObject *dt_arrive(PyObject *self_, PyObject *const *args,
                           Py_ssize_t nargs) {
    DTObject *t = (DTObject *)self_;
    if (nargs != 4) {
        PyErr_SetString(PyExc_TypeError,
                        "arrive(key, flow, copy, source)");
        return NULL;
    }
    PyObject *payload = NULL;
    switch (dtc_arrive(t, args[0], args[1], args[2], args[3],
                       &payload)) {
    case 2:
        return payload;
    case 1:
        Py_RETURN_NONE;
    case 0:
        Py_RETURN_FALSE;   /* miss: caller create()s, then re-arrives */
    default:
        return NULL;
    }
}

static Py_ssize_t dt_length(PyObject *self_) {
    return PyDict_Size(((DTObject *)self_)->table);
}

static void dt_dealloc(PyObject *self_) {
    Py_CLEAR(((DTObject *)self_)->table);
    Py_TYPE(self_)->tp_free(self_);
}

static PyObject *dt_new(PyTypeObject *type, PyObject *args,
                        PyObject *kwds) {
    (void)args;
    (void)kwds;
    DTObject *t = (DTObject *)type->tp_alloc(type, 0);
    if (t) {
        t->table = PyDict_New();
        if (!t->table) {
            Py_DECREF(t);
            return NULL;
        }
    }
    return (PyObject *)t;
}

static PyMethodDef dt_methods[] = {
    {"create", (PyCFunction)(void (*)(void))dt_create, METH_FASTCALL,
     "create(key, expected, locals): install a countdown record"},
    {"arrive", (PyCFunction)(void (*)(void))dt_arrive, METH_FASTCALL,
     "arrive(key, flow, copy, source) -> None | False | "
     "(locals, inputs, sources)"},
    {NULL, NULL, 0, NULL}};

static PySequenceMethods dt_as_sequence = {
    .sq_length = dt_length,
};

static PyTypeObject DTType = {
    PyVarObject_HEAD_INIT(NULL, 0).tp_name = "schedext.DepTable",
    .tp_basicsize = sizeof(DTObject),
    .tp_dealloc = dt_dealloc,
    .tp_as_sequence = &dt_as_sequence,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_methods = dt_methods,
    .tp_new = dt_new,
};

/* ------------------------------------------------------------------ */
/* TaskCore: the C task object (reference: parsec_task_t as a plain   */
/* C struct).  Field-for-field twin of core/task.py Task's slots so   */
/* every Python consumer (engine, devices, profilers, recovery) works */
/* unchanged by attribute access; construction and the trivial        */
/* progress chain below never enter bytecode.                         */
/* ------------------------------------------------------------------ */

#include <structmember.h>

/* TaskStatus values (core/task.py TaskStatus IntEnum; asserted at
 * vtable construction on the Python side so drift cannot go silent) */
#define ST_PENDING 0
#define ST_PREPARED 2
#define ST_RUNNING 3
#define ST_COMPLETE 4

typedef struct {
    PyObject_HEAD
    PyObject *task_class, *taskpool, *locals, *key, *data;
    PyObject *input_sources, *pinned_flows, *device, *prof, *dtd;
    PyObject *ready_at, *mtr_t0, *retry_snap;
    PyObject *vt;          /* TaskVT or NULL (reads as None) */
    long long priority, seq, pool_epoch;
    int status, chore_mask, retries;
} TCObject;

typedef struct {
    PyObject_HEAD
    PyObject *task_class, *taskpool;
    PyObject *name;         /* tc.name (the key head) */
    PyObject *param_names;  /* tuple of str, make_key order */
    PyObject *flow_names;   /* tuple of str, every flow */
    PyObject *priority_fn;  /* callable or None */
    PyObject *key_fn;       /* callable or None */
    PyObject *hook;         /* the single cpu hook, or None */
    int trivial;
    /* extended (non-trivial) chain: the per-class binding tables
     * computed by TaskClass.native_vt (reference: the generated
     * data_lookup / iterate_successors tables of parsec_task_class_t).
     * prep:   ((flow_name, ((guard|None, kind, payload), ...)), ...)
     *         per in-flow; kind 0=NULL 1=FROMDESC(ref_fn) 2=NEW(arena)
     *         3=FROMTASK(dep) 4=BAIL (statically ineligible dep)
     * noin:   (flow_name, ...) flows with no input deps (bind None)
     * outs:   ((flow_name, flow_index, access,
     *           ((guard|None, kind, payload), ...)), ...) per out-flow;
     *         kind 10=TOTASK(payload=(end, succ_tc, succ_flow,
     *         succ_write)) 11=BAIL (ToDesc / reshape / missing class)
     * wflows: (flow_name, ...) write-access flows (version bumps) */
    PyObject *prep, *noin, *outs, *wflows;
    int cchain;
} VTObject;

/* hard cap on per-class flow tables the extended chain will take (the
 * plan below keeps per-flow state on the stack); native_vt enforces
 * the same bound so cchain never arrives oversized */
#define MAX_CFLOWS 16

/* ------------------------------------------------------------------ */
/* bailout observability: every fast-path refusal is counted by       */
/* reason (process-global, GIL-serialized), scraped via               */
/* bailout_stats() into the metrics family                            */
/* parsec_sched_native_bailouts_total{reason} and the bench JSON —    */
/* a silently-degraded C chain is visible without an A/B run.         */
/* ------------------------------------------------------------------ */

enum {
    BR_NON_TRIVIAL = 0,   /* class shape the C chain does not cover   */
    BR_COMM_BUFFERED,     /* a successor lives on another rank        */
    BR_LINEAGE,           /* recovery lineage / minimal-replay filter */
    BR_CANCELLED,         /* cancelled pool (Python discard path)     */
    BR_FAULT_ARMED,       /* fault-injection plan armed               */
    BR_RETRY,             /* retry budget armed / task already retried*/
    BR_CHORE,             /* incarnation disabled or chore-masked     */
    BR_POOL,              /* pool/context feature (grapher/ici/dyn)   */
    BR_NREASONS
};

static const char *const bail_names[BR_NREASONS] = {
    "non_trivial", "comm_buffered", "lineage", "cancelled",
    "fault_armed", "retry", "chore", "pool"};

static uint64_t g_bail[BR_NREASONS];

/* interned attribute names for the progress chain (module init) */
static PyObject *s_pins_map, *s_running_task, *s_nb_tasks_done,
    *s_td_acc, *s_cancelled, *s_lineage, *s_context, *s_comm,
    *s_run_epoch, *s_termdet, *s_addto, *s_chore_disabled,
    *s_select, *s_exec_begin, *s_exec_end, *s_complete_exec,
    *s_task_discard;

/* extended-chain interned names (module init) */
static PyObject *s_data_attr, *s_device_attr, *s_complete_write,
    *s_repo, *s_lookup_entry, *s_addto_usage, *s_copies, *s_on_retire,
    *s_arena_attr, *s_retain_copy, *s_get_copy, *s_arenas, *s_flags,
    *s_resolve, *s_copy_on, *s_multiplicity, *s_instances,
    *s_affinity, *s_rank_of, *s_param_names_attr, *s_complete_locals,
    *s_native_deps, *s_vt_attr, *s_native_vt, *s_nb_task_inputs,
    *s_deliver_dep, *s_ring_doorbell, *s_record_error, *s_rank,
    *s_ready_stamp, *s_retry_max, *s_grapher, *s_ici,
    *s_replay_filter, *s_priority_attr;

/* lazily-bound runtime objects (cached after first use; importing an
 * already-loaded module is a sys.modules dict hit) */
static PyObject *g_seq_iter;      /* core.task._task_seq (itertools.count) */
static PyObject *g_fi_dict;       /* utils.faultinject module __dict__ */
static PyObject *g_body_failed;   /* scheduling._native_body_failed */
static PyObject *g_hook_return;   /* scheduling._native_hook_return */
static PyObject *g_one, *g_neg1;  /* cached small ints (module init) */
static PyObject *g_zero;          /* cached small int (module init) */
/* extended-chain runtime twins (core.engine / utils.output) */
static PyObject *g_engine_deliver;   /* engine.deliver_dep (fallback) */
static PyObject *g_engine_retire;    /* engine._make_retire */
static PyObject *g_engine_cow;       /* engine._cow_copy */
static PyObject *g_engine_consume;   /* engine.consume_inputs */
static PyObject *g_engine_stage;     /* engine.stage_in_host */
static PyObject *g_warning;          /* utils.output.warning */
static PyObject *g_null_fwd_fmt;     /* NULL-forward warning format */
static long long g_flag_scratch;     /* data.data.FLAG_SCRATCH */

static int ensure_runtime(void) {
    if (g_body_failed)
        return 0;
    PyObject *m = PyImport_ImportModule("parsec_tpu.core.task");
    if (!m)
        return -1;
    g_seq_iter = PyObject_GetAttrString(m, "_task_seq");
    Py_DECREF(m);
    if (!g_seq_iter)
        return -1;
    m = PyImport_ImportModule("parsec_tpu.utils.faultinject");
    if (!m)
        return -1;
    g_fi_dict = PyModule_GetDict(m);   /* borrowed, module is cached */
    Py_INCREF(g_fi_dict);
    Py_DECREF(m);
    m = PyImport_ImportModule("parsec_tpu.core.scheduling");
    if (!m)
        return -1;
    g_hook_return = PyObject_GetAttrString(m, "_native_hook_return");
    g_body_failed = PyObject_GetAttrString(m, "_native_body_failed");
    Py_DECREF(m);
    if (!g_hook_return || !g_body_failed) {
        Py_CLEAR(g_body_failed);
        Py_CLEAR(g_hook_return);
        return -1;
    }
    m = PyImport_ImportModule("parsec_tpu.core.engine");
    if (!m)
        goto fail;
    g_engine_deliver = PyObject_GetAttrString(m, "deliver_dep");
    g_engine_retire = PyObject_GetAttrString(m, "_make_retire");
    g_engine_cow = PyObject_GetAttrString(m, "_cow_copy");
    g_engine_consume = PyObject_GetAttrString(m, "consume_inputs");
    g_engine_stage = PyObject_GetAttrString(m, "stage_in_host");
    Py_DECREF(m);
    if (!g_engine_deliver || !g_engine_retire || !g_engine_cow ||
        !g_engine_consume || !g_engine_stage)
        goto fail;
    m = PyImport_ImportModule("parsec_tpu.utils.output");
    if (!m)
        goto fail;
    g_warning = PyObject_GetAttrString(m, "warning");
    Py_DECREF(m);
    if (!g_warning)
        goto fail;
    m = PyImport_ImportModule("parsec_tpu.data.data");
    if (!m)
        goto fail;
    {
        PyObject *fs = PyObject_GetAttrString(m, "FLAG_SCRATCH");
        Py_DECREF(m);
        if (!fs)
            goto fail;
        g_flag_scratch = PyLong_AsLongLong(fs);
        Py_DECREF(fs);
        if (g_flag_scratch == -1 && PyErr_Occurred())
            goto fail;
    }
    return 0;
fail:
    Py_CLEAR(g_body_failed);
    Py_CLEAR(g_hook_return);
    Py_CLEAR(g_engine_deliver);
    Py_CLEAR(g_engine_retire);
    Py_CLEAR(g_engine_cow);
    Py_CLEAR(g_engine_consume);
    Py_CLEAR(g_engine_stage);
    Py_CLEAR(g_warning);
    return -1;
}

/* -- TaskCore type -------------------------------------------------- */

static PyMemberDef tc_members[] = {
    {"task_class", T_OBJECT, offsetof(TCObject, task_class), 0, NULL},
    {"taskpool", T_OBJECT, offsetof(TCObject, taskpool), 0, NULL},
    {"locals", T_OBJECT, offsetof(TCObject, locals), 0, NULL},
    {"key", T_OBJECT, offsetof(TCObject, key), 0, NULL},
    {"data", T_OBJECT, offsetof(TCObject, data), 0, NULL},
    {"input_sources", T_OBJECT, offsetof(TCObject, input_sources), 0, NULL},
    {"pinned_flows", T_OBJECT, offsetof(TCObject, pinned_flows), 0, NULL},
    {"device", T_OBJECT, offsetof(TCObject, device), 0, NULL},
    {"prof", T_OBJECT, offsetof(TCObject, prof), 0, NULL},
    {"dtd", T_OBJECT, offsetof(TCObject, dtd), 0, NULL},
    {"ready_at", T_OBJECT, offsetof(TCObject, ready_at), 0, NULL},
    {"mtr_t0", T_OBJECT, offsetof(TCObject, mtr_t0), 0, NULL},
    {"retry_snap", T_OBJECT, offsetof(TCObject, retry_snap), 0, NULL},
    {"vt", T_OBJECT, offsetof(TCObject, vt), READONLY, NULL},
    {"priority", T_LONGLONG, offsetof(TCObject, priority), 0, NULL},
    {"seq", T_LONGLONG, offsetof(TCObject, seq), 0, NULL},
    {"pool_epoch", T_LONGLONG, offsetof(TCObject, pool_epoch), 0, NULL},
    {"status", T_INT, offsetof(TCObject, status), 0, NULL},
    {"chore_mask", T_INT, offsetof(TCObject, chore_mask), 0, NULL},
    {"retries", T_INT, offsetof(TCObject, retries), 0, NULL},
    {NULL, 0, 0, 0, NULL}};

static int tc_traverse(PyObject *self_, visitproc visit, void *arg) {
    TCObject *t = (TCObject *)self_;
    Py_VISIT(t->task_class);
    Py_VISIT(t->taskpool);
    Py_VISIT(t->locals);
    Py_VISIT(t->key);
    Py_VISIT(t->data);
    Py_VISIT(t->input_sources);
    Py_VISIT(t->pinned_flows);
    Py_VISIT(t->device);
    Py_VISIT(t->prof);
    Py_VISIT(t->dtd);
    Py_VISIT(t->ready_at);
    Py_VISIT(t->mtr_t0);
    Py_VISIT(t->retry_snap);
    Py_VISIT(t->vt);
    return 0;
}

static int tc_clear(PyObject *self_) {
    TCObject *t = (TCObject *)self_;
    Py_CLEAR(t->task_class);
    Py_CLEAR(t->taskpool);
    Py_CLEAR(t->locals);
    Py_CLEAR(t->key);
    Py_CLEAR(t->data);
    Py_CLEAR(t->input_sources);
    Py_CLEAR(t->pinned_flows);
    Py_CLEAR(t->device);
    Py_CLEAR(t->prof);
    Py_CLEAR(t->dtd);
    Py_CLEAR(t->ready_at);
    Py_CLEAR(t->mtr_t0);
    Py_CLEAR(t->retry_snap);
    Py_CLEAR(t->vt);
    return 0;
}

static void tc_dealloc(PyObject *self_) {
    PyObject_GC_UnTrack(self_);
    tc_clear(self_);
    Py_TYPE(self_)->tp_free(self_);
}

/* repr matches core/task.py Task: "Name(k=1,m=2)" */
static PyObject *tc_repr(PyObject *self_) {
    TCObject *t = (TCObject *)self_;
    PyObject *name = t->task_class
        ? PyObject_GetAttrString(t->task_class, "name") : NULL;
    if (!name) {
        PyErr_Clear();
        name = PyUnicode_FromString("?");
        if (!name)
            return NULL;
    }
    PyObject *parts = PyList_New(0);
    if (!parts) {
        Py_DECREF(name);
        return NULL;
    }
    if (t->locals && PyDict_Check(t->locals)) {
        PyObject *k, *v;
        Py_ssize_t pos = 0;
        while (PyDict_Next(t->locals, &pos, &k, &v)) {
            PyObject *s = PyUnicode_FromFormat("%U=%S", k, v);
            if (!s || PyList_Append(parts, s) < 0) {
                Py_XDECREF(s);
                Py_DECREF(parts);
                Py_DECREF(name);
                return NULL;
            }
            Py_DECREF(s);
        }
    }
    PyObject *sep = PyUnicode_FromString(",");
    PyObject *args = sep ? PyUnicode_Join(sep, parts) : NULL;
    Py_XDECREF(sep);
    Py_DECREF(parts);
    if (!args) {
        Py_DECREF(name);
        return NULL;
    }
    PyObject *out = PyUnicode_FromFormat("%U(%U)", name, args);
    Py_DECREF(name);
    Py_DECREF(args);
    return out;
}

static PyTypeObject TCType = {
    PyVarObject_HEAD_INIT(NULL, 0).tp_name = "schedext.TaskCore",
    .tp_basicsize = sizeof(TCObject),
    .tp_dealloc = tc_dealloc,
    .tp_repr = tc_repr,
    .tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC,
    .tp_traverse = tc_traverse,
    .tp_clear = tc_clear,
    .tp_members = tc_members,
    .tp_new = NULL,   /* construct via TaskVT.build_* only */
};

/* -- TaskVT: the per-task-class vtable ------------------------------ */

static int vt_traverse(PyObject *self_, visitproc visit, void *arg) {
    VTObject *v = (VTObject *)self_;
    Py_VISIT(v->task_class);
    Py_VISIT(v->taskpool);
    Py_VISIT(v->name);
    Py_VISIT(v->param_names);
    Py_VISIT(v->flow_names);
    Py_VISIT(v->priority_fn);
    Py_VISIT(v->key_fn);
    Py_VISIT(v->hook);
    Py_VISIT(v->prep);
    Py_VISIT(v->noin);
    Py_VISIT(v->outs);
    Py_VISIT(v->wflows);
    return 0;
}

static int vt_clear(PyObject *self_) {
    VTObject *v = (VTObject *)self_;
    Py_CLEAR(v->task_class);
    Py_CLEAR(v->taskpool);
    Py_CLEAR(v->name);
    Py_CLEAR(v->param_names);
    Py_CLEAR(v->flow_names);
    Py_CLEAR(v->priority_fn);
    Py_CLEAR(v->key_fn);
    Py_CLEAR(v->hook);
    Py_CLEAR(v->prep);
    Py_CLEAR(v->noin);
    Py_CLEAR(v->outs);
    Py_CLEAR(v->wflows);
    return 0;
}

static void vt_dealloc(PyObject *self_) {
    PyObject_GC_UnTrack(self_);
    vt_clear(self_);
    Py_TYPE(self_)->tp_free(self_);
}

static int vt_init(PyObject *self_, PyObject *args, PyObject *kwds) {
    (void)kwds;
    VTObject *v = (VTObject *)self_;
    PyObject *tc, *tp, *name, *pnames, *fnames, *prio, *keyfn, *hook;
    PyObject *prep, *noin, *outs, *wflows;
    int trivial, cchain;
    if (!PyArg_ParseTuple(args, "OOO!O!O!OOOpiO!O!O!O!", &tc, &tp,
                          &PyUnicode_Type, &name,
                          &PyTuple_Type, &pnames,
                          &PyTuple_Type, &fnames,
                          &prio, &keyfn, &hook, &trivial, &cchain,
                          &PyTuple_Type, &prep,
                          &PyTuple_Type, &noin,
                          &PyTuple_Type, &outs,
                          &PyTuple_Type, &wflows))
        return -1;
    Py_INCREF(tc);
    Py_XSETREF(v->task_class, tc);
    Py_INCREF(tp);
    Py_XSETREF(v->taskpool, tp);
    Py_INCREF(name);
    Py_XSETREF(v->name, name);
    Py_INCREF(pnames);
    Py_XSETREF(v->param_names, pnames);
    Py_INCREF(fnames);
    Py_XSETREF(v->flow_names, fnames);
    Py_INCREF(prio);
    Py_XSETREF(v->priority_fn, prio);
    Py_INCREF(keyfn);
    Py_XSETREF(v->key_fn, keyfn);
    Py_INCREF(hook);
    Py_XSETREF(v->hook, hook);
    Py_INCREF(prep);
    Py_XSETREF(v->prep, prep);
    Py_INCREF(noin);
    Py_XSETREF(v->noin, noin);
    Py_INCREF(outs);
    Py_XSETREF(v->outs, outs);
    Py_INCREF(wflows);
    Py_XSETREF(v->wflows, wflows);
    v->trivial = trivial && hook != Py_None;
    /* the extended chain keeps per-flow plan state on the stack: a
     * class wider than MAX_CFLOWS (native_vt enforces the same bound)
     * or without a single cpu hook falls back to Python */
    v->cchain = cchain && hook != Py_None && !v->trivial
        && PyTuple_GET_SIZE(prep) <= MAX_CFLOWS
        && PyTuple_GET_SIZE(noin) <= MAX_CFLOWS
        && PyTuple_GET_SIZE(outs) <= MAX_CFLOWS
        && PyTuple_GET_SIZE(wflows) <= MAX_CFLOWS;
    return 0;
}

static PyObject *vt_new(PyTypeObject *type, PyObject *args,
                        PyObject *kwds) {
    (void)args;
    (void)kwds;
    VTObject *v = (VTObject *)type->tp_alloc(type, 0);
    return (PyObject *)v;
}

static long long vt_attr_ll(PyObject *obj, const char *name,
                            long long dflt) {
    PyObject *a = PyObject_GetAttrString(obj, name);
    if (!a) {
        PyErr_Clear();
        return dflt;
    }
    long long r = PyLong_AsLongLong(a);
    Py_DECREF(a);
    if (r == -1 && PyErr_Occurred()) {
        PyErr_Clear();
        return dflt;
    }
    return r;
}

/* make_key's twin: (name,) + params, or (name, key_fn(locals)) */
static PyObject *vt_key(VTObject *v, PyObject *locals) {
    if (v->key_fn != Py_None) {
        PyObject *k2 = PyObject_CallFunctionObjArgs(v->key_fn, locals,
                                                    NULL);
        if (!k2)
            return NULL;
        PyObject *key = PyTuple_Pack(2, v->name, k2);
        Py_DECREF(k2);
        return key;
    }
    Py_ssize_t np = PyTuple_GET_SIZE(v->param_names);
    PyObject *key = PyTuple_New(1 + np);
    if (!key)
        return NULL;
    Py_INCREF(v->name);
    PyTuple_SET_ITEM(key, 0, v->name);
    for (Py_ssize_t i = 0; i < np; i++) {
        PyObject *pv = PyDict_GetItemWithError(
            locals, PyTuple_GET_ITEM(v->param_names, i));
        if (!pv) {
            if (!PyErr_Occurred())
                PyErr_Format(PyExc_KeyError, "task param %R missing",
                             PyTuple_GET_ITEM(v->param_names, i));
            Py_DECREF(key);
            return NULL;
        }
        Py_INCREF(pv);
        PyTuple_SET_ITEM(key, 1 + i, pv);
    }
    return key;
}

/* one task: locals is ALIASED (the caller guarantees a fresh,
 * exclusively-owned dict — iter_space / the DepTable record both
 * produce one per instance) */
static PyObject *vt_build_task(VTObject *v, PyObject *locals,
                               long long epoch, long long pool_prio) {
    if (ensure_runtime() < 0)
        return NULL;
    TCObject *t = (TCObject *)TCType.tp_alloc(&TCType, 0);
    if (!t)
        return NULL;
    Py_INCREF(v->task_class);
    t->task_class = v->task_class;
    Py_INCREF(v->taskpool);
    t->taskpool = v->taskpool;
    Py_INCREF(locals);
    t->locals = locals;
    Py_INCREF((PyObject *)v);
    t->vt = (PyObject *)v;
    t->status = ST_PENDING;
    t->chore_mask = 0xFFFF;
    t->retries = 0;
    t->pool_epoch = epoch;
    t->priority = pool_prio;
    t->key = vt_key(v, locals);
    if (!t->key)
        goto fail;
    if (v->priority_fn != Py_None) {
        PyObject *p = PyObject_CallFunctionObjArgs(v->priority_fn,
                                                   locals, NULL);
        if (!p)
            goto fail;
        long long cp = PyLong_AsLongLong(p);
        Py_DECREF(p);
        if (cp == -1 && PyErr_Occurred())
            goto fail;
        t->priority += cp;
    }
    {
        /* itertools.count: the ONE process-global task sequence,
         * shared with Python Task.__init__ */
        PyObject *seq = PyIter_Next(g_seq_iter);
        if (!seq)
            goto fail;
        t->seq = PyLong_AsLongLong(seq);
        Py_DECREF(seq);
    }
    t->data = PyDict_New();
    t->input_sources = PyDict_New();
    t->pinned_flows = PySet_New(NULL);
    if (!t->data || !t->input_sources || !t->pinned_flows)
        goto fail;
    /* tp_alloc already GC-tracked the object (PyType_GenericAlloc) */
    return (PyObject *)t;
fail:
    Py_DECREF((PyObject *)t);
    return NULL;
}

/* build_batch(locals_seq) -> [TaskCore, ...]: one crossing for the
 * whole enumeration stream (Python Task.__init__ leaves the hot loop) */
static PyObject *vt_build_batch(PyObject *self_, PyObject *arg) {
    VTObject *v = (VTObject *)self_;
    PyObject *fast = PySequence_Fast(arg, "locals_seq must be a sequence");
    if (!fast)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    long long epoch = vt_attr_ll(v->taskpool, "run_epoch", 0);
    long long prio = vt_attr_ll(v->taskpool, "priority", 0);
    PyObject *out = PyList_New(n);
    if (!out) {
        Py_DECREF(fast);
        return NULL;
    }
    PyObject **items = PySequence_Fast_ITEMS(fast);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *t = vt_build_task(v, items[i], epoch, prio);
        if (!t) {
            Py_DECREF(out);
            Py_DECREF(fast);
            return NULL;
        }
        PyList_SET_ITEM(out, i, t);
    }
    Py_DECREF(fast);
    return out;
}

/* build_range(name, start, stop, step) -> [TaskCore, ...]: the flat
 * single-parameter space fully enumerated AND constructed in C (the
 * independent-task shape: locals dicts, keys, tasks — zero bytecode
 * per instance) */
static PyObject *vt_build_range(PyObject *self_, PyObject *const *args,
                                Py_ssize_t nargs) {
    VTObject *v = (VTObject *)self_;
    if (nargs != 4) {
        PyErr_SetString(PyExc_TypeError,
                        "build_range(name, start, stop, step)");
        return NULL;
    }
    PyObject *name = args[0];
    long long start = PyLong_AsLongLong(args[1]);
    long long stop = PyLong_AsLongLong(args[2]);
    long long step = PyLong_AsLongLong(args[3]);
    if (PyErr_Occurred())
        return NULL;
    if (step == 0) {
        PyErr_SetString(PyExc_ValueError, "step must not be zero");
        return NULL;
    }
    long long count = 0;
    if (step > 0 && stop > start)
        count = (stop - start + step - 1) / step;
    else if (step < 0 && stop < start)
        count = (start - stop + (-step) - 1) / (-step);
    long long epoch = vt_attr_ll(v->taskpool, "run_epoch", 0);
    long long prio = vt_attr_ll(v->taskpool, "priority", 0);
    PyObject *out = PyList_New((Py_ssize_t)count);
    if (!out)
        return NULL;
    long long val = start;
    for (Py_ssize_t i = 0; i < (Py_ssize_t)count; i++, val += step) {
        PyObject *locals = PyDict_New();
        PyObject *pv = locals ? PyLong_FromLongLong(val) : NULL;
        if (!pv || PyDict_SetItem(locals, name, pv) < 0) {
            Py_XDECREF(pv);
            Py_XDECREF(locals);
            Py_DECREF(out);
            return NULL;
        }
        Py_DECREF(pv);
        PyObject *t = vt_build_task(v, locals, epoch, prio);
        Py_DECREF(locals);
        if (!t) {
            Py_DECREF(out);
            return NULL;
        }
        PyList_SET_ITEM(out, i, t);
    }
    return out;
}

/* build_one(locals) -> TaskCore (the deliver_dep readiness path) */
static PyObject *vt_build_one(PyObject *self_, PyObject *locals) {
    VTObject *v = (VTObject *)self_;
    if (!PyDict_Check(locals)) {
        PyErr_SetString(PyExc_TypeError, "locals must be a dict");
        return NULL;
    }
    return vt_build_task(v, locals,
                         vt_attr_ll(v->taskpool, "run_epoch", 0),
                         vt_attr_ll(v->taskpool, "priority", 0));
}

static PyMethodDef vt_methods[] = {
    {"build_batch", (PyCFunction)vt_build_batch, METH_O,
     "build_batch(locals_seq) -> [TaskCore]"},
    {"build_range", (PyCFunction)(void (*)(void))vt_build_range,
     METH_FASTCALL,
     "build_range(name, start, stop, step) -> [TaskCore] (flat space)"},
    {"build_one", (PyCFunction)vt_build_one, METH_O,
     "build_one(locals) -> TaskCore"},
    {NULL, NULL, 0, NULL}};

static PyMemberDef vt_members[] = {
    {"task_class", T_OBJECT, offsetof(VTObject, task_class), READONLY,
     NULL},
    {"taskpool", T_OBJECT, offsetof(VTObject, taskpool), READONLY, NULL},
    {"trivial", T_INT, offsetof(VTObject, trivial), READONLY, NULL},
    {"cchain", T_INT, offsetof(VTObject, cchain), READONLY, NULL},
    {NULL, 0, 0, 0, NULL}};

static PyTypeObject VTType = {
    PyVarObject_HEAD_INIT(NULL, 0).tp_name = "schedext.TaskVT",
    .tp_basicsize = sizeof(VTObject),
    .tp_dealloc = vt_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC,
    .tp_traverse = vt_traverse,
    .tp_clear = vt_clear,
    .tp_methods = vt_methods,
    .tp_members = vt_members,
    .tp_init = vt_init,
    .tp_new = vt_new,
};

/* ------------------------------------------------------------------ */
/* run_quantum: the worker inner loop in one crossing                  */
/* ------------------------------------------------------------------ */

/* dispatch one PINS event to a callback list (borrowed refs) */
static int pins_dispatch(PyObject *cbs, PyObject *es, PyObject *event,
                         PyObject *task) {
    if (!cbs || !PyList_Check(cbs))
        return 0;
    for (Py_ssize_t i = 0; i < PyList_GET_SIZE(cbs); i++) {
        PyObject *r = PyObject_CallFunctionObjArgs(
            PyList_GET_ITEM(cbs, i), es, event, task, NULL);
        if (!r)
            return -1;
        Py_DECREF(r);
    }
    return 0;
}

/* which chains the (pool, class) gates allow */
#define FL_TRIV 1   /* the trivial (no-flow) chain */
#define FL_EXT 2    /* the extended (data-carrying) chain */

/* per-quantum cached state (refreshed each run_quantum call) */
typedef struct {
    PyObject *es, *pins_map, *td_acc;
    PyObject *es_ctx;      /* OWNED: es.context (doorbell / rank / errors) */
    PyObject *cb_select, *cb_begin, *cb_end, *cb_complete, *cb_discard;
    PyObject *cb_deliver;  /* borrowed: deliver_dep PINS list */
    PyObject *last_tp;     /* OWNED: last gate-checked pool (a borrowed
                            * pointer could be freed mid-quantum and a
                            * new pool allocated at the same address
                            * would inherit stale gate results) */
    PyObject *last_vt;     /* OWNED: the gate cache is keyed on the
                            * (pool, class) PAIR — chore_disabled_mask
                            * is per CLASS, and one class's disable
                            * must not poison its pool siblings */
    int last_flags;        /* FL_* mask for (last_tp, last_vt) */
    int reason_triv;       /* BR_* why FL_TRIV is clear */
    int reason_ext;        /* BR_* why FL_EXT is clear */
    long long myrank;      /* ctx.rank for the cached pool */
    int ready_stamp;       /* ctx._ready_stamp truth, read per quantum */
    int fi_armed;
    /* complete_exec stride gates (__pins_stride__ on the callback,
     * read once per quantum): a callback advertising stride N is
     * SKIPPED unless es.nb_tasks_done % N == 0 — the metrics
     * handler's own unsampled early-return, without the call */
    long long cstride[8];
    Py_ssize_t n_complete;
} quantum_t;

/* interned-name attribute read as long long, default on absence */
static long long attr_ll(PyObject *obj, PyObject *name, long long dflt) {
    PyObject *a = PyObject_GetAttr(obj, name);
    if (!a) {
        PyErr_Clear();
        return dflt;
    }
    long long r = PyLong_AsLongLong(a);
    Py_DECREF(a);
    if (r == -1 && PyErr_Occurred()) {
        PyErr_Clear();
        return dflt;
    }
    return r;
}

/* raise an exception object (with its original traceback) */
static PyObject *fetch_exc(void) {
    PyObject *et, *ev, *tb;
    PyErr_Fetch(&et, &ev, &tb);
    PyErr_NormalizeException(&et, &ev, &tb);
    if (tb)
        PyException_SetTraceback(ev, tb);
    Py_XDECREF(et);
    Py_XDECREF(tb);
    return ev;   /* owned */
}

/* 1 if obj.name exists and is not None, 0 otherwise (missing = None) */
static int attr_not_none(PyObject *obj, PyObject *name) {
    PyObject *a = PyObject_GetAttr(obj, name);
    if (!a) {
        PyErr_Clear();
        return 0;
    }
    int r = (a != Py_None);
    Py_DECREF(a);
    return r;
}

/* (pool, class) fast-path gates: which chains may take this task.
 * Cached per (pool, class) pair for the quantum (a cancel landing
 * mid-quantum is observed at the next quantum — in-flight tasks
 * finish, exactly the documented cancellation contract).  NOTE the
 * comm-attached fast-complete: an attached RemoteDepEngine no longer
 * disqualifies — a trivial class has no out flows (flush_activations
 * is a strict no-op on its empty outbox) and the extended chain bails
 * at plan time on ANY remote successor, so a zero-remote-successor
 * task rides C even on a distributed run. */
static int gates_for(quantum_t *qs, TCObject *t, VTObject *vt) {
    PyObject *tp = t->taskpool;
    if (tp == qs->last_tp && (PyObject *)vt == qs->last_vt)
        return qs->last_flags;
    Py_INCREF(tp);
    Py_XSETREF(qs->last_tp, tp);
    Py_INCREF((PyObject *)vt);
    Py_XSETREF(qs->last_vt, (PyObject *)vt);
    qs->last_flags = 0;
    qs->myrank = 0;
    qs->reason_triv = qs->reason_ext = BR_POOL;
    PyObject *a = PyObject_GetAttr(tp, s_cancelled);
    if (!a)
        return -1;
    int truth = PyObject_IsTrue(a);
    Py_DECREF(a);
    if (truth < 0)
        return -1;
    if (truth) {
        qs->reason_triv = qs->reason_ext = BR_CANCELLED;
        return 0;
    }
    a = PyObject_GetAttr(tp, s_lineage);
    if (!a)
        return -1;
    int has = (a != Py_None);
    Py_DECREF(a);
    if (has) {
        /* recovery lineage records at complete: Python path */
        qs->reason_triv = qs->reason_ext = BR_LINEAGE;
        return 0;
    }
    a = PyObject_GetAttr(vt->task_class, s_chore_disabled);
    if (!a)
        return -1;
    long long dis = PyLong_AsLongLong(a);
    Py_DECREF(a);
    if (dis == -1 && PyErr_Occurred())
        return -1;
    if (dis) {
        qs->reason_triv = qs->reason_ext = BR_CHORE;
        return 0;
    }
    int flags = FL_TRIV | FL_EXT;
    PyObject *ctx = PyObject_GetAttr(tp, s_context);
    if (!ctx)
        return -1;
    if (ctx != Py_None) {
        qs->myrank = attr_ll(ctx, s_rank, 0);
        if (attr_ll(ctx, s_retry_max, 0) > 0) {
            /* write-flow snapshots before first execution: Python */
            flags &= ~FL_EXT;
            qs->reason_ext = BR_RETRY;
        }
        if ((flags & FL_EXT) && (attr_not_none(ctx, s_grapher) ||
                                 attr_not_none(ctx, s_ici))) {
            /* DAG grapher edges / ICI placement ride release_deps */
            flags &= ~FL_EXT;
            qs->reason_ext = BR_POOL;
        }
    }
    Py_DECREF(ctx);
    if ((flags & FL_EXT) && attr_not_none(tp, s_replay_filter)) {
        /* minimal-replay delivery filtering: Python walk */
        flags &= ~FL_EXT;
        qs->reason_ext = BR_LINEAGE;
    }
    qs->last_flags = flags;
    return flags;
}

/* ------------------------------------------------------------------ */
/* the extended chain: per-instance plan -> prepare -> delivery walk  */
/* (reference: generated data_lookup + iterate_successors +           */
/* release_deps, jdf2c.c:43,7175,7631 -> parsec.c:1783)               */
/* ------------------------------------------------------------------ */

/* binding-table kinds (mirrored by TaskClass.native_vt) */
#define CK_NULL 0        /* bind None (Null dep / no active dep) */
#define CK_FROMDESC 1    /* payload = ref_fn */
#define CK_NEW 2         /* payload = arena name */
#define CK_FROMTASK 3    /* payload = dep (unbound: mult==0 -> None) */
#define CK_BAIL 4        /* statically ineligible input dep */
#define CK_TOTASK 10     /* payload = (end, succ_tc, succ_flow, w) */
#define CK_OBAIL 11      /* statically ineligible output dep */

/* one planned local delivery */
typedef struct {
    PyObject *succ_tc;      /* borrowed from the vt table */
    PyObject *succ_locals;  /* OWNED completed-locals dict */
    PyObject *dflow;        /* borrowed successor flow name */
    int succ_write;         /* successor flow has WRITE access */
} cdeliv_t;

#define CPLAN_DSTACK 8

/* the per-instance execution plan, built BEFORE exec_begin so a bail
 * re-runs the whole chain in Python with every PINS event firing
 * exactly once */
typedef struct {
    struct {
        PyObject *name;      /* borrowed flow name */
        int kind;
        PyObject *payload;   /* borrowed from the vt table */
    } prep[MAX_CFLOWS];
    Py_ssize_t nprep;
    struct {
        PyObject *name;      /* borrowed flow name */
        Py_ssize_t findex;   /* flow_index into entry.copies */
        long long access;
        Py_ssize_t start, count;   /* span into deliv[] */
    } outs[MAX_CFLOWS];
    Py_ssize_t nouts;
    cdeliv_t dstack[CPLAN_DSTACK];
    cdeliv_t *deliv;
    Py_ssize_t ndeliv, dcap;
} cplan_t;

static void plan_init(cplan_t *p) {
    p->nprep = p->nouts = p->ndeliv = 0;
    p->deliv = p->dstack;
    p->dcap = CPLAN_DSTACK;
}

static void plan_free(cplan_t *p) {
    for (Py_ssize_t i = 0; i < p->ndeliv; i++)
        Py_DECREF(p->deliv[i].succ_locals);
    if (p->deliv != p->dstack)
        free(p->deliv);
    p->deliv = p->dstack;
    p->ndeliv = 0;
    p->dcap = CPLAN_DSTACK;
}

/* append one delivery; steals the succ_locals reference on success */
static int plan_push_deliv(cplan_t *p, PyObject *succ_tc,
                           PyObject *succ_locals, PyObject *dflow,
                           int succ_write) {
    if (p->ndeliv >= p->dcap) {
        Py_ssize_t ncap = p->dcap * 2;
        cdeliv_t *nd;
        if (p->deliv == p->dstack) {
            nd = (cdeliv_t *)malloc((size_t)ncap * sizeof(cdeliv_t));
            if (nd)
                memcpy(nd, p->dstack, sizeof(p->dstack));
        } else {
            nd = (cdeliv_t *)realloc(p->deliv,
                                     (size_t)ncap * sizeof(cdeliv_t));
        }
        if (!nd) {
            PyErr_NoMemory();
            return -1;
        }
        p->deliv = nd;
        p->dcap = ncap;
    }
    cdeliv_t *d = &p->deliv[p->ndeliv++];
    d->succ_tc = succ_tc;
    d->succ_locals = succ_locals;
    d->dflow = dflow;
    d->succ_write = succ_write;
    return 0;
}

/* complete_locals' twin: fill derived params (fast path: every param
 * already present -> alias the dict) */
static PyObject *c_complete_locals(PyObject *succ_tc, PyObject *locals) {
    PyObject *pn = PyObject_GetAttr(succ_tc, s_param_names_attr);
    if (pn && PyTuple_Check(pn) && PyDict_Check(locals)) {
        int all = 1;
        for (Py_ssize_t i = 0; i < PyTuple_GET_SIZE(pn); i++) {
            int has = PyDict_Contains(locals, PyTuple_GET_ITEM(pn, i));
            if (has < 0) {
                Py_DECREF(pn);
                return NULL;
            }
            if (!has) {
                all = 0;
                break;
            }
        }
        Py_DECREF(pn);
        if (all) {
            Py_INCREF(locals);
            return locals;
        }
    } else {
        Py_XDECREF(pn);
        PyErr_Clear();
    }
    return PyObject_CallMethodObjArgs(succ_tc, s_complete_locals,
                                      locals, NULL);
}

/* evaluate a dep guard against locals: 1 applies, 0 not, -1 error */
static int guard_applies(PyObject *guard, PyObject *locals) {
    if (guard == Py_None)
        return 1;
    PyObject *r = PyObject_CallFunctionObjArgs(guard, locals, NULL);
    if (!r)
        return -1;
    int truth = PyObject_IsTrue(r);
    Py_DECREF(r);
    return truth;
}

/* build the per-instance plan from the vt binding tables.  Returns
 * 0 = covered, 1 = bail to Python (*breason set; plan freed); plan
 * evaluation is read-only, so ANY exception (a guard raising, an
 * instance expression failing) clears and bails — the Python re-run
 * surfaces it at the same site with the correct containment. */
static int plan_build(quantum_t *qs, TCObject *t, VTObject *vt,
                      cplan_t *plan, int *breason) {
    Py_ssize_t np = PyTuple_GET_SIZE(vt->prep);
    Py_ssize_t no = PyTuple_GET_SIZE(vt->outs);
    plan_init(plan);
    *breason = BR_NON_TRIVIAL;
    /* in-flows: pick this instance's binding (guards are mutually
     * exclusive: the FIRST applying dep wins, active_input's contract) */
    for (Py_ssize_t i = 0; i < np; i++) {
        PyObject *ent = PyTuple_GET_ITEM(vt->prep, i);
        PyObject *name = PyTuple_GET_ITEM(ent, 0);
        int has = PyDict_Contains(t->data, name);
        if (has < 0)
            goto excbail;
        if (has)
            continue;   /* task-fed, bound at delivery */
        PyObject *deps = PyTuple_GET_ITEM(ent, 1);
        int chosen = 0;
        for (Py_ssize_t j = 0; j < PyTuple_GET_SIZE(deps); j++) {
            PyObject *dent = PyTuple_GET_ITEM(deps, j);
            int ap = guard_applies(PyTuple_GET_ITEM(dent, 0), t->locals);
            if (ap < 0)
                goto excbail;
            if (!ap)
                continue;
            long kind = PyLong_AsLong(PyTuple_GET_ITEM(dent, 1));
            if (kind == -1 && PyErr_Occurred())
                goto excbail;
            if (kind == CK_BAIL)
                goto bail;
            plan->prep[plan->nprep].name = name;
            plan->prep[plan->nprep].kind = (int)kind;
            plan->prep[plan->nprep].payload = PyTuple_GET_ITEM(dent, 2);
            plan->nprep++;
            chosen = 1;
            break;
        }
        if (!chosen) {
            /* no active dep: bind None (prepare_input's dep-is-None) */
            plan->prep[plan->nprep].name = name;
            plan->prep[plan->nprep].kind = CK_NULL;
            plan->prep[plan->nprep].payload = NULL;
            plan->nprep++;
        }
    }
    /* out-flows: expand EVERY applying dep's instances (outputs are
     * not mutually exclusive); a remote successor bails the task to
     * Python, whose release_deps buffers the remote activation */
    for (Py_ssize_t i = 0; i < no; i++) {
        PyObject *ent = PyTuple_GET_ITEM(vt->outs, i);
        PyObject *name = PyTuple_GET_ITEM(ent, 0);
        Py_ssize_t start = plan->ndeliv;
        PyObject *deps = PyTuple_GET_ITEM(ent, 3);
        for (Py_ssize_t j = 0; j < PyTuple_GET_SIZE(deps); j++) {
            PyObject *dent = PyTuple_GET_ITEM(deps, j);
            int ap = guard_applies(PyTuple_GET_ITEM(dent, 0), t->locals);
            if (ap < 0)
                goto excbail;
            if (!ap)
                continue;
            long kind = PyLong_AsLong(PyTuple_GET_ITEM(dent, 1));
            if (kind == -1 && PyErr_Occurred())
                goto excbail;
            if (kind != CK_TOTASK)
                goto bail;
            PyObject *pl = PyTuple_GET_ITEM(dent, 2);
            PyObject *end = PyTuple_GET_ITEM(pl, 0);
            PyObject *succ_tc = PyTuple_GET_ITEM(pl, 1);
            PyObject *dflow = PyTuple_GET_ITEM(pl, 2);
            long sw = PyLong_AsLong(PyTuple_GET_ITEM(pl, 3));
            if (sw == -1 && PyErr_Occurred())
                goto excbail;
            PyObject *insts = PyObject_CallMethodObjArgs(
                end, s_instances, t->locals, NULL);
            if (!insts)
                goto excbail;
            PyObject *fast = PySequence_Fast(insts,
                                             "instances not a sequence");
            Py_DECREF(insts);
            if (!fast)
                goto excbail;
            Py_ssize_t ni = PySequence_Fast_GET_SIZE(fast);
            for (Py_ssize_t k = 0; k < ni; k++) {
                PyObject *cl = c_complete_locals(
                    succ_tc, PySequence_Fast_GET_ITEM(fast, k));
                if (!cl) {
                    Py_DECREF(fast);
                    goto excbail;
                }
                /* rank check (rank_of: affinity-owner placement) */
                long long rank = 0;
                if (attr_not_none(succ_tc, s_affinity)) {
                    PyObject *rk = PyObject_CallMethodObjArgs(
                        succ_tc, s_rank_of, cl, NULL);
                    if (!rk) {
                        Py_DECREF(cl);
                        Py_DECREF(fast);
                        goto excbail;
                    }
                    rank = PyLong_AsLongLong(rk);
                    Py_DECREF(rk);
                    if (rank == -1 && PyErr_Occurred()) {
                        Py_DECREF(cl);
                        Py_DECREF(fast);
                        goto excbail;
                    }
                }
                if (rank != qs->myrank) {
                    /* remote successor: Python buffers the activation */
                    Py_DECREF(cl);
                    Py_DECREF(fast);
                    *breason = BR_COMM_BUFFERED;
                    goto bail;
                }
                if (plan_push_deliv(plan, succ_tc, cl, dflow,
                                    (int)sw) < 0) {
                    Py_DECREF(cl);
                    Py_DECREF(fast);
                    goto excbail;
                }
            }
            Py_DECREF(fast);
        }
        long long findex = PyLong_AsLongLong(PyTuple_GET_ITEM(ent, 1));
        long long access = PyLong_AsLongLong(PyTuple_GET_ITEM(ent, 2));
        if (PyErr_Occurred())
            goto excbail;
        plan->outs[plan->nouts].name = name;
        plan->outs[plan->nouts].findex = (Py_ssize_t)findex;
        plan->outs[plan->nouts].access = access;
        plan->outs[plan->nouts].start = start;
        plan->outs[plan->nouts].count = plan->ndeliv - start;
        plan->nouts++;
    }
    return 0;
excbail:
    PyErr_Clear();
bail:
    plan_free(plan);
    return 1;
}

/* prepare_input's twin over the plan (exceptions left SET: the caller
 * routes them through _native_body_failed, task_progress's except
 * branch).  ASCII-only format strings (PyErr_Format requirement). */
static int c_prepare(TCObject *t, VTObject *vt, cplan_t *plan) {
    PyObject *noin = vt->noin;
    for (Py_ssize_t i = 0; i < PyTuple_GET_SIZE(noin); i++) {
        PyObject *name = PyTuple_GET_ITEM(noin, i);
        int has = PyDict_Contains(t->data, name);
        if (has < 0)
            return -1;
        if (!has && PyDict_SetItem(t->data, name, Py_None) < 0)
            return -1;
    }
    for (Py_ssize_t i = 0; i < plan->nprep; i++) {
        PyObject *name = plan->prep[i].name;
        PyObject *payload = plan->prep[i].payload;
        switch (plan->prep[i].kind) {
        case CK_NULL:
            if (PyDict_SetItem(t->data, name, Py_None) < 0)
                return -1;
            break;
        case CK_FROMDESC: {
            PyObject *ref = PyObject_CallFunctionObjArgs(payload,
                                                         t->locals, NULL);
            if (!ref)
                return -1;
            PyObject *datum = PyObject_CallMethodObjArgs(ref, s_resolve,
                                                         NULL);
            if (!datum) {
                Py_DECREF(ref);
                return -1;
            }
            PyObject *copy = PyObject_CallMethodObjArgs(datum, s_copy_on,
                                                        g_zero, NULL);
            Py_DECREF(datum);
            if (!copy) {
                Py_DECREF(ref);
                return -1;
            }
            if (copy == Py_None) {
                PyErr_Format(PyExc_RuntimeError,
                             "%S: no host copy for %S", t, ref);
                Py_DECREF(copy);
                Py_DECREF(ref);
                return -1;
            }
            Py_DECREF(ref);
            int rc = PyDict_SetItem(t->data, name, copy);
            Py_DECREF(copy);
            if (rc < 0)
                return -1;
            break;
        }
        case CK_NEW: {
            PyObject *arenas = PyObject_GetAttr(t->taskpool, s_arenas);
            if (!arenas)
                return -1;
            PyObject *arena = PyObject_GetItem(arenas, payload);
            Py_DECREF(arenas);
            if (!arena) {
                PyErr_Clear();
                PyErr_Format(PyExc_RuntimeError,
                             "%S: flow %U needs arena %R but the "
                             "taskpool has none", t, name, payload);
                return -1;
            }
            PyObject *copy = PyObject_CallMethodObjArgs(arena, s_get_copy,
                                                        NULL);
            Py_DECREF(arena);
            if (!copy)
                return -1;
            /* copy.flags |= FLAG_SCRATCH (np.empty scratch: nothing
             * may read it before the first write) */
            long long fl = attr_ll(copy, s_flags, 0);
            PyObject *nf = PyLong_FromLongLong(fl | g_flag_scratch);
            if (!nf) {
                Py_DECREF(copy);
                return -1;
            }
            int rc = PyObject_SetAttr(copy, s_flags, nf);
            Py_DECREF(nf);
            if (rc < 0 || PyDict_SetItem(t->data, name, copy) < 0) {
                Py_DECREF(copy);
                return -1;
            }
            Py_DECREF(copy);
            break;
        }
        case CK_FROMTASK: {
            PyObject *mult = PyObject_CallMethodObjArgs(
                payload, s_multiplicity, t->locals, NULL);
            if (!mult)
                return -1;
            long long m = PyLong_AsLongLong(mult);
            Py_DECREF(mult);
            if (m == -1 && PyErr_Occurred())
                return -1;
            if (m == 0) {
                /* empty JDF range at a boundary: no edge, no data */
                if (PyDict_SetItem(t->data, name, Py_None) < 0)
                    return -1;
                break;
            }
            PyErr_Format(PyExc_RuntimeError,
                         "%S: task-fed flow %U reached prepare_input "
                         "unbound - activation protocol error", t, name);
            return -1;
        }
        default:
            PyErr_SetString(PyExc_RuntimeError,
                            "corrupt native binding plan");
            return -1;
        }
    }
    return 0;
}

/* complete_execution's containment: record the pending exception on
 * the context and continue (-1 only if record_error itself failed) */
static int contained_record(quantum_t *qs, PyObject *task) {
    PyObject *exc = fetch_exc();
    if (!exc) {
        Py_INCREF(Py_None);
        exc = Py_None;
    }
    PyObject *ctx = qs->es_ctx ? qs->es_ctx : Py_None;
    PyObject *r = PyObject_CallMethodObjArgs(ctx, s_record_error, exc,
                                             task, NULL);
    Py_DECREF(exc);
    if (!r)
        return -1;
    Py_DECREF(r);
    return 0;
}

/* deliver_dep's twin for one planned local delivery: returns the
 * newly-ready task (new ref), Py_None (not ready yet), or NULL on
 * error.  Falls back to engine.deliver_dep BEFORE any arrive() when
 * the successor has no native dep table or vtable, so the arrival is
 * never double-counted. */
static PyObject *c_deliver(quantum_t *qs, PyObject *tp, cdeliv_t *d,
                           PyObject *dcopy, PyObject *src) {
    PyObject *nd = PyObject_GetAttr(tp, s_native_deps);
    PyObject *svt;
    if (!nd)
        return NULL;
    if (Py_TYPE(nd) != &DTType)
        goto fallback;
    svt = PyObject_GetAttr(d->succ_tc, s_vt_attr);
    if (!svt)
        PyErr_Clear();
    if (!svt || Py_TYPE(svt) != &VTType) {
        /* unresolved (False sentinel) or off: resolve via native_vt() */
        Py_XDECREF(svt);
        svt = PyObject_CallMethodObjArgs(d->succ_tc, s_native_vt, NULL);
        if (!svt) {
            Py_DECREF(nd);
            return NULL;
        }
        if (Py_TYPE(svt) != &VTType) {
            Py_DECREF(svt);
            goto fallback;
        }
    }
    {
        VTObject *sv = (VTObject *)svt;
        PyObject *payload = NULL;
        PyObject *locals_, *inputs, *sources, *newt;
        TCObject *nt;
        int st;
        PyObject *key = vt_key(sv, d->succ_locals);
        if (!key)
            goto fail;
        st = dtc_arrive((DTObject *)nd, key, d->dflow, dcopy, src,
                        &payload);
        if (st == 0) {
            /* first arrival: install the countdown record, re-arrive */
            PyObject *exp = PyObject_CallMethodObjArgs(
                d->succ_tc, s_nb_task_inputs, d->succ_locals, NULL);
            if (!exp) {
                Py_DECREF(key);
                goto fail;
            }
            long long expected = PyLong_AsLongLong(exp);
            Py_DECREF(exp);
            if (expected == -1 && PyErr_Occurred()) {
                Py_DECREF(key);
                goto fail;
            }
            PyObject *lc = PyDict_Copy(d->succ_locals);
            if (!lc) {
                Py_DECREF(key);
                goto fail;
            }
            int rc = dtc_create((DTObject *)nd, key, expected, lc);
            Py_DECREF(lc);
            if (rc < 0) {
                Py_DECREF(key);
                goto fail;
            }
            st = dtc_arrive((DTObject *)nd, key, d->dflow, dcopy, src,
                            &payload);
        }
        Py_DECREF(key);
        if (st < 0)
            goto fail;
        if (st == 1) {
            Py_DECREF(svt);
            Py_DECREF(nd);
            Py_RETURN_NONE;
        }
        /* ready: build the successor task (locals ALIASED — the
         * record's dict is exclusively owned, build_one's contract) */
        locals_ = PyTuple_GET_ITEM(payload, 0);
        inputs = PyTuple_GET_ITEM(payload, 1);
        sources = PyTuple_GET_ITEM(payload, 2);
        newt = vt_build_task(sv, locals_,
                             attr_ll(tp, s_run_epoch, 0),
                             attr_ll(tp, s_priority_attr, 0));
        if (!newt) {
            Py_DECREF(payload);
            goto fail;
        }
        nt = (TCObject *)newt;
        if (inputs != Py_None) {
            if (PyDict_Update(nt->data, inputs) < 0)
                goto newfail;
            PyObject *k, *val;
            Py_ssize_t pos = 0;
            while (PyDict_Next(inputs, &pos, &k, &val)) {
                if (val != Py_None &&
                    PySet_Add(nt->pinned_flows, k) < 0)
                    goto newfail;
            }
        }
        if (sources != Py_None &&
            PyDict_Update(nt->input_sources, sources) < 0)
            goto newfail;
        Py_DECREF(payload);
        Py_DECREF(svt);
        Py_DECREF(nd);
        return newt;
    newfail:
        Py_DECREF(newt);
        Py_DECREF(payload);
    fail:
        Py_DECREF(svt);
        Py_DECREF(nd);
        return NULL;
    }
fallback:
    Py_DECREF(nd);
    return PyObject_CallFunctionObjArgs(g_engine_deliver, tp,
                                        d->succ_tc, d->succ_locals,
                                        d->dflow, dcopy, src, NULL);
}

/* release_deps' local-only core over the plan, plus schedule(): write-
 * flow version bumps, per-delivery COW / repo holds / countdown
 * arrivals, heap insert of newly-ready tasks, doorbell.  Remote
 * successors / reshape / grapher / ICI / dynamic_release are
 * structurally absent — the plan or the gates bailed those shapes to
 * Python.  -1 with exception set; the caller contains. */
static int c_release_walk(quantum_t *qs, RQObject *q, TCObject *t,
                          VTObject *vt, cplan_t *plan) {
    /* write-flow version bumps: copy.data.complete_write(copy.device) */
    PyObject *wf = vt->wflows;
    for (Py_ssize_t i = 0; i < PyTuple_GET_SIZE(wf); i++) {
        PyObject *copy = PyDict_GetItemWithError(
            t->data, PyTuple_GET_ITEM(wf, i));
        if (!copy) {
            if (PyErr_Occurred())
                return -1;
            continue;
        }
        if (copy == Py_None)
            continue;
        PyObject *datum = PyObject_GetAttr(copy, s_data_attr);
        if (!datum)
            return -1;
        if (datum == Py_None) {
            Py_DECREF(datum);
            continue;
        }
        PyObject *dev = PyObject_GetAttr(copy, s_device_attr);
        if (!dev) {
            Py_DECREF(datum);
            return -1;
        }
        PyObject *r = PyObject_CallMethodObjArgs(datum, s_complete_write,
                                                 dev, NULL);
        Py_DECREF(datum);
        Py_DECREF(dev);
        if (!r)
            return -1;
        Py_DECREF(r);
    }
    PyObject *entry = NULL;   /* lazily-created repo entry (owned) */
    PyObject *repo = NULL;
    long long consumers = 0;
    PyObject *ready = PyList_New(0);
    if (!ready)
        return -1;
    for (Py_ssize_t fi = 0; fi < plan->nouts; fi++) {
        PyObject *name = plan->outs[fi].name;
        Py_ssize_t start = plan->outs[fi].start;
        Py_ssize_t count = plan->outs[fi].count;
        PyObject *copy = PyDict_GetItemWithError(t->data, name);
        if (!copy) {
            if (PyErr_Occurred())
                goto fail;
            copy = Py_None;
        }
        int real = (copy != Py_None);
        if (!real && count > 0 && plan->outs[fi].access != 0) {
            /* NULL forwarded on a data flow: legal but almost always a
             * graph bug (ptgpp forward_NULL golden behavior) */
            PyObject *cnt = PyLong_FromSsize_t(count);
            if (!cnt)
                goto fail;
            PyObject *r = PyObject_CallFunctionObjArgs(
                g_warning, g_null_fwd_fmt, (PyObject *)t, name, cnt,
                NULL);
            Py_DECREF(cnt);
            if (!r)
                goto fail;
            Py_DECREF(r);
        }
        for (Py_ssize_t di = start; di < start + count; di++) {
            cdeliv_t *d = &plan->deliv[di];
            PyObject *dcopy = copy;          /* borrowed unless COW */
            PyObject *owned_dcopy = NULL;
            if (real && count > 1 && d->succ_write) {
                /* fan-out onto a WRITE consumer: hand a copy-on-write
                 * duplicate or its in-place update races the readers */
                owned_dcopy = PyObject_CallFunctionObjArgs(g_engine_cow,
                                                           copy, NULL);
                if (!owned_dcopy)
                    goto fail;
                dcopy = owned_dcopy;
            }
            if (real && !entry) {
                repo = PyObject_GetAttr(t->task_class, s_repo);
                if (!repo) {
                    Py_XDECREF(owned_dcopy);
                    goto fail;
                }
                entry = PyObject_CallMethodObjArgs(repo, s_lookup_entry,
                                                   t->key, NULL);
                if (!entry) {
                    Py_XDECREF(owned_dcopy);
                    goto fail;
                }
            }
            if (real) {
                /* repo hold: a NEW-flow copy chained through several
                 * tasks lives in every producer's entry, and only the
                 * LAST retirement returns it to the freelist */
                PyObject *copies = PyObject_GetAttr(entry, s_copies);
                if (!copies) {
                    Py_XDECREF(owned_dcopy);
                    goto fail;
                }
                if (!PyList_Check(copies) || plan->outs[fi].findex < 0 ||
                    plan->outs[fi].findex >= PyList_GET_SIZE(copies)) {
                    PyErr_SetString(PyExc_RuntimeError,
                                    "repo entry copies list malformed");
                    Py_DECREF(copies);
                    Py_XDECREF(owned_dcopy);
                    goto fail;
                }
                PyObject *cur = PyList_GET_ITEM(copies,
                                                plan->outs[fi].findex);
                if (cur != copy) {
                    PyObject *arena = PyObject_GetAttr(copy,
                                                       s_arena_attr);
                    if (!arena) {
                        Py_DECREF(copies);
                        Py_XDECREF(owned_dcopy);
                        goto fail;
                    }
                    if (arena != Py_None) {
                        PyObject *r = PyObject_CallMethodObjArgs(
                            arena, s_retain_copy, copy, NULL);
                        if (!r) {
                            Py_DECREF(arena);
                            Py_DECREF(copies);
                            Py_XDECREF(owned_dcopy);
                            goto fail;
                        }
                        Py_DECREF(r);
                    }
                    Py_DECREF(arena);
                }
                Py_INCREF(copy);
                if (PyList_SetItem(copies, plan->outs[fi].findex,
                                   copy) < 0) {
                    Py_DECREF(copies);
                    Py_XDECREF(owned_dcopy);
                    goto fail;
                }
                Py_DECREF(copies);
                consumers++;
            }
            PyObject *src;
            if (real) {
                src = PyTuple_Pack(2, t->task_class, t->key);
                if (!src) {
                    Py_XDECREF(owned_dcopy);
                    goto fail;
                }
            } else {
                src = Py_None;
                Py_INCREF(src);
            }
            if (qs->cb_deliver && PyList_Check(qs->cb_deliver) &&
                PyList_GET_SIZE(qs->cb_deliver) > 0) {
                PyObject *pl = PyTuple_Pack(4, (PyObject *)t,
                                            d->succ_tc, d->succ_locals,
                                            d->dflow);
                int pr = pl ? pins_dispatch(qs->cb_deliver, qs->es,
                                            s_deliver_dep, pl) : -1;
                Py_XDECREF(pl);
                if (pr < 0) {
                    Py_DECREF(src);
                    Py_XDECREF(owned_dcopy);
                    goto fail;
                }
            }
            PyObject *newt = c_deliver(qs, t->taskpool, d, dcopy,
                                       real ? src : Py_None);
            Py_DECREF(src);
            Py_XDECREF(owned_dcopy);
            if (!newt)
                goto fail;
            if (newt != Py_None && PyList_Append(ready, newt) < 0) {
                Py_DECREF(newt);
                goto fail;
            }
            Py_DECREF(newt);
        }
    }
    if (entry) {
        PyObject *ret_fn = PyObject_CallFunctionObjArgs(
            g_engine_retire, (PyObject *)t, NULL);
        if (!ret_fn)
            goto fail;
        int rc = PyObject_SetAttr(entry, s_on_retire, ret_fn);
        Py_DECREF(ret_fn);
        if (rc < 0)
            goto fail;
        PyObject *climit = PyLong_FromLongLong(consumers);
        if (!climit)
            goto fail;
        PyObject *r = PyObject_CallMethodObjArgs(repo, s_addto_usage,
                                                 t->key, climit, NULL);
        Py_DECREF(climit);
        if (!r)
            goto fail;
        Py_DECREF(r);
    }
    /* schedule(es, ready): the native push + doorbell, in C */
    {
        Py_ssize_t nready = PyList_GET_SIZE(ready);
        if (nready > 0) {
            double now = qs->ready_stamp ? now_monotonic() : 0.0;
            for (Py_ssize_t i = 0; i < nready; i++) {
                if (rq_push_one(q, PyList_GET_ITEM(ready, i),
                                qs->ready_stamp, 0, now) < 0)
                    goto fail;
            }
            if (qs->es_ctx && qs->es_ctx != Py_None) {
                PyObject *n = PyLong_FromSsize_t(nready);
                if (!n)
                    goto fail;
                PyObject *r = PyObject_CallMethodObjArgs(
                    qs->es_ctx, s_ring_doorbell, n, NULL);
                Py_DECREF(n);
                if (!r)
                    goto fail;
                Py_DECREF(r);
            }
        }
    }
    Py_XDECREF(entry);
    Py_XDECREF(repo);
    Py_DECREF(ready);
    return 0;
fail:
    Py_XDECREF(entry);
    Py_XDECREF(repo);
    Py_DECREF(ready);
    return -1;
}

/* complete_execution's dep half for the extended chain, with the
 * Python path's exact containment structure: {write bumps + release
 * walk + schedule} in one contained block, consume_inputs in its own */
static int c_complete_deps(quantum_t *qs, RQObject *q, TCObject *t,
                           VTObject *vt, cplan_t *plan) {
    if (c_release_walk(qs, q, t, vt, plan) < 0) {
        if (contained_record(qs, (PyObject *)t) < 0)
            return -1;
    }
    if (t->input_sources && PyDict_Size(t->input_sources) > 0) {
        PyObject *r = PyObject_CallFunctionObjArgs(
            g_engine_consume, (PyObject *)t, NULL);
        if (!r) {
            if (contained_record(qs, (PyObject *)t) < 0)
                return -1;
        } else {
            Py_DECREF(r);
        }
    }
    return 0;
}

/* the C progress chain: returns 1 handled, 0 fall back to the Python
 * task_progress (exactly one bailout counter bumped), -1 error.  Two
 * chains share the claim/fence/execute skeleton: FL_TRIV (no flows,
 * empty completion) and FL_EXT (binding-table classes: plan ->
 * prepare -> stage -> execute -> local release walk). */
static int fast_progress(quantum_t *qs, RQObject *q, PyObject *task) {
    if (Py_TYPE(task) != &TCType) {
        g_bail[BR_NON_TRIVIAL]++;
        return 0;
    }
    TCObject *t = (TCObject *)task;
    if (!t->vt || Py_TYPE(t->vt) != &VTType) {
        g_bail[BR_NON_TRIVIAL]++;
        return 0;
    }
    VTObject *vt = (VTObject *)t->vt;
    int want = vt->trivial ? FL_TRIV : (vt->cchain ? FL_EXT : 0);
    if (!want) {
        g_bail[BR_NON_TRIVIAL]++;
        return 0;
    }
    if (qs->fi_armed) {
        g_bail[BR_FAULT_ARMED]++;
        return 0;
    }
    if (!(t->chore_mask & 1)) {
        g_bail[BR_CHORE]++;
        return 0;
    }
    if (t->retries) {
        g_bail[BR_RETRY]++;
        return 0;
    }
    int g = gates_for(qs, t, vt);
    if (g < 0)
        return -1;
    if (!(g & want)) {
        g_bail[want == FL_TRIV ? qs->reason_triv : qs->reason_ext]++;
        return 0;
    }
    /* extended chain: build the whole plan BEFORE any side effect
     * (claim, PINS) — a bail here re-runs the task in Python with
     * every event firing exactly once */
    cplan_t plan;
    int have_plan = 0;
    if (want == FL_EXT) {
        int breason;
        if (plan_build(qs, t, vt, &plan, &breason)) {
            g_bail[breason]++;
            return 0;
        }
        have_plan = 1;
    }
    PyObject *es = qs->es;
    PyObject *ret = NULL;
    /* claim BEFORE the fence check (the recovery drain contract —
     * see task_progress's comment).  The claim also freezes the
     * fence: the drain waits on running_task, so run_epoch cannot
     * move between here and completion. */
    if (PyObject_SetAttr(es, s_running_task, task) < 0) {
        if (have_plan)
            plan_free(&plan);
        return -1;
    }
    /* the recovery fence reads run_epoch FRESH per task — a restart
     * bumping it mid-quantum must discard every later stale task */
    if (t->pool_epoch != attr_ll(t->taskpool, s_run_epoch, 0)) {
        /* stale generation: discard without executing or decrementing */
        t->status = ST_COMPLETE;
        if (pins_dispatch(qs->cb_discard, es, s_task_discard, task) < 0)
            goto err;
        goto done;
    }
    if (qs->cb_begin &&
        pins_dispatch(qs->cb_begin, es, s_exec_begin, task) < 0)
        goto err;
    if (t->status < ST_PREPARED) {
        if (want == FL_TRIV) {
            /* trivial prepare: every flow binds None (no input deps) */
            PyObject *fn = vt->flow_names;
            for (Py_ssize_t i = 0; i < PyTuple_GET_SIZE(fn); i++) {
                if (PyDict_SetItem(t->data, PyTuple_GET_ITEM(fn, i),
                                   Py_None) < 0)
                    goto err;
            }
        } else if (c_prepare(t, vt, &plan) < 0) {
            /* binding error: task_progress's except branch */
            goto bodyfail;
        }
        t->status = ST_PREPARED;
    }
    if (want == FL_EXT) {
        /* execute()'s host staging (a device-pinned input lands a
         * host mirror before the cpu body runs) */
        PyObject *r = PyObject_CallFunctionObjArgs(g_engine_stage,
                                                   task, NULL);
        if (!r)
            goto bodyfail;
        Py_DECREF(r);
    }
    t->status = ST_RUNNING;
    ret = PyObject_CallFunctionObjArgs(vt->hook, es, task, NULL);
    if (!ret) {
    bodyfail:
        /* body/binding raised: the Python twin of task_progress's
         * except branch (retry / record_error / complete failed) */
        {
            PyObject *exc = fetch_exc();
            if (!exc) {
                Py_INCREF(Py_None);
                exc = Py_None;
            }
            PyObject *r = PyObject_CallFunctionObjArgs(g_body_failed,
                                                       es, task, exc,
                                                       NULL);
            Py_DECREF(exc);
            if (!r)
                goto err;
            Py_DECREF(r);
            goto done;
        }
    }
    if (ret != Py_None) {
        /* AGAIN / ASYNC / DISABLE / values: the Python helper mirrors
         * execute()'s normalization + task_progress's dispatch */
        PyObject *r = PyObject_CallFunctionObjArgs(g_hook_return, es,
                                                   task, ret, NULL);
        Py_DECREF(ret);
        if (!r)
            goto err;
        Py_DECREF(r);
        goto done;
    }
    Py_DECREF(ret);
    if (qs->cb_end &&
        pins_dispatch(qs->cb_end, es, s_exec_end, task) < 0)
        goto err;
    if (want == FL_EXT) {
        /* complete_execution's dep half: write bumps + local release
         * walk + schedule + consume_inputs, Python-contained */
        if (c_complete_deps(qs, q, t, vt, &plan) < 0)
            goto err;
    }
    /* for a trivial class the dep half is structurally empty: no
     * writebacks, no release_deps, no repo holds */
    t->status = ST_COMPLETE;
    {
        long long nbv = attr_ll(es, s_nb_tasks_done, 0);
        PyObject *cbs = qs->cb_complete;
        if (cbs && PyList_Check(cbs)) {
            Py_ssize_t ncb = PyList_GET_SIZE(cbs);
            /* a list resized mid-quantum invalidates the cached
             * strides: dispatch everything (stride 1) */
            int gated = (ncb == qs->n_complete);
            for (Py_ssize_t i = 0; i < ncb; i++) {
                if (gated && qs->cstride[i] > 1 &&
                    (nbv % qs->cstride[i]) != 0)
                    continue;
                PyObject *r = PyObject_CallFunctionObjArgs(
                    PyList_GET_ITEM(cbs, i), es, s_complete_exec,
                    task, NULL);
                if (!r)
                    goto err;
                Py_DECREF(r);
            }
        }
        PyObject *nb2 = PyLong_FromLongLong(nbv + 1);
        if (!nb2)
            goto err;
        int rc = PyObject_SetAttr(es, s_nb_tasks_done, nb2);
        Py_DECREF(nb2);
        if (rc < 0)
            goto err;
    }
    /* batched termdet: bump the per-worker accumulator (flushed by
     * worker_loop at batch boundaries / idle); es._td_acc is None
     * when termdet_batch <= 1 — then pay the locked decrement here */
    if (qs->td_acc && qs->td_acc != Py_None) {
        PyObject *entry = PyDict_GetItemWithError(qs->td_acc,
                                                  t->taskpool);
        if (!entry && PyErr_Occurred())
            goto err;
        long long ep = t->pool_epoch;
        if (entry && PyList_Check(entry)
            && PyLong_AsLongLong(PyList_GET_ITEM(entry, 0)) == ep) {
            PyObject *n2 = PyNumber_Add(PyList_GET_ITEM(entry, 1),
                                        g_one);
            if (!n2)
                goto err;
            if (PyList_SetItem(entry, 1, n2) < 0)
                goto err;
        } else {
            PyObject *fresh = Py_BuildValue("[Li]", ep, 1);
            if (!fresh)
                goto err;
            int rc = PyDict_SetItem(qs->td_acc, t->taskpool, fresh);
            Py_DECREF(fresh);
            if (rc < 0)
                goto err;
        }
    } else {
        PyObject *td = PyObject_GetAttr(t->taskpool, s_termdet);
        if (!td)
            goto err;
        PyObject *r = PyObject_CallMethodObjArgs(
            td, s_addto, t->taskpool, g_neg1, NULL);
        Py_DECREF(td);
        if (!r)
            goto err;
        Py_DECREF(r);
    }
done:
    if (have_plan)
        plan_free(&plan);
    if (PyObject_SetAttr(qs->es, s_running_task, Py_None) < 0)
        return -1;
    return 1;
err:
    if (have_plan)
        plan_free(&plan);
    PyObject_SetAttr(qs->es, s_running_task, Py_None);
    return -1;
}

/* run_quantum(es, ready_queue, limit) -> (ndone, task_or_None):
 * pop + select-PINS + the whole prepare/execute/complete chain —
 * trivial AND binding-table (data-carrying) classes — for up to
 * ``limit`` tasks in ONE crossing.  A task the fast path cannot take
 * (uncovered class shape, cancelled pool, armed fault plan, recorded
 * lineage, remote successor on this instance) pops out with its
 * select event already fired, for the Python task_progress; each
 * bail bumps its reason counter (bailout_stats). */
static PyObject *mod_run_quantum(PyObject *mod, PyObject *const *args,
                                 Py_ssize_t nargs) {
    (void)mod;
    if (nargs != 3) {
        PyErr_SetString(PyExc_TypeError,
                        "run_quantum(es, ready_queue, limit)");
        return NULL;
    }
    if (Py_TYPE(args[1]) != &RQType) {
        PyErr_SetString(PyExc_TypeError, "second arg must be ReadyQueue");
        return NULL;
    }
    if (ensure_runtime() < 0)
        return NULL;
    RQObject *q = (RQObject *)args[1];
    long limit = PyLong_AsLong(args[2]);
    if (limit == -1 && PyErr_Occurred())
        return NULL;
    quantum_t qs;
    memset(&qs, 0, sizeof(qs));
    qs.es = args[0];
    qs.pins_map = PyObject_GetAttr(qs.es, s_pins_map);
    if (!qs.pins_map)
        return NULL;
    qs.td_acc = PyObject_GetAttr(qs.es, s_td_acc);
    if (!qs.td_acc) {
        PyErr_Clear();
        qs.td_acc = Py_None;
        Py_INCREF(Py_None);
    }
    /* es.context once per quantum: doorbell / record_error / the
     * ready-stamp switch all hang off it */
    qs.es_ctx = PyObject_GetAttr(qs.es, s_context);
    if (!qs.es_ctx) {
        PyErr_Clear();
        qs.es_ctx = Py_None;
        Py_INCREF(Py_None);
    }
    qs.ready_stamp = (qs.es_ctx != Py_None &&
                      attr_ll(qs.es_ctx, s_ready_stamp, 0) != 0);
    /* borrowed cb lists, refetched per quantum (pins_register mutates
     * the lists in place; new events land within one quantum bound) */
    qs.cb_select = PyDict_GetItemWithError(qs.pins_map, s_select);
    qs.cb_begin = PyDict_GetItemWithError(qs.pins_map, s_exec_begin);
    qs.cb_end = PyDict_GetItemWithError(qs.pins_map, s_exec_end);
    qs.cb_complete = PyDict_GetItemWithError(qs.pins_map,
                                             s_complete_exec);
    qs.cb_discard = PyDict_GetItemWithError(qs.pins_map, s_task_discard);
    qs.cb_deliver = PyDict_GetItemWithError(qs.pins_map, s_deliver_dep);
    {
        PyObject *armed = g_fi_dict
            ? PyDict_GetItemString(g_fi_dict, "ARMED") : NULL;
        qs.fi_armed = armed ? PyObject_IsTrue(armed) : 0;
    }
    /* read each complete_exec callback's advertised sampling stride
     * once per quantum (missing attribute = stride 1 = always call) */
    qs.n_complete = -1;   /* sentinel: gate disabled */
    if (qs.cb_complete && PyList_Check(qs.cb_complete) &&
        PyList_GET_SIZE(qs.cb_complete) <=
            (Py_ssize_t)(sizeof(qs.cstride) / sizeof(qs.cstride[0]))) {
        qs.n_complete = PyList_GET_SIZE(qs.cb_complete);
        for (Py_ssize_t i = 0; i < qs.n_complete; i++) {
            long long v = 1;
            PyObject *st = PyObject_GetAttrString(
                PyList_GET_ITEM(qs.cb_complete, i), "__pins_stride__");
            if (st) {
                v = PyLong_AsLongLong(st);
                Py_DECREF(st);
                if (v < 1) {
                    PyErr_Clear();
                    v = 1;
                }
            } else {
                PyErr_Clear();
            }
            qs.cstride[i] = v;
        }
    }
    long ndone = 0;
    PyObject *out_task = NULL;
    while (ndone < limit) {
        if (q->len == 0)
            break;
        PyObject *task = q->heap[0].task;   /* ownership moves here */
        q->len--;
        if (q->len > 0) {
            q->heap[0] = q->heap[q->len];
            rq_sift_down(q, 0);
        }
        q->pops++;
        if (qs.cb_select &&
            pins_dispatch(qs.cb_select, qs.es, s_select, task) < 0) {
            Py_DECREF(task);
            goto fail;
        }
        int rc = fast_progress(&qs, q, task);
        if (rc < 0) {
            Py_DECREF(task);
            goto fail;
        }
        if (rc == 0) {
            out_task = task;   /* Python task_progress takes it */
            break;
        }
        Py_DECREF(task);
        ndone++;
    }
    {
        PyObject *res = Py_BuildValue("(lO)", ndone,
                                      out_task ? out_task : Py_None);
        Py_XDECREF(out_task);
        Py_XDECREF(qs.last_tp);
        Py_XDECREF(qs.last_vt);
        Py_XDECREF(qs.es_ctx);
        Py_DECREF(qs.pins_map);
        Py_DECREF(qs.td_acc);
        return res;
    }
fail:
    Py_XDECREF(qs.last_tp);
    Py_XDECREF(qs.last_vt);
    Py_XDECREF(qs.es_ctx);
    Py_DECREF(qs.pins_map);
    Py_DECREF(qs.td_acc);
    return NULL;
}

/* bailout_stats() -> {reason: count}: cumulative fast-path bailouts
 * since module load (scraped by prof.metrics; deltas by bench.py) */
static PyObject *mod_bailout_stats(PyObject *self_, PyObject *noargs) {
    (void)self_;
    (void)noargs;
    PyObject *d = PyDict_New();
    if (!d)
        return NULL;
    for (int i = 0; i < BR_NREASONS; i++) {
        PyObject *v = PyLong_FromUnsignedLongLong(
            (unsigned long long)g_bail[i]);
        if (!v || PyDict_SetItemString(d, bail_names[i], v) < 0) {
            Py_XDECREF(v);
            Py_DECREF(d);
            return NULL;
        }
        Py_DECREF(v);
    }
    return d;
}

/* ------------------------------------------------------------------ */

static PyObject *mod_now(PyObject *self_, PyObject *noargs) {
    (void)self_;
    (void)noargs;
    return PyFloat_FromDouble(now_monotonic());
}

static PyMethodDef mod_methods[] = {
    {"now", mod_now, METH_NOARGS, "CLOCK_MONOTONIC seconds"},
    {"run_quantum", (PyCFunction)(void (*)(void))mod_run_quantum,
     METH_FASTCALL,
     "run_quantum(es, ready_queue, limit) -> (ndone, task_or_None)"},
    {"bailout_stats", mod_bailout_stats, METH_NOARGS,
     "cumulative fast-path bailout counts by reason"},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef schedext_module = {
    PyModuleDef_HEAD_INIT, "schedext",
    "native scheduler hot path: ready queue + dep countdown", -1,
    mod_methods, NULL, NULL, NULL, NULL};

PyMODINIT_FUNC PyInit_schedext(void) {
    s_status = PyUnicode_InternFromString("status");
    s_ready_at = PyUnicode_InternFromString("ready_at");
    s_priority = PyUnicode_InternFromString("priority");
    s_pins_map = PyUnicode_InternFromString("_pins_map");
    s_running_task = PyUnicode_InternFromString("running_task");
    s_nb_tasks_done = PyUnicode_InternFromString("nb_tasks_done");
    s_td_acc = PyUnicode_InternFromString("_td_acc");
    s_cancelled = PyUnicode_InternFromString("cancelled");
    s_lineage = PyUnicode_InternFromString("_lineage");
    s_context = PyUnicode_InternFromString("context");
    s_comm = PyUnicode_InternFromString("comm");
    s_run_epoch = PyUnicode_InternFromString("run_epoch");
    s_termdet = PyUnicode_InternFromString("termdet");
    s_addto = PyUnicode_InternFromString("taskpool_addto_nb_tasks");
    s_chore_disabled = PyUnicode_InternFromString("chore_disabled_mask");
    s_select = PyUnicode_InternFromString("select");
    s_exec_begin = PyUnicode_InternFromString("exec_begin");
    s_exec_end = PyUnicode_InternFromString("exec_end");
    s_complete_exec = PyUnicode_InternFromString("complete_exec");
    s_task_discard = PyUnicode_InternFromString("task_discard");
    if (!s_status || !s_ready_at || !s_priority || !s_pins_map ||
        !s_running_task || !s_nb_tasks_done || !s_td_acc ||
        !s_cancelled || !s_lineage || !s_context || !s_comm ||
        !s_run_epoch || !s_termdet || !s_addto || !s_chore_disabled ||
        !s_select || !s_exec_begin || !s_exec_end || !s_complete_exec ||
        !s_task_discard)
        return NULL;
    s_data_attr = PyUnicode_InternFromString("data");
    s_device_attr = PyUnicode_InternFromString("device");
    s_complete_write = PyUnicode_InternFromString("complete_write");
    s_repo = PyUnicode_InternFromString("repo");
    s_lookup_entry = PyUnicode_InternFromString("lookup_entry_and_create");
    s_addto_usage = PyUnicode_InternFromString("entry_addto_usage_limit");
    s_copies = PyUnicode_InternFromString("copies");
    s_on_retire = PyUnicode_InternFromString("on_retire");
    s_arena_attr = PyUnicode_InternFromString("arena");
    s_retain_copy = PyUnicode_InternFromString("retain_copy");
    s_get_copy = PyUnicode_InternFromString("get_copy");
    s_arenas = PyUnicode_InternFromString("arenas");
    s_flags = PyUnicode_InternFromString("flags");
    s_resolve = PyUnicode_InternFromString("resolve");
    s_copy_on = PyUnicode_InternFromString("copy_on");
    s_multiplicity = PyUnicode_InternFromString("multiplicity");
    s_instances = PyUnicode_InternFromString("instances");
    s_affinity = PyUnicode_InternFromString("affinity");
    s_rank_of = PyUnicode_InternFromString("rank_of");
    s_param_names_attr = PyUnicode_InternFromString("_param_names");
    s_complete_locals = PyUnicode_InternFromString("complete_locals");
    s_native_deps = PyUnicode_InternFromString("_native_deps");
    s_vt_attr = PyUnicode_InternFromString("_vt");
    s_native_vt = PyUnicode_InternFromString("native_vt");
    s_nb_task_inputs = PyUnicode_InternFromString("nb_task_inputs");
    s_deliver_dep = PyUnicode_InternFromString("deliver_dep");
    s_ring_doorbell = PyUnicode_InternFromString("ring_doorbell");
    s_record_error = PyUnicode_InternFromString("record_error");
    s_rank = PyUnicode_InternFromString("rank");
    s_ready_stamp = PyUnicode_InternFromString("_ready_stamp");
    s_retry_max = PyUnicode_InternFromString("_retry_max");
    s_grapher = PyUnicode_InternFromString("grapher");
    s_ici = PyUnicode_InternFromString("ici");
    s_replay_filter = PyUnicode_InternFromString("_replay_filter");
    s_priority_attr = PyUnicode_InternFromString("priority");
    if (!s_data_attr || !s_device_attr || !s_complete_write || !s_repo ||
        !s_lookup_entry || !s_addto_usage || !s_copies || !s_on_retire ||
        !s_arena_attr || !s_retain_copy || !s_get_copy || !s_arenas ||
        !s_flags || !s_resolve || !s_copy_on || !s_multiplicity ||
        !s_instances || !s_affinity || !s_rank_of ||
        !s_param_names_attr || !s_complete_locals || !s_native_deps ||
        !s_vt_attr || !s_native_vt || !s_nb_task_inputs ||
        !s_deliver_dep || !s_ring_doorbell || !s_record_error ||
        !s_rank || !s_ready_stamp || !s_retry_max || !s_grapher ||
        !s_ici || !s_replay_filter || !s_priority_attr)
        return NULL;
    g_one = PyLong_FromLong(1L);
    g_neg1 = PyLong_FromLong(-1L);
    g_zero = PyLong_FromLong(0L);
    g_null_fwd_fmt = PyUnicode_FromString(
        "A NULL is forwarded from %s flow %s to %d successor(s)");
    if (!g_one || !g_neg1 || !g_zero || !g_null_fwd_fmt)
        return NULL;
    if (PyType_Ready(&RQType) < 0 || PyType_Ready(&DepRecType) < 0 ||
        PyType_Ready(&DTType) < 0 || PyType_Ready(&TCType) < 0 ||
        PyType_Ready(&VTType) < 0)
        return NULL;
    PyObject *m = PyModule_Create(&schedext_module);
    if (!m)
        return NULL;
    Py_INCREF(&RQType);
    if (PyModule_AddObject(m, "ReadyQueue", (PyObject *)&RQType) < 0) {
        Py_DECREF(&RQType);
        Py_DECREF(m);
        return NULL;
    }
    Py_INCREF(&DTType);
    if (PyModule_AddObject(m, "DepTable", (PyObject *)&DTType) < 0) {
        Py_DECREF(&DTType);
        Py_DECREF(m);
        return NULL;
    }
    Py_INCREF(&TCType);
    if (PyModule_AddObject(m, "TaskCore", (PyObject *)&TCType) < 0) {
        Py_DECREF(&TCType);
        Py_DECREF(m);
        return NULL;
    }
    Py_INCREF(&VTType);
    if (PyModule_AddObject(m, "TaskVT", (PyObject *)&VTType) < 0) {
        Py_DECREF(&VTType);
        Py_DECREF(m);
        return NULL;
    }
    return m;
}
