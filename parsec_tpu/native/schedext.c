/* Native scheduler hot path: the ready queue and the dep countdown in C.
 *
 * Rebuild of the reference's native scheduling core (reference:
 * parsec/mca/sched/* queue disciplines over parsec_list_item rings and
 * the atomic dep countdown of parsec_internal.h:355-366
 * update_deps_with_counter): the per-scheduling-event Python work —
 * status transition, Task.ready_at stamping, priority-ordered
 * push/pop, and the dep-counter decrement + ready-transition test —
 * collapses into ONE METH_FASTCALL crossing per event, the pinsext.c
 * pattern (tracer 5.0 -> 1.16 us/task) applied to the scheduler.
 *
 * Concurrency model: every entry point runs under the GIL and never
 * releases it (no callbacks into Python between state mutations except
 * where noted), so the GIL itself is the queue lock — the Python
 * fallback pays a threading.Lock round-trip per operation ON TOP of
 * the GIL; this pays neither.  The heap entries own strong references
 * to their tasks (the C-side twin of NativeDequeue's park/claim side
 * table, without the ctypes crossing or the id-keyed parking dict).
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

static inline double now_monotonic(void) {
    struct timespec t;
    clock_gettime(CLOCK_MONOTONIC, &t);
    return (double)t.tv_sec + (double)t.tv_nsec * 1e-9;
}

/* interned attribute names, created at module init */
static PyObject *s_status, *s_ready_at, *s_priority;

/* ------------------------------------------------------------------ */
/* ReadyQueue: binary max-heap of (priority, FIFO seq) -> task        */
/* ------------------------------------------------------------------ */

typedef struct {
    int64_t prio;       /* higher pops first */
    uint64_t seq;       /* FIFO among equal priorities */
    PyObject *task;     /* strong reference */
} rq_ent_t;

typedef struct {
    PyObject_HEAD
    rq_ent_t *heap;
    Py_ssize_t len, cap;
    uint64_t seq;
    /* stats (display_stats / metrics scrape) */
    uint64_t pushes, pops;
    Py_ssize_t max_len;
    PyObject *ready_status;   /* TaskStatus.READY, set at construction */
} RQObject;

static int rq_grow(RQObject *q) {
    Py_ssize_t ncap = q->cap ? q->cap * 2 : 1024;
    rq_ent_t *nh = (rq_ent_t *)realloc(q->heap,
                                       (size_t)ncap * sizeof(rq_ent_t));
    if (!nh) {
        PyErr_NoMemory();
        return -1;
    }
    q->heap = nh;
    q->cap = ncap;
    return 0;
}

/* entry a beats entry b (pops first)? */
static inline int rq_before(const rq_ent_t *a, const rq_ent_t *b) {
    if (a->prio != b->prio)
        return a->prio > b->prio;
    return a->seq < b->seq;
}

static void rq_sift_up(RQObject *q, Py_ssize_t i) {
    rq_ent_t e = q->heap[i];
    while (i > 0) {
        Py_ssize_t p = (i - 1) / 2;
        if (!rq_before(&e, &q->heap[p]))
            break;
        q->heap[i] = q->heap[p];
        i = p;
    }
    q->heap[i] = e;
}

static void rq_sift_down(RQObject *q, Py_ssize_t i) {
    rq_ent_t e = q->heap[i];
    Py_ssize_t n = q->len;
    for (;;) {
        Py_ssize_t c = 2 * i + 1;
        if (c >= n)
            break;
        if (c + 1 < n && rq_before(&q->heap[c + 1], &q->heap[c]))
            c++;
        if (!rq_before(&q->heap[c], &e))
            break;
        q->heap[i] = q->heap[c];
        i = c;
    }
    q->heap[i] = e;
}

/* push one task: read .priority, set .status (and .ready_at when
 * stamping), insert.  prio_override INT64_MIN means "back of the
 * queue" (the fairness contract for distance-rescheduled tasks). */
static int rq_push_one(RQObject *q, PyObject *task, int stamp,
                       int to_back, double now) {
    int64_t prio = 0;
    if (to_back) {
        prio = INT64_MIN;
    } else {
        PyObject *p = PyObject_GetAttr(task, s_priority);
        if (!p)
            return -1;
        prio = PyLong_AsLongLong(p);
        Py_DECREF(p);
        if (prio == -1 && PyErr_Occurred())
            return -1;
    }
    if (PyObject_SetAttr(task, s_status, q->ready_status) < 0)
        return -1;
    if (stamp) {
        PyObject *ts = PyFloat_FromDouble(now);
        if (!ts)
            return -1;
        int r = PyObject_SetAttr(task, s_ready_at, ts);
        Py_DECREF(ts);
        if (r < 0)
            return -1;
    }
    if (q->len >= q->cap && rq_grow(q) < 0)
        return -1;
    rq_ent_t *e = &q->heap[q->len++];
    e->prio = prio;
    e->seq = q->seq++;
    e->task = task;
    Py_INCREF(task);
    rq_sift_up(q, q->len - 1);
    q->pushes++;
    if (q->len > q->max_len)
        q->max_len = q->len;
    return 0;
}

/* push_batch(tasks, stamp, to_back=0) — ONE crossing per scheduling
 * event: the whole ready ring transitions to READY (ready_at stamped
 * from one clock read: the batch became ready at the same moment,
 * matching core/scheduling.schedule's Python fallback) and lands in
 * the heap. */
static PyObject *rq_push_batch(PyObject *self_, PyObject *const *args,
                               Py_ssize_t nargs) {
    RQObject *q = (RQObject *)self_;
    if (nargs < 2 || nargs > 3) {
        PyErr_SetString(PyExc_TypeError,
                        "push_batch(tasks, stamp[, to_back])");
        return NULL;
    }
    int stamp = PyObject_IsTrue(args[1]);
    if (stamp < 0)
        return NULL;
    int to_back = 0;
    if (nargs == 3) {
        to_back = PyObject_IsTrue(args[2]);
        if (to_back < 0)
            return NULL;
    }
    PyObject *fast = PySequence_Fast(args[0], "tasks must be a sequence");
    if (!fast)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    PyObject **items = PySequence_Fast_ITEMS(fast);
    double now = stamp ? now_monotonic() : 0.0;
    for (Py_ssize_t i = 0; i < n; i++) {
        if (rq_push_one(q, items[i], stamp, to_back, now) < 0) {
            Py_DECREF(fast);
            return NULL;
        }
    }
    Py_DECREF(fast);
    Py_RETURN_NONE;
}

static PyObject *rq_pop(PyObject *self_, PyObject *noargs) {
    (void)noargs;
    RQObject *q = (RQObject *)self_;
    if (q->len == 0)
        Py_RETURN_NONE;
    PyObject *task = q->heap[0].task;   /* ownership moves to caller */
    q->len--;
    if (q->len > 0) {
        q->heap[0] = q->heap[q->len];
        rq_sift_down(q, 0);
    }
    q->pops++;
    return task;
}

static PyObject *rq_stats(PyObject *self_, PyObject *noargs) {
    (void)noargs;
    RQObject *q = (RQObject *)self_;
    return Py_BuildValue("(KKnn)", (unsigned long long)q->pushes,
                         (unsigned long long)q->pops, q->max_len, q->len);
}

static Py_ssize_t rq_length(PyObject *self_) {
    return ((RQObject *)self_)->len;
}

static void rq_dealloc(PyObject *self_) {
    RQObject *q = (RQObject *)self_;
    for (Py_ssize_t i = 0; i < q->len; i++)
        Py_DECREF(q->heap[i].task);
    free(q->heap);
    Py_CLEAR(q->ready_status);
    Py_TYPE(self_)->tp_free(self_);
}

static int rq_init(PyObject *self_, PyObject *args, PyObject *kwds) {
    (void)kwds;
    RQObject *q = (RQObject *)self_;
    PyObject *ready;
    if (!PyArg_ParseTuple(args, "O", &ready))
        return -1;
    Py_INCREF(ready);
    Py_XSETREF(q->ready_status, ready);
    return 0;
}

static PyObject *rq_new(PyTypeObject *type, PyObject *args,
                        PyObject *kwds) {
    (void)args;
    (void)kwds;
    RQObject *q = (RQObject *)type->tp_alloc(type, 0);
    if (q) {
        q->heap = NULL;
        q->len = q->cap = 0;
        q->seq = 0;
        q->pushes = q->pops = 0;
        q->max_len = 0;
        q->ready_status = NULL;
    }
    return (PyObject *)q;
}

static PyMethodDef rq_methods[] = {
    {"push_batch", (PyCFunction)(void (*)(void))rq_push_batch,
     METH_FASTCALL,
     "push_batch(tasks, stamp[, to_back]): READY-transition + ready_at "
     "stamp + priority-ordered insert, one crossing per event"},
    {"pop", (PyCFunction)rq_pop, METH_NOARGS,
     "pop the highest-priority task (FIFO among equals), or None"},
    {"stats", (PyCFunction)rq_stats, METH_NOARGS,
     "(pushes, pops, max_len, len)"},
    {NULL, NULL, 0, NULL}};

static PySequenceMethods rq_as_sequence = {
    .sq_length = rq_length,
};

static PyTypeObject RQType = {
    PyVarObject_HEAD_INIT(NULL, 0).tp_name = "schedext.ReadyQueue",
    .tp_basicsize = sizeof(RQObject),
    .tp_dealloc = rq_dealloc,
    .tp_as_sequence = &rq_as_sequence,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_methods = rq_methods,
    .tp_init = rq_init,
    .tp_new = rq_new,
};

/* ------------------------------------------------------------------ */
/* DepTable: the dep-countdown record store (engine.deliver_dep)      */
/* ------------------------------------------------------------------ */

/* One pending record, a private heap type so records live as dict
 * values.  Mirrors engine.PendingRecord. */
typedef struct {
    PyObject_HEAD
    int64_t expected, arrivals;
    PyObject *locals;    /* dict */
    PyObject *inputs;    /* dict or NULL (lazily created) */
    PyObject *sources;   /* dict or NULL */
} DepRec;

static void deprec_dealloc(PyObject *self_) {
    DepRec *r = (DepRec *)self_;
    Py_CLEAR(r->locals);
    Py_CLEAR(r->inputs);
    Py_CLEAR(r->sources);
    Py_TYPE(self_)->tp_free(self_);
}

static PyTypeObject DepRecType = {
    PyVarObject_HEAD_INIT(NULL, 0).tp_name = "schedext._DepRec",
    .tp_basicsize = sizeof(DepRec),
    .tp_dealloc = deprec_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_new = NULL,   /* internal only */
};

typedef struct {
    PyObject_HEAD
    PyObject *table;    /* dict: key -> DepRec */
} DTObject;

/* create(key, expected, locals): install a fresh countdown record
 * (called once per successor, on the first arrival's MISS).  A record
 * that appeared since the caller's miss is KEPT — two workers racing
 * the first two arrivals of one successor both observe the miss, and
 * the second create must not wipe the first's recorded arrival. */
static PyObject *dt_create(PyObject *self_, PyObject *const *args,
                           Py_ssize_t nargs) {
    DTObject *t = (DTObject *)self_;
    if (nargs != 3) {
        PyErr_SetString(PyExc_TypeError, "create(key, expected, locals)");
        return NULL;
    }
    PyObject *existing = PyDict_GetItemWithError(t->table, args[0]);
    if (existing)
        Py_RETURN_NONE;
    if (PyErr_Occurred())
        return NULL;
    long long expected = PyLong_AsLongLong(args[1]);
    if (expected == -1 && PyErr_Occurred())
        return NULL;
    DepRec *r = (DepRec *)DepRecType.tp_alloc(&DepRecType, 0);
    if (!r)
        return NULL;
    r->expected = expected;
    r->arrivals = 0;
    Py_INCREF(args[2]);
    r->locals = args[2];
    r->inputs = NULL;
    r->sources = NULL;
    int rc = PyDict_SetItem(t->table, args[0], (PyObject *)r);
    Py_DECREF(r);
    if (rc < 0)
        return NULL;
    Py_RETURN_NONE;
}

/* arrive(key, flow, copy, source) -> None (not ready), False (no
 * record: caller must create() then re-arrive), or the ready payload
 * (locals, inputs_or_None, sources_or_None) with the record removed.
 * The JDF gather rule is enforced here: a data flow receiving two
 * copies raises (range deps may only gather CTL). */
static PyObject *dt_arrive(PyObject *self_, PyObject *const *args,
                           Py_ssize_t nargs) {
    DTObject *t = (DTObject *)self_;
    if (nargs != 4) {
        PyErr_SetString(PyExc_TypeError,
                        "arrive(key, flow, copy, source)");
        return NULL;
    }
    PyObject *key = args[0], *flow = args[1];
    PyObject *copy = args[2], *source = args[3];
    PyObject *ent = PyDict_GetItemWithError(t->table, key);
    if (!ent) {
        if (PyErr_Occurred())
            return NULL;
        Py_RETURN_FALSE;   /* miss: caller create()s, then re-arrives */
    }
    DepRec *r = (DepRec *)ent;
    r->arrivals++;
    /* record EVERY arrival's binding, None included — a CTL delivery
     * must land flow->None in task.data so prepare_input sees the
     * task-fed flow as bound (exact twin of the Python record path) */
    if (!r->inputs) {
        r->inputs = PyDict_New();
        if (!r->inputs)
            return NULL;
    } else if (copy != Py_None) {
        PyObject *prev = PyDict_GetItemWithError(r->inputs, flow);
        if (!prev && PyErr_Occurred())
            return NULL;
        if (prev && prev != Py_None) {
            /* ASCII only: PyErr_Format's format string must be */
            PyErr_Format(PyExc_RuntimeError,
                         "data flow %R received two copies - range "
                         "deps may only gather CTL", flow);
            return NULL;
        }
    }
    {
        /* a gather's earlier real copy must survive a later None
         * arrival on the same flow (CTL range edges all carry None) */
        int has = PyDict_Contains(r->inputs, flow);
        if (has < 0)
            return NULL;
        if (copy != Py_None || !has) {
            if (PyDict_SetItem(r->inputs, flow, copy) < 0)
                return NULL;
        }
    }
    if (source != Py_None) {
        if (!r->sources) {
            r->sources = PyDict_New();
            if (!r->sources)
                return NULL;
        }
        if (PyDict_SetItem(r->sources, flow, source) < 0)
            return NULL;
    }
    if (r->arrivals < r->expected)
        Py_RETURN_NONE;
    /* ready transition: hand the record's contents to the caller and
     * drop the entry in the same crossing */
    PyObject *out = PyTuple_New(3);
    if (!out)
        return NULL;
    Py_INCREF(r->locals);
    PyTuple_SET_ITEM(out, 0, r->locals);
    PyObject *ins = r->inputs ? r->inputs : Py_None;
    Py_INCREF(ins);
    PyTuple_SET_ITEM(out, 1, ins);
    PyObject *srcs = r->sources ? r->sources : Py_None;
    Py_INCREF(srcs);
    PyTuple_SET_ITEM(out, 2, srcs);
    if (PyDict_DelItem(t->table, key) < 0) {
        Py_DECREF(out);
        return NULL;
    }
    return out;
}

static Py_ssize_t dt_length(PyObject *self_) {
    return PyDict_Size(((DTObject *)self_)->table);
}

static void dt_dealloc(PyObject *self_) {
    Py_CLEAR(((DTObject *)self_)->table);
    Py_TYPE(self_)->tp_free(self_);
}

static PyObject *dt_new(PyTypeObject *type, PyObject *args,
                        PyObject *kwds) {
    (void)args;
    (void)kwds;
    DTObject *t = (DTObject *)type->tp_alloc(type, 0);
    if (t) {
        t->table = PyDict_New();
        if (!t->table) {
            Py_DECREF(t);
            return NULL;
        }
    }
    return (PyObject *)t;
}

static PyMethodDef dt_methods[] = {
    {"create", (PyCFunction)(void (*)(void))dt_create, METH_FASTCALL,
     "create(key, expected, locals): install a countdown record"},
    {"arrive", (PyCFunction)(void (*)(void))dt_arrive, METH_FASTCALL,
     "arrive(key, flow, copy, source) -> None | False | "
     "(locals, inputs, sources)"},
    {NULL, NULL, 0, NULL}};

static PySequenceMethods dt_as_sequence = {
    .sq_length = dt_length,
};

static PyTypeObject DTType = {
    PyVarObject_HEAD_INIT(NULL, 0).tp_name = "schedext.DepTable",
    .tp_basicsize = sizeof(DTObject),
    .tp_dealloc = dt_dealloc,
    .tp_as_sequence = &dt_as_sequence,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_methods = dt_methods,
    .tp_new = dt_new,
};

/* ------------------------------------------------------------------ */

static PyObject *mod_now(PyObject *self_, PyObject *noargs) {
    (void)self_;
    (void)noargs;
    return PyFloat_FromDouble(now_monotonic());
}

static PyMethodDef mod_methods[] = {
    {"now", mod_now, METH_NOARGS, "CLOCK_MONOTONIC seconds"},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef schedext_module = {
    PyModuleDef_HEAD_INIT, "schedext",
    "native scheduler hot path: ready queue + dep countdown", -1,
    mod_methods, NULL, NULL, NULL, NULL};

PyMODINIT_FUNC PyInit_schedext(void) {
    s_status = PyUnicode_InternFromString("status");
    s_ready_at = PyUnicode_InternFromString("ready_at");
    s_priority = PyUnicode_InternFromString("priority");
    if (!s_status || !s_ready_at || !s_priority)
        return NULL;
    if (PyType_Ready(&RQType) < 0 || PyType_Ready(&DepRecType) < 0 ||
        PyType_Ready(&DTType) < 0)
        return NULL;
    PyObject *m = PyModule_Create(&schedext_module);
    if (!m)
        return NULL;
    Py_INCREF(&RQType);
    if (PyModule_AddObject(m, "ReadyQueue", (PyObject *)&RQType) < 0) {
        Py_DECREF(&RQType);
        Py_DECREF(m);
        return NULL;
    }
    Py_INCREF(&DTType);
    if (PyModule_AddObject(m, "DepTable", (PyObject *)&DTType) < 0) {
        Py_DECREF(&DTType);
        Py_DECREF(m);
        return NULL;
    }
    return m;
}
