/* Native scheduler hot path: the ready queue and the dep countdown in C.
 *
 * Rebuild of the reference's native scheduling core (reference:
 * parsec/mca/sched/* queue disciplines over parsec_list_item rings and
 * the atomic dep countdown of parsec_internal.h:355-366
 * update_deps_with_counter): the per-scheduling-event Python work —
 * status transition, Task.ready_at stamping, priority-ordered
 * push/pop, and the dep-counter decrement + ready-transition test —
 * collapses into ONE METH_FASTCALL crossing per event, the pinsext.c
 * pattern (tracer 5.0 -> 1.16 us/task) applied to the scheduler.
 *
 * Concurrency model: every entry point runs under the GIL and never
 * releases it (no callbacks into Python between state mutations except
 * where noted), so the GIL itself is the queue lock — the Python
 * fallback pays a threading.Lock round-trip per operation ON TOP of
 * the GIL; this pays neither.  The heap entries own strong references
 * to their tasks (the C-side twin of NativeDequeue's park/claim side
 * table, without the ctypes crossing or the id-keyed parking dict).
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

static inline double now_monotonic(void) {
    struct timespec t;
    clock_gettime(CLOCK_MONOTONIC, &t);
    return (double)t.tv_sec + (double)t.tv_nsec * 1e-9;
}

/* interned attribute names, created at module init */
static PyObject *s_status, *s_ready_at, *s_priority;

/* ------------------------------------------------------------------ */
/* ReadyQueue: binary max-heap of (priority, FIFO seq) -> task        */
/* ------------------------------------------------------------------ */

typedef struct {
    int64_t prio;       /* higher pops first */
    uint64_t seq;       /* FIFO among equal priorities */
    PyObject *task;     /* strong reference */
} rq_ent_t;

typedef struct {
    PyObject_HEAD
    rq_ent_t *heap;
    Py_ssize_t len, cap;
    uint64_t seq;
    /* stats (display_stats / metrics scrape) */
    uint64_t pushes, pops;
    Py_ssize_t max_len;
    PyObject *ready_status;   /* TaskStatus.READY, set at construction */
} RQObject;

static int rq_grow(RQObject *q) {
    Py_ssize_t ncap = q->cap ? q->cap * 2 : 1024;
    rq_ent_t *nh = (rq_ent_t *)realloc(q->heap,
                                       (size_t)ncap * sizeof(rq_ent_t));
    if (!nh) {
        PyErr_NoMemory();
        return -1;
    }
    q->heap = nh;
    q->cap = ncap;
    return 0;
}

/* entry a beats entry b (pops first)? */
static inline int rq_before(const rq_ent_t *a, const rq_ent_t *b) {
    if (a->prio != b->prio)
        return a->prio > b->prio;
    return a->seq < b->seq;
}

static void rq_sift_up(RQObject *q, Py_ssize_t i) {
    rq_ent_t e = q->heap[i];
    while (i > 0) {
        Py_ssize_t p = (i - 1) / 2;
        if (!rq_before(&e, &q->heap[p]))
            break;
        q->heap[i] = q->heap[p];
        i = p;
    }
    q->heap[i] = e;
}

static void rq_sift_down(RQObject *q, Py_ssize_t i) {
    rq_ent_t e = q->heap[i];
    Py_ssize_t n = q->len;
    for (;;) {
        Py_ssize_t c = 2 * i + 1;
        if (c >= n)
            break;
        if (c + 1 < n && rq_before(&q->heap[c + 1], &q->heap[c]))
            c++;
        if (!rq_before(&q->heap[c], &e))
            break;
        q->heap[i] = q->heap[c];
        i = c;
    }
    q->heap[i] = e;
}

/* push one task: read .priority, set .status (and .ready_at when
 * stamping), insert.  prio_override INT64_MIN means "back of the
 * queue" (the fairness contract for distance-rescheduled tasks). */
static int rq_push_one(RQObject *q, PyObject *task, int stamp,
                       int to_back, double now) {
    int64_t prio = 0;
    if (to_back) {
        prio = INT64_MIN;
    } else {
        PyObject *p = PyObject_GetAttr(task, s_priority);
        if (!p)
            return -1;
        prio = PyLong_AsLongLong(p);
        Py_DECREF(p);
        if (prio == -1 && PyErr_Occurred())
            return -1;
    }
    if (PyObject_SetAttr(task, s_status, q->ready_status) < 0)
        return -1;
    if (stamp) {
        PyObject *ts = PyFloat_FromDouble(now);
        if (!ts)
            return -1;
        int r = PyObject_SetAttr(task, s_ready_at, ts);
        Py_DECREF(ts);
        if (r < 0)
            return -1;
    }
    if (q->len >= q->cap && rq_grow(q) < 0)
        return -1;
    rq_ent_t *e = &q->heap[q->len++];
    e->prio = prio;
    e->seq = q->seq++;
    e->task = task;
    Py_INCREF(task);
    rq_sift_up(q, q->len - 1);
    q->pushes++;
    if (q->len > q->max_len)
        q->max_len = q->len;
    return 0;
}

/* push_batch(tasks, stamp, to_back=0) — ONE crossing per scheduling
 * event: the whole ready ring transitions to READY (ready_at stamped
 * from one clock read: the batch became ready at the same moment,
 * matching core/scheduling.schedule's Python fallback) and lands in
 * the heap. */
static PyObject *rq_push_batch(PyObject *self_, PyObject *const *args,
                               Py_ssize_t nargs) {
    RQObject *q = (RQObject *)self_;
    if (nargs < 2 || nargs > 3) {
        PyErr_SetString(PyExc_TypeError,
                        "push_batch(tasks, stamp[, to_back])");
        return NULL;
    }
    int stamp = PyObject_IsTrue(args[1]);
    if (stamp < 0)
        return NULL;
    int to_back = 0;
    if (nargs == 3) {
        to_back = PyObject_IsTrue(args[2]);
        if (to_back < 0)
            return NULL;
    }
    PyObject *fast = PySequence_Fast(args[0], "tasks must be a sequence");
    if (!fast)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    PyObject **items = PySequence_Fast_ITEMS(fast);
    double now = stamp ? now_monotonic() : 0.0;
    for (Py_ssize_t i = 0; i < n; i++) {
        if (rq_push_one(q, items[i], stamp, to_back, now) < 0) {
            Py_DECREF(fast);
            return NULL;
        }
    }
    Py_DECREF(fast);
    Py_RETURN_NONE;
}

static PyObject *rq_pop(PyObject *self_, PyObject *noargs) {
    (void)noargs;
    RQObject *q = (RQObject *)self_;
    if (q->len == 0)
        Py_RETURN_NONE;
    PyObject *task = q->heap[0].task;   /* ownership moves to caller */
    q->len--;
    if (q->len > 0) {
        q->heap[0] = q->heap[q->len];
        rq_sift_down(q, 0);
    }
    q->pops++;
    return task;
}

static PyObject *rq_stats(PyObject *self_, PyObject *noargs) {
    (void)noargs;
    RQObject *q = (RQObject *)self_;
    return Py_BuildValue("(KKnn)", (unsigned long long)q->pushes,
                         (unsigned long long)q->pops, q->max_len, q->len);
}

static Py_ssize_t rq_length(PyObject *self_) {
    return ((RQObject *)self_)->len;
}

static void rq_dealloc(PyObject *self_) {
    RQObject *q = (RQObject *)self_;
    for (Py_ssize_t i = 0; i < q->len; i++)
        Py_DECREF(q->heap[i].task);
    free(q->heap);
    Py_CLEAR(q->ready_status);
    Py_TYPE(self_)->tp_free(self_);
}

static int rq_init(PyObject *self_, PyObject *args, PyObject *kwds) {
    (void)kwds;
    RQObject *q = (RQObject *)self_;
    PyObject *ready;
    if (!PyArg_ParseTuple(args, "O", &ready))
        return -1;
    Py_INCREF(ready);
    Py_XSETREF(q->ready_status, ready);
    return 0;
}

static PyObject *rq_new(PyTypeObject *type, PyObject *args,
                        PyObject *kwds) {
    (void)args;
    (void)kwds;
    RQObject *q = (RQObject *)type->tp_alloc(type, 0);
    if (q) {
        q->heap = NULL;
        q->len = q->cap = 0;
        q->seq = 0;
        q->pushes = q->pops = 0;
        q->max_len = 0;
        q->ready_status = NULL;
    }
    return (PyObject *)q;
}

static PyMethodDef rq_methods[] = {
    {"push_batch", (PyCFunction)(void (*)(void))rq_push_batch,
     METH_FASTCALL,
     "push_batch(tasks, stamp[, to_back]): READY-transition + ready_at "
     "stamp + priority-ordered insert, one crossing per event"},
    {"pop", (PyCFunction)rq_pop, METH_NOARGS,
     "pop the highest-priority task (FIFO among equals), or None"},
    {"stats", (PyCFunction)rq_stats, METH_NOARGS,
     "(pushes, pops, max_len, len)"},
    {NULL, NULL, 0, NULL}};

static PySequenceMethods rq_as_sequence = {
    .sq_length = rq_length,
};

static PyTypeObject RQType = {
    PyVarObject_HEAD_INIT(NULL, 0).tp_name = "schedext.ReadyQueue",
    .tp_basicsize = sizeof(RQObject),
    .tp_dealloc = rq_dealloc,
    .tp_as_sequence = &rq_as_sequence,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_methods = rq_methods,
    .tp_init = rq_init,
    .tp_new = rq_new,
};

/* ------------------------------------------------------------------ */
/* DepTable: the dep-countdown record store (engine.deliver_dep)      */
/* ------------------------------------------------------------------ */

/* One pending record, a private heap type so records live as dict
 * values.  Mirrors engine.PendingRecord. */
typedef struct {
    PyObject_HEAD
    int64_t expected, arrivals;
    PyObject *locals;    /* dict */
    PyObject *inputs;    /* dict or NULL (lazily created) */
    PyObject *sources;   /* dict or NULL */
} DepRec;

static void deprec_dealloc(PyObject *self_) {
    DepRec *r = (DepRec *)self_;
    Py_CLEAR(r->locals);
    Py_CLEAR(r->inputs);
    Py_CLEAR(r->sources);
    Py_TYPE(self_)->tp_free(self_);
}

static PyTypeObject DepRecType = {
    PyVarObject_HEAD_INIT(NULL, 0).tp_name = "schedext._DepRec",
    .tp_basicsize = sizeof(DepRec),
    .tp_dealloc = deprec_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_new = NULL,   /* internal only */
};

typedef struct {
    PyObject_HEAD
    PyObject *table;    /* dict: key -> DepRec */
} DTObject;

/* create(key, expected, locals): install a fresh countdown record
 * (called once per successor, on the first arrival's MISS).  A record
 * that appeared since the caller's miss is KEPT — two workers racing
 * the first two arrivals of one successor both observe the miss, and
 * the second create must not wipe the first's recorded arrival. */
static PyObject *dt_create(PyObject *self_, PyObject *const *args,
                           Py_ssize_t nargs) {
    DTObject *t = (DTObject *)self_;
    if (nargs != 3) {
        PyErr_SetString(PyExc_TypeError, "create(key, expected, locals)");
        return NULL;
    }
    PyObject *existing = PyDict_GetItemWithError(t->table, args[0]);
    if (existing)
        Py_RETURN_NONE;
    if (PyErr_Occurred())
        return NULL;
    long long expected = PyLong_AsLongLong(args[1]);
    if (expected == -1 && PyErr_Occurred())
        return NULL;
    DepRec *r = (DepRec *)DepRecType.tp_alloc(&DepRecType, 0);
    if (!r)
        return NULL;
    r->expected = expected;
    r->arrivals = 0;
    Py_INCREF(args[2]);
    r->locals = args[2];
    r->inputs = NULL;
    r->sources = NULL;
    int rc = PyDict_SetItem(t->table, args[0], (PyObject *)r);
    Py_DECREF(r);
    if (rc < 0)
        return NULL;
    Py_RETURN_NONE;
}

/* arrive(key, flow, copy, source) -> None (not ready), False (no
 * record: caller must create() then re-arrive), or the ready payload
 * (locals, inputs_or_None, sources_or_None) with the record removed.
 * The JDF gather rule is enforced here: a data flow receiving two
 * copies raises (range deps may only gather CTL). */
static PyObject *dt_arrive(PyObject *self_, PyObject *const *args,
                           Py_ssize_t nargs) {
    DTObject *t = (DTObject *)self_;
    if (nargs != 4) {
        PyErr_SetString(PyExc_TypeError,
                        "arrive(key, flow, copy, source)");
        return NULL;
    }
    PyObject *key = args[0], *flow = args[1];
    PyObject *copy = args[2], *source = args[3];
    PyObject *ent = PyDict_GetItemWithError(t->table, key);
    if (!ent) {
        if (PyErr_Occurred())
            return NULL;
        Py_RETURN_FALSE;   /* miss: caller create()s, then re-arrives */
    }
    DepRec *r = (DepRec *)ent;
    r->arrivals++;
    /* record EVERY arrival's binding, None included — a CTL delivery
     * must land flow->None in task.data so prepare_input sees the
     * task-fed flow as bound (exact twin of the Python record path) */
    if (!r->inputs) {
        r->inputs = PyDict_New();
        if (!r->inputs)
            return NULL;
    } else if (copy != Py_None) {
        PyObject *prev = PyDict_GetItemWithError(r->inputs, flow);
        if (!prev && PyErr_Occurred())
            return NULL;
        if (prev && prev != Py_None) {
            /* ASCII only: PyErr_Format's format string must be */
            PyErr_Format(PyExc_RuntimeError,
                         "data flow %R received two copies - range "
                         "deps may only gather CTL", flow);
            return NULL;
        }
    }
    {
        /* a gather's earlier real copy must survive a later None
         * arrival on the same flow (CTL range edges all carry None) */
        int has = PyDict_Contains(r->inputs, flow);
        if (has < 0)
            return NULL;
        if (copy != Py_None || !has) {
            if (PyDict_SetItem(r->inputs, flow, copy) < 0)
                return NULL;
        }
    }
    if (source != Py_None) {
        if (!r->sources) {
            r->sources = PyDict_New();
            if (!r->sources)
                return NULL;
        }
        if (PyDict_SetItem(r->sources, flow, source) < 0)
            return NULL;
    }
    if (r->arrivals < r->expected)
        Py_RETURN_NONE;
    /* ready transition: hand the record's contents to the caller and
     * drop the entry in the same crossing */
    PyObject *out = PyTuple_New(3);
    if (!out)
        return NULL;
    Py_INCREF(r->locals);
    PyTuple_SET_ITEM(out, 0, r->locals);
    PyObject *ins = r->inputs ? r->inputs : Py_None;
    Py_INCREF(ins);
    PyTuple_SET_ITEM(out, 1, ins);
    PyObject *srcs = r->sources ? r->sources : Py_None;
    Py_INCREF(srcs);
    PyTuple_SET_ITEM(out, 2, srcs);
    if (PyDict_DelItem(t->table, key) < 0) {
        Py_DECREF(out);
        return NULL;
    }
    return out;
}

static Py_ssize_t dt_length(PyObject *self_) {
    return PyDict_Size(((DTObject *)self_)->table);
}

static void dt_dealloc(PyObject *self_) {
    Py_CLEAR(((DTObject *)self_)->table);
    Py_TYPE(self_)->tp_free(self_);
}

static PyObject *dt_new(PyTypeObject *type, PyObject *args,
                        PyObject *kwds) {
    (void)args;
    (void)kwds;
    DTObject *t = (DTObject *)type->tp_alloc(type, 0);
    if (t) {
        t->table = PyDict_New();
        if (!t->table) {
            Py_DECREF(t);
            return NULL;
        }
    }
    return (PyObject *)t;
}

static PyMethodDef dt_methods[] = {
    {"create", (PyCFunction)(void (*)(void))dt_create, METH_FASTCALL,
     "create(key, expected, locals): install a countdown record"},
    {"arrive", (PyCFunction)(void (*)(void))dt_arrive, METH_FASTCALL,
     "arrive(key, flow, copy, source) -> None | False | "
     "(locals, inputs, sources)"},
    {NULL, NULL, 0, NULL}};

static PySequenceMethods dt_as_sequence = {
    .sq_length = dt_length,
};

static PyTypeObject DTType = {
    PyVarObject_HEAD_INIT(NULL, 0).tp_name = "schedext.DepTable",
    .tp_basicsize = sizeof(DTObject),
    .tp_dealloc = dt_dealloc,
    .tp_as_sequence = &dt_as_sequence,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_methods = dt_methods,
    .tp_new = dt_new,
};

/* ------------------------------------------------------------------ */
/* TaskCore: the C task object (reference: parsec_task_t as a plain   */
/* C struct).  Field-for-field twin of core/task.py Task's slots so   */
/* every Python consumer (engine, devices, profilers, recovery) works */
/* unchanged by attribute access; construction and the trivial        */
/* progress chain below never enter bytecode.                         */
/* ------------------------------------------------------------------ */

#include <structmember.h>

/* TaskStatus values (core/task.py TaskStatus IntEnum; asserted at
 * vtable construction on the Python side so drift cannot go silent) */
#define ST_PENDING 0
#define ST_PREPARED 2
#define ST_RUNNING 3
#define ST_COMPLETE 4

typedef struct {
    PyObject_HEAD
    PyObject *task_class, *taskpool, *locals, *key, *data;
    PyObject *input_sources, *pinned_flows, *device, *prof, *dtd;
    PyObject *ready_at, *mtr_t0, *retry_snap;
    PyObject *vt;          /* TaskVT or NULL (reads as None) */
    long long priority, seq, pool_epoch;
    int status, chore_mask, retries;
} TCObject;

typedef struct {
    PyObject_HEAD
    PyObject *task_class, *taskpool;
    PyObject *name;         /* tc.name (the key head) */
    PyObject *param_names;  /* tuple of str, make_key order */
    PyObject *flow_names;   /* tuple of str, every flow */
    PyObject *priority_fn;  /* callable or None */
    PyObject *key_fn;       /* callable or None */
    PyObject *hook;         /* the single trivial cpu hook, or None */
    int trivial;
} VTObject;

/* interned attribute names for the progress chain (module init) */
static PyObject *s_pins_map, *s_running_task, *s_nb_tasks_done,
    *s_td_acc, *s_cancelled, *s_lineage, *s_context, *s_comm,
    *s_run_epoch, *s_termdet, *s_addto, *s_chore_disabled,
    *s_select, *s_exec_begin, *s_exec_end, *s_complete_exec,
    *s_task_discard;

/* lazily-bound runtime objects (cached after first use; importing an
 * already-loaded module is a sys.modules dict hit) */
static PyObject *g_seq_iter;      /* core.task._task_seq (itertools.count) */
static PyObject *g_fi_dict;       /* utils.faultinject module __dict__ */
static PyObject *g_body_failed;   /* scheduling._native_body_failed */
static PyObject *g_hook_return;   /* scheduling._native_hook_return */
static PyObject *g_one, *g_neg1;  /* cached small ints (module init) */

static int ensure_runtime(void) {
    if (g_body_failed)
        return 0;
    PyObject *m = PyImport_ImportModule("parsec_tpu.core.task");
    if (!m)
        return -1;
    g_seq_iter = PyObject_GetAttrString(m, "_task_seq");
    Py_DECREF(m);
    if (!g_seq_iter)
        return -1;
    m = PyImport_ImportModule("parsec_tpu.utils.faultinject");
    if (!m)
        return -1;
    g_fi_dict = PyModule_GetDict(m);   /* borrowed, module is cached */
    Py_INCREF(g_fi_dict);
    Py_DECREF(m);
    m = PyImport_ImportModule("parsec_tpu.core.scheduling");
    if (!m)
        return -1;
    g_hook_return = PyObject_GetAttrString(m, "_native_hook_return");
    g_body_failed = PyObject_GetAttrString(m, "_native_body_failed");
    Py_DECREF(m);
    if (!g_hook_return || !g_body_failed) {
        Py_CLEAR(g_body_failed);
        Py_CLEAR(g_hook_return);
        return -1;
    }
    return 0;
}

/* -- TaskCore type -------------------------------------------------- */

static PyMemberDef tc_members[] = {
    {"task_class", T_OBJECT, offsetof(TCObject, task_class), 0, NULL},
    {"taskpool", T_OBJECT, offsetof(TCObject, taskpool), 0, NULL},
    {"locals", T_OBJECT, offsetof(TCObject, locals), 0, NULL},
    {"key", T_OBJECT, offsetof(TCObject, key), 0, NULL},
    {"data", T_OBJECT, offsetof(TCObject, data), 0, NULL},
    {"input_sources", T_OBJECT, offsetof(TCObject, input_sources), 0, NULL},
    {"pinned_flows", T_OBJECT, offsetof(TCObject, pinned_flows), 0, NULL},
    {"device", T_OBJECT, offsetof(TCObject, device), 0, NULL},
    {"prof", T_OBJECT, offsetof(TCObject, prof), 0, NULL},
    {"dtd", T_OBJECT, offsetof(TCObject, dtd), 0, NULL},
    {"ready_at", T_OBJECT, offsetof(TCObject, ready_at), 0, NULL},
    {"mtr_t0", T_OBJECT, offsetof(TCObject, mtr_t0), 0, NULL},
    {"retry_snap", T_OBJECT, offsetof(TCObject, retry_snap), 0, NULL},
    {"vt", T_OBJECT, offsetof(TCObject, vt), READONLY, NULL},
    {"priority", T_LONGLONG, offsetof(TCObject, priority), 0, NULL},
    {"seq", T_LONGLONG, offsetof(TCObject, seq), 0, NULL},
    {"pool_epoch", T_LONGLONG, offsetof(TCObject, pool_epoch), 0, NULL},
    {"status", T_INT, offsetof(TCObject, status), 0, NULL},
    {"chore_mask", T_INT, offsetof(TCObject, chore_mask), 0, NULL},
    {"retries", T_INT, offsetof(TCObject, retries), 0, NULL},
    {NULL, 0, 0, 0, NULL}};

static int tc_traverse(PyObject *self_, visitproc visit, void *arg) {
    TCObject *t = (TCObject *)self_;
    Py_VISIT(t->task_class);
    Py_VISIT(t->taskpool);
    Py_VISIT(t->locals);
    Py_VISIT(t->key);
    Py_VISIT(t->data);
    Py_VISIT(t->input_sources);
    Py_VISIT(t->pinned_flows);
    Py_VISIT(t->device);
    Py_VISIT(t->prof);
    Py_VISIT(t->dtd);
    Py_VISIT(t->ready_at);
    Py_VISIT(t->mtr_t0);
    Py_VISIT(t->retry_snap);
    Py_VISIT(t->vt);
    return 0;
}

static int tc_clear(PyObject *self_) {
    TCObject *t = (TCObject *)self_;
    Py_CLEAR(t->task_class);
    Py_CLEAR(t->taskpool);
    Py_CLEAR(t->locals);
    Py_CLEAR(t->key);
    Py_CLEAR(t->data);
    Py_CLEAR(t->input_sources);
    Py_CLEAR(t->pinned_flows);
    Py_CLEAR(t->device);
    Py_CLEAR(t->prof);
    Py_CLEAR(t->dtd);
    Py_CLEAR(t->ready_at);
    Py_CLEAR(t->mtr_t0);
    Py_CLEAR(t->retry_snap);
    Py_CLEAR(t->vt);
    return 0;
}

static void tc_dealloc(PyObject *self_) {
    PyObject_GC_UnTrack(self_);
    tc_clear(self_);
    Py_TYPE(self_)->tp_free(self_);
}

/* repr matches core/task.py Task: "Name(k=1,m=2)" */
static PyObject *tc_repr(PyObject *self_) {
    TCObject *t = (TCObject *)self_;
    PyObject *name = t->task_class
        ? PyObject_GetAttrString(t->task_class, "name") : NULL;
    if (!name) {
        PyErr_Clear();
        name = PyUnicode_FromString("?");
        if (!name)
            return NULL;
    }
    PyObject *parts = PyList_New(0);
    if (!parts) {
        Py_DECREF(name);
        return NULL;
    }
    if (t->locals && PyDict_Check(t->locals)) {
        PyObject *k, *v;
        Py_ssize_t pos = 0;
        while (PyDict_Next(t->locals, &pos, &k, &v)) {
            PyObject *s = PyUnicode_FromFormat("%U=%S", k, v);
            if (!s || PyList_Append(parts, s) < 0) {
                Py_XDECREF(s);
                Py_DECREF(parts);
                Py_DECREF(name);
                return NULL;
            }
            Py_DECREF(s);
        }
    }
    PyObject *sep = PyUnicode_FromString(",");
    PyObject *args = sep ? PyUnicode_Join(sep, parts) : NULL;
    Py_XDECREF(sep);
    Py_DECREF(parts);
    if (!args) {
        Py_DECREF(name);
        return NULL;
    }
    PyObject *out = PyUnicode_FromFormat("%U(%U)", name, args);
    Py_DECREF(name);
    Py_DECREF(args);
    return out;
}

static PyTypeObject TCType = {
    PyVarObject_HEAD_INIT(NULL, 0).tp_name = "schedext.TaskCore",
    .tp_basicsize = sizeof(TCObject),
    .tp_dealloc = tc_dealloc,
    .tp_repr = tc_repr,
    .tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC,
    .tp_traverse = tc_traverse,
    .tp_clear = tc_clear,
    .tp_members = tc_members,
    .tp_new = NULL,   /* construct via TaskVT.build_* only */
};

/* -- TaskVT: the per-task-class vtable ------------------------------ */

static int vt_traverse(PyObject *self_, visitproc visit, void *arg) {
    VTObject *v = (VTObject *)self_;
    Py_VISIT(v->task_class);
    Py_VISIT(v->taskpool);
    Py_VISIT(v->name);
    Py_VISIT(v->param_names);
    Py_VISIT(v->flow_names);
    Py_VISIT(v->priority_fn);
    Py_VISIT(v->key_fn);
    Py_VISIT(v->hook);
    return 0;
}

static int vt_clear(PyObject *self_) {
    VTObject *v = (VTObject *)self_;
    Py_CLEAR(v->task_class);
    Py_CLEAR(v->taskpool);
    Py_CLEAR(v->name);
    Py_CLEAR(v->param_names);
    Py_CLEAR(v->flow_names);
    Py_CLEAR(v->priority_fn);
    Py_CLEAR(v->key_fn);
    Py_CLEAR(v->hook);
    return 0;
}

static void vt_dealloc(PyObject *self_) {
    PyObject_GC_UnTrack(self_);
    vt_clear(self_);
    Py_TYPE(self_)->tp_free(self_);
}

static int vt_init(PyObject *self_, PyObject *args, PyObject *kwds) {
    (void)kwds;
    VTObject *v = (VTObject *)self_;
    PyObject *tc, *tp, *name, *pnames, *fnames, *prio, *keyfn, *hook;
    int trivial;
    if (!PyArg_ParseTuple(args, "OOO!O!O!OOOp", &tc, &tp,
                          &PyUnicode_Type, &name,
                          &PyTuple_Type, &pnames,
                          &PyTuple_Type, &fnames,
                          &prio, &keyfn, &hook, &trivial))
        return -1;
    Py_INCREF(tc);
    Py_XSETREF(v->task_class, tc);
    Py_INCREF(tp);
    Py_XSETREF(v->taskpool, tp);
    Py_INCREF(name);
    Py_XSETREF(v->name, name);
    Py_INCREF(pnames);
    Py_XSETREF(v->param_names, pnames);
    Py_INCREF(fnames);
    Py_XSETREF(v->flow_names, fnames);
    Py_INCREF(prio);
    Py_XSETREF(v->priority_fn, prio);
    Py_INCREF(keyfn);
    Py_XSETREF(v->key_fn, keyfn);
    Py_INCREF(hook);
    Py_XSETREF(v->hook, hook);
    v->trivial = trivial && hook != Py_None;
    return 0;
}

static PyObject *vt_new(PyTypeObject *type, PyObject *args,
                        PyObject *kwds) {
    (void)args;
    (void)kwds;
    VTObject *v = (VTObject *)type->tp_alloc(type, 0);
    return (PyObject *)v;
}

static long long vt_attr_ll(PyObject *obj, const char *name,
                            long long dflt) {
    PyObject *a = PyObject_GetAttrString(obj, name);
    if (!a) {
        PyErr_Clear();
        return dflt;
    }
    long long r = PyLong_AsLongLong(a);
    Py_DECREF(a);
    if (r == -1 && PyErr_Occurred()) {
        PyErr_Clear();
        return dflt;
    }
    return r;
}

/* one task: locals is ALIASED (the caller guarantees a fresh,
 * exclusively-owned dict — iter_space / the DepTable record both
 * produce one per instance) */
static PyObject *vt_build_task(VTObject *v, PyObject *locals,
                               long long epoch, long long pool_prio) {
    if (ensure_runtime() < 0)
        return NULL;
    TCObject *t = (TCObject *)TCType.tp_alloc(&TCType, 0);
    if (!t)
        return NULL;
    Py_INCREF(v->task_class);
    t->task_class = v->task_class;
    Py_INCREF(v->taskpool);
    t->taskpool = v->taskpool;
    Py_INCREF(locals);
    t->locals = locals;
    Py_INCREF((PyObject *)v);
    t->vt = (PyObject *)v;
    t->status = ST_PENDING;
    t->chore_mask = 0xFFFF;
    t->retries = 0;
    t->pool_epoch = epoch;
    t->priority = pool_prio;
    /* key = (name,) + params, or (name, key_fn(locals)) */
    if (v->key_fn != Py_None) {
        PyObject *k2 = PyObject_CallFunctionObjArgs(v->key_fn, locals,
                                                    NULL);
        if (!k2)
            goto fail;
        t->key = PyTuple_Pack(2, v->name, k2);
        Py_DECREF(k2);
        if (!t->key)
            goto fail;
    } else {
        Py_ssize_t np = PyTuple_GET_SIZE(v->param_names);
        t->key = PyTuple_New(1 + np);
        if (!t->key)
            goto fail;
        Py_INCREF(v->name);
        PyTuple_SET_ITEM(t->key, 0, v->name);
        for (Py_ssize_t i = 0; i < np; i++) {
            PyObject *pv = PyDict_GetItemWithError(
                locals, PyTuple_GET_ITEM(v->param_names, i));
            if (!pv) {
                if (!PyErr_Occurred())
                    PyErr_Format(PyExc_KeyError, "task param %R missing",
                                 PyTuple_GET_ITEM(v->param_names, i));
                goto fail;
            }
            Py_INCREF(pv);
            PyTuple_SET_ITEM(t->key, 1 + i, pv);
        }
    }
    if (v->priority_fn != Py_None) {
        PyObject *p = PyObject_CallFunctionObjArgs(v->priority_fn,
                                                   locals, NULL);
        if (!p)
            goto fail;
        long long cp = PyLong_AsLongLong(p);
        Py_DECREF(p);
        if (cp == -1 && PyErr_Occurred())
            goto fail;
        t->priority += cp;
    }
    {
        /* itertools.count: the ONE process-global task sequence,
         * shared with Python Task.__init__ */
        PyObject *seq = PyIter_Next(g_seq_iter);
        if (!seq)
            goto fail;
        t->seq = PyLong_AsLongLong(seq);
        Py_DECREF(seq);
    }
    t->data = PyDict_New();
    t->input_sources = PyDict_New();
    t->pinned_flows = PySet_New(NULL);
    if (!t->data || !t->input_sources || !t->pinned_flows)
        goto fail;
    /* tp_alloc already GC-tracked the object (PyType_GenericAlloc) */
    return (PyObject *)t;
fail:
    Py_DECREF((PyObject *)t);
    return NULL;
}

/* build_batch(locals_seq) -> [TaskCore, ...]: one crossing for the
 * whole enumeration stream (Python Task.__init__ leaves the hot loop) */
static PyObject *vt_build_batch(PyObject *self_, PyObject *arg) {
    VTObject *v = (VTObject *)self_;
    PyObject *fast = PySequence_Fast(arg, "locals_seq must be a sequence");
    if (!fast)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    long long epoch = vt_attr_ll(v->taskpool, "run_epoch", 0);
    long long prio = vt_attr_ll(v->taskpool, "priority", 0);
    PyObject *out = PyList_New(n);
    if (!out) {
        Py_DECREF(fast);
        return NULL;
    }
    PyObject **items = PySequence_Fast_ITEMS(fast);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *t = vt_build_task(v, items[i], epoch, prio);
        if (!t) {
            Py_DECREF(out);
            Py_DECREF(fast);
            return NULL;
        }
        PyList_SET_ITEM(out, i, t);
    }
    Py_DECREF(fast);
    return out;
}

/* build_range(name, start, stop, step) -> [TaskCore, ...]: the flat
 * single-parameter space fully enumerated AND constructed in C (the
 * independent-task shape: locals dicts, keys, tasks — zero bytecode
 * per instance) */
static PyObject *vt_build_range(PyObject *self_, PyObject *const *args,
                                Py_ssize_t nargs) {
    VTObject *v = (VTObject *)self_;
    if (nargs != 4) {
        PyErr_SetString(PyExc_TypeError,
                        "build_range(name, start, stop, step)");
        return NULL;
    }
    PyObject *name = args[0];
    long long start = PyLong_AsLongLong(args[1]);
    long long stop = PyLong_AsLongLong(args[2]);
    long long step = PyLong_AsLongLong(args[3]);
    if (PyErr_Occurred())
        return NULL;
    if (step == 0) {
        PyErr_SetString(PyExc_ValueError, "step must not be zero");
        return NULL;
    }
    long long count = 0;
    if (step > 0 && stop > start)
        count = (stop - start + step - 1) / step;
    else if (step < 0 && stop < start)
        count = (start - stop + (-step) - 1) / (-step);
    long long epoch = vt_attr_ll(v->taskpool, "run_epoch", 0);
    long long prio = vt_attr_ll(v->taskpool, "priority", 0);
    PyObject *out = PyList_New((Py_ssize_t)count);
    if (!out)
        return NULL;
    long long val = start;
    for (Py_ssize_t i = 0; i < (Py_ssize_t)count; i++, val += step) {
        PyObject *locals = PyDict_New();
        PyObject *pv = locals ? PyLong_FromLongLong(val) : NULL;
        if (!pv || PyDict_SetItem(locals, name, pv) < 0) {
            Py_XDECREF(pv);
            Py_XDECREF(locals);
            Py_DECREF(out);
            return NULL;
        }
        Py_DECREF(pv);
        PyObject *t = vt_build_task(v, locals, epoch, prio);
        Py_DECREF(locals);
        if (!t) {
            Py_DECREF(out);
            return NULL;
        }
        PyList_SET_ITEM(out, i, t);
    }
    return out;
}

/* build_one(locals) -> TaskCore (the deliver_dep readiness path) */
static PyObject *vt_build_one(PyObject *self_, PyObject *locals) {
    VTObject *v = (VTObject *)self_;
    if (!PyDict_Check(locals)) {
        PyErr_SetString(PyExc_TypeError, "locals must be a dict");
        return NULL;
    }
    return vt_build_task(v, locals,
                         vt_attr_ll(v->taskpool, "run_epoch", 0),
                         vt_attr_ll(v->taskpool, "priority", 0));
}

static PyMethodDef vt_methods[] = {
    {"build_batch", (PyCFunction)vt_build_batch, METH_O,
     "build_batch(locals_seq) -> [TaskCore]"},
    {"build_range", (PyCFunction)(void (*)(void))vt_build_range,
     METH_FASTCALL,
     "build_range(name, start, stop, step) -> [TaskCore] (flat space)"},
    {"build_one", (PyCFunction)vt_build_one, METH_O,
     "build_one(locals) -> TaskCore"},
    {NULL, NULL, 0, NULL}};

static PyMemberDef vt_members[] = {
    {"task_class", T_OBJECT, offsetof(VTObject, task_class), READONLY,
     NULL},
    {"taskpool", T_OBJECT, offsetof(VTObject, taskpool), READONLY, NULL},
    {"trivial", T_INT, offsetof(VTObject, trivial), READONLY, NULL},
    {NULL, 0, 0, 0, NULL}};

static PyTypeObject VTType = {
    PyVarObject_HEAD_INIT(NULL, 0).tp_name = "schedext.TaskVT",
    .tp_basicsize = sizeof(VTObject),
    .tp_dealloc = vt_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC,
    .tp_traverse = vt_traverse,
    .tp_clear = vt_clear,
    .tp_methods = vt_methods,
    .tp_members = vt_members,
    .tp_init = vt_init,
    .tp_new = vt_new,
};

/* ------------------------------------------------------------------ */
/* run_quantum: the worker inner loop in one crossing                  */
/* ------------------------------------------------------------------ */

/* dispatch one PINS event to a callback list (borrowed refs) */
static int pins_dispatch(PyObject *cbs, PyObject *es, PyObject *event,
                         PyObject *task) {
    if (!cbs || !PyList_Check(cbs))
        return 0;
    for (Py_ssize_t i = 0; i < PyList_GET_SIZE(cbs); i++) {
        PyObject *r = PyObject_CallFunctionObjArgs(
            PyList_GET_ITEM(cbs, i), es, event, task, NULL);
        if (!r)
            return -1;
        Py_DECREF(r);
    }
    return 0;
}

/* per-quantum cached state (refreshed each run_quantum call) */
typedef struct {
    PyObject *es, *pins_map, *td_acc;
    PyObject *cb_select, *cb_begin, *cb_end, *cb_complete, *cb_discard;
    PyObject *last_tp;     /* OWNED: last gate-checked pool (a borrowed
                            * pointer could be freed mid-quantum and a
                            * new pool allocated at the same address
                            * would inherit stale gate results) */
    int last_ok;           /* gates passed for last_tp */
    int fi_armed;
    /* complete_exec stride gates (__pins_stride__ on the callback,
     * read once per quantum): a callback advertising stride N is
     * SKIPPED unless es.nb_tasks_done % N == 0 — the metrics
     * handler's own unsampled early-return, without the call */
    long long cstride[8];
    Py_ssize_t n_complete;
} quantum_t;

/* interned-name attribute read as long long, default on absence */
static long long attr_ll(PyObject *obj, PyObject *name, long long dflt) {
    PyObject *a = PyObject_GetAttr(obj, name);
    if (!a) {
        PyErr_Clear();
        return dflt;
    }
    long long r = PyLong_AsLongLong(a);
    Py_DECREF(a);
    if (r == -1 && PyErr_Occurred()) {
        PyErr_Clear();
        return dflt;
    }
    return r;
}

/* raise an exception object (with its original traceback) */
static PyObject *fetch_exc(void) {
    PyObject *et, *ev, *tb;
    PyErr_Fetch(&et, &ev, &tb);
    PyErr_NormalizeException(&et, &ev, &tb);
    if (tb)
        PyException_SetTraceback(ev, tb);
    Py_XDECREF(et);
    Py_XDECREF(tb);
    return ev;   /* owned */
}

/* pool-level fast-path gates: cancelled / lineage / comm / disabled
 * chores.  Cached per pool for the quantum (a cancel landing mid-
 * quantum is observed at the next quantum — in-flight tasks finish,
 * exactly the documented cancellation contract). */
static int gates_ok(quantum_t *qs, TCObject *t, VTObject *vt) {
    PyObject *tp = t->taskpool;
    if (tp == qs->last_tp)
        return qs->last_ok;
    Py_INCREF(tp);
    Py_XSETREF(qs->last_tp, tp);
    qs->last_ok = 0;
    PyObject *a = PyObject_GetAttr(tp, s_cancelled);
    if (!a)
        return -1;
    int truth = PyObject_IsTrue(a);
    Py_DECREF(a);
    if (truth)
        return truth < 0 ? -1 : 0;
    a = PyObject_GetAttr(tp, s_lineage);
    if (!a)
        return -1;
    int has = (a != Py_None);
    Py_DECREF(a);
    if (has)
        return 0;   /* recovery lineage records at complete: Python path */
    PyObject *ctx = PyObject_GetAttr(tp, s_context);
    if (!ctx)
        return -1;
    if (ctx == Py_None) {
        Py_DECREF(ctx);
        return 0;
    }
    a = PyObject_GetAttr(ctx, s_comm);
    Py_DECREF(ctx);
    if (!a)
        return -1;
    has = (a != Py_None);
    Py_DECREF(a);
    if (has)
        return 0;   /* distributed: flush_activations must still run */
    a = PyObject_GetAttr(vt->task_class, s_chore_disabled);
    if (!a)
        return -1;
    long long dis = PyLong_AsLongLong(a);
    Py_DECREF(a);
    if (dis == -1 && PyErr_Occurred())
        return -1;
    if (dis)
        return 0;
    qs->last_ok = 1;
    return 1;
}

/* the trivial progress chain: returns 1 handled, 0 fall back to the
 * Python task_progress, -1 error */
static int fast_progress(quantum_t *qs, PyObject *task) {
    if (Py_TYPE(task) != &TCType)
        return 0;
    TCObject *t = (TCObject *)task;
    if (!t->vt || Py_TYPE(t->vt) != &VTType)
        return 0;
    VTObject *vt = (VTObject *)t->vt;
    if (!vt->trivial || qs->fi_armed || !(t->chore_mask & 1)
        || t->retries)
        return 0;
    int g = gates_ok(qs, t, vt);
    if (g <= 0)
        return g;
    PyObject *es = qs->es;
    PyObject *ret = NULL;
    /* claim BEFORE the fence check (the recovery drain contract —
     * see task_progress's comment) */
    if (PyObject_SetAttr(es, s_running_task, task) < 0)
        return -1;
    /* the recovery fence reads run_epoch FRESH per task — a restart
     * bumping it mid-quantum must discard every later stale task */
    if (t->pool_epoch != attr_ll(t->taskpool, s_run_epoch, 0)) {
        /* stale generation: discard without executing or decrementing */
        t->status = ST_COMPLETE;
        if (pins_dispatch(qs->cb_discard, es, s_task_discard, task) < 0)
            goto err;
        goto done;
    }
    if (qs->cb_begin &&
        pins_dispatch(qs->cb_begin, es, s_exec_begin, task) < 0)
        goto err;
    if (t->status < ST_PREPARED) {
        /* trivial prepare: every flow binds None (no input deps) */
        PyObject *fn = vt->flow_names;
        for (Py_ssize_t i = 0; i < PyTuple_GET_SIZE(fn); i++) {
            if (PyDict_SetItem(t->data, PyTuple_GET_ITEM(fn, i),
                               Py_None) < 0)
                goto err;
        }
        t->status = ST_PREPARED;
    }
    t->status = ST_RUNNING;
    ret = PyObject_CallFunctionObjArgs(vt->hook, es, task, NULL);
    if (!ret) {
        /* body raised: the Python twin of task_progress's except
         * branch (retry / record_error / complete failed) */
        PyObject *exc = fetch_exc();
        if (!exc) {
            Py_INCREF(Py_None);
            exc = Py_None;
        }
        PyObject *r = PyObject_CallFunctionObjArgs(g_body_failed, es,
                                                   task, exc, NULL);
        Py_DECREF(exc);
        if (!r)
            goto err;
        Py_DECREF(r);
        goto done;
    }
    if (ret != Py_None) {
        /* AGAIN / ASYNC / DISABLE / values: the Python helper mirrors
         * execute()'s normalization + task_progress's dispatch */
        PyObject *r = PyObject_CallFunctionObjArgs(g_hook_return, es,
                                                   task, ret, NULL);
        Py_DECREF(ret);
        if (!r)
            goto err;
        Py_DECREF(r);
        goto done;
    }
    Py_DECREF(ret);
    if (qs->cb_end &&
        pins_dispatch(qs->cb_end, es, s_exec_end, task) < 0)
        goto err;
    /* complete_execution's empty-flow path: no writebacks, no
     * release_deps, no repo holds — version bumps and successor
     * delivery are structurally empty for a trivial class */
    t->status = ST_COMPLETE;
    {
        long long nbv = attr_ll(es, s_nb_tasks_done, 0);
        PyObject *cbs = qs->cb_complete;
        if (cbs && PyList_Check(cbs)) {
            Py_ssize_t ncb = PyList_GET_SIZE(cbs);
            /* a list resized mid-quantum invalidates the cached
             * strides: dispatch everything (stride 1) */
            int gated = (ncb == qs->n_complete);
            for (Py_ssize_t i = 0; i < ncb; i++) {
                if (gated && qs->cstride[i] > 1 &&
                    (nbv % qs->cstride[i]) != 0)
                    continue;
                PyObject *r = PyObject_CallFunctionObjArgs(
                    PyList_GET_ITEM(cbs, i), es, s_complete_exec,
                    task, NULL);
                if (!r)
                    goto err;
                Py_DECREF(r);
            }
        }
        PyObject *nb2 = PyLong_FromLongLong(nbv + 1);
        if (!nb2)
            goto err;
        int rc = PyObject_SetAttr(es, s_nb_tasks_done, nb2);
        Py_DECREF(nb2);
        if (rc < 0)
            goto err;
    }
    /* batched termdet: bump the per-worker accumulator (flushed by
     * worker_loop at batch boundaries / idle); es._td_acc is None
     * when termdet_batch <= 1 — then pay the locked decrement here */
    if (qs->td_acc && qs->td_acc != Py_None) {
        PyObject *entry = PyDict_GetItemWithError(qs->td_acc,
                                                  t->taskpool);
        if (!entry && PyErr_Occurred())
            goto err;
        long long ep = t->pool_epoch;
        if (entry && PyList_Check(entry)
            && PyLong_AsLongLong(PyList_GET_ITEM(entry, 0)) == ep) {
            PyObject *n2 = PyNumber_Add(PyList_GET_ITEM(entry, 1),
                                        g_one);
            if (!n2)
                goto err;
            if (PyList_SetItem(entry, 1, n2) < 0)
                goto err;
        } else {
            PyObject *fresh = Py_BuildValue("[Li]", ep, 1);
            if (!fresh)
                goto err;
            int rc = PyDict_SetItem(qs->td_acc, t->taskpool, fresh);
            Py_DECREF(fresh);
            if (rc < 0)
                goto err;
        }
    } else {
        PyObject *td = PyObject_GetAttr(t->taskpool, s_termdet);
        if (!td)
            goto err;
        PyObject *r = PyObject_CallMethodObjArgs(
            td, s_addto, t->taskpool, g_neg1, NULL);
        Py_DECREF(td);
        if (!r)
            goto err;
        Py_DECREF(r);
    }
done:
    if (PyObject_SetAttr(qs->es, s_running_task, Py_None) < 0)
        return -1;
    return 1;
err:
    PyObject_SetAttr(qs->es, s_running_task, Py_None);
    return -1;
}

/* run_quantum(es, ready_queue, limit) -> (ndone, task_or_None):
 * pop + select-PINS + the whole trivial prepare/execute/complete
 * chain for up to ``limit`` tasks in ONE crossing.  A task the fast
 * path cannot take (non-trivial class, cancelled pool, armed fault
 * plan, recorded lineage, attached comm engine) pops out with its
 * select event already fired, for the Python task_progress. */
static PyObject *mod_run_quantum(PyObject *mod, PyObject *const *args,
                                 Py_ssize_t nargs) {
    (void)mod;
    if (nargs != 3) {
        PyErr_SetString(PyExc_TypeError,
                        "run_quantum(es, ready_queue, limit)");
        return NULL;
    }
    if (Py_TYPE(args[1]) != &RQType) {
        PyErr_SetString(PyExc_TypeError, "second arg must be ReadyQueue");
        return NULL;
    }
    if (ensure_runtime() < 0)
        return NULL;
    RQObject *q = (RQObject *)args[1];
    long limit = PyLong_AsLong(args[2]);
    if (limit == -1 && PyErr_Occurred())
        return NULL;
    quantum_t qs;
    memset(&qs, 0, sizeof(qs));
    qs.es = args[0];
    qs.pins_map = PyObject_GetAttr(qs.es, s_pins_map);
    if (!qs.pins_map)
        return NULL;
    qs.td_acc = PyObject_GetAttr(qs.es, s_td_acc);
    if (!qs.td_acc) {
        PyErr_Clear();
        qs.td_acc = Py_None;
        Py_INCREF(Py_None);
    }
    /* borrowed cb lists, refetched per quantum (pins_register mutates
     * the lists in place; new events land within one quantum bound) */
    qs.cb_select = PyDict_GetItemWithError(qs.pins_map, s_select);
    qs.cb_begin = PyDict_GetItemWithError(qs.pins_map, s_exec_begin);
    qs.cb_end = PyDict_GetItemWithError(qs.pins_map, s_exec_end);
    qs.cb_complete = PyDict_GetItemWithError(qs.pins_map,
                                             s_complete_exec);
    qs.cb_discard = PyDict_GetItemWithError(qs.pins_map, s_task_discard);
    {
        PyObject *armed = g_fi_dict
            ? PyDict_GetItemString(g_fi_dict, "ARMED") : NULL;
        qs.fi_armed = armed ? PyObject_IsTrue(armed) : 0;
    }
    /* read each complete_exec callback's advertised sampling stride
     * once per quantum (missing attribute = stride 1 = always call) */
    qs.n_complete = -1;   /* sentinel: gate disabled */
    if (qs.cb_complete && PyList_Check(qs.cb_complete) &&
        PyList_GET_SIZE(qs.cb_complete) <=
            (Py_ssize_t)(sizeof(qs.cstride) / sizeof(qs.cstride[0]))) {
        qs.n_complete = PyList_GET_SIZE(qs.cb_complete);
        for (Py_ssize_t i = 0; i < qs.n_complete; i++) {
            long long v = 1;
            PyObject *st = PyObject_GetAttrString(
                PyList_GET_ITEM(qs.cb_complete, i), "__pins_stride__");
            if (st) {
                v = PyLong_AsLongLong(st);
                Py_DECREF(st);
                if (v < 1) {
                    PyErr_Clear();
                    v = 1;
                }
            } else {
                PyErr_Clear();
            }
            qs.cstride[i] = v;
        }
    }
    long ndone = 0;
    PyObject *out_task = NULL;
    while (ndone < limit) {
        if (q->len == 0)
            break;
        PyObject *task = q->heap[0].task;   /* ownership moves here */
        q->len--;
        if (q->len > 0) {
            q->heap[0] = q->heap[q->len];
            rq_sift_down(q, 0);
        }
        q->pops++;
        if (qs.cb_select &&
            pins_dispatch(qs.cb_select, qs.es, s_select, task) < 0) {
            Py_DECREF(task);
            goto fail;
        }
        int rc = fast_progress(&qs, task);
        if (rc < 0) {
            Py_DECREF(task);
            goto fail;
        }
        if (rc == 0) {
            out_task = task;   /* Python task_progress takes it */
            break;
        }
        Py_DECREF(task);
        ndone++;
    }
    {
        PyObject *res = Py_BuildValue("(lO)", ndone,
                                      out_task ? out_task : Py_None);
        Py_XDECREF(out_task);
        Py_XDECREF(qs.last_tp);
        Py_DECREF(qs.pins_map);
        Py_DECREF(qs.td_acc);
        return res;
    }
fail:
    Py_XDECREF(qs.last_tp);
    Py_DECREF(qs.pins_map);
    Py_DECREF(qs.td_acc);
    return NULL;
}

/* ------------------------------------------------------------------ */

static PyObject *mod_now(PyObject *self_, PyObject *noargs) {
    (void)self_;
    (void)noargs;
    return PyFloat_FromDouble(now_monotonic());
}

static PyMethodDef mod_methods[] = {
    {"now", mod_now, METH_NOARGS, "CLOCK_MONOTONIC seconds"},
    {"run_quantum", (PyCFunction)(void (*)(void))mod_run_quantum,
     METH_FASTCALL,
     "run_quantum(es, ready_queue, limit) -> (ndone, task_or_None)"},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef schedext_module = {
    PyModuleDef_HEAD_INIT, "schedext",
    "native scheduler hot path: ready queue + dep countdown", -1,
    mod_methods, NULL, NULL, NULL, NULL};

PyMODINIT_FUNC PyInit_schedext(void) {
    s_status = PyUnicode_InternFromString("status");
    s_ready_at = PyUnicode_InternFromString("ready_at");
    s_priority = PyUnicode_InternFromString("priority");
    s_pins_map = PyUnicode_InternFromString("_pins_map");
    s_running_task = PyUnicode_InternFromString("running_task");
    s_nb_tasks_done = PyUnicode_InternFromString("nb_tasks_done");
    s_td_acc = PyUnicode_InternFromString("_td_acc");
    s_cancelled = PyUnicode_InternFromString("cancelled");
    s_lineage = PyUnicode_InternFromString("_lineage");
    s_context = PyUnicode_InternFromString("context");
    s_comm = PyUnicode_InternFromString("comm");
    s_run_epoch = PyUnicode_InternFromString("run_epoch");
    s_termdet = PyUnicode_InternFromString("termdet");
    s_addto = PyUnicode_InternFromString("taskpool_addto_nb_tasks");
    s_chore_disabled = PyUnicode_InternFromString("chore_disabled_mask");
    s_select = PyUnicode_InternFromString("select");
    s_exec_begin = PyUnicode_InternFromString("exec_begin");
    s_exec_end = PyUnicode_InternFromString("exec_end");
    s_complete_exec = PyUnicode_InternFromString("complete_exec");
    s_task_discard = PyUnicode_InternFromString("task_discard");
    if (!s_status || !s_ready_at || !s_priority || !s_pins_map ||
        !s_running_task || !s_nb_tasks_done || !s_td_acc ||
        !s_cancelled || !s_lineage || !s_context || !s_comm ||
        !s_run_epoch || !s_termdet || !s_addto || !s_chore_disabled ||
        !s_select || !s_exec_begin || !s_exec_end || !s_complete_exec ||
        !s_task_discard)
        return NULL;
    g_one = PyLong_FromLong(1L);
    g_neg1 = PyLong_FromLong(-1L);
    if (!g_one || !g_neg1)
        return NULL;
    if (PyType_Ready(&RQType) < 0 || PyType_Ready(&DepRecType) < 0 ||
        PyType_Ready(&DTType) < 0 || PyType_Ready(&TCType) < 0 ||
        PyType_Ready(&VTType) < 0)
        return NULL;
    PyObject *m = PyModule_Create(&schedext_module);
    if (!m)
        return NULL;
    Py_INCREF(&RQType);
    if (PyModule_AddObject(m, "ReadyQueue", (PyObject *)&RQType) < 0) {
        Py_DECREF(&RQType);
        Py_DECREF(m);
        return NULL;
    }
    Py_INCREF(&DTType);
    if (PyModule_AddObject(m, "DepTable", (PyObject *)&DTType) < 0) {
        Py_DECREF(&DTType);
        Py_DECREF(m);
        return NULL;
    }
    Py_INCREF(&TCType);
    if (PyModule_AddObject(m, "TaskCore", (PyObject *)&TCType) < 0) {
        Py_DECREF(&TCType);
        Py_DECREF(m);
        return NULL;
    }
    Py_INCREF(&VTType);
    if (PyModule_AddObject(m, "TaskVT", (PyObject *)&VTType) < 0) {
        Py_DECREF(&VTType);
        Py_DECREF(m);
        return NULL;
    }
    return m;
}
