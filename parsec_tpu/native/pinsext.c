/* Fast trace sink: the binary tracer's per-event hot path in C.
 *
 * Rebuild of the reference's profiling record path (reference:
 * parsec/profiling.c — parsec_profiling_trace_flags writes one
 * fixed-size record into a per-thread buffer with no allocation and
 * takes its own timestamp; tests/profiling-standalone/sp-perf.c is the
 * overhead harness).  ctypes costs ~1us per crossing, which is the
 * whole tracer budget, so this is a real CPython extension: one
 * METH_FASTCALL per event (~0.1-0.2us), timestamp taken in C with
 * CLOCK_MONOTONIC — the same clock CPython's time.perf_counter reads on
 * Linux, so C-stamped and Python-stamped events merge on one timeline.
 *
 * Single-writer discipline per sink (one sink per execution stream,
 * calls made under the GIL); drain() returns the records as tuples and
 * resets the buffer.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <stdlib.h>
#include <time.h>

typedef struct {
    int32_t key;
    int32_t flags;
    int64_t tp;
    int64_t eid;
    int64_t oid;
    double ts;
} pe_t;

typedef struct {
    PyObject_HEAD
    pe_t *buf;
    Py_ssize_t len, cap;
} SinkObject;

static inline double now_monotonic(void) {
    struct timespec t;
    clock_gettime(CLOCK_MONOTONIC, &t);
    return (double)t.tv_sec + (double)t.tv_nsec * 1e-9;
}

static int sink_grow(SinkObject *s) {
    Py_ssize_t ncap = s->cap ? s->cap * 2 : 4096;
    pe_t *nb = (pe_t *)realloc(s->buf, (size_t)ncap * sizeof(pe_t));
    if (!nb) {
        PyErr_NoMemory();
        return -1;
    }
    s->buf = nb;
    s->cap = ncap;
    return 0;
}

/* event(key, flags, taskpool_id, event_id, object_id) — timestamp taken
 * here, in C, at call time. */
static PyObject *sink_event(PyObject *self_, PyObject *const *args,
                            Py_ssize_t nargs) {
    SinkObject *s = (SinkObject *)self_;
    if (nargs != 5) {
        PyErr_SetString(PyExc_TypeError,
                        "event(key, flags, tp, eid, oid)");
        return NULL;
    }
    long long k = PyLong_AsLongLong(args[0]);
    long long f = PyLong_AsLongLong(args[1]);
    long long tp = PyLong_AsLongLong(args[2]);
    long long e = PyLong_AsLongLong(args[3]);
    long long o = PyLong_AsLongLong(args[4]);
    if (PyErr_Occurred())
        return NULL;
    if (s->len >= s->cap && sink_grow(s) < 0)
        return NULL;
    pe_t *ev = &s->buf[s->len++];
    ev->key = (int32_t)k;
    ev->flags = (int32_t)f;
    ev->tp = tp;
    ev->eid = e;
    ev->oid = o;
    ev->ts = now_monotonic();
    Py_RETURN_NONE;
}

/* event_at(key, flags, tp, eid, oid, ts) — caller-supplied timestamp. */
static PyObject *sink_event_at(PyObject *self_, PyObject *const *args,
                               Py_ssize_t nargs) {
    SinkObject *s = (SinkObject *)self_;
    if (nargs != 6) {
        PyErr_SetString(PyExc_TypeError,
                        "event_at(key, flags, tp, eid, oid, ts)");
        return NULL;
    }
    long long k = PyLong_AsLongLong(args[0]);
    long long f = PyLong_AsLongLong(args[1]);
    long long tp = PyLong_AsLongLong(args[2]);
    long long e = PyLong_AsLongLong(args[3]);
    long long o = PyLong_AsLongLong(args[4]);
    double ts = PyFloat_AsDouble(args[5]);
    if (PyErr_Occurred())
        return NULL;
    if (s->len >= s->cap && sink_grow(s) < 0)
        return NULL;
    pe_t *ev = &s->buf[s->len++];
    ev->key = (int32_t)k;
    ev->flags = (int32_t)f;
    ev->tp = tp;
    ev->eid = e;
    ev->oid = o;
    ev->ts = ts;
    Py_RETURN_NONE;
}

/* interval(key, tp, eid, oid, ts_begin, fstart, fend) — append BOTH
 * edges of one task interval in a single crossing: the START record
 * carries the caller-captured begin timestamp (a perf_counter() read,
 * same CLOCK_MONOTONIC timeline), the END record is stamped here in C.
 * One C call per task instead of two (VERDICT r5 #5: the begin/end
 * pairing moves C-side; prof/pins.py keeps the two-call fallback). */
static PyObject *sink_interval(PyObject *self_, PyObject *const *args,
                               Py_ssize_t nargs) {
    SinkObject *s = (SinkObject *)self_;
    if (nargs != 7) {
        PyErr_SetString(PyExc_TypeError,
                        "interval(key, tp, eid, oid, ts_begin, fstart, "
                        "fend)");
        return NULL;
    }
    long long k = PyLong_AsLongLong(args[0]);
    long long tp = PyLong_AsLongLong(args[1]);
    long long e = PyLong_AsLongLong(args[2]);
    long long o = PyLong_AsLongLong(args[3]);
    double t0 = PyFloat_AsDouble(args[4]);
    long long fs = PyLong_AsLongLong(args[5]);
    long long fe = PyLong_AsLongLong(args[6]);
    if (PyErr_Occurred())
        return NULL;
    while (s->len + 2 > s->cap) {
        if (sink_grow(s) < 0)
            return NULL;
    }
    pe_t *ev = &s->buf[s->len];
    ev[0].key = (int32_t)k;
    ev[0].flags = (int32_t)fs;
    ev[0].tp = tp;
    ev[0].eid = e;
    ev[0].oid = o;
    ev[0].ts = t0;
    ev[1].key = (int32_t)k;
    ev[1].flags = (int32_t)fe;
    ev[1].tp = tp;
    ev[1].eid = e;
    ev[1].oid = o;
    ev[1].ts = now_monotonic();
    s->len += 2;
    Py_RETURN_NONE;
}

static PyObject *sink_drain(PyObject *self_, PyObject *noargs) {
    (void)noargs;
    SinkObject *s = (SinkObject *)self_;
    PyObject *out = PyList_New(s->len);
    if (!out)
        return NULL;
    for (Py_ssize_t i = 0; i < s->len; i++) {
        pe_t *ev = &s->buf[i];
        PyObject *t = Py_BuildValue(
            "(iiLLLd)", (int)ev->key, (int)ev->flags,
            (long long)ev->tp, (long long)ev->eid, (long long)ev->oid,
            ev->ts);
        if (!t) {
            Py_DECREF(out);
            return NULL;
        }
        PyList_SET_ITEM(out, i, t);
    }
    s->len = 0;
    return out;
}

static Py_ssize_t sink_length(PyObject *self_) {
    return ((SinkObject *)self_)->len;
}

static void sink_dealloc(PyObject *self_) {
    SinkObject *s = (SinkObject *)self_;
    free(s->buf);
    Py_TYPE(self_)->tp_free(self_);
}

static PyObject *sink_new(PyTypeObject *type, PyObject *args,
                          PyObject *kwds) {
    (void)args;
    (void)kwds;
    SinkObject *s = (SinkObject *)type->tp_alloc(type, 0);
    if (s) {
        s->buf = NULL;
        s->len = 0;
        s->cap = 0;
    }
    return (PyObject *)s;
}

static PyMethodDef sink_methods[] = {
    {"event", (PyCFunction)(void (*)(void))sink_event, METH_FASTCALL,
     "append one record, timestamped in C"},
    {"event_at", (PyCFunction)(void (*)(void))sink_event_at,
     METH_FASTCALL, "append one record with a caller timestamp"},
    {"interval", (PyCFunction)(void (*)(void))sink_interval,
     METH_FASTCALL,
     "append a START (caller ts) + END (C ts) pair in one crossing"},
    {"drain", (PyCFunction)sink_drain, METH_NOARGS,
     "return all records as tuples and reset"},
    {NULL, NULL, 0, NULL}};

static PySequenceMethods sink_as_sequence = {
    .sq_length = sink_length,
};

static PyTypeObject SinkType = {
    PyVarObject_HEAD_INIT(NULL, 0).tp_name = "pinsext.TraceSink",
    .tp_basicsize = sizeof(SinkObject),
    .tp_dealloc = sink_dealloc,
    .tp_as_sequence = &sink_as_sequence,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_methods = sink_methods,
    .tp_new = sink_new,
};

static PyObject *mod_now(PyObject *self_, PyObject *noargs) {
    (void)self_;
    (void)noargs;
    return PyFloat_FromDouble(now_monotonic());
}

static PyMethodDef mod_methods[] = {
    {"now", mod_now, METH_NOARGS, "CLOCK_MONOTONIC seconds"},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef pinsext_module = {
    PyModuleDef_HEAD_INIT, "pinsext",
    "C trace sink for the binary tracer hot path", -1, mod_methods,
    NULL, NULL, NULL, NULL};

PyMODINIT_FUNC PyInit_pinsext(void) {
    PyObject *m;
    if (PyType_Ready(&SinkType) < 0)
        return NULL;
    m = PyModule_Create(&pinsext_module);
    if (!m)
        return NULL;
    Py_INCREF(&SinkType);
    if (PyModule_AddObject(m, "TraceSink", (PyObject *)&SinkType) < 0) {
        Py_DECREF(&SinkType);
        Py_DECREF(m);
        return NULL;
    }
    return m;
}
