"""Tiled QR factorization (dgeqrf): the irregular-DAG driver.

The DPLASMA-style tiled QR (reference: BASELINE.json names "DPLASMA
dgeqrf tiled QR (irregular DAG, pod-scale comm/compute overlap)" as a
headline config).  Classic flat-tree tile algorithm:

    GEQRT(k)    : QR of the diagonal tile; R stays in A[k,k], the
                  orthogonal factor Q1 (mb x mb) travels on a dataflow
                  edge.
    UNMQR(k,n)  : A[k,n] = Q1^T @ A[k,n]                     (n > k)
    TSQRT(m,k)  : QR of [R; A[m,k]] stacked — updates R in A[k,k] and
                  zeroes A[m,k]; the stacked factor Q2 (2mb x mb)
                  travels on an edge.                         (m > k)
    TSMQR(m,n,k): applies Q2^T to the stacked [A[k,n]; A[m,n]] pair.
                  (m > k, n > k)

Unlike the storage-compact Householder form, the Q factors ride dataflow
edges as explicit matrices (NEW-arena temporaries) — the natural choice
when every kernel is an XLA op (jnp.linalg.qr + matmuls) and edges are
cheap HBM-resident tiles.  R ends in the upper triangle; tiles below are
zeroed.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from parsec_tpu.core.taskpool import ParameterizedTaskpool
from parsec_tpu.data.matrix import TiledMatrix
from parsec_tpu.dsl.ptg.api import DATA, IN, NEW, OUT, PTG, Range, TASK

_kernels = {}


def _k(name, maker):
    fn = _kernels.get(name)
    if fn is None:
        fn = maker()
        _kernels[name] = fn
    return fn


def _mk_geqrt():
    def fn(T, Q):
        import jax.numpy as jnp
        q, r = jnp.linalg.qr(T, mode="complete")
        return {"T": r, "Q": q}
    return fn


def _mk_unmqr():
    def fn(Q, C):
        import jax.numpy as jnp
        return {"C": jnp.matmul(Q.T, C)}
    return fn


def _mk_tsqrt():
    def fn(T, B, Q):
        import jax.numpy as jnp
        mb = T.shape[0]
        stacked = jnp.concatenate([T, B], axis=0)        # (2mb, mb)
        q, r = jnp.linalg.qr(stacked, mode="complete")   # q: (2mb, 2mb)
        return {"T": r[:mb, :], "B": jnp.zeros_like(B), "Q": q}
    return fn


def _mk_tsmqr():
    def fn(Q, C1, C2):
        import jax.numpy as jnp
        mb = C1.shape[0]
        stacked = jnp.concatenate([C1, C2], axis=0)
        out = jnp.matmul(Q.T, stacked)
        return {"C1": out[:mb, :], "C2": out[mb:, :]}
    return fn


def qr_taskpool(A: TiledMatrix, device: str = "tpu") -> ParameterizedTaskpool:
    """Factor A in place: R in the upper triangle (Q is applied, not
    stored).  Requires a square tile grid evenly dividing A."""
    if A.mt != A.nt:
        raise ValueError("qr driver needs a square tile grid")
    if A.lm % A.mb or A.ln % A.nb:
        raise ValueError("qr tiles must divide the matrix evenly")
    NT = A.mt
    mb = A.mb
    use_device = device in ("tpu", "xla", "gpu")

    def bodies(tb, kernel, cpu_fn):
        if use_device:
            tb.body(kernel, device=device)
        tb.body(cpu_fn)
        return tb

    p = PTG("geqrf", NT=NT)
    p.arena("q1", (mb, mb))
    p.arena("q2", (2 * mb, 2 * mb))

    # GEQRT(k): diagonal QR
    tb = p.task("GEQRT", k=Range(0, NT - 1)) \
        .affinity(lambda k, A=A: A(k, k)) \
        .priority(lambda k, NT=NT: 4 * (NT - k) + 3) \
        .flow("T", "RW",
              IN(DATA(lambda k, A=A: A(k, k)), when=lambda k: k == 0),
              IN(TASK("TSMQR", "C2", lambda k: dict(m=k, n=k, k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("TSQRT", "T", lambda k, NT=NT: dict(m=k + 1, k=k)),
                  when=lambda k, NT=NT: k < NT - 1),
              OUT(DATA(lambda k, A=A: A(k, k)),
                  when=lambda k, NT=NT: k == NT - 1)) \
        .flow("Q", "RW",
              IN(NEW("q1")),
              OUT(TASK("UNMQR", "Q",
                       lambda k, NT=NT: [dict(k=k, n=n)
                                         for n in range(k + 1, NT)]),
                  when=lambda k, NT=NT: k < NT - 1))

    def cpu_geqrt(T, Q):
        q, r = np.linalg.qr(np.asarray(T), mode="complete")
        return {"T": r, "Q": q}
    bodies(tb, _k("geqrt", _mk_geqrt), cpu_geqrt)

    # UNMQR(k, n): apply Q1^T across the k-th block row
    tb = p.task("UNMQR", k=Range(0, NT - 2), n=Range(lambda k: k + 1,
                                                     NT - 1)) \
        .affinity(lambda k, n, A=A: A(k, n)) \
        .priority(lambda k, NT=NT: 4 * (NT - k) + 2) \
        .flow("Q", "READ", IN(TASK("GEQRT", "Q", lambda k: dict(k=k)))) \
        .flow("C", "RW",
              IN(DATA(lambda k, n, A=A: A(k, n)), when=lambda k: k == 0),
              IN(TASK("TSMQR", "C2", lambda k, n: dict(m=k, n=n, k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("TSMQR", "C1", lambda k, n: dict(m=k + 1, n=n, k=k))))

    def cpu_unmqr(Q, C):
        return {"C": np.asarray(Q).T @ np.asarray(C)}
    bodies(tb, _k("unmqr", _mk_unmqr), cpu_unmqr)

    # TSQRT(m, k): fold block-column tile m into R(k)
    tb = p.task("TSQRT", k=Range(0, NT - 2), m=Range(lambda k: k + 1,
                                                     NT - 1)) \
        .affinity(lambda m, k, A=A: A(m, k)) \
        .priority(lambda k, NT=NT: 4 * (NT - k) + 1) \
        .flow("T", "RW",
              IN(TASK("GEQRT", "T", lambda k: dict(k=k)),
                 when=lambda m, k: m == k + 1),
              IN(TASK("TSQRT", "T", lambda m, k: dict(m=m - 1, k=k)),
                 when=lambda m, k: m > k + 1),
              OUT(TASK("TSQRT", "T", lambda m, k: dict(m=m + 1, k=k)),
                  when=lambda m, NT=NT: m < NT - 1),
              OUT(DATA(lambda k, A=A: A(k, k)),
                  when=lambda m, NT=NT: m == NT - 1)) \
        .flow("B", "RW",
              IN(DATA(lambda m, k, A=A: A(m, k)), when=lambda k: k == 0),
              IN(TASK("TSMQR", "C2", lambda m, k: dict(m=m, n=k, k=k - 1)),
                 when=lambda k: k > 0),
              OUT(DATA(lambda m, k, A=A: A(m, k)))) \
        .flow("Q", "RW",
              IN(NEW("q2")),
              OUT(TASK("TSMQR", "Q",
                       lambda m, k, NT=NT: [dict(m=m, n=n, k=k)
                                            for n in range(k + 1, NT)]),
                  when=lambda k, NT=NT: k < NT - 1))

    def cpu_tsqrt(T, B, Q):
        mb_ = np.asarray(T).shape[0]
        stacked = np.concatenate([np.asarray(T), np.asarray(B)], axis=0)
        q, r = np.linalg.qr(stacked, mode="complete")
        return {"T": r[:mb_, :], "B": np.zeros_like(np.asarray(B)),
                "Q": q}
    bodies(tb, _k("tsqrt", _mk_tsqrt), cpu_tsqrt)

    # TSMQR(m, n, k): apply Q2^T to the [A(k,n); A(m,n)] pair
    tb = p.task("TSMQR", k=Range(0, NT - 2),
                m=Range(lambda k: k + 1, NT - 1),
                n=Range(lambda k: k + 1, NT - 1)) \
        .affinity(lambda m, n, A=A: A(m, n)) \
        .priority(lambda k, NT=NT: 4 * (NT - k)) \
        .flow("Q", "READ", IN(TASK("TSQRT", "Q", lambda m, k: dict(m=m,
                                                                   k=k)))) \
        .flow("C1", "RW",
              IN(TASK("UNMQR", "C", lambda n, k: dict(k=k, n=n)),
                 when=lambda m, k: m == k + 1),
              IN(TASK("TSMQR", "C1", lambda m, n, k: dict(m=m - 1, n=n,
                                                          k=k)),
                 when=lambda m, k: m > k + 1),
              OUT(TASK("TSMQR", "C1", lambda m, n, k: dict(m=m + 1, n=n,
                                                           k=k)),
                  when=lambda m, NT=NT: m < NT - 1),
              OUT(DATA(lambda k, n, A=A: A(k, n)),
                  when=lambda m, NT=NT: m == NT - 1)) \
        .flow("C2", "RW",
              IN(DATA(lambda m, n, A=A: A(m, n)), when=lambda k: k == 0),
              IN(TASK("TSMQR", "C2", lambda m, n, k: dict(m=m, n=n,
                                                          k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("GEQRT", "T", lambda m: dict(k=m)),
                  when=lambda m, n, k: m == k + 1 and n == k + 1),
              OUT(TASK("TSQRT", "B", lambda m, n, k: dict(m=m, k=k + 1)),
                  when=lambda m, n, k: m > k + 1 and n == k + 1),
              OUT(TASK("UNMQR", "C", lambda m, n, k: dict(k=k + 1, n=n)),
                  when=lambda m, n, k: m == k + 1 and n > k + 1),
              OUT(TASK("TSMQR", "C2", lambda m, n, k: dict(m=m, n=n,
                                                           k=k + 1)),
                  when=lambda m, n, k: m > k + 1 and n > k + 1))
    def cpu_tsmqr(Q, C1, C2):
        mb_ = np.asarray(C1).shape[0]
        stacked = np.concatenate([np.asarray(C1), np.asarray(C2)], axis=0)
        out = np.asarray(Q).T @ stacked
        return {"C1": out[:mb_, :], "C2": out[mb_:, :]}
    bodies(tb, _k("tsmqr", _mk_tsmqr), cpu_tsmqr)

    return p.build()
