"""Tiled QR factorization (dgeqrf): the irregular-DAG driver.

The DPLASMA-style tiled QR (reference: BASELINE.json names "DPLASMA
dgeqrf tiled QR (irregular DAG, pod-scale comm/compute overlap)" as a
headline config).  Classic flat-tree tile algorithm:

    GEQRT(k)    : QR of the diagonal tile; R stays in A[k,k], the
                  orthogonal factor Q1 (mb x mb) travels on a dataflow
                  edge.
    UNMQR(k,n)  : A[k,n] = Q1^T @ A[k,n]                     (n > k)
    TSQRT(m,k)  : QR of [R; A[m,k]] stacked — updates R in A[k,k] and
                  zeroes A[m,k]; the compact-WY pair (V, T^T)
                  travels on an edge.                         (m > k)
    TSMQR(m,n,k): applies the WY transform to [A(k,n); A(m,n)].
                  (m > k, n > k)

TPU-first design of the tall-skinny kernels: XLA's QR expander (and
especially ``mode="complete"`` — an extra (2mb)^3 of Q formation) runs
far below matmul peak on TPU, so TSQRT computes the stacked QR by
CHOLESKY-QR on the mb x mb Gram matrix and derives an EXACT compact-WY
representation in closed form:

    G  = R^T R + B^T B;   R' = +-chol(G)^T   (Householder sign choice:
                                sign(R'_jj) = -sign(R_jj), no
                                cancellation in S)
    S  = R - R';   V = B S^-1;   T^T = I - R'^-T R^T

so the 2mb x 2mb orthogonal transform is Phi^T = I - [I;V] T^T [I;V]^T
(annihilation AND orthogonality hold identically — the general inverse
in the textbook T^T = S (R + V^T B)^-1 collapses to triangular ones via
M = -S^-T R'^T S).  TSQRT is then one mb-sized Cholesky + two
triangular inverses (recursive Newton, apps/potrf.tri_inv) + matmuls,
and TSMQR is five mb^3-class matmuls:

    Z = T^T (C1 + V^T C2);   C1 -= Z;   C2 -= V Z

Everything lowers to the systolic array; the Q edges shrink from
(2mb)^2 dense factors to the (2mb x mb) [V; T^T] pair.  R ends in the
upper triangle; tiles below are zeroed.

INNER BLOCKING (ib; the DPLASMA dgeqrf panel discipline, r6): the
panel CONSTRUCTION is cond^2-sensitive and must run at HIGHEST matmul
precision (true f32 — DEFAULT's bf16 passes destroy the factorization,
measured residual 1.19; BENCH.md geqrf note), but HIGHEST is ~3x
DEFAULT on the MXU.  Factoring the panel in ib-wide column blocks
confines the HIGHEST-precision math (per-block Gram, Cholesky,
triangular inverses, WY assembly) to O(mb^2*ib) per panel instead of
O(mb^3), while the O(mb^3) intra-panel trailing updates — where errors
enter the data LINEARLY, like TSMQR — run at DEFAULT precision:

    GEQRT: blocked CholeskyQR2 (BCGS2-flavored: each block is
           re-projected once against the accumulated basis at HIGHEST
           before its own two Cholesky-QR passes), trailing columns
           updated at DEFAULT.
    TSQRT: per-block compact-WY from the ib x ib Gram of [R_jj; B_j],
           trailing columns of [R; B] updated by the 5-matmul WY
           application at DEFAULT, and the per-block (V_j, T_j^T)
           pairs aggregated into ONE panel-wide (V, T^T) with the
           standard T-accumulation
               T^T[J, :s] = -T_j^T (V_j^T V[:, :s]) T^T[:s, :s]
           (block lower triangular), so TSMQR's 5-matmul application
           and the q2 edge layout are UNCHANGED.

Knobs: --mca qr_ib N (0 = unblocked; ignored unless 0 < ib < mb and
ib | mb) and --mca qr_update_precision {default,highest} for the
intra-panel trailing updates.  Per-block Cholesky failures fall back
to the unblocked construction (which carries its own Householder-QR
guard), keeping LAPACK-class robustness behind the fast path.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from parsec_tpu.apps.potrf import tri_inv
from parsec_tpu.core.taskpool import ParameterizedTaskpool
from parsec_tpu.data.matrix import TiledMatrix
from parsec_tpu.dsl.ptg.api import DATA, IN, NEW, OUT, PTG, Range, TASK
from parsec_tpu.utils.mca import params

params.register("qr_ib", 512,
                "inner blocking of the QR panel construction: the "
                "HIGHEST-precision work per panel drops from O(mb^3) "
                "to O(mb^2*ib) (DPLASMA dgeqrf ib discipline); 0 "
                "disables — ignored unless 0 < ib < mb and ib | mb")
params.register("qr_update_precision", "default",
                "matmul precision of the intra-panel trailing updates "
                "(errors enter linearly there): 'default' rides the "
                "MXU's fast path, 'highest' forces true f32")

_kernels = {}


def effective_ib(mb: int) -> int:
    """The inner blocking actually used for an mb-wide panel: the
    ``qr_ib`` MCA param, clamped to 0 (unblocked) when it does not
    evenly block the panel."""
    try:
        ib = int(params.get("qr_ib", 512))
    except (TypeError, ValueError):
        return 0
    if ib <= 0 or ib >= mb or mb % ib:
        return 0
    return ib


def _update_precision():
    """Precision of intra-panel trailing updates (None = DEFAULT)."""
    import jax
    val = str(params.get("qr_update_precision", "default")).lower()
    return jax.lax.Precision.HIGHEST if val == "highest" else None


def _cholqr2(cols, jnp, hi, gram=None):
    """CholeskyQR2 of one mb x ib column block at HIGHEST precision:
    returns (Q, R) with Q orthonormal (two Gram+Cholesky passes — one
    pass loses orthogonality as cond^2*eps) and R = L2^T L^T upper
    triangular.  NaNs from an ill-conditioned block propagate to the
    caller's finiteness guard.  ``gram`` swaps the Gram products for a
    hand-written kernel (apps/pallas_kernels.pallas_gram_tile)."""
    gram = gram or (lambda X: jnp.matmul(X.T, X, precision=hi))
    G = gram(cols)
    dg = jnp.sqrt(jnp.clip(jnp.diagonal(G), 1e-30, None))
    L = jnp.linalg.cholesky(G / dg[:, None] / dg[None, :]) * dg[:, None]
    Q1 = jnp.matmul(cols, tri_inv(L, precision=hi).T, precision=hi)
    G2 = gram(Q1)
    L2 = jnp.linalg.cholesky(G2)
    Q = jnp.matmul(Q1, tri_inv(L2, precision=hi).T, precision=hi)
    R = jnp.matmul(L2.T, L.T, precision=hi)
    return Q, R


def _k(name, maker):
    fn = _kernels.get(name)
    if fn is None:
        fn = maker()
        _kernels[name] = fn
    return fn


def _mk_geqrt(ib: int = 0, pallas_gram: bool = False):
    def fn(T, Q):
        import jax
        import jax.numpy as jnp
        from jax import lax
        # factor in f32 even under bf16 tile storage (mp mode); results
        # land back in the storage dtype (kernels are dtype-FOLLOWING,
        # same discipline as apps/potrf.py).
        # Cholesky-QR fast path (r5): XLA's QR expander runs at ~13 TF/s
        # on this chip (measured) — ~43ms per diagonal tile — while
        # gram+chol+tri_inv+matmul is matmul-class; the same
        # equilibrate-then-guard discipline as TSQRT keeps LAPACK-class
        # stability behind the cold fallback.  Construction at HIGHEST
        # precision (cond^2-sensitive; see _mk_tsqrt).
        hi = jax.lax.Precision.HIGHEST
        Tf = T.astype(jnp.float32)
        mb = Tf.shape[0]

        def stable(_):
            return jnp.linalg.qr(Tf, mode="reduced")[::-1]

        if 0 < ib < mb and mb % ib == 0:
            # inner-blocked panel (module docstring): per-block
            # CholeskyQR2 + one re-projection against the accumulated
            # basis at HIGHEST (O(mb^2*ib) total), trailing columns
            # updated at DEFAULT (errors enter linearly).  Q comes out
            # explicit — the blocks ARE its orthonormal columns — so
            # the q1 edge and UNMQR are unchanged.
            up = _update_precision()
            gram = None
            if pallas_gram:
                from parsec_tpu.apps.pallas_kernels import pallas_gram_tile
                gram = pallas_gram_tile()
            A = Tf
            R = jnp.zeros((mb, mb), jnp.float32)
            Qacc = None
            for s in range(0, mb, ib):
                cols = A[:, s:s + ib]
                if Qacc is not None:
                    # BCGS2-flavored reorthogonalization: the trailing
                    # updates already projected this block, but rounding
                    # reintroduces ~eps*cond components; one extra
                    # HIGHEST-precision pass restores inter-block
                    # orthogonality.  The coefficients fold into R
                    # exactly.
                    prj = jnp.matmul(Qacc.T, cols, precision=hi)
                    cols = cols - jnp.matmul(Qacc, prj, precision=hi)
                    R = R.at[:s, s:s + ib].add(prj)
                Qj, Rjj = _cholqr2(cols, jnp, hi, gram=gram)
                R = R.at[s:s + ib, s:s + ib].set(Rjj)
                if s + ib < mb:
                    rest = A[:, s + ib:]
                    Rjk = jnp.matmul(Qj.T, rest, precision=up)
                    A = A.at[:, s + ib:].set(
                        rest - jnp.matmul(Qj, Rjk, precision=up))
                    R = R.at[s:s + ib, s + ib:].set(Rjk)
                Qacc = Qj if Qacc is None else \
                    jnp.concatenate([Qacc, Qj], axis=1)
            ok = jnp.logical_and(jnp.all(jnp.isfinite(R)),
                                 jnp.all(jnp.isfinite(Qacc)))
            R, Qm = lax.cond(ok, lambda o: o, stable, operand=(R, Qacc))
            return {"T": R.astype(T.dtype), "Q": Qm.astype(T.dtype)}

        G = jnp.matmul(Tf.T, Tf, precision=hi)
        dg = jnp.sqrt(jnp.clip(jnp.diagonal(G), 1e-30, None))
        Ls = jnp.linalg.cholesky(G / dg[:, None] / dg[None, :])
        L = Ls * dg[:, None]
        # CholeskyQR2: one Cholesky-QR pass loses orthogonality as
        # cond^2*eps — tiles with cond in ~1e2..3e3 pass the finite
        # check yet come out visibly non-orthogonal in f32.  A second
        # Gram+chol pass on Q1 (whose cond is ~1+cond^2*eps, so its
        # Cholesky is unconditionally benign whenever L was finite)
        # restores eps-level orthogonality; still pure matmul+chol, so
        # the whole fast path stays on the MXU.  R folds exactly:
        # A = Q1 L^T, Q1 = Q2 L2^T  =>  A = Q2 (L2^T L^T).
        Q1 = jnp.matmul(Tf, tri_inv(L, precision=hi).T, precision=hi)
        G2 = jnp.matmul(Q1.T, Q1, precision=hi)
        L2 = jnp.linalg.cholesky(G2)

        def fast(_):
            R = jnp.matmul(L2.T, L.T, precision=hi)
            Qm = jnp.matmul(Q1, tri_inv(L2, precision=hi).T,
                            precision=hi)
            return R, Qm

        ok = jnp.logical_and(jnp.all(jnp.isfinite(L)),
                             jnp.all(jnp.isfinite(L2)))
        R, Qm = lax.cond(ok, fast, stable, operand=None)
        return {"T": R.astype(T.dtype), "Q": Qm.astype(T.dtype)}
    return fn


def _mk_unmqr():
    def fn(Q, C):
        import jax.numpy as jnp
        acc = jnp.matmul(Q.T, C, preferred_element_type=jnp.float32)
        return {"C": acc.astype(C.dtype)}
    return fn


def _wy_from_L(R, B, L, xp, ti, precision=None):
    """Closed-form compact-WY pair from ANY lower-triangular L with
    L L^T = R^T R + B^T B (Cholesky of the Gram matrix, however it was
    obtained): returns (R', V, T^T).

    ``precision``: matmul precision for the CONSTRUCTION (numpy path
    ignores it).  On TPU this must be HIGHEST: the construction is
    cond^2-sensitive, and XLA's DEFAULT f32 matmul (bf16 passes, ~1e-3
    relative) amplifies through the triangular inverses to a DESTROYED
    factorization — measured residual 1.19 at bench scale vs the
    algorithm's true-f32 level of ~5e-3 (r5 diagnostic)."""
    mb = R.shape[0]
    mm = (xp.matmul if precision is None
          else (lambda a, b: xp.matmul(a, b, precision=precision)))
    # Householder sign choice: R'_jj = -sign(R_jj) * |R'_jj| makes
    # S = R - R' diagonally safe (|S_jj| >= |R'_jj|)
    d = xp.where(xp.diagonal(R) >= 0, -1.0, 1.0).astype(R.dtype)
    Rp = d[:, None] * L.T
    S = R - Rp
    Sinv = ti(S.T).T                  # S upper-tri -> invert transpose
    V = mm(B, Sinv)
    Linv = ti(L)
    # R'^-T = (R'^T)^-1 = (L d)^-1 ... with the sign fold:
    # R' = D L^T  =>  R'^T = L D  =>  R'^-T = D^-1 L^-1 = D L^-1
    Tt = xp.eye(mb, dtype=R.dtype) - mm(d[:, None] * Linv, R.T)
    return Rp, V, Tt


def _tsqrt_wy(R, B, xp, chol, ti):
    """Shared TSQRT math (jax and numpy incarnations): returns
    (R', V, T^T) of the compact-WY Cholesky-QR above."""
    G = R.T @ R + B.T @ B
    return _wy_from_L(R, B, chol(G), xp, ti)


def _tsqrt_blocked(T, B, ib, jnp, hi, up):
    """Inner-blocked TSQRT construction (module docstring): returns the
    panel-wide (R', V, T^T) with T^T block lower triangular.  HIGHEST
    work is O(mb^2*ib); the trailing updates of [R; B] run at ``up``
    precision.  NaNs from an ill-conditioned block propagate to the
    caller's finiteness guard."""
    mb = T.shape[0]
    Rc, Bc = T, B
    V = jnp.zeros((mb, mb), jnp.float32)
    Tt = jnp.zeros((mb, mb), jnp.float32)
    for s in range(0, mb, ib):
        Rjj = Rc[s:s + ib, s:s + ib]
        Bj = Bc[:, s:s + ib]
        G = (jnp.matmul(Rjj.T, Rjj, precision=hi)
             + jnp.matmul(Bj.T, Bj, precision=hi))
        dg = jnp.sqrt(jnp.clip(jnp.diagonal(G), 1e-30, None))
        L = jnp.linalg.cholesky(G / dg[:, None] / dg[None, :]) \
            * dg[:, None]
        Rpjj, Vj, Tjt = _wy_from_L(Rjj, Bj, L, jnp,
                                   lambda M: tri_inv(M, precision=hi),
                                   precision=hi)
        Rc = Rc.at[s:s + ib, s:s + ib].set(Rpjj)
        if s + ib < mb:
            # 5-matmul WY application to the trailing columns of the
            # stacked panel (same shape as TSMQR, errors enter linearly)
            C1 = Rc[s:s + ib, s + ib:]
            C2 = Bc[:, s + ib:]
            Z = jnp.matmul(Tjt,
                           C1 + jnp.matmul(Vj.T, C2, precision=up),
                           precision=up)
            Rc = Rc.at[s:s + ib, s + ib:].set(C1 - Z)
            Bc = Bc.at[:, s + ib:].set(
                C2 - jnp.matmul(Vj, Z, precision=up))
        if s:
            # T-accumulation: Q^T = Q_j^T Q_prev^T collapses to one
            # compact-WY pair with the block-lower-triangular
            # T^T[J, :s] = -T_j^T (W_j^T W_prev) T^T[:s, :s]; the unit
            # tops of W are disjoint identity columns, so W_j^T W_prev
            # = V_j^T V[:, :s]
            cross = jnp.matmul(Vj.T, V[:, :s], precision=hi)
            Tt = Tt.at[s:s + ib, :s].set(
                -jnp.matmul(Tjt, jnp.matmul(cross, Tt[:s, :s],
                                            precision=hi),
                            precision=hi))
        V = V.at[:, s:s + ib].set(Vj)
        Tt = Tt.at[s:s + ib, s:s + ib].set(Tjt)
    return Rc, V, Tt


def _mk_tsqrt(ib: int = 0):
    def fn(T, B, Q):
        import jax
        import jax.numpy as jnp
        from jax import lax
        T = T.astype(jnp.float32)      # WY construction runs in f32
        B = B.astype(jnp.float32)
        # Fast path: Cholesky of the Gram matrix (pure matmul + chol,
        # rides the MXU).  Cholesky-QR squares cond(panel), so chol(G)
        # yields NaNs for ill-conditioned stacked panels; guard with a
        # Householder QR of the stacked panel (LAPACK-class stability,
        # reference TSQRT's algorithm: dplasma CORE_dtsqrt) that
        # produces the SAME triangular factor, then rebuild the
        # identical closed-form WY pair from it.
        #
        # The whole panel CONSTRUCTION runs at HIGHEST matmul precision
        # (true f32): the Gram matrix, the triangular inverses, and the
        # WY products are cond^2-sensitive, and DEFAULT's bf16 passes
        # destroy the factorization (residual 1.19 measured).  Only the
        # O(nt^2)-many panel tasks pay the ~3x; the O(nt^3) TSMQR bulk
        # stays at DEFAULT, where errors enter the data linearly.
        # Jacobi equilibration before the factor: D G D with unit
        # diagonal keeps the decaying-R dynamic range out of the chol;
        # the exact factor is recovered as L = D^-1 chol(D G D).
        hi = jax.lax.Precision.HIGHEST
        mb = T.shape[0]

        def unblocked(_):
            G = (jnp.matmul(T.T, T, precision=hi)
                 + jnp.matmul(B.T, B, precision=hi))
            dg = jnp.sqrt(jnp.clip(jnp.diagonal(G), 1e-30, None))
            L = jnp.linalg.cholesky(G / dg[:, None] / dg[None, :]) \
                * dg[:, None]

            def stable_L(_):
                Rh = jnp.linalg.qr(jnp.concatenate([T, B], axis=0),
                                   mode="r")
                s = jnp.where(jnp.diagonal(Rh) >= 0, 1.0,
                              -1.0).astype(T.dtype)
                return (s[:, None] * Rh).T   # positive-diag lower factor

            L = lax.cond(jnp.all(jnp.isfinite(L)), lambda _: L, stable_L,
                         operand=None)
            return _wy_from_L(T, B, L, jnp,
                              lambda M: tri_inv(M, precision=hi),
                              precision=hi)

        if 0 < ib < mb and mb % ib == 0:
            # inner-blocked fast path; an ill-conditioned BLOCK (NaN
            # anywhere in the result) falls back to the unblocked
            # construction, which carries its own Householder-QR guard
            res = _tsqrt_blocked(T, B, ib, jnp, hi, _update_precision())
            ok = jnp.all(jnp.array([jnp.all(jnp.isfinite(x))
                                    for x in res]))
            Rp, V, Tt = lax.cond(ok, lambda o: o, unblocked, operand=res)
        else:
            Rp, V, Tt = unblocked(None)
        dt = Q.dtype                    # NEW-flow arena dtype = storage
        return {"T": Rp.astype(dt), "B": jnp.zeros_like(B, dtype=dt),
                "Q": jnp.concatenate([V, Tt], axis=0).astype(dt)}
    return fn


def _mk_tsmqr():
    def fn(Q, C1, C2):
        import jax.numpy as jnp
        mb = C1.shape[0]
        V, Tt = Q[:mb, :], Q[mb:, :]
        # f32 accumulation through the 5-matmul WY application; outputs
        # round back to the tile storage dtype (bf16 in mp mode)
        inner = (C1.astype(jnp.float32)
                 + jnp.matmul(V.T, C2, preferred_element_type=jnp.float32))
        Z = jnp.matmul(Tt, inner.astype(Tt.dtype),
                       preferred_element_type=jnp.float32)
        C2n = C2.astype(jnp.float32) - jnp.matmul(
            V, Z.astype(V.dtype), preferred_element_type=jnp.float32)
        return {"C1": (C1.astype(jnp.float32) - Z).astype(C1.dtype),
                "C2": C2n.astype(C2.dtype)}
    return fn


def _np_tri_inv(L):
    import scipy.linalg as sl
    return sl.solve_triangular(L, np.eye(L.shape[0], dtype=L.dtype),
                               lower=True)


def qr_taskpool(A: TiledMatrix, device: str = "tpu") -> ParameterizedTaskpool:
    """Factor A in place: R in the upper triangle (Q is applied, not
    stored).  Requires a square tile grid evenly dividing A."""
    if A.mt != A.nt:
        raise ValueError("qr driver needs a square tile grid")
    if A.lm % A.mb or A.ln % A.nb:
        raise ValueError("qr tiles must divide the matrix evenly")
    NT = A.mt
    mb = A.mb
    use_device = device in ("tpu", "xla", "gpu")
    # inner blocking + trailing-update precision resolve ONCE per build;
    # they key the kernel memo so an MCA change cannot alias a stale jit
    ib = effective_ib(mb)
    upd = str(params.get("qr_update_precision", "default")).lower()
    from parsec_tpu.apps.pallas_kernels import use_pallas_qr_gram
    pg = use_pallas_qr_gram()
    # Owner-computes discipline for the final R tiles: the LAST TSQRT of
    # column k (and the last TSMQR of each row-k tile) runs where
    # A(NT-1, k) lives, but its R output belongs home at A(k, *).  On
    # one rank the write-back is local; across ranks it is routed
    # through a store task pinned to the home tile, so the payload rides
    # a normal dataflow edge (remote-dep protocol) instead of a
    # cross-rank direct write (reference counterpart: remote output
    # deps land via the ACTIVATE/GET protocol, remote_dep_mpi.c, never
    # by writing another rank's memory).
    routed = A.nodes > 1

    def bodies(tb, kernel, cpu_fn):
        if use_device:
            tb.body(kernel, device=device)
        tb.body(cpu_fn)
        return tb

    p = PTG("geqrf", NT=NT)
    p.arena("q1", (mb, mb), dtype=A.dtype)
    p.arena("q2", (2 * mb, mb), dtype=A.dtype)   # stacked [V; T^T]

    # GEQRT(k): diagonal QR
    tb = p.task("GEQRT", k=Range(0, NT - 1)) \
        .affinity(lambda k, A=A: A(k, k)) \
        .priority(lambda k, NT=NT: 4 * (NT - k) + 3) \
        .flow("T", "RW",
              IN(DATA(lambda k, A=A: A(k, k)), when=lambda k: k == 0),
              IN(TASK("TSMQR", "C2", lambda k: dict(m=k, n=k, k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("TSQRT", "T", lambda k, NT=NT: dict(m=k + 1, k=k)),
                  when=lambda k, NT=NT: k < NT - 1),
              OUT(DATA(lambda k, A=A: A(k, k)),
                  when=lambda k, NT=NT: k == NT - 1)) \
        .flow("Q", "RW",
              IN(NEW("q1")),
              OUT(TASK("UNMQR", "Q",
                       lambda k, NT=NT: [dict(k=k, n=n)
                                         for n in range(k + 1, NT)]),
                  when=lambda k, NT=NT: k < NT - 1))

    def cpu_geqrt(T, Q):
        q, r = np.linalg.qr(np.asarray(T), mode="complete")
        return {"T": r, "Q": q}
    bodies(tb, _k(("geqrt", ib, upd, pg), lambda: _mk_geqrt(ib, pg)),
           cpu_geqrt)

    # UNMQR(k, n): apply Q1^T across the k-th block row
    tb = p.task("UNMQR", k=Range(0, NT - 2), n=Range(lambda k: k + 1,
                                                     NT - 1)) \
        .affinity(lambda k, n, A=A: A(k, n)) \
        .priority(lambda k, NT=NT: 4 * (NT - k) + 2) \
        .flow("Q", "READ", IN(TASK("GEQRT", "Q", lambda k: dict(k=k)))) \
        .flow("C", "RW",
              IN(DATA(lambda k, n, A=A: A(k, n)), when=lambda k: k == 0),
              IN(TASK("TSMQR", "C2", lambda k, n: dict(m=k, n=n, k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("TSMQR", "C1", lambda k, n: dict(m=k + 1, n=n, k=k))))

    def cpu_unmqr(Q, C):
        return {"C": np.asarray(Q).T @ np.asarray(C)}
    bodies(tb, _k("unmqr", _mk_unmqr), cpu_unmqr)

    # TSQRT(m, k): fold block-column tile m into R(k)
    tb = p.task("TSQRT", k=Range(0, NT - 2), m=Range(lambda k: k + 1,
                                                     NT - 1)) \
        .affinity(lambda m, k, A=A: A(m, k)) \
        .priority(lambda k, NT=NT: 4 * (NT - k) + 1) \
        .flow("T", "RW",
              IN(TASK("GEQRT", "T", lambda k: dict(k=k)),
                 when=lambda m, k: m == k + 1),
              IN(TASK("TSQRT", "T", lambda m, k: dict(m=m - 1, k=k)),
                 when=lambda m, k: m > k + 1),
              OUT(TASK("TSQRT", "T", lambda m, k: dict(m=m + 1, k=k)),
                  when=lambda m, NT=NT: m < NT - 1),
              (OUT(TASK("RSTORE", "X", lambda k: dict(k=k)),
                   when=lambda m, NT=NT: m == NT - 1) if routed else
               OUT(DATA(lambda k, A=A: A(k, k)),
                   when=lambda m, NT=NT: m == NT - 1))) \
        .flow("B", "RW",
              IN(DATA(lambda m, k, A=A: A(m, k)), when=lambda k: k == 0),
              IN(TASK("TSMQR", "C2", lambda m, k: dict(m=m, n=k, k=k - 1)),
                 when=lambda k: k > 0),
              OUT(DATA(lambda m, k, A=A: A(m, k)))) \
        .flow("Q", "RW",
              IN(NEW("q2")),
              OUT(TASK("TSMQR", "Q",
                       lambda m, k, NT=NT: [dict(m=m, n=n, k=k)
                                            for n in range(k + 1, NT)]),
                  when=lambda k, NT=NT: k < NT - 1))

    def cpu_tsqrt(T, B, Q):
        # same compact-WY math as the device kernel, in float64 for
        # stability (Cholesky-QR squares the condition number)
        R64 = np.asarray(T, dtype=np.float64)
        B64 = np.asarray(B, dtype=np.float64)
        try:
            Rp, V, Tt = _tsqrt_wy(R64, B64, np, np.linalg.cholesky,
                                  _np_tri_inv)
        except np.linalg.LinAlgError:
            # non-PD Gram matrix: Householder QR of the stacked panel
            # gives the same triangular factor, unconditionally stably
            Rh = np.linalg.qr(np.concatenate([R64, B64], axis=0),
                              mode="r")
            s = np.where(np.diagonal(Rh) >= 0, 1.0, -1.0)
            Rp, V, Tt = _wy_from_L(R64, B64, (s[:, None] * Rh).T, np,
                                   _np_tri_inv)
        dt = np.asarray(T).dtype
        return {"T": Rp.astype(dt), "B": np.zeros_like(np.asarray(B)),
                "Q": np.concatenate([V, Tt], axis=0).astype(dt)}
    bodies(tb, _k(("tsqrt", ib, upd), lambda: _mk_tsqrt(ib)), cpu_tsqrt)

    # TSMQR(m, n, k): apply Q2^T to the [A(k,n); A(m,n)] pair
    tb = p.task("TSMQR", k=Range(0, NT - 2),
                m=Range(lambda k: k + 1, NT - 1),
                n=Range(lambda k: k + 1, NT - 1)) \
        .affinity(lambda m, n, A=A: A(m, n)) \
        .priority(lambda k, NT=NT: 4 * (NT - k)) \
        .flow("Q", "READ", IN(TASK("TSQRT", "Q", lambda m, k: dict(m=m,
                                                                   k=k)))) \
        .flow("C1", "RW",
              IN(TASK("UNMQR", "C", lambda n, k: dict(k=k, n=n)),
                 when=lambda m, k: m == k + 1),
              IN(TASK("TSMQR", "C1", lambda m, n, k: dict(m=m - 1, n=n,
                                                          k=k)),
                 when=lambda m, k: m > k + 1),
              OUT(TASK("TSMQR", "C1", lambda m, n, k: dict(m=m + 1, n=n,
                                                           k=k)),
                  when=lambda m, NT=NT: m < NT - 1),
              (OUT(TASK("CSTORE", "X", lambda k, n: dict(k=k, n=n)),
                   when=lambda m, NT=NT: m == NT - 1) if routed else
               OUT(DATA(lambda k, n, A=A: A(k, n)),
                   when=lambda m, NT=NT: m == NT - 1))) \
        .flow("C2", "RW",
              IN(DATA(lambda m, n, A=A: A(m, n)), when=lambda k: k == 0),
              IN(TASK("TSMQR", "C2", lambda m, n, k: dict(m=m, n=n,
                                                          k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("GEQRT", "T", lambda m: dict(k=m)),
                  when=lambda m, n, k: m == k + 1 and n == k + 1),
              OUT(TASK("TSQRT", "B", lambda m, n, k: dict(m=m, k=k + 1)),
                  when=lambda m, n, k: m > k + 1 and n == k + 1),
              OUT(TASK("UNMQR", "C", lambda m, n, k: dict(k=k + 1, n=n)),
                  when=lambda m, n, k: m == k + 1 and n > k + 1),
              OUT(TASK("TSMQR", "C2", lambda m, n, k: dict(m=m, n=n,
                                                           k=k + 1)),
                  when=lambda m, n, k: m > k + 1 and n > k + 1))
    def cpu_tsmqr(Q, C1, C2):
        mb_ = np.asarray(C1).shape[0]
        Qn = np.asarray(Q)
        V, Tt = Qn[:mb_, :], Qn[mb_:, :]
        C1n, C2n = np.asarray(C1), np.asarray(C2)
        Z = Tt @ (C1n + V.T @ C2n)
        return {"C1": C1n - Z, "C2": C2n - V @ Z}
    bodies(tb, _k("tsmqr", _mk_tsmqr), cpu_tsmqr)

    if routed:
        tb = p.task("RSTORE", k=Range(0, NT - 2)) \
            .affinity(lambda k, A=A: A(k, k)) \
            .flow("X", "RW",
                  IN(TASK("TSQRT", "T", lambda k, NT=NT: dict(m=NT - 1,
                                                              k=k))),
                  OUT(DATA(lambda k, A=A: A(k, k))))
        bodies(tb, _k("store", lambda: (lambda X: X)),
               lambda X: np.asarray(X))
        tb = p.task("CSTORE", k=Range(0, NT - 2),
                    n=Range(lambda k: k + 1, NT - 1)) \
            .affinity(lambda k, n, A=A: A(k, n)) \
            .flow("X", "RW",
                  IN(TASK("TSMQR", "C1",
                          lambda k, n, NT=NT: dict(m=NT - 1, n=n, k=k))),
                  OUT(DATA(lambda k, n, A=A: A(k, n))))
        bodies(tb, _k("store", lambda: (lambda X: X)),
               lambda X: np.asarray(X))

    tp = p.build()
    for name, tc in tp.task_classes.items():
        # executed-flop weights for device load balancing (stores move
        # a tile, no flops)
        tc.properties["flops"] = {"GEQRT": 2.0 * mb ** 3,
                                  "UNMQR": 2.0 * mb ** 3,
                                  "TSQRT": 6.0 * mb ** 3,
                                  "TSMQR": 10.0 * mb ** 3}.get(name, 1.0)
    # cross-panel fused dispatch (devices/xla.py chain fusion): the
    # GEQRT(k) -> TSQRT(k+1,k) -> ... -> TSQRT(NT-1,k) column is the
    # serial spine of the DAG — each link's only missing input is its
    # predecessor's T, so the device layer holds the head and traces the
    # whole column INTO its consumers' launch (one dispatch round trip
    # instead of one per link).  TSQRT co-locates on the diagonal tile's
    # device so the column is a chain on ONE device.
    tp.task_classes["GEQRT"].properties["fuse_chain"] = ("T", "TSQRT")
    tp.task_classes["TSQRT"].properties["fuse_chain"] = ("T", "TSQRT")
    tp.task_classes["TSQRT"].properties["coaffinity"] = \
        lambda loc, A=A: A(loc["k"], loc["k"])
    return tp


def geqrf_flops(m: int, n: int) -> float:
    """Useful FLOPs of an m x n QR factorization (2mn^2 - 2n^3/3;
    = 4n^3/3 when square)."""
    return 2.0 * m * n * n - 2.0 * n ** 3 / 3.0
