"""Ping-pong: task round-trip latency and dataflow bandwidth probes.

Rebuild of the reference's comm perf harnesses (reference:
tests/apps/pingpong/rtt.jdf — a datum bounced between 2 ranks through
dataflow edges, wall time / hops = task round-trip; bandwidth.jdf — the
same chain with large payloads measures dataflow edge bandwidth).
"""

from __future__ import annotations

import time
from typing import Tuple

import numpy as np

from parsec_tpu.core.taskpool import ParameterizedTaskpool
from parsec_tpu.data.matrix import VectorTwoDimCyclic
from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK


def pingpong_taskpool(V: VectorTwoDimCyclic,
                      hops: int) -> ParameterizedTaskpool:
    """A chain of ``hops`` tasks alternating ownership over V's tiles
    (tile h % NT, so with a 2-rank 1D-cyclic V the datum ping-pongs)."""
    NT = V.mt
    p = PTG("pingpong", H=hops)
    p.task("P", h=Range(0, hops - 1)) \
        .affinity(lambda h, V=V, NT=NT: V(h % NT)) \
        .flow("T", "RW",
              IN(DATA(lambda V=V: V(0)), when=lambda h: h == 0),
              IN(TASK("P", "T", lambda h: dict(h=h - 1)),
                 when=lambda h: h > 0),
              OUT(TASK("P", "T", lambda h, H=hops: dict(h=h + 1)),
                  when=lambda h, H=hops: h < H - 1),
              OUT(DATA(lambda h, V=V, NT=NT: V(h % NT)),
                  when=lambda h, H=hops: h == H - 1)) \
        .body(lambda T: T + 1.0)
    return p.build()


def run_pingpong(ctx, nbytes: int, hops: int) -> Tuple[float, float]:
    """Returns (seconds per hop, MB/s of payload motion).  SPMD: call on
    every rank of the context's communicator."""
    elems = max(1, nbytes // 4)
    V = VectorTwoDimCyclic(mb=elems, lm=elems * max(2, ctx.nranks),
                           nodes=ctx.nranks, myrank=ctx.rank, name="PP")
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = 0.0
    t0 = time.perf_counter()
    ctx.add_taskpool(pingpong_taskpool(V, hops))
    ctx.wait()
    dt = time.perf_counter() - t0
    per_hop = dt / hops
    mbps = (nbytes / per_hop) / 1e6 if per_hop > 0 else float("inf")
    return per_hop, mbps
