"""Irregular tree applications: DTD merge sort and the adaptive Haar
projection.

Reference: tests/apps/merge_sort/ (DTD merge sort over tiles) and
tests/apps/haar_tree/ (adaptive wavelet tree walk; project_dyn.jdf runs
it under DYNAMIC termination detection because the tree's size is
data-dependent and unknowable up front).  Both are DTD applications:
merge sort inserts its reduction tree statically bottom-up; the Haar
projection discovers its tree AT RUNTIME — task bodies insert their own
children — and terminates through the user_trigger termdet when the
outstanding-node count drains to zero.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from parsec_tpu.dsl.dtd.insert import (DTDTaskpool, INOUT, INPUT, OUTPUT,
                                       VALUE)


# ---------------------------------------------------------------------------
# DTD merge sort (reference: tests/apps/merge_sort)
# ---------------------------------------------------------------------------

def merge_sort_dtd(tp: DTDTaskpool, data: np.ndarray,
                   leaf: int = 64) -> "DTDTile":
    """Sort ``data`` via leaf sorts + a pairwise merge tree of DTD tasks;
    returns the tile holding the fully sorted result (read it after
    ``tp.wait()``)."""
    n = len(data)
    if n == 0:
        return tp.tile_new((0,), dtype=data.dtype)
    level: List = []
    # leaves: sort each chunk in place
    for lo in range(0, n, leaf):
        chunk = np.array(data[lo:lo + leaf])
        t = tp.tile_new((len(chunk),), dtype=data.dtype)
        np.copyto(np.asarray(t.data.copy_on(0).payload), chunk)
        tp.insert_task(lambda x: np.sort(np.asarray(x)), (t, INOUT))
        level.append(t)
    # merge tree, bottom-up
    while len(level) > 1:
        nxt = []
        for i in range(0, len(level) - 1, 2):
            a, b = level[i], level[i + 1]
            la = a.data.copy_on(0).payload.shape[0]
            lb = b.data.copy_on(0).payload.shape[0]
            out = tp.tile_new((la + lb,), dtype=data.dtype)

            def merge(x, y, o):
                m = np.concatenate([np.asarray(x), np.asarray(y)])
                m.sort(kind="mergesort")
                return m
            tp.insert_task(merge, (a, INPUT), (b, INPUT), (out, OUTPUT))
            nxt.append(out)
        if len(level) % 2:
            nxt.append(level[-1])
        level = nxt
    return level[0]


# ---------------------------------------------------------------------------
# Adaptive Haar projection (reference: tests/apps/haar_tree —
# project_dyn.jdf + dynamic termdet)
# ---------------------------------------------------------------------------

class HaarProjection:
    """Adaptive piecewise-constant projection of ``f`` on [0, 1): each
    node averages its interval and REFINES (spawning two child tasks
    from its own body) while the two halves differ by more than ``eps``
    and the interval is wider than ``min_width``.  The tree's shape —
    and therefore the task count — depends on the data, so the pool runs
    under the user_trigger termdet and fires it when the outstanding-
    node counter drains (reference: the dynamic-termdet contract of
    project_dyn.jdf)."""

    def __init__(self, fn: Callable[[np.ndarray], np.ndarray],
                 eps: float = 1e-2, min_width: float = 1e-3,
                 samples: int = 16):
        self.fn = fn
        self.eps = eps
        self.min_width = min_width
        self.samples = samples
        self.leaves: Dict[Tuple[float, float], float] = {}
        self._lock = threading.Lock()
        self._outstanding = 0
        self.nodes = 0

    def _avg(self, lo: float, hi: float) -> float:
        xs = np.linspace(lo, hi, self.samples, endpoint=False)
        return float(np.mean(self.fn(xs)))

    def _spawn(self, tp: DTDTaskpool, lo: float, hi: float) -> None:
        with self._lock:
            self._outstanding += 1
            self.nodes += 1
        tp.insert_task(lambda lo, hi, tp=tp: self._node(tp, lo, hi),
                       (lo, VALUE), (hi, VALUE))

    def _node(self, tp: DTDTaskpool, lo: float, hi: float) -> None:
        mid = (lo + hi) / 2.0
        left, right = self._avg(lo, mid), self._avg(mid, hi)
        if abs(left - right) > self.eps and (hi - lo) > self.min_width:
            # refine: the task DISCOVERS its children at runtime
            self._spawn(tp, lo, mid)
            self._spawn(tp, mid, hi)
        else:
            with self._lock:
                self.leaves[(lo, hi)] = (left + right) / 2.0
        done = False
        with self._lock:
            self._outstanding -= 1
            done = self._outstanding == 0
        if done:
            # the algorithm, not a task count, declares completion
            tp.termdet.trigger(tp)

    def run(self, tp: DTDTaskpool) -> None:
        """Seed the root; callers create ``tp`` with
        ``termdet_name='user_trigger'`` and ``tp.wait()`` afterwards."""
        if tp.termdet_name != "user_trigger":
            raise ValueError("HaarProjection needs a user_trigger pool: "
                             "its task count is data-dependent")
        self._spawn(tp, 0.0, 1.0)

    def evaluate(self, x: np.ndarray) -> np.ndarray:
        """Evaluate the projection (piecewise constant over the leaves)."""
        out = np.zeros_like(np.asarray(x, dtype=np.float64))
        for (lo, hi), v in self.leaves.items():
            mask = (x >= lo) & (x < hi)
            out[mask] = v
        return out
