"""Neighbor-shift wavefront: P producers each feeding the consumer on the
next device — the minimal DAG whose cross-device single-consumer edges
form one full CollectivePermute round (SURVEY §5.8 "batched per DAG
wavefront"; reference counterpart: a one-hop slice of the dataflow
pipelines in tests/apps/pingpong/ and tests/apps/stencil/).

Used by both the ICI runtime tests and the multichip dryrun, so the wave
wiring and its expected result live in exactly one place.
"""

from __future__ import annotations

import numpy as np

from parsec_tpu.core.taskpool import ParameterizedTaskpool
from parsec_tpu.data.matrix import TiledMatrix
from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK


def permute_wave_taskpool(V: TiledMatrix, W: TiledMatrix,
                          device: str = "tpu") -> ParameterizedTaskpool:
    """P(q): doubles block ``V(q)``; C(q): adds P((q-1) mod P)'s result
    into ``W(q)``.  Every P->C edge is a cross-device single-consumer
    hop when ``V``/``W`` are distributed one block per device.

    A CTL-gather GATE holds every consumer until the whole producer wave
    completed — the shape batched placement is FOR: consumers that are
    not instantly runnable (multi-input joins, later pipeline stages), so
    the full round of edges rides one CollectivePermute before any
    consumer stages in.  Ungated, each consumer races its edge and lazy
    stage-in usually wins (that path is exercised by the serialized-chain
    test instead)."""
    nd = V.mt
    if W.mt != nd:
        raise ValueError("one W block per party")
    p = PTG("wave", ND=nd)
    tb = p.task("P", q=Range(0, nd - 1)) \
        .affinity(lambda q, V=V: V(q)) \
        .flow("T", "RW",
              IN(DATA(lambda q, V=V: V(q))),
              OUT(TASK("C", "S", lambda q, ND=nd: dict(q=(q + 1) % ND)))) \
        .flow("ctl", "CTL",
              OUT(TASK("GATE", "ctl", lambda q: dict())))
    if device in ("tpu", "xla", "gpu"):
        tb.body(lambda T: T * 2.0, device=device)
    tb.body(lambda T: np.asarray(T) * 2.0)
    p.task("GATE") \
        .flow("ctl", "CTL",
              IN(TASK("P", "ctl",
                      lambda ND=nd: [dict(q=q) for q in range(ND)])),
              OUT(TASK("C", "go",
                       lambda ND=nd: [dict(q=q) for q in range(ND)]))) \
        .body(lambda: None)
    tb = p.task("C", q=Range(0, nd - 1)) \
        .affinity(lambda q, W=W: W(q)) \
        .flow("go", "CTL", IN(TASK("GATE", "ctl", lambda q: dict()))) \
        .flow("S", "READ",
              IN(TASK("P", "T", lambda q, ND=nd: dict(q=(q - 1) % ND)))) \
        .flow("A", "RW",
              IN(DATA(lambda q, W=W: W(q))),
              OUT(DATA(lambda q, W=W: W(q))))
    if device in ("tpu", "xla", "gpu"):
        tb.body(lambda S, A: S + A, device=device)
    tb.body(lambda S, A: np.asarray(S) + np.asarray(A))
    return p.build()


def fill_wave_inputs(V: TiledMatrix, W: TiledMatrix) -> None:
    """Canonical inputs: V(q) := q, W(q) := 0."""
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = float(m)
    for m, _ in W.local_tiles():
        W.data_of(m).copy_on(0).payload[:] = 0.0


def expected_wave_result(nd: int, q: int) -> float:
    """W(q) after the wave over the canonical inputs: twice the value
    party (q-1) mod P started with."""
    return 2.0 * float((q - 1) % nd)
