"""1D periodic stencil: iterative halo-exchange pipeline.

Rebuild of the reference's stencil mini-app (reference:
tests/apps/stencil/testing_stencil_1D.c + stencil_1D.jdf — a radius-R 1D
stencil iterated T times, each tile exchanging halos with its neighbors
every step; the wavefront pipeline is the canonical PTG pattern).  Here
the exchange is whole-tile (periodic boundaries) and each S(t, i) task
consumes its own tile plus both neighbors from step t-1 — the producer's
copy fans out to one writer and two readers, exercising the engine's
copy-on-write fan-out semantics.

The same computation lowers to one shard_map program on a mesh
(parallel/spmd.halo_stencil_fn) — the task graph is the irregular/
multi-pool form, the SPMD schedule the regular one.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from parsec_tpu.core.taskpool import ParameterizedTaskpool
from parsec_tpu.data.matrix import TiledMatrix
from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK

_kernels = {}


def _k_step():
    fn = _kernels.get("step")
    if fn is None:
        def fn(L, C, R):
            import jax.numpy as jnp
            ext = jnp.concatenate([L[-1:], C, R[:1]])
            return (ext[:-2] + ext[2:] + C) / 3.0
        _kernels["step"] = fn
    return fn


def _k_fused():
    fn = _kernels.get("fused")
    if fn is None:
        def fn(L, C, R, ns):
            # ns consecutive sweeps in ONE kernel over the concatenated
            # [L C R] array: the array's outer edges evolve with wrapped
            # garbage, but wrongness propagates one element per sweep
            # and never reaches the center tile for ns <= mb — the
            # S-deep-halo trade (VERDICT r4 #4, the GEMM k-chain trick
            # applied to sweeps).  ns is a task local -> static argnum:
            # at most two distinct programs (full blocks + remainder).
            import jax.numpy as jnp
            from jax import lax
            ext = jnp.concatenate([L, C, R])

            def one(_, u):
                e = jnp.concatenate([u[-1:], u, u[:1]])
                return (e[:-2] + e[2:] + u) / 3.0
            out = lax.fori_loop(0, ns, one, ext)
            mb = C.shape[0]
            return out[mb:2 * mb]
        _kernels["fused"] = fn
    return fn


def stencil_taskpool(V: TiledMatrix, steps: int,
                     device: str = "tpu",
                     fuse: int = 1) -> ParameterizedTaskpool:
    """Iterate the 3-point periodic mean stencil ``steps`` times over the
    tile vector V (in place).

    ``fuse``: sweeps fused per task (S-deep halo; requires
    ``fuse <= V.mb``).  Each task runs ``fuse`` sweeps in one kernel
    over its 3-tile neighborhood, cutting the per-point runtime
    overhead by the fusion depth at 3x the element updates — the right
    trade for an overhead-bound fine-grained pipeline (reference
    harness: tests/apps/stencil/testing_stencil_1D.c)."""
    if fuse > 1:
        if fuse > V.mb:
            raise ValueError(f"fuse depth {fuse} exceeds tile size {V.mb}")
        return _stencil_taskpool_fused(V, steps, device, fuse)
    NT = V.mt
    if NT < 2:
        raise ValueError("stencil needs at least 2 tiles")

    def cpu_step(L, C, R):
        ext = np.concatenate([np.asarray(L)[-1:], np.asarray(C),
                              np.asarray(R)[:1]])
        return (ext[:-2] + ext[2:] + np.asarray(C)) / 3.0

    p = PTG("stencil", NT=NT, T=steps)
    # INIT(i) reads each tile once and broadcasts it to the three t=0
    # consumers — reading AND writing a collection tile at the same
    # wavefront without a dep edge would be a DAG race (and remote reads
    # are not allowed anyway); the fan-out then rides the engine's
    # copy-on-write semantics.
    p.task("INIT", i=Range(0, NT - 1)) \
        .affinity(lambda i, V=V: V(i)) \
        .flow("X", "READ",
              IN(DATA(lambda i, V=V: V(i))),
              OUT(TASK("S", "C", lambda i: dict(t=0, i=i))),
              OUT(TASK("S", "L", lambda i, NT=NT: dict(t=0,
                                                       i=(i + 1) % NT))),
              OUT(TASK("S", "R", lambda i, NT=NT: dict(t=0,
                                                       i=(i - 1) % NT)))) \
        .body(lambda: None)
    tb = p.task("S", t=Range(0, steps - 1), i=Range(0, NT - 1)) \
        .affinity(lambda i, V=V: V(i)) \
        .priority(lambda t, T=steps: T - t) \
        .flow("L", "READ",
              IN(TASK("INIT", "X", lambda i, NT=NT: dict(i=(i - 1) % NT)),
                 when=lambda t: t == 0),
              IN(TASK("S", "C", lambda t, i, NT=NT: dict(t=t - 1,
                                                         i=(i - 1) % NT)),
                 when=lambda t: t > 0)) \
        .flow("R", "READ",
              IN(TASK("INIT", "X", lambda i, NT=NT: dict(i=(i + 1) % NT)),
                 when=lambda t: t == 0),
              IN(TASK("S", "C", lambda t, i, NT=NT: dict(t=t - 1,
                                                         i=(i + 1) % NT)),
                 when=lambda t: t > 0)) \
        .flow("C", "RW",
              IN(TASK("INIT", "X", lambda i: dict(i=i)),
                 when=lambda t: t == 0),
              IN(TASK("S", "C", lambda t, i: dict(t=t - 1, i=i)),
                 when=lambda t: t > 0),
              OUT(TASK("S", "C", lambda t, i: dict(t=t + 1, i=i)),
                  when=lambda t, T=steps: t < T - 1),
              OUT(TASK("S", "L", lambda t, i, NT=NT: dict(t=t + 1,
                                                          i=(i + 1) % NT)),
                  when=lambda t, T=steps: t < T - 1),
              OUT(TASK("S", "R", lambda t, i, NT=NT: dict(t=t + 1,
                                                          i=(i - 1) % NT)),
                  when=lambda t, T=steps: t < T - 1),
              OUT(DATA(lambda i, V=V: V(i)),
                  when=lambda t, T=steps: t == T - 1))
    if device in ("tpu", "xla", "gpu"):
        tb.body(_k_step(), device=device)
    tb.body(cpu_step)
    return p.build()


def _stencil_taskpool_fused(V: TiledMatrix, steps: int, device: str,
                            fuse: int) -> ParameterizedTaskpool:
    """The fused-sweep variant: blocks of ``fuse`` sweeps per task; the
    last block carries the remainder as its ``ns`` local."""
    NT = V.mt
    if NT < 2:
        raise ValueError("stencil needs at least 2 tiles")
    NB = -(-steps // fuse)          # ceil

    def ns_of(globals_, locals_):
        return [min(fuse, steps - locals_["b"] * fuse)]

    def cpu_fused(L, C, R, ns):
        u = np.concatenate([np.asarray(L), np.asarray(C), np.asarray(R)])
        for _ in range(int(ns)):
            e = np.concatenate([u[-1:], u, u[:1]])
            u = (e[:-2] + e[2:] + u) / 3.0
        mb = np.asarray(C).shape[0]
        return u[mb:2 * mb]

    p = PTG("stencil", NT=NT, T=steps)
    p.task("INIT", i=Range(0, NT - 1)) \
        .affinity(lambda i, V=V: V(i)) \
        .flow("X", "READ",
              IN(DATA(lambda i, V=V: V(i))),
              OUT(TASK("S", "C", lambda i: dict(b=0, i=i))),
              OUT(TASK("S", "L", lambda i, NT=NT: dict(b=0,
                                                       i=(i + 1) % NT))),
              OUT(TASK("S", "R", lambda i, NT=NT: dict(b=0,
                                                       i=(i - 1) % NT)))) \
        .body(lambda: None)
    tb = p.task("S", b=Range(0, NB - 1), i=Range(0, NT - 1), ns=ns_of) \
        .affinity(lambda i, V=V: V(i)) \
        .priority(lambda b, NB=NB: NB - b) \
        .flow("L", "READ",
              IN(TASK("INIT", "X", lambda i, NT=NT: dict(i=(i - 1) % NT)),
                 when=lambda b: b == 0),
              IN(TASK("S", "C", lambda b, i, NT=NT: dict(b=b - 1,
                                                         i=(i - 1) % NT)),
                 when=lambda b: b > 0)) \
        .flow("R", "READ",
              IN(TASK("INIT", "X", lambda i, NT=NT: dict(i=(i + 1) % NT)),
                 when=lambda b: b == 0),
              IN(TASK("S", "C", lambda b, i, NT=NT: dict(b=b - 1,
                                                         i=(i + 1) % NT)),
                 when=lambda b: b > 0)) \
        .flow("C", "RW",
              IN(TASK("INIT", "X", lambda i: dict(i=i)),
                 when=lambda b: b == 0),
              IN(TASK("S", "C", lambda b, i: dict(b=b - 1, i=i)),
                 when=lambda b: b > 0),
              OUT(TASK("S", "C", lambda b, i: dict(b=b + 1, i=i)),
                  when=lambda b, NB=NB: b < NB - 1),
              OUT(TASK("S", "L", lambda b, i, NT=NT: dict(b=b + 1,
                                                          i=(i + 1) % NT)),
                  when=lambda b, NB=NB: b < NB - 1),
              OUT(TASK("S", "R", lambda b, i, NT=NT: dict(b=b + 1,
                                                          i=(i - 1) % NT)),
                  when=lambda b, NB=NB: b < NB - 1),
              OUT(DATA(lambda i, V=V: V(i)),
                  when=lambda b, NB=NB: b == NB - 1))
    if device in ("tpu", "xla", "gpu"):
        tb.body(_k_fused(), device=device)
    tb.body(cpu_fused)
    return p.build()


def stencil_reference(x: np.ndarray, steps: int) -> np.ndarray:
    """Serial reference of the same periodic stencil."""
    u = x.astype(np.float64)
    for _ in range(steps):
        ext = np.concatenate([u[-1:], u, u[:1]])
        u = (ext[:-2] + ext[2:] + u) / 3.0
    return u
