"""Tiled Cholesky factorization (dpotrf, lower): the north-star driver.

The DPLASMA-style dpotrf_L dataflow (reference: BASELINE.md/BASELINE.json
name DPLASMA tiled Cholesky as the headline target; the JDF structure
follows the classic four-kernel tiled algorithm the reference's PTG model
was built for — README.rst:22-27 "compact problem-size-independent
representation"):

    POTRF(k)    : L[k,k]  = chol(A[k,k])
    TRSM(m,k)   : A[m,k]  = A[m,k] @ L[k,k]^-T          (m > k)
    SYRK(k,m)   : A[m,m] -= A[m,k] @ A[m,k]^T           (k < m)
    GEMM(m,n,k) : A[m,n] -= A[m,k] @ A[n,k]^T           (m > n > k)

Every flow is task-to-task except the first touch of each tile, so the
same taskpool runs single-chip or distributed (TRSM panels broadcast down
their block row/column through the comm layer's bcast trees).

TPU notes: all four kernels are single fused XLA ops (cholesky,
triangular solve, two matmuls) jitted once per tile shape; the priority
schedule drives the critical path (POTRF > TRSM > SYRK > GEMM at equal
k) exactly like DPLASMA's priority hints.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from parsec_tpu.core.taskpool import ParameterizedTaskpool
from parsec_tpu.data.matrix import TiledMatrix
from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK

_kernels = {}


def _k_potrf(precision):
    fn = _kernels.get(("potrf", precision))
    if fn is None:
        def fn(T):
            import jax.numpy as jnp
            return jnp.linalg.cholesky(T)
        _kernels[("potrf", precision)] = fn
    return fn


def _k_trsm(precision):
    fn = _kernels.get(("trsm", precision))
    if fn is None:
        def fn(L, C):
            import jax.scipy.linalg as jsl
            # C <- C @ L^-T  ==  (L^-1 C^T)^T
            return jsl.solve_triangular(L, C.T, lower=True).T
        _kernels[("trsm", precision)] = fn
    return fn


def _k_syrk(precision):
    fn = _kernels.get(("syrk", precision))
    if fn is None:
        def fn(T, R):
            import jax.numpy as jnp
            return T - jnp.matmul(R, R.T, precision=precision)
        _kernels[("syrk", precision)] = fn
    return fn


def _k_gemm(precision):
    fn = _kernels.get(("gemm", precision))
    if fn is None:
        def fn(C, L, R):
            import jax.numpy as jnp
            return C - jnp.matmul(L, R.T, precision=precision)
        _kernels[("gemm", precision)] = fn
    return fn


def potrf_taskpool(A: TiledMatrix, device: str = "tpu",
                   precision: Optional[str] = None) -> ParameterizedTaskpool:
    """Factor the lower triangle of A in place: A = L @ L^T."""
    if A.mt != A.nt:
        raise ValueError("potrf needs a square tile grid")
    if A.lm % A.mb or A.ln % A.nb:
        raise ValueError("potrf tiles must divide the matrix evenly")
    NT = A.mt
    mb = A.mb
    use_device = device in ("tpu", "xla", "gpu")

    def add_bodies(tb, kernel, cpu_fn):
        if use_device:
            tb.body(kernel, device=device)
        tb.body(cpu_fn)
        return tb

    p = PTG("potrf", NT=NT)

    tb = p.task("POTRF", k=Range(0, NT - 1)) \
        .affinity(lambda k, A=A: A(k, k)) \
        .priority(lambda k, NT=NT: 3 * NT - 3 * k + 3) \
        .flow("T", "RW",
              IN(DATA(lambda k, A=A: A(k, k)), when=lambda k: k == 0),
              IN(TASK("SYRK", "T", lambda k: dict(k=k - 1, m=k)),
                 when=lambda k: k > 0),
              OUT(TASK("TRSM", "L",
                       lambda k, NT=NT: [dict(m=m, k=k)
                                         for m in range(k + 1, NT)]),
                  when=lambda k, NT=NT: k < NT - 1),
              OUT(DATA(lambda k, A=A: A(k, k))))
    add_bodies(tb, _k_potrf(precision),
               lambda T: np.linalg.cholesky(np.asarray(T)))

    tb = p.task("TRSM", k=Range(0, NT - 2),
                m=Range(lambda k: k + 1, NT - 1)) \
        .affinity(lambda m, k, A=A: A(m, k)) \
        .priority(lambda k, NT=NT: 3 * NT - 3 * k + 2) \
        .flow("L", "READ", IN(TASK("POTRF", "T", lambda k: dict(k=k)))) \
        .flow("C", "RW",
              IN(DATA(lambda m, k, A=A: A(m, k)), when=lambda k: k == 0),
              IN(TASK("GEMM", "C", lambda m, k: dict(m=m, n=k, k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("SYRK", "R", lambda m, k: dict(k=k, m=m))),
              OUT(TASK("GEMM", "L",
                       lambda m, k: [dict(m=m, n=n, k=k)
                                     for n in range(k + 1, m)]),
                  when=lambda m, k: m > k + 1),
              OUT(TASK("GEMM", "R",
                       lambda m, k, NT=NT: [dict(m=m2, n=m, k=k)
                                            for m2 in range(m + 1, NT)]),
                  when=lambda m, NT=NT: m < NT - 1),
              OUT(DATA(lambda m, k, A=A: A(m, k))))

    def cpu_trsm(L, C):
        import scipy.linalg as sl
        return sl.solve_triangular(np.asarray(L), np.asarray(C).T,
                                   lower=True).T
    add_bodies(tb, _k_trsm(precision), cpu_trsm)

    tb = p.task("SYRK", m=Range(1, NT - 1), k=Range(0, lambda m: m - 1)) \
        .affinity(lambda m, A=A: A(m, m)) \
        .priority(lambda k, NT=NT: 3 * NT - 3 * k + 1) \
        .flow("T", "RW",
              IN(DATA(lambda m, A=A: A(m, m)), when=lambda k: k == 0),
              IN(TASK("SYRK", "T", lambda m, k: dict(m=m, k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("POTRF", "T", lambda m: dict(k=m)),
                  when=lambda m, k: k == m - 1),
              OUT(TASK("SYRK", "T", lambda m, k: dict(m=m, k=k + 1)),
                  when=lambda m, k: k < m - 1)) \
        .flow("R", "READ", IN(TASK("TRSM", "C", lambda m, k: dict(m=m,
                                                                  k=k))))
    add_bodies(tb, _k_syrk(precision),
               lambda T, R: np.asarray(T) -
               np.asarray(R) @ np.asarray(R).T)

    tb = p.task("GEMM", n=Range(1, NT - 2),
                m=Range(lambda n: n + 1, NT - 1),
                k=Range(0, lambda n: n - 1)) \
        .affinity(lambda m, n, A=A: A(m, n)) \
        .priority(lambda k, NT=NT: 3 * NT - 3 * k) \
        .flow("C", "RW",
              IN(DATA(lambda m, n, A=A: A(m, n)), when=lambda k: k == 0),
              IN(TASK("GEMM", "C", lambda m, n, k: dict(m=m, n=n, k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("TRSM", "C", lambda m, n: dict(m=m, k=n)),
                  when=lambda n, k: k == n - 1),
              OUT(TASK("GEMM", "C", lambda m, n, k: dict(m=m, n=n, k=k + 1)),
                  when=lambda n, k: k < n - 1)) \
        .flow("L", "READ", IN(TASK("TRSM", "C", lambda m, k: dict(m=m,
                                                                  k=k)))) \
        .flow("R", "READ", IN(TASK("TRSM", "C", lambda n, k: dict(m=n,
                                                                  k=k))))
    add_bodies(tb, _k_gemm(precision),
               lambda C, L, R: np.asarray(C) -
               np.asarray(L) @ np.asarray(R).T)

    tp = p.build()
    for name, tc in tp.task_classes.items():
        tc.properties["flops"] = {"POTRF": mb ** 3 / 3.0,
                                  "TRSM": mb ** 3,
                                  "SYRK": mb ** 3,
                                  "GEMM": 2.0 * mb ** 3}[name]
    return tp


def potrf_flops(n: int) -> float:
    """Useful FLOPs of an n x n Cholesky (n^3/3)."""
    return n ** 3 / 3.0
