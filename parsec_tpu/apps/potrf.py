"""Tiled Cholesky factorization (dpotrf, lower): the north-star driver.

The DPLASMA-style dpotrf_L dataflow (reference: BASELINE.md/BASELINE.json
name DPLASMA tiled Cholesky as the headline target; the JDF structure
follows the classic four-kernel tiled algorithm the reference's PTG model
was built for — README.rst:22-27 "compact problem-size-independent
representation"):

    POTRF(k)    : L[k,k]  = chol(A[k,k]);  W[k] = L[k,k]^-1
    TRSM(m,k)   : A[m,k]  = A[m,k] @ W[k]^T                 (m > k)
    SYRK(k,m)   : A[m,m] -= A[m,k] @ A[m,k]^T               (k < m)
    GEMM(m,n,k) : A[m,n] -= A[m,k] @ A[n,k]^T               (m > n > k)

Every flow is task-to-task except the first touch of each tile, so the
same taskpool runs single-chip or distributed (the W panel broadcasts down
its block column through the comm layer's bcast trees).

TPU-first design of the solve step: XLA's ``triangular_solve`` runs an
order of magnitude below matmul peak on TPU (it serializes block
back-substitution), so POTRF additionally emits the tile inverse W =
L^-1 — computed by recursive block inversion whose leaves use the Newton
iteration X <- X(2I - LX).  For triangular L with X0 = diag(L)^-1 the
residual I - LX0 is strictly lower triangular, i.e. NILPOTENT, and the
iteration SQUARES it, so ceil(log2(n)) iterations reach the exact
inverse — everything is matmuls on the MXU.  Each TRSM then becomes a
single matmul A[m,k] @ W^T at full systolic-array rate instead of a
triangular solve.  The extra mb^3/3 inverse flops per panel are ~1% of
the factorization and buy back a >4x faster panel wave (measured on
v5e: jsl trsm ~18 TF/s vs matmul ~150 TF/s).

The priority schedule drives the critical path (POTRF > TRSM > SYRK >
GEMM at equal k) exactly like DPLASMA's priority hints, and same-class
waves (the TRSM panel, the SYRK/GEMM trailing updates) are fused into
single XLA launches by the device layer's wavefront launch fusion
(devices/xla.py) so the runtime amortizes per-launch latency.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

from parsec_tpu.core.taskpool import ParameterizedTaskpool
from parsec_tpu.data.matrix import TiledMatrix
from parsec_tpu.dsl.ptg.api import DATA, IN, NEW, OUT, PTG, Range, TASK

_kernels = {}

#: recursive-inversion leaf: below this order the Newton iteration runs
#: directly (log2(leaf) matmuls of leaf x leaf — MXU noise)
_INV_LEAF = 512


def tri_inv(L, precision=None):
    """Lower-triangular inverse as pure matmuls (jax-traceable).

    Recursive 2x2 block inversion
        [[L11, 0], [L21, L22]]^-1 =
        [[X11, 0], [-X22 @ L21 @ X11, X22]]
    with Newton--Schulz leaves: X <- X(2I - LX) starting from
    X0 = diag(L)^-1 converges EXACTLY in ceil(log2(n)) steps because the
    initial residual I - LX0 is strictly triangular (nilpotent) and each
    step squares it.  No triangular solve anywhere: everything lowers to
    the systolic array.
    """
    import jax.numpy as jnp
    n = L.shape[0]
    if n <= _INV_LEAF:
        X = jnp.diag(1.0 / jnp.diag(L))
        I = jnp.eye(n, dtype=L.dtype)
        for _ in range(int(math.ceil(math.log2(max(n, 2)))) + 1):
            X = jnp.matmul(X, 2.0 * I - jnp.matmul(L, X,
                                                   precision=precision),
                           precision=precision)
        return X
    h = n // 2
    X11 = tri_inv(L[:h, :h], precision)
    X22 = tri_inv(L[h:, h:], precision)
    X21 = -jnp.matmul(X22, jnp.matmul(L[h:, :h], X11, precision=precision),
                      precision=precision)
    top = jnp.concatenate([X11, jnp.zeros((h, n - h), L.dtype)], axis=1)
    return jnp.concatenate([top, jnp.concatenate([X21, X22], axis=1)],
                           axis=0)


def _k_potrf(precision):
    fn = _kernels.get(("potrf", precision))
    if fn is None:
        def fn(T, W):
            import jax.numpy as jnp
            # factor in f32 even under bf16 tile storage (the mp mode):
            # the inverse W always stays f32 — it multiplies every panel
            L = jnp.linalg.cholesky(T.astype(jnp.float32))
            return {"T": L.astype(T.dtype), "W": tri_inv(L, precision)}
        _kernels[("potrf", precision)] = fn
    return fn


def _k_potrf_last(precision):
    # the last diagonal tile has no TRSM consumers: plain cholesky, no
    # inverse flops and no W scratch on the critical path's final task
    fn = _kernels.get(("potrf_last", precision))
    if fn is None:
        def fn(T):
            import jax.numpy as jnp
            return jnp.linalg.cholesky(T.astype(jnp.float32)).astype(T.dtype)
        _kernels[("potrf_last", precision)] = fn
    return fn


def _k_trsm(precision):
    # Kernels are dtype-FOLLOWING: products always accumulate in f32
    # (preferred_element_type), the Cholesky itself runs in f32 (upcast
    # in _k_potrf), and results land back in the tile's STORAGE dtype —
    # so the same code path serves full-f32 tiles and the bf16-storage
    # mixed-precision mode (HPL-AI-style: all tiles stored bf16, halving
    # HBM footprint+traffic, results rounded to bf16 between steps; the
    # panel inverse W alone stays f32; bench.py PARSEC_BENCH_POTRF_MP).
    fn = _kernels.get(("trsm", precision))
    if fn is None:
        def fn(W, C):
            import jax.numpy as jnp
            # C <- C @ L^-T  ==  C @ W^T  (W = L^-1 from POTRF)
            acc = jnp.matmul(C, W.T, precision=precision,
                             preferred_element_type=jnp.float32)
            return acc.astype(C.dtype)
        _kernels[("trsm", precision)] = fn
    return fn


def _k_syrk(precision):
    fn = _kernels.get(("syrk", precision))
    if fn is None:
        def fn(T, R):
            import jax.numpy as jnp
            acc = jnp.matmul(R, R.T, precision=precision,
                             preferred_element_type=jnp.float32)
            return (T.astype(jnp.float32) - acc).astype(T.dtype)
        _kernels[("syrk", precision)] = fn
    return fn


def _k_gemm(precision):
    fn = _kernels.get(("gemm", precision))
    if fn is None:
        def fn(C, L, R):
            import jax.numpy as jnp
            acc = jnp.matmul(L, R.T, precision=precision,
                             preferred_element_type=jnp.float32)
            return (C.astype(jnp.float32) - acc).astype(C.dtype)
        _kernels[("gemm", precision)] = fn
    return fn


def potrf_taskpool(A: TiledMatrix, device: str = "tpu",
                   precision: Optional[str] = None) -> ParameterizedTaskpool:
    """Factor the lower triangle of A in place: A = L @ L^T."""
    if A.mt != A.nt:
        raise ValueError("potrf needs a square tile grid")
    if A.lm % A.mb or A.ln % A.nb:
        raise ValueError("potrf tiles must divide the matrix evenly")
    NT = A.mt
    mb = A.mb
    use_device = device in ("tpu", "xla", "gpu")

    def add_bodies(tb, kernel, cpu_fn):
        if use_device:
            tb.body(kernel, device=device)
        tb.body(cpu_fn)
        return tb

    p = PTG("potrf", NT=NT)
    # the panel inverse is always f32, even when tiles store bf16 (mp)
    p.arena("w", (mb, mb), dtype=np.float32)

    tb = p.task("POTRF", k=Range(0, NT - 2)) \
        .affinity(lambda k, A=A: A(k, k)) \
        .priority(lambda k, NT=NT: 3 * NT - 3 * k + 3) \
        .flow("T", "RW",
              IN(DATA(lambda k, A=A: A(k, k)), when=lambda k: k == 0),
              IN(TASK("SYRK", "T", lambda k: dict(k=k - 1, m=k)),
                 when=lambda k: k > 0),
              OUT(DATA(lambda k, A=A: A(k, k)))) \
        .flow("W", "RW",
              IN(NEW("w")),
              OUT(TASK("TRSM", "W",
                       lambda k, NT=NT: [dict(m=m, k=k)
                                         for m in range(k + 1, NT)])))

    def cpu_potrf(T, W):
        import scipy.linalg as sl
        L = np.linalg.cholesky(np.asarray(T, dtype=np.float32))
        Winv = sl.solve_triangular(L, np.eye(L.shape[0], dtype=L.dtype),
                                   lower=True)
        return {"T": L.astype(np.asarray(T).dtype), "W": Winv}
    add_bodies(tb, _k_potrf(precision), cpu_potrf)

    # the final diagonal tile: no panel below it, so no inverse is needed
    tb = p.task("POTRFL") \
        .affinity(lambda A=A, NT=NT: A(NT - 1, NT - 1)) \
        .priority(lambda NT=NT: 6) \
        .flow("T", "RW",
              IN(DATA(lambda A=A, NT=NT: A(NT - 1, NT - 1)),
                 when=lambda NT=NT: NT == 1),
              IN(TASK("SYRK", "T", lambda NT=NT: dict(k=NT - 2, m=NT - 1)),
                 when=lambda NT=NT: NT > 1),
              OUT(DATA(lambda A=A, NT=NT: A(NT - 1, NT - 1))))
    add_bodies(tb, _k_potrf_last(precision),
               lambda T: np.linalg.cholesky(
                   np.asarray(T, dtype=np.float32)
               ).astype(np.asarray(T).dtype))

    tb = p.task("TRSM", k=Range(0, NT - 2),
                m=Range(lambda k: k + 1, NT - 1)) \
        .affinity(lambda m, k, A=A: A(m, k)) \
        .priority(lambda k, NT=NT: 3 * NT - 3 * k + 2) \
        .flow("W", "READ", IN(TASK("POTRF", "W", lambda k: dict(k=k)))) \
        .flow("C", "RW",
              IN(DATA(lambda m, k, A=A: A(m, k)), when=lambda k: k == 0),
              IN(TASK("GEMM", "C", lambda m, k: dict(m=m, n=k, k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("SYRK", "R", lambda m, k: dict(k=k, m=m))),
              OUT(TASK("GEMM", "L",
                       lambda m, k: [dict(m=m, n=n, k=k)
                                     for n in range(k + 1, m)]),
                  when=lambda m, k: m > k + 1),
              OUT(TASK("GEMM", "R",
                       lambda m, k, NT=NT: [dict(m=m2, n=m, k=k)
                                            for m2 in range(m + 1, NT)]),
                  when=lambda m, NT=NT: m < NT - 1),
              OUT(DATA(lambda m, k, A=A: A(m, k))))

    def cpu_trsm(W, C):
        out = np.asarray(C, dtype=np.float32) @ \
            np.asarray(W, dtype=np.float32).T
        return out.astype(np.asarray(C).dtype)
    add_bodies(tb, _k_trsm(precision), cpu_trsm)

    tb = p.task("SYRK", m=Range(1, NT - 1), k=Range(0, lambda m: m - 1)) \
        .affinity(lambda m, A=A: A(m, m)) \
        .priority(lambda k, NT=NT: 3 * NT - 3 * k + 1) \
        .flow("T", "RW",
              IN(DATA(lambda m, A=A: A(m, m)), when=lambda k: k == 0),
              IN(TASK("SYRK", "T", lambda m, k: dict(m=m, k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("POTRF", "T", lambda m: dict(k=m)),
                  when=lambda m, k, NT=NT: k == m - 1 and m < NT - 1),
              OUT(TASK("POTRFL", "T", lambda: dict()),
                  when=lambda m, k, NT=NT: k == m - 1 and m == NT - 1),
              OUT(TASK("SYRK", "T", lambda m, k: dict(m=m, k=k + 1)),
                  when=lambda m, k: k < m - 1)) \
        .flow("R", "READ", IN(TASK("TRSM", "C", lambda m, k: dict(m=m,
                                                                  k=k))))
    def cpu_syrk(T, R):
        r = np.asarray(R, dtype=np.float32)
        return (np.asarray(T, dtype=np.float32) -
                r @ r.T).astype(np.asarray(T).dtype)
    add_bodies(tb, _k_syrk(precision), cpu_syrk)

    tb = p.task("GEMM", n=Range(1, NT - 2),
                m=Range(lambda n: n + 1, NT - 1),
                k=Range(0, lambda n: n - 1)) \
        .affinity(lambda m, n, A=A: A(m, n)) \
        .priority(lambda k, NT=NT: 3 * NT - 3 * k) \
        .flow("C", "RW",
              IN(DATA(lambda m, n, A=A: A(m, n)), when=lambda k: k == 0),
              IN(TASK("GEMM", "C", lambda m, n, k: dict(m=m, n=n, k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("TRSM", "C", lambda m, n: dict(m=m, k=n)),
                  when=lambda n, k: k == n - 1),
              OUT(TASK("GEMM", "C", lambda m, n, k: dict(m=m, n=n, k=k + 1)),
                  when=lambda n, k: k < n - 1)) \
        .flow("L", "READ", IN(TASK("TRSM", "C", lambda m, k: dict(m=m,
                                                                  k=k)))) \
        .flow("R", "READ", IN(TASK("TRSM", "C", lambda n, k: dict(m=n,
                                                                  k=k))))
    def cpu_gemm(C, L, R):
        acc = np.asarray(L, dtype=np.float32) @ \
            np.asarray(R, dtype=np.float32).T
        return (np.asarray(C, dtype=np.float32) -
                acc).astype(np.asarray(C).dtype)
    add_bodies(tb, _k_gemm(precision), cpu_gemm)

    tp = p.build()
    for name, tc in tp.task_classes.items():
        # executed-flop weights for device load balancing (TRSM runs as a
        # full matmul against W, so it carries 2mb^3, not the mb^3 of a
        # true triangular solve)
        tc.properties["flops"] = {"POTRF": mb ** 3,
                                  "POTRFL": mb ** 3 / 3.0,
                                  "TRSM": 2.0 * mb ** 3,
                                  "SYRK": 2.0 * mb ** 3,
                                  "GEMM": 2.0 * mb ** 3}[name]
    # cross-panel fused dispatch (devices/xla.py chain fusion): the
    # POTRF(k) -> TRSM(*,k) panel is the dispatch-latency-bound spine of
    # the DAG (each TRSM's only missing input is W) — the device layer
    # holds POTRF(k) and traces it INTO the TRSM wave's launch, so the
    # panel chain costs ONE dispatch round trip instead of two plus the
    # Python scheduling latency between them.  TRSM co-locates on the
    # diagonal tile's device so the whole panel is one wave there.
    # A/B knob: PARSEC_MCA_DEVICE_FUSE_PANEL=0 restores the per-kernel
    # panel path.
    tp.task_classes["POTRF"].properties["fuse_chain"] = ("W", "TRSM")
    tp.task_classes["TRSM"].properties["coaffinity"] = \
        lambda loc, A=A: A(loc["k"], loc["k"])
    # recovery spec (core/recovery.py): the whole dataflow reads and
    # writes A, so a peer death can re-map A's lost partition onto the
    # survivors and re-enumerate this pool from the restored tiles —
    # give A an init_fn (A.set_init) so ADOPTED tiles have a
    # re-runnable source, and the pool recovers instead of failing
    tp.recovery_collections = [A]
    return tp


def potrf_flops(n: int) -> float:
    """Useful FLOPs of an n x n Cholesky (n^3/3)."""
    return n ** 3 / 3.0
