"""Tiled GEMM driver: C = alpha*A@B + beta*C as a parameterized task graph.

The flagship throughput app, mirroring the reference's DTD GEMM perf
harness (reference: tests/dsl/dtd/dtd_test_simple_gemm.c — GFLOPS =
2*M*N*K/t, :659-666) but expressed as a PTG: one GEMM(m, n, k) task per
(C tile, k panel), chained over k so each C tile flows through its own
accumulation pipeline while independent (m, n) chains run concurrently
across devices.  Owner-computes: the task runs where C(m, n) lives.

TPU notes: tiles should be MXU-shaped (multiples of 128; 512-2048 sweet
spot) and bf16 for peak; the kernel is a single fused jax matmul-add that
XLA maps straight onto the systolic array, jitted once per tile shape.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from parsec_tpu.core.taskpool import ParameterizedTaskpool
from parsec_tpu.data.matrix import TiledMatrix
from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK


#: kernel functions memoized per (alpha, precision) so repeated taskpool
#: builds share one function object — and therefore one jit cache entry
#: (XlaKernel._jit_cache) — across runs
_kernels = {}


def _tile_kernel(alpha: float, precision=None):
    """Accumulation step of the k-chain: Ci += alpha * Ai@Bi.
    (beta is applied once by the SCALE task class, not per step.)

    ``--mca gemm_pallas 1`` swaps in the hand-written Pallas blocked
    kernel (apps/pallas_kernels.py) — the user-kernel seam the reference
    fills with BODY [type=CUDA] bodies."""
    from parsec_tpu.apps.pallas_kernels import (pallas_gemm_tile,
                                                use_pallas_gemm)
    if use_pallas_gemm():
        key = ("pallas", alpha, precision)
        fn = _kernels.get(key)
        if fn is None:
            fn = _kernels[key] = pallas_gemm_tile(alpha,
                                                  precision=precision)
        return fn
    key = (alpha, precision)
    fn = _kernels.get(key)
    if fn is None:
        def fn(Ai, Bi, Ci):
            import jax.numpy as jnp
            # accumulate in C's dtype: bf16 A/B panels with an f32 C give
            # MXU-native multiplies with f32 accumulation (the TPU-idiomatic
            # mixed-precision GEMM)
            acc = jnp.matmul(Ai, Bi, precision=precision,
                             preferred_element_type=Ci.dtype)
            return Ci + (acc if alpha == 1.0 else alpha * acc)
        _kernels[key] = fn
    return fn


def gemm_taskpool(A: TiledMatrix, B: TiledMatrix, C: TiledMatrix,
                  alpha: float = 1.0, beta: float = 1.0,
                  device: str = "tpu",
                  precision: Optional[str] = None,
                  panel_bcast: Optional[bool] = None
                  ) -> ParameterizedTaskpool:
    """Build the C = alpha*A@B + beta*C taskpool over tiled collections.

    ``precision``: jax matmul precision ("highest" forces fp32 accumulate
    on TPU; None keeps the backend default, bf16 on TPU).
    ``panel_bcast``: route each A-row/B-column panel through a reader task
    whose output fans out to every consumer — the dataflow broadcast form
    that multi-rank runs need (remote bcast trees) and that multi-DEVICE
    runs lower to one ICI collective per panel (comm/ici.prebroadcast).
    Default: on when the collections are distributed.
    """
    if A.nt != B.mt or A.mt != C.mt or B.nt != C.nt:
        raise ValueError(
            f"tile grids do not agree: A {A.mt}x{A.nt}, B {B.mt}x{B.nt}, "
            f"C {C.mt}x{C.nt}")
    mt, nt, kt = C.mt, C.nt, A.nt
    mb, nb, kb = C.mb, C.nb, A.nb
    flops_per_task = 2.0 * mb * nb * kb
    use_device = device in ("tpu", "xla", "gpu")
    kernel = _tile_kernel(alpha, precision)
    prescale = beta != 1.0

    def cpu_body(Ai, Bi, Ci):
        return np.asarray(Ci) + alpha * np.matmul(np.asarray(Ai),
                                                  np.asarray(Bi))

    distributed = C.nodes > 1
    if panel_bcast is None:
        panel_bcast = distributed
    p = PTG("gemm", MT=mt, NT=nt, KT=kt)
    if panel_bcast:
        # Owner-computes reader tasks broadcast each A-row / B-column
        # panel to the GEMM tasks that consume it — the dataflow bcast
        # tree of the reference (remote_dep.c star/chain/binomial) and
        # the PTG form of SUMMA's panel broadcasts.  Single-rank builds
        # skip the indirection and read the collection directly.
        p.task("RA", m=Range(0, mt - 1), k=Range(0, kt - 1)) \
            .affinity(lambda m, k, A=A: A(m, k)) \
            .flow("T", "READ",
                  IN(DATA(lambda m, k, A=A: A(m, k))),
                  OUT(TASK("GEMM", "Ai",
                           lambda m, k, NT=nt: [dict(m=m, n=n, k=k)
                                                for n in range(NT)]))) \
            .body(lambda: None)
        p.task("RB", k=Range(0, kt - 1), n=Range(0, nt - 1)) \
            .affinity(lambda k, n, B=B: B(k, n)) \
            .flow("T", "READ",
                  IN(DATA(lambda k, n, B=B: B(k, n))),
                  OUT(TASK("GEMM", "Bi",
                           lambda k, n, MT=mt: [dict(m=m, n=n, k=k)
                                                for m in range(MT)]))) \
            .body(lambda: None)
    if prescale:
        # one-time beta scaling of each C tile, feeding the k=0 step
        # (the reference harness folds beta the same way: the chain
        # itself is pure accumulation)
        sb = p.task("SCALE", m=Range(0, mt - 1), n=Range(0, nt - 1)) \
            .affinity(lambda m, n, C=C: C(m, n)) \
            .flow("Ci", "RW",
                  IN(DATA(lambda m, n, C=C: C(m, n))),
                  OUT(TASK("GEMM", "Ci", lambda m, n: dict(m=m, n=n, k=0))))
        if use_device:
            sb.body(_scale_kernel(beta), device=device)
        sb.body(lambda Ci: beta * np.asarray(Ci))
    tb = p.task("GEMM",
                m=Range(0, mt - 1), n=Range(0, nt - 1), k=Range(0, kt - 1)) \
        .affinity(lambda m, n, C=C: C(m, n)) \
        .priority(lambda k, KT=kt: KT - k) \
        .flow("Ai", "READ",
              IN(TASK("RA", "T", lambda m, k: dict(m=m, k=k)))
              if panel_bcast else
              IN(DATA(lambda m, k, A=A: A(m, k)))) \
        .flow("Bi", "READ",
              IN(TASK("RB", "T", lambda k, n: dict(k=k, n=n)))
              if panel_bcast else
              IN(DATA(lambda k, n, B=B: B(k, n)))) \
        .flow("Ci", "RW",
              IN(TASK("SCALE", "Ci", lambda m, n: dict(m=m, n=n)),
                 when=lambda k: k == 0) if prescale else
              IN(DATA(lambda m, n, C=C: C(m, n)),
                 when=lambda k: k == 0),
              IN(TASK("GEMM", "Ci", lambda m, n, k: dict(m=m, n=n, k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("GEMM", "Ci", lambda m, n, k: dict(m=m, n=n, k=k + 1)),
                  when=lambda k, KT=kt: k < KT - 1),
              OUT(DATA(lambda m, n, C=C: C(m, n)),
                  when=lambda k, KT=kt: k == KT - 1)) \
        .property("flops", flops_per_task)
    if use_device:
        tb.body(kernel, device=device)
    tb.body(cpu_body)
    return p.build()


def _scale_kernel(beta: float):
    key = ("scale", beta)
    fn = _kernels.get(key)
    if fn is None:
        def fn(Ci):
            return beta * Ci
        _kernels[key] = fn
    return fn


def total_flops(m: int, n: int, k: int) -> float:
    """Useful FLOPs of C[m,n] = A[m,k]@B[k,n] (2*M*N*K)."""
    return 2.0 * m * n * k
