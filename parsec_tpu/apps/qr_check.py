"""Numerical accounting for the tiled QR (apps/qr.py): the mp-QR
accuracy ladder (VERDICT r5 #9 — mirror of apps/potrf_check.py's
HPL-AI story for the dgeqrf-class driver).

The bench's factorization residual (||R^T R z - A^T A z|| / ||A^T A z||,
bench.py) bounds how good the FACTOR is: bf16 tile storage rounds R to
~bf16 epsilon, so the raw residual sits at the 1e-2/1e-3 class.  What
justifies low-precision storage is the same contract as potrf's
``refine_solve``: the factor is a fine PRECONDITIONER, and the accuracy
is recovered where it is consumed — the least-squares/linear solve.

``ls_refine`` solves A x = b through the corrected semi-normal
equations (CSNE; Björck's refinement for QR factors): with R from the
factorization,

    x_0     = R^{-1} R^{-T} (A^T b)
    r_k     = b - A x_k;   d_k = R^{-1} R^{-T} (A^T r_k);   x_{k+1} += d_k

every product in f32 at HIGHEST matmul precision and the triangular
solves on R's tile grid (vector RHS — O(n^2) per step).  Each step
contracts the error by ~the factor's relative error, so a bf16-storage
factor recovers f32-class solution accuracy in 1-3 steps.  The bench
records the per-step relative error history like potrf's
``ir_residuals``.

Operates on the CURRENT tile payloads of a factored TiledMatrix (R in
the upper block triangle; device arrays on the bench path, numpy under
CPU tests) plus a caller-supplied ``orig_tile(m, n)`` regenerating the
pre-factorization tile — nothing here needs a second resident copy.
"""

from __future__ import annotations

from typing import Callable, List

import numpy as np

_jit_cache = {}


def _kernels():
    import jax
    import jax.numpy as jnp
    k = _jit_cache.get("k")
    if k is None:
        hi = jax.lax.Precision.HIGHEST

        def mv(y, O, x):             # y += O @ x  (f32, HIGHEST)
            return y + jnp.matmul(O.astype(jnp.float32), x, precision=hi)

        def mtv(y, O, x):            # y += O^T @ x
            return y + jnp.matmul(O.astype(jnp.float32).T, x,
                                  precision=hi)

        def trsv(R, b, trans):
            # R upper triangular; trans solves R^T z = b
            from jax.scipy.linalg import solve_triangular
            return solve_triangular(R.astype(jnp.float32), b,
                                    lower=False, trans=1 if trans else 0)

        k = _jit_cache["k"] = {
            "mv": jax.jit(mv), "mtv": jax.jit(mtv),
            "trsv": jax.jit(trsv, static_argnames=("trans",)),
        }
    return k


def _tile(A, m, n):
    """Current newest payload of tile (m, n) — device array or numpy."""
    d = A.data_of(m, n)
    v = d.newest_version()
    for _sp, c in d.copies().items():
        if c.version == v and c.payload is not None:
            return c.payload
    c = d.pull_to_host()
    return c.payload


def _r_tile(A, i, j):
    import jax.numpy as jnp
    t = jnp.asarray(_tile(A, i, j)).astype(jnp.float32)
    return jnp.triu(t) if i == j else t


def _matvec(orig_tile, NT, x):
    """y = A @ x with A regenerated tile-wise."""
    import jax.numpy as jnp
    k = _kernels()
    y = [jnp.zeros_like(x[0], dtype=jnp.float32) for _ in range(NT)]
    for i in range(NT):
        for j in range(NT):
            y[i] = k["mv"](y[i], jnp.asarray(orig_tile(i, j)), x[j])
    return y


def _matvec_t(orig_tile, NT, x):
    """y = A^T @ x with A regenerated tile-wise."""
    import jax.numpy as jnp
    k = _kernels()
    y = [jnp.zeros_like(x[0], dtype=jnp.float32) for _ in range(NT)]
    for i in range(NT):
        for j in range(NT):
            y[j] = k["mtv"](y[j], jnp.asarray(orig_tile(i, j)), x[i])
    return y


def _rtr_solve(A, b):
    """z = R^{-1} R^{-T} b over the upper-block-triangular tile grid
    (vector RHS: O(n^2) tiled forward+backward substitution in f32)."""
    import jax.numpy as jnp
    k = _kernels()
    NT = A.mt
    # forward: R^T y = b  (R^T lower block triangular: R^T[i][j] =
    # R[j][i]^T, j <= i)
    y: List[object] = []
    for i in range(NT):
        rhs = b[i].astype(jnp.float32)
        for j in range(i):
            rhs = rhs - jnp.matmul(_r_tile(A, j, i).T, y[j])
        y.append(k["trsv"](_r_tile(A, i, i), rhs, trans=True))
    # backward: R x = y
    x: List[object] = [None] * NT
    for i in range(NT - 1, -1, -1):
        rhs = y[i]
        for j in range(i + 1, NT):
            rhs = rhs - jnp.matmul(_r_tile(A, i, j), x[j])
        x[i] = k["trsv"](_r_tile(A, i, i), rhs, trans=False)
    return x


def ls_refine(A, orig_tile: Callable[[int, int], object],
              steps: int = 3, seed: int = 0):
    """The mp-QR accuracy ladder: solve A x = b (b = A x_true for a
    deterministic random x_true, so the truth is known without storing
    Q) through CSNE with the factored R as preconditioner and ``steps``
    refinement rounds.  Returns the per-iterate relative error history
    ||x_k - x_true||_2 / ||x_true||_2 (entry 0 = the direct CSNE
    solve) — the geqrf analog of potrf's ``ir_residuals``."""
    import jax.numpy as jnp
    NT, mb = A.mt, A.mb
    rng = np.random.default_rng(seed)
    x_true = [jnp.asarray(rng.standard_normal(mb).astype(np.float32))
              for _ in range(NT)]
    tn = float(np.sqrt(sum(float(jnp.sum(t ** 2)) for t in x_true)))
    b = _matvec(orig_tile, NT, x_true)
    # x_0 via CSNE
    x = _rtr_solve(A, _matvec_t(orig_tile, NT, b))
    hist = []
    for it in range(steps + 1):
        en = float(np.sqrt(sum(
            float(jnp.sum((xx - tt) ** 2))
            for xx, tt in zip(x, x_true))))
        hist.append(en / max(tn, 1e-300))
        if it == steps:
            break
        ax = _matvec(orig_tile, NT, x)
        r = [bb - aa for bb, aa in zip(b, ax)]
        d = _rtr_solve(A, _matvec_t(orig_tile, NT, r))
        x = [xx + dd for xx, dd in zip(x, d)]
    return hist
