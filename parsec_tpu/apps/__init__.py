"""Linear-algebra / mini-app drivers built on the runtime.

The analog of the reference's tests/apps and of DPLASMA's tiled drivers
(reference: tests/dsl/dtd/dtd_test_simple_gemm.c, tests/apps/stencil/,
BASELINE.md north-star configs): each app builds a parameterized taskpool
over tiled-matrix collections with TPU incarnations and CPU fallbacks.
"""

from parsec_tpu.apps.gemm import gemm_taskpool  # noqa: F401
