"""Ring attention: the long-context flagship over the ring pipeline.

The reference has no attention dimension (SURVEY §5.7 — its dataflow
rings are the PRIMITIVE); this app is the TPU-first instantiation the
survey prescribes: sequence parallelism where each of P parties holds
one query block resident and the K/V blocks circulate the ring, with a
numerically-stable ONLINE-SOFTMAX accumulator (the flash-attention
recurrence) as the per-visit combine.  After P rounds every query block
has attended over the FULL sequence while only (2·Tkv·d)-sized KV
payloads ever moved — the classic ring-attention data movement, here as
a plain PTG over the runtime's neighbor-exchange schedule
(apps/ring.py), so it runs single-chip, over the multi-device ICI
preplace path, or across ranks on the comm engine unchanged.

Packing (everything rides two TiledMatrix collections):
- circulating block ``V(q)``: ``[K_q ; V_q]`` stacked — (2·Tkv, d)
- resident accumulator ``A(q)``: ``[Q_q | O | m | l]`` — (Tq, 2d+2)
  with the running output O, row-max m, and row-denominator l of the
  online softmax.  ``finalize`` unpacks O/l into the attention output.
"""

from __future__ import annotations

import numpy as np

from parsec_tpu.apps.ring import ring_pipeline_taskpool
from parsec_tpu.core.taskpool import ParameterizedTaskpool
from parsec_tpu.data.matrix import TiledMatrix


#: finite "minus infinity" for the running row-max: a literal -inf
#: would make the fully-masked causal case produce exp(-inf - -inf)
#: = exp(nan); masked probabilities are zeroed explicitly instead
_NEG = -1e30


def pack_query(Q: np.ndarray) -> np.ndarray:
    """Initial accumulator [Q | O=0 | m=-NEG | l=0] for one query block."""
    Tq, d = Q.shape
    acc = np.zeros((Tq, 2 * d + 2), np.float32)
    acc[:, :d] = Q
    acc[:, 2 * d] = _NEG
    return acc


def pack_kv(K: np.ndarray, V: np.ndarray) -> np.ndarray:
    return np.concatenate([K, V], axis=0).astype(np.float32)


def unpack_output(acc: np.ndarray, d: int) -> np.ndarray:
    """O / l — the softmax-normalized attention output."""
    o = acc[:, d:2 * d]
    l = acc[:, 2 * d + 1:2 * d + 2]
    return o / np.maximum(l, 1e-30)


def _combine(acc, blk, xp, mask=None):
    """One online-softmax visit: fold KV block ``blk`` into ``acc``
    (the flash-attention m/l/O recurrence, jax- and numpy-generic).
    ``mask`` (Tq, Tkv) of 0/1 zeroes disallowed probabilities — scores
    are shifted to _NEG AND p is multiplied by the mask, so a fully
    masked block is an exact no-op (l and O unchanged)."""
    d = (acc.shape[1] - 2) // 2
    Tkv = blk.shape[0] // 2
    q = acc[:, :d]
    o = acc[:, d:2 * d]
    m = acc[:, 2 * d]
    l = acc[:, 2 * d + 1]
    k = blk[:Tkv]
    v = blk[Tkv:]
    s = (q @ k.T) * (1.0 / np.sqrt(d))
    if mask is not None:
        s = s * mask + _NEG * (1.0 - mask)
    m_new = xp.maximum(m, s.max(axis=-1))
    p = xp.exp(s - m_new[:, None])
    if mask is not None:
        p = p * mask
    alpha = xp.exp(m - m_new)
    l_new = alpha * l + p.sum(axis=-1)
    o_new = alpha[:, None] * o + p @ v
    parts = [q, o_new, m_new[:, None], l_new[:, None]]
    return xp.concatenate(parts, axis=1)


def _combine_np(acc, blk):
    return _combine(np.asarray(acc, np.float32),
                    np.asarray(blk, np.float32), np)


def _combine_jax(acc, blk):
    import jax.numpy as jnp
    return _combine(acc.astype(jnp.float32), blk.astype(jnp.float32),
                    jnp)


# Causal masking rides the ring's VISIT CLASS (0 = block fully in the
# future: exact no-op; 1 = the diagonal block: lower triangle; 2 =
# fully in the past: unmasked).  With equal query/KV block lengths the
# diagonal mask is position-independent, so kernels compile once per
# CLASS — three variants total — instead of once per (q, t) pair, and
# wavefront launch fusion still groups same-class visits.


def _causal_visit_class(P):
    def vc(q, t):
        kv = (q - t) % P
        return 2 if kv < q else (1 if kv == q else 0)
    return vc


def _combine_np_causal(Tq, Tkv):
    diag = np.tril(np.ones((Tq, Tkv), np.float32))
    def fn(acc, blk, vc):
        a = np.asarray(acc, np.float32)
        if int(vc) == 0:
            return a
        mask = None if int(vc) == 2 else diag
        return _combine(a, np.asarray(blk, np.float32), np, mask)
    return fn


def _combine_jax_causal(Tq, Tkv):
    def fn(acc, blk, vc):
        import jax.numpy as jnp
        # vc is a STATIC kernel argument (task-local, 3 values): the
        # branch resolves at trace time into one of 3 compiled variants
        if int(vc) == 0:
            return acc.astype(jnp.float32)
        mask = None if int(vc) == 2 \
            else jnp.tril(jnp.ones((Tq, Tkv), jnp.float32))
        return _combine(acc.astype(jnp.float32),
                        blk.astype(jnp.float32), jnp, mask)
    return fn


def ring_attention_taskpool(KV: TiledMatrix, ACC: TiledMatrix,
                            device: str = "cpu",
                            causal: bool = False) -> ParameterizedTaskpool:
    """P-party ring attention: ``KV(q)`` are the circulating packed
    [K;V] blocks, ``ACC(q)`` the resident packed [Q|O|m|l] accumulators
    (fill with pack_query/pack_kv; read back with unpack_output).
    ``causal=True`` applies the global-position causal mask per visit
    (block skips and the diagonal triangle fall out of one arithmetic
    mask, so the ring schedule is unchanged)."""
    on_dev = device in ("tpu", "xla", "gpu")
    if causal:
        P = KV.mt
        Tkv = KV.mb // 2
        Tq = ACC.mb
        if Tq != Tkv:
            raise ValueError(
                "causal ring attention needs equal query/KV block "
                "lengths (the diagonal mask is then class-invariant)")
        combine = _combine_jax_causal(Tq, Tkv) if on_dev \
            else _combine_np_causal(Tq, Tkv)
        return ring_pipeline_taskpool(
            KV, ACC, combine=combine, device=device,
            visit_class=_causal_visit_class(P))
    combine = _combine_jax if on_dev else _combine_np
    return ring_pipeline_taskpool(KV, ACC, combine=combine,
                                  device=device)


def dense_reference(Q: np.ndarray, K: np.ndarray, V: np.ndarray,
                    causal: bool = False) -> np.ndarray:
    """Materialized-softmax attention over the full sequence."""
    d = Q.shape[1]
    s = (Q @ K.T) / np.sqrt(d)
    if causal:
        n = Q.shape[0]
        s = np.where(np.tril(np.ones((n, n), bool)), s, -np.inf)
    p = np.exp(s - s.max(axis=-1, keepdims=True))
    return (p / p.sum(axis=-1, keepdims=True)) @ V
