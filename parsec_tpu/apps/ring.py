"""Ring pipeline: neighbor-exchange dataflow (the sequence-parallel
primitive).

The reference has no attention/sequence dimension (SURVEY §5.7), but its
dataflow bcast trees are the primitive ring schedules are built from.
This app expresses the canonical ring exchange — each of P parties holds
a block, and over P rounds every block visits every party while a local
accumulator combines it — as a plain PTG.  That is exactly the data
movement of ring attention (KV blocks circulating past resident Q) and
of ring allreduce; ``combine`` is the per-visit operator (attention
scores, a sum, ...).

Placement: party q's tasks run where ``A(q)`` lives, so on P ranks every
edge is a neighbor hop on the interconnect (DCN via the comm engine;
multi-device single-host hops ride the ICI preplace path).  After the
pool completes, every party's accumulator has combined ALL blocks.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from parsec_tpu.core.taskpool import ParameterizedTaskpool
from parsec_tpu.data.matrix import TiledMatrix
from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK


def ring_pipeline_taskpool(V: TiledMatrix, A: TiledMatrix,
                           combine: Optional[Callable] = None,
                           device: str = "cpu") -> ParameterizedTaskpool:
    """Build the P-party ring: ``V(q)`` are the circulating blocks,
    ``A(q)`` the resident accumulators (initialized by the caller;
    updated as ``A(q) = combine(A(q), block)`` once per visiting block).
    Default ``combine`` is addition — the ring-allreduce instance."""
    P = V.mt
    if A.mt != P:
        raise ValueError("one accumulator per party")
    if combine is None:
        def combine(acc, blk):
            return np.asarray(acc) + np.asarray(blk)

    def body(B, Acc):
        return {"Acc": combine(Acc, B)}

    p = PTG("ring", P=P)
    # R(q, t): party q, round t.  Round 0 combines the party's OWN block
    # and launches it around the ring; round t receives the block that
    # started at party (q - t) mod P and forwards it until it has
    # visited everyone.
    tb = p.task("R", q=Range(0, P - 1), t=Range(0, P - 1)) \
        .affinity(lambda q, A=A: A(q)) \
        .priority(lambda t, P=P: P - t) \
        .flow("B", "READ",
              IN(DATA(lambda q, V=V: V(q)), when=lambda t: t == 0),
              IN(TASK("R", "B",
                      lambda q, t, P=P: dict(q=(q - 1) % P, t=t - 1)),
                 when=lambda t: t > 0),
              OUT(TASK("R", "B",
                       lambda q, t, P=P: dict(q=(q + 1) % P, t=t + 1)),
                  when=lambda t, P=P: t < P - 1)) \
        .flow("Acc", "RW",
              IN(DATA(lambda q, A=A: A(q)), when=lambda t: t == 0),
              IN(TASK("R", "Acc", lambda q, t: dict(q=q, t=t - 1)),
                 when=lambda t: t > 0),
              OUT(TASK("R", "Acc", lambda q, t: dict(q=q, t=t + 1)),
                  when=lambda t, P=P: t < P - 1),
              OUT(DATA(lambda q, A=A: A(q)),
                  when=lambda t, P=P: t == P - 1))
    if device in ("tpu", "xla", "gpu"):
        def kernel(B, Acc):
            return combine(Acc, B)
        tb.body(kernel, device=device)
    tb.body(body)
    return p.build()
