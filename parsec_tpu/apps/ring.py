"""Ring pipeline: neighbor-exchange dataflow (the sequence-parallel
primitive).

The reference has no attention/sequence dimension (SURVEY §5.7), but its
dataflow bcast trees are the primitive ring schedules are built from.
This app expresses the canonical ring exchange — each of P parties holds
a block, and over P rounds every block visits every party while a local
accumulator combines it — as a plain PTG.  That is exactly the data
movement of ring attention (KV blocks circulating past resident Q) and
of ring allreduce; ``combine`` is the per-visit operator (attention
scores, a sum, ...).

Placement: party q's tasks run where ``A(q)`` lives, so on P ranks every
edge is a neighbor hop on the interconnect (DCN via the comm engine;
multi-device single-host hops ride the ICI preplace path).  After the
pool completes, every party's accumulator has combined ALL blocks.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from parsec_tpu.core.taskpool import ParameterizedTaskpool
from parsec_tpu.data.matrix import TiledMatrix
from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK


def ring_pipeline_taskpool(V: TiledMatrix, A: TiledMatrix,
                           combine: Optional[Callable] = None,
                           device: str = "cpu",
                           visit_class: Optional[Callable] = None
                           ) -> ParameterizedTaskpool:
    """Build the P-party ring: ``V(q)`` are the circulating blocks,
    ``A(q)`` the resident accumulators (initialized by the caller;
    updated as ``A(q) = combine(A(q), block)`` once per visiting block).
    Default ``combine`` is addition — the ring-allreduce instance.

    Position-dependent operators: a combine whose parameters are
    literally named ``(acc, blk, q, t)`` receives the party and round
    indices ((q - t) mod P recovers which block is visiting).  For
    DEVICE combines prefer declaring ``(acc, blk, vc)`` with a
    ``visit_class(q, t) -> small int``: the class rides as a derived
    task parameter, so the kernel compiles once per CLASS (e.g. 3
    causal variants) instead of once per (q, t) pair — per-(q,t)
    statics would defeat wavefront launch fusion and trigger P^2
    recompiles."""
    P = V.mt
    if A.mt != P:
        raise ValueError("one accumulator per party")
    if combine is None:
        def combine(acc, blk):
            return np.asarray(acc) + np.asarray(blk)

    # protocol detection by parameter NAMES (arity would be spoofed by
    # unrelated optional params — a 2-ary combine with two kwargs must
    # not silently receive q/t)
    import inspect
    pnames = list(inspect.signature(combine).parameters)
    wants_vc = "vc" in pnames
    wants_pos = (not wants_vc) and "q" in pnames and "t" in pnames
    if wants_vc and visit_class is None:
        raise ValueError("a combine declaring 'vc' needs visit_class=")

    if wants_vc:
        def body(B, Acc, vc):
            return {"Acc": combine(Acc, B, vc)}
    elif wants_pos:
        def body(B, Acc, q, t):
            return {"Acc": combine(Acc, B, q, t)}
    else:
        def body(B, Acc):
            return {"Acc": combine(Acc, B)}

    p = PTG("ring", P=P)
    # R(q, t): party q, round t.  Round 0 combines the party's OWN block
    # and launches it around the ring; round t receives the block that
    # started at party (q - t) mod P and forwards it until it has
    # visited everyone.  ``vc`` (when requested) is a derived 1-value
    # parameter — the JDF derived-local idiom — so it lands in
    # task.locals and binds to kernels by name.
    params = dict(q=Range(0, P - 1), t=Range(0, P - 1))
    if wants_vc:
        params["vc"] = Range(lambda q, t: int(visit_class(q, t)),
                             lambda q, t: int(visit_class(q, t)))
    tb = p.task("R", **params)
    if wants_vc:
        # dep expressions name peers by (q, t) alone — vc is derived,
        # so it must not participate in the task key
        tb.make_key(lambda q, t: (q, t))
    tb = tb \
        .affinity(lambda q, A=A: A(q)) \
        .priority(lambda t, P=P: P - t) \
        .flow("B", "READ",
              IN(DATA(lambda q, V=V: V(q)), when=lambda t: t == 0),
              IN(TASK("R", "B",
                      lambda q, t, P=P: dict(q=(q - 1) % P, t=t - 1)),
                 when=lambda t: t > 0),
              OUT(TASK("R", "B",
                       lambda q, t, P=P: dict(q=(q + 1) % P, t=t + 1)),
                  when=lambda t, P=P: t < P - 1)) \
        .flow("Acc", "RW",
              IN(DATA(lambda q, A=A: A(q)), when=lambda t: t == 0),
              IN(TASK("R", "Acc", lambda q, t: dict(q=q, t=t - 1)),
                 when=lambda t: t > 0),
              OUT(TASK("R", "Acc", lambda q, t: dict(q=q, t=t + 1)),
                  when=lambda t, P=P: t < P - 1),
              OUT(DATA(lambda q, A=A: A(q)),
                  when=lambda t, P=P: t == P - 1))
    if device in ("tpu", "xla", "gpu"):
        if wants_vc:
            def kernel(B, Acc, vc):
                return combine(Acc, B, vc)
        elif wants_pos:
            def kernel(B, Acc, q, t):
                return combine(Acc, B, q, t)
        else:
            def kernel(B, Acc):
                return combine(Acc, B)
        tb.body(kernel, device=device)
    tb.body(body)
    return p.build()
