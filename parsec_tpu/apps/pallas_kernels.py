"""Pallas TPU kernels for the hot tile operations.

The runtime's device bodies are ordinarily single fused XLA ops (jnp
matmul & friends) — XLA already schedules those onto the MXU well.  This
module provides hand-written Pallas alternatives for the hottest tile
op, the GEMM accumulate step, demonstrating the kernel seam the
reference fills with cuBLAS/user CUDA kernels (reference: the BODY
[type=CUDA] incarnations; SURVEY §7 "tile kernels as Pallas/XLA
computations"):

- a blocked ``Ci + alpha * Ai @ Bi`` with a VMEM f32 accumulator and a
  K-innermost grid, bf16/f32 inputs straight onto the MXU;
- selection via ``--mca gemm_pallas 1`` (apps/gemm.py consults it), or
  call :func:`pallas_gemm_tile` directly as a PTG/DTD device body.

Off-TPU the kernels run in interpreter mode so the same tests cover CPU
CI; shapes that do not tile evenly fall back to the fused-XLA path.

Measured (v5e, 4096-tile GEMM through the runtime): the Pallas blocked
kernel sustains ~36 TFLOP/s vs ~48 for the fused XLA matmul — XLA's MXU
pipeline wins for plain GEMM, so it stays the default; the Pallas path
is the seam for ops XLA does NOT fuse well (custom epilogues, quantized
accumulation), selected per-kernel.
"""

from __future__ import annotations

import functools
from typing import Optional

from parsec_tpu.utils.mca import params

params.register("gemm_pallas", 0,
                "use the hand-written Pallas GEMM tile kernel instead of "
                "the fused XLA matmul")


def _interpret() -> bool:
    import jax
    # "axon" is the tunneled-TPU PJRT platform name (devices/xla.py)
    return jax.devices()[0].platform not in ("tpu", "axon")


@functools.lru_cache(maxsize=None)
def _blocked_matmul(alpha: float, bm: int, bn: int, bk: int,
                    interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    scratch = [pltpu.VMEM((bm, bn), jnp.float32)]

    def kernel(a_ref, b_ref, c_ref, o_ref, acc_ref):
        k = pl.program_id(2)
        nk = pl.num_programs(2)

        @pl.when(k == 0)
        def _init():
            acc_ref[:, :] = c_ref[:, :].astype(jnp.float32)

        prod = jax.lax.dot_general(
            a_ref[:, :], b_ref[:, :], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_ref[:, :] += prod if alpha == 1.0 else alpha * prod

        @pl.when(k == nk - 1)
        def _fin():
            o_ref[:, :] = acc_ref[:, :].astype(o_ref.dtype)

    def run(Ai, Bi, Ci):
        m, kk = Ai.shape
        _, n = Bi.shape
        grid = (m // bm, n // bn, kk // bk)
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
                pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
                pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
            ],
            out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
            out_shape=jax.ShapeDtypeStruct(Ci.shape, Ci.dtype),
            scratch_shapes=scratch,
            interpret=interpret,
        )(Ai, Bi, Ci)

    return run


def pallas_gemm_tile(alpha: float = 1.0, bm: int = 512, bn: int = 512,
                     bk: int = 512, precision=None):
    """A device-body kernel ``fn(Ai, Bi, Ci) -> Ci + alpha*Ai@Bi`` run as
    a blocked Pallas program (f32 VMEM accumulator, K-innermost grid).

    The Pallas path requires MXU-aligned shapes: every dimension must be
    a multiple of 128 AND divide by the (clamped) block sizes — Mosaic
    rejects unaligned blocks at compile time.  Anything else falls back
    to the fused XLA matmul with the same semantics (``precision``
    honored there exactly as in the default kernel)."""

    def fn(Ai, Bi, Ci):
        import jax.numpy as jnp
        m, kk = Ai.shape
        _, n = Bi.shape
        cbm, cbn, cbk = min(bm, m), min(bn, n), min(bk, kk)
        aligned = all(d % 128 == 0 for d in (m, n, kk))
        if not aligned or m % cbm or n % cbn or kk % cbk:
            acc = jnp.matmul(Ai, Bi, precision=precision,
                             preferred_element_type=Ci.dtype)
            return Ci + (acc if alpha == 1.0 else alpha * acc)
        return _blocked_matmul(alpha, cbm, cbn, cbk, _interpret())(
            Ai, Bi, Ci)

    fn.__name__ = f"pallas_gemm_a{alpha}"
    return fn


def use_pallas_gemm() -> bool:
    try:
        return bool(int(params.get("gemm_pallas", 0)))
    except (TypeError, ValueError):
        return False


# ---------------------------------------------------------------------------
# blocked Gram kernel: the HIGHEST-precision hot spot of the
# inner-blocked QR panels (apps/qr.py _cholqr2 — G = X^T X of an
# mb x ib column block, computed per ib-block of every GEQRT/TSQRT)
# ---------------------------------------------------------------------------

params.register("qr_pallas_gram", 0,
                "use the hand-written Pallas blocked Gram kernel for "
                "the inner-blocked QR panel construction (apps/qr.py) "
                "instead of the fused XLA matmul")


@functools.lru_cache(maxsize=None)
def _blocked_gram(bn: int, bk: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    scratch = [pltpu.VMEM((bn, bn), jnp.float32)]

    def kernel(xi_ref, xj_ref, o_ref, acc_ref):
        k = pl.program_id(2)
        nk = pl.num_programs(2)

        @pl.when(k == 0)
        def _init():
            acc_ref[:, :] = jnp.zeros_like(acc_ref)

        # X_i^T X_j with f32 accumulation; HIGHEST so the Gram matrix —
        # the cond^2-sensitive input of the panel Cholesky — never rides
        # the MXU's bf16 passes (apps/qr.py precision discipline)
        acc_ref[:, :] += jax.lax.dot_general(
            xi_ref[:, :], xj_ref[:, :], (((0,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32)

        @pl.when(k == nk - 1)
        def _fin():
            o_ref[:, :] = acc_ref[:, :].astype(o_ref.dtype)

    def run(X):
        m, n = X.shape
        grid = (n // bn, n // bn, m // bk)
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((bk, bn), lambda i, j, k: (k, i)),
                pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
            ],
            out_specs=pl.BlockSpec((bn, bn), lambda i, j, k: (i, j)),
            out_shape=jax.ShapeDtypeStruct((n, n), jnp.float32),
            scratch_shapes=scratch,
            interpret=interpret,
        )(X, X)

    return run


def pallas_gram_tile(bn: int = 256, bk: int = 512):
    """``fn(X) -> X^T X`` (f32, HIGHEST) as a blocked Pallas program:
    K-innermost grid over X's rows with an f32 VMEM accumulator, the
    same shape discipline as :func:`pallas_gemm_tile`.  Unaligned
    shapes fall back to the fused XLA matmul with identical
    semantics."""

    def fn(X):
        import jax
        import jax.numpy as jnp
        m, n = X.shape
        cbn, cbk = min(bn, n), min(bk, m)
        aligned = m % 128 == 0 and n % 128 == 0
        if not aligned or m % cbk or n % cbn:
            return jnp.matmul(X.T, X,
                              precision=jax.lax.Precision.HIGHEST,
                              preferred_element_type=jnp.float32)
        return _blocked_gram(cbn, cbk, _interpret())(X)

    return fn


def use_pallas_qr_gram() -> bool:
    try:
        return bool(int(params.get("qr_pallas_gram", 0)))
    except (TypeError, ValueError):
        return False
