"""Redistribution: move a tiled matrix between two distributions.

Rebuild of the reference's generic redistribution (reference:
parsec/data_dist/matrix/redistribute/redistribute_dtd.c — a DTD-driven
copy of every tile from a source collection/distribution to a target
one; tests/collections/redistribute).  Same tile grid, arbitrary rank
mappings: a reader task at each source owner ships the tile through a
dataflow edge to a writer task at the target owner.
"""

from __future__ import annotations

import numpy as np

from parsec_tpu.core.taskpool import ParameterizedTaskpool
from parsec_tpu.data.matrix import TiledMatrix
from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK


def redistribute_taskpool(S: TiledMatrix,
                          T: TiledMatrix) -> ParameterizedTaskpool:
    """Copy S into T (matching tile grids, any rank mappings)."""
    if (S.mt, S.nt) != (T.mt, T.nt) or (S.mb, S.nb) != (T.mb, T.nb):
        raise ValueError("redistribute requires matching tile grids")
    p = PTG("redistribute", MT=S.mt, NT=S.nt)
    p.task("R", m=Range(0, S.mt - 1), n=Range(0, S.nt - 1)) \
        .affinity(lambda m, n, S=S: S(m, n)) \
        .flow("X", "READ",
              IN(DATA(lambda m, n, S=S: S(m, n))),
              OUT(TASK("W", "X", lambda m, n: dict(m=m, n=n)))) \
        .body(lambda: None)
    p.task("W", m=Range(0, S.mt - 1), n=Range(0, S.nt - 1)) \
        .affinity(lambda m, n, T=T: T(m, n)) \
        .flow("X", "READ", IN(TASK("R", "X", lambda m, n: dict(m=m, n=n)))) \
        .flow("O", "RW",
              IN(DATA(lambda m, n, T=T: T(m, n))),
              OUT(DATA(lambda m, n, T=T: T(m, n)))) \
        .body(lambda X: {"O": np.asarray(X)})
    return p.build()
