"""Numerical accounting for the tiled Cholesky (apps/potrf.py).

Two checks the bench publishes alongside the GFLOP/s number:

- ``backward_error``: the exact normwise backward error
  ||A - L L^T||_F / ||A||_F over the factored tile grid, computed
  tile-wise on device (one f32-accumulated matmul per (i,j,k) triple —
  n^3 flops, a ~3x-the-factorization one-off).  This is the bound the
  mixed-precision (bf16-storage) mode must report to claim anything:
  bf16 storage rounds every intermediate tile, so the factor's backward
  error sits at bf16 epsilon (~4e-3), not f32 (~6e-8).

- ``refine_solve``: the HPL-AI-style justification for the mp mode
  (reference metric context: BASELINE.json names DPLASMA dpotrf; the
  HPL-AI benchmark's contract is "factor in low precision, recover
  accuracy by iterative refinement on the solve").  Solves A x = b with
  the (possibly bf16) factor as the preconditioner of a fixed-point
  refinement iteration run in f32: x += (LL^T)^{-1} (b - A x).  Each
  step contracts the error by ~the factor's backward error, so a bf16
  factor reaches f32-class solution accuracy in 2-4 steps, at O(n^2)
  cost per step.

Both operate on the CURRENT tile payloads of a factored TiledMatrix
(device arrays on the bench path, numpy under the CPU tests) plus a
caller-supplied ``orig_tile(m, n)`` regenerating the pre-factorization
tile, so nothing here needs a second resident copy of A.
"""

from __future__ import annotations

from typing import Callable, List

import numpy as np

_jit_cache = {}


def _kernels():
    import jax
    import jax.numpy as jnp
    k = _jit_cache.get("k")
    if k is None:
        # f32-accumulated residual accumulation: R -= L1 @ L2^T.
        # HIGHEST precision so the CHECK itself does not round through
        # bf16 passes on TPU — the measurement must be sharper than the
        # error it measures (inputs upcast to f32 first).
        def acc(R, L1, L2):
            return R - jnp.matmul(L1.astype(jnp.float32),
                                  L2.astype(jnp.float32).T,
                                  precision=jax.lax.Precision.HIGHEST)

        def symm(O):
            o = O.astype(jnp.float32)
            return jnp.tril(o) + jnp.tril(o, -1).T

        def sqn(R):
            return jnp.sum(R.astype(jnp.float32) ** 2)

        def mv(y, O, x):             # y += O @ x  (f32)
            return y + jnp.matmul(O.astype(jnp.float32), x,
                                  precision=jax.lax.Precision.HIGHEST)

        def mtv(y, O, x):            # y += O^T @ x
            return y + jnp.matmul(O.astype(jnp.float32).T, x,
                                  precision=jax.lax.Precision.HIGHEST)

        def trsv(L, b, lower, trans):
            from jax.scipy.linalg import solve_triangular
            return solve_triangular(L.astype(jnp.float32), b,
                                    lower=lower, trans=1 if trans else 0)

        k = _jit_cache["k"] = {
            "acc": jax.jit(acc), "symm": jax.jit(symm),
            "sqn": jax.jit(sqn), "mv": jax.jit(mv), "mtv": jax.jit(mtv),
            "trsv": jax.jit(trsv, static_argnames=("lower", "trans")),
        }
    return k


def _tile(A, m, n):
    """Current newest payload of tile (m, n) — device array or numpy."""
    d = A.data_of(m, n)
    v = d.newest_version()
    for _sp, c in d.copies().items():
        if c.version == v and c.payload is not None:
            return c.payload
    c = d.pull_to_host()
    return c.payload


def backward_error(A, orig_tile: Callable[[int, int], object]) -> float:
    """Exact ||A - L L^T||_F / ||A||_F over the lower triangle of the
    factored tile grid (the effective symmetric A: lower tiles as
    generated, diagonal tiles symmetrized from their lower triangle —
    Cholesky never read anything else)."""
    import jax.numpy as jnp
    k = _kernels()
    NT = A.mt
    num = 0.0
    den = 0.0

    def L_of(i, j):
        # diagonal factor tiles are lower-triangularized ON USE (the
        # tile's upper triangle holds stale A values chol never wrote);
        # no f32 copies are cached — at bench scale (nt=16, mb=6144)
        # cached trils would cost GBs of HBM next to the resident grid
        t = jnp.asarray(_tile(A, i, j))
        return jnp.tril(t.astype(jnp.float32)) if i == j else t

    for i in range(NT):
        for j in range(i + 1):
            O = jnp.asarray(orig_tile(i, j))
            A0 = k["symm"](O) if i == j else O.astype(jnp.float32)
            den += float(k["sqn"](A0))
            if i != j:
                den += float(k["sqn"](A0))    # the mirrored upper tile
            R = A0
            for kk in range(j + 1):
                R = k["acc"](R, L_of(i, kk), L_of(j, kk))
            s = float(k["sqn"](R))
            num += s if i == j else 2.0 * s
    return float(np.sqrt(num) / max(np.sqrt(den), 1e-300))


def _solve_factored(A, b_blocks):
    """x = (L L^T)^{-1} b via tiled forward+backward substitution in f32
    (diagonal trsv per tile, matvec updates — O(n^2))."""
    k = _kernels()
    NT = A.mt
    # forward: L y = b
    import jax.numpy as jnp
    y: List[object] = []
    for i in range(NT):
        rhs = b_blocks[i].astype(jnp.float32)
        for j in range(i):
            rhs = rhs - jnp.matmul(
                jnp.asarray(_tile(A, i, j)).astype(jnp.float32), y[j])
        y.append(k["trsv"](jnp.tril(
            jnp.asarray(_tile(A, i, i)).astype(jnp.float32)), rhs,
            lower=True, trans=False))
    # backward: L^T x = y
    x: List[object] = [None] * NT
    for i in range(NT - 1, -1, -1):
        rhs = y[i]
        for j in range(i + 1, NT):
            rhs = rhs - jnp.matmul(
                jnp.asarray(_tile(A, j, i)).astype(jnp.float32).T, x[j])
        x[i] = k["trsv"](jnp.tril(
            jnp.asarray(_tile(A, i, i)).astype(jnp.float32)), rhs,
            lower=True, trans=True)
    return x


def _matvec(orig_tile, NT, x_blocks):
    """y = A_eff @ x with the effective symmetric A regenerated tile-wise
    (lower tiles + symmetrized diagonal + mirrored upper)."""
    import jax.numpy as jnp
    k = _kernels()
    y = [jnp.zeros_like(x_blocks[0], dtype=jnp.float32)
         for _ in range(NT)]
    for i in range(NT):
        for j in range(i + 1):
            O = jnp.asarray(orig_tile(i, j))
            if i == j:
                y[i] = k["mv"](y[i], k["symm"](O), x_blocks[i])
            else:
                y[i] = k["mv"](y[i], O, x_blocks[j])
                y[j] = k["mtv"](y[j], O, x_blocks[i])
    return y


def refine_solve(A, orig_tile: Callable[[int, int], object],
                 steps: int = 3, seed: int = 0):
    """Solve A x = b with the factored tiles as preconditioner and
    ``steps`` rounds of f32 iterative refinement.  Returns the list of
    normwise relative residuals ||b - A x||_2 / ||b||_2, one entry per
    iterate (entry 0 = the direct solve with the factor)."""
    import jax.numpy as jnp
    NT, mb = A.mt, A.mb
    rng = np.random.default_rng(seed)
    b = [jnp.asarray(rng.standard_normal(mb).astype(np.float32))
         for _ in range(NT)]
    bn = float(np.sqrt(sum(float(jnp.sum(bb ** 2)) for bb in b)))
    x = _solve_factored(A, b)
    hist = []
    for it in range(steps + 1):
        ax = _matvec(orig_tile, NT, x)
        r = [bb - aa for bb, aa in zip(b, ax)]
        rn = float(np.sqrt(sum(float(jnp.sum(rr ** 2)) for rr in r)))
        hist.append(rn / max(bn, 1e-300))
        if it == steps:
            break            # the last residual is recorded; a further
                             # solve+update would never be observed
        dx = _solve_factored(A, r)
        x = [xx + dd for xx, dd in zip(x, dx)]
    return hist
