"""Leveled, multiplexed output/debug streams.

Rebuild of the reference's output and debug facilities
(reference: parsec/utils/output.c, parsec/utils/debug.c, utils/colors.c):
numbered output streams with independent verbosity, optional color, optional
per-stream files, plus the ``fatal`` / ``warning`` / ``inform`` /
``debug_verbose`` entry points.  Verbosity is driven by MCA params
(``debug_verbose``, ``debug_color``) so ``--mca debug_verbose 10`` works like
the reference.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, TextIO

from parsec_tpu.utils.mca import params

params.register("debug_verbose", 1, "global debug verbosity (0=errors only)")
params.register("debug_color", True, "colorize terminal output")
params.register("debug_history", 64, "debug-mark ring buffer size (0=off)")

_COLORS = {
    "fatal": "\x1b[1;31m", "warning": "\x1b[33m", "inform": "\x1b[36m",
    "debug": "\x1b[2m", "reset": "\x1b[0m",
}


@dataclass
class OutputStream:
    """One multiplexed output stream (reference: parsec_output_stream_t)."""
    stream_id: int
    prefix: str = ""
    verbosity: int = 1
    file: Optional[TextIO] = None
    want_stderr: bool = True

    def close(self):
        if self.file is not None and self.file not in (sys.stdout, sys.stderr):
            self.file.close()
            self.file = None


class Output:
    def __init__(self):
        self._lock = threading.Lock()
        self._streams: Dict[int, OutputStream] = {
            0: OutputStream(stream_id=0, verbosity=params.get("debug_verbose", 1))
        }
        self._next_id = 1
        self.rank = 0  # stamped by the comm layer at init

    # -- stream management (parsec_output_open/close/set_verbosity) ------
    def open(self, prefix: str = "", verbosity: int = 1,
             filename: Optional[str] = None) -> int:
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            f = open(filename, "a") if filename else None
            self._streams[sid] = OutputStream(stream_id=sid, prefix=prefix,
                                              verbosity=verbosity, file=f)
            return sid

    def close(self, sid: int) -> None:
        with self._lock:
            s = self._streams.pop(sid, None)
        if s:
            s.close()

    def set_verbosity(self, sid: int, level: int) -> None:
        with self._lock:
            if sid in self._streams:
                self._streams[sid].verbosity = level

    def get_verbosity(self, sid: int) -> int:
        with self._lock:
            s = self._streams.get(sid)
            return s.verbosity if s else -1

    # -- emit ------------------------------------------------------------
    def emit(self, sid: int, level: int, kind: str, msg: str) -> None:
        with self._lock:
            s = self._streams.get(sid) or self._streams[0]
            if level > s.verbosity:
                return
            target = s.file if s.file else (sys.stderr if s.want_stderr else sys.stdout)
            use_color = (params.get("debug_color", True)
                         and getattr(target, "isatty", lambda: False)())
            c0 = _COLORS.get(kind, "") if use_color else ""
            c1 = _COLORS["reset"] if use_color and c0 else ""
            stamp = time.strftime("%H:%M:%S")
            line = (f"{c0}[{stamp}][R{self.rank}]"
                    f"{('[' + s.prefix + ']') if s.prefix else ''}"
                    f"[{kind[0].upper()}] {msg}{c1}\n")
            target.write(line)
            target.flush()
        _history.record(kind, msg)


output = Output()


# ---------------------------------------------------------------------------
# Debug-history ring buffer (reference: parsec/utils/debug_marks, debug.c
# PARSEC_DEBUG_HISTORY) — cheap always-on marks dumpable post-mortem.
# ---------------------------------------------------------------------------

class _DebugHistory:
    def __init__(self):
        self._lock = threading.Lock()
        self._ring = []
        self._pos = 0

    def record(self, kind: str, msg: str) -> None:
        size = params.get("debug_history", 64)
        if not size:
            return
        with self._lock:
            entry = (time.time(), threading.get_ident(), kind, msg)
            if len(self._ring) < size:
                self._ring.append(entry)
            else:
                self._ring[self._pos % size] = entry
            self._pos += 1

    def mark(self, msg: str) -> None:
        self.record("mark", msg)

    def dump(self) -> list:
        with self._lock:
            size = len(self._ring)
            if size == 0:
                return []
            start = self._pos % size if self._pos > size else 0
            return self._ring[start:] + self._ring[:start]


_history = _DebugHistory()
debug_history = _history


# -- reference-style entry points -------------------------------------------

class FatalError(RuntimeError):
    pass


def fatal(msg: str, *args) -> None:
    """parsec_fatal: unrecoverable — raises instead of abort()."""
    m = msg % args if args else msg
    output.emit(0, 0, "fatal", m)
    raise FatalError(m)


def warning(msg: str, *args) -> None:
    output.emit(0, 0, "warning", msg % args if args else msg)


def inform(msg: str, *args) -> None:
    output.emit(0, 1, "inform", msg % args if args else msg)


def debug_verbose(level: int, msg: str, *args, stream: int = 0) -> None:
    output.emit(stream, level, "debug", msg % args if args else msg)
