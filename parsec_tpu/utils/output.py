"""Leveled, multiplexed output/debug streams.

Rebuild of the reference's output and debug facilities
(reference: parsec/utils/output.c, parsec/utils/debug.c, utils/colors.c):
numbered output streams with independent verbosity, optional color, optional
per-stream files, plus the ``fatal`` / ``warning`` / ``inform`` /
``debug_verbose`` entry points.  Verbosity is driven by MCA params
(``debug_verbose``, ``debug_color``) so ``--mca debug_verbose 10`` works like
the reference.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, TextIO

from parsec_tpu.utils.mca import params

params.register("debug_verbose", 1, "global debug verbosity (0=errors only)")
params.register("debug_color", True, "colorize terminal output")
# (ring size and tier live in utils/debug_history: debug_history_size,
# debug_paranoid)

_COLORS = {
    "fatal": "\x1b[1;31m", "warning": "\x1b[33m", "inform": "\x1b[36m",
    "debug": "\x1b[2m", "reset": "\x1b[0m",
}


@dataclass
class OutputStream:
    """One multiplexed output stream (reference: parsec_output_stream_t)."""
    stream_id: int
    prefix: str = ""
    verbosity: int = 1
    file: Optional[TextIO] = None
    want_stderr: bool = True

    def close(self):
        if self.file is not None and self.file not in (sys.stdout, sys.stderr):
            self.file.close()
            self.file = None


class Output:
    def __init__(self):
        self._lock = threading.Lock()
        self._streams: Dict[int, OutputStream] = {
            0: OutputStream(stream_id=0, verbosity=params.get("debug_verbose", 1))
        }
        self._next_id = 1
        self.rank = 0  # stamped by the comm layer at init

    # -- stream management (parsec_output_open/close/set_verbosity) ------
    def open(self, prefix: str = "", verbosity: int = 1,
             filename: Optional[str] = None) -> int:
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            f = open(filename, "a") if filename else None
            self._streams[sid] = OutputStream(stream_id=sid, prefix=prefix,
                                              verbosity=verbosity, file=f)
            return sid

    def close(self, sid: int) -> None:
        with self._lock:
            s = self._streams.pop(sid, None)
        if s:
            s.close()

    def set_verbosity(self, sid: int, level: int) -> None:
        with self._lock:
            if sid in self._streams:
                self._streams[sid].verbosity = level

    def get_verbosity(self, sid: int) -> int:
        with self._lock:
            s = self._streams.get(sid)
            return s.verbosity if s else -1

    # -- emit ------------------------------------------------------------
    def emit(self, sid: int, level: int, kind: str, msg: str) -> None:
        with self._lock:
            s = self._streams.get(sid) or self._streams[0]
            if level > s.verbosity:
                return
            target = s.file if s.file else (sys.stderr if s.want_stderr else sys.stdout)
            use_color = (params.get("debug_color", True)
                         and getattr(target, "isatty", lambda: False)())
            c0 = _COLORS.get(kind, "") if use_color else ""
            c1 = _COLORS["reset"] if use_color and c0 else ""
            stamp = time.strftime("%H:%M:%S")
            line = (f"{c0}[{stamp}][R{self.rank}]"
                    f"{('[' + s.prefix + ']') if s.prefix else ''}"
                    f"[{kind[0].upper()}] {msg}{c1}\n")
            target.write(line)
            target.flush()
        _history.record(kind, msg)


output = Output()


# ---------------------------------------------------------------------------
# Debug-history ring buffer (reference: parsec/utils/debug_marks, debug.c
# PARSEC_DEBUG_HISTORY) — cheap always-on marks dumpable post-mortem.
# ---------------------------------------------------------------------------

class _DebugHistory:
    """Back-compat facade over utils.debug_history — ONE ring for output
    lines and protocol marks (lazy import: mca <-> output cycle)."""

    def record(self, kind: str, msg: str) -> None:
        from parsec_tpu.utils.debug_history import mark
        mark("%s: %s", kind, msg)

    def mark(self, msg: str) -> None:
        from parsec_tpu.utils.debug_history import mark
        mark("%s", msg)

    def dump(self) -> list:
        from parsec_tpu.utils.debug_history import dump_history
        return dump_history()


_history = _DebugHistory()
debug_history = _history


# -- reference-style entry points -------------------------------------------

class FatalError(RuntimeError):
    pass


def fatal(msg: str, *args) -> None:
    """parsec_fatal: unrecoverable — raises instead of abort()."""
    m = msg % args if args else msg
    output.emit(0, 0, "fatal", m)
    raise FatalError(m)


def warning(msg: str, *args) -> None:
    output.emit(0, 0, "warning", msg % args if args else msg)


def inform(msg: str, *args) -> None:
    output.emit(0, 1, "inform", msg % args if args else msg)


def debug_verbose(level: int, msg: str, *args, stream: int = 0) -> None:
    output.emit(stream, level, "debug", msg % args if args else msg)


# -- templated help/error texts (reference: utils/show_help.{c,h}) ----------

#: topic -> template; ``register_help`` lets components ship their own
#: texts the way the reference installs help-*.txt files
_help_topics = {
    "no-comm-engine": (
        "A task has successors on other ranks but no comm engine is\n"
        "attached to this context.  Wire a SocketCE + RemoteDepEngine\n"
        "(see parsec_tpu.comm.launch.run_distributed) before adding\n"
        "distributed taskpools."),
    "device-oom": (
        "The device HBM budget ({budget} MiB) cannot hold a {nbytes}-byte\n"
        "tile while every resident copy is pinned.  Raise --mca\n"
        "device_mem_mb, shrink tiles, or lower device_inflight_depth."),
    "scheduler-unknown": (
        "Unknown scheduler component {name!r}; available: {available}."),
}


def register_help(topic: str, template: str) -> None:
    _help_topics[topic] = template


def show_help(topic: str, *, warn: bool = True, **kwargs) -> str:
    """Emit a templated help text (reference: parsec_show_help): returns
    the formatted message and, by default, prints it as a warning."""
    template = _help_topics.get(topic)
    if template is None:
        text = f"(no help text for topic {topic!r}; args: {kwargs})"
    else:
        try:
            text = template.format(**kwargs)
        except (KeyError, IndexError):
            text = f"{template}  [unformatted args: {kwargs}]"
    if warn:
        output.emit(0, 0, "help", f"[{topic}]\n{text}")
    return text
