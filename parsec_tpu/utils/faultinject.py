"""Seeded fault-plan engine: deterministic failure injection.

The PR 3/4 flakes taught the usual lesson: load-sensitive races are
observable with the causal tracer but not reproducible on demand.  This
module turns "flake we wait for" into "fault plan we replay" — a seeded,
MCA-configured plan of comm/task/device faults with named hook points
compiled to near-zero-cost checks when no plan is armed (every hook site
guards on the module-global ``ARMED`` flag; one attribute read per
event).

Plan grammar (``PARSEC_MCA_FAULT_PLAN`` / ``--mca fault_plan``)::

    seed=7;drop_frame=tag:ACT,p=0.01;kill_rank=1@t+2s,mode=hang;
    delay_frame=tag:DTD,p=0.5,ms=120;fail_task=key~POTRF,n=1

Directives (``;``-separated; fields ``,``-separated):

``drop_frame``    drop a matching outbound frame (the Safra balance is
                  reconciled through the transport's ``app_sent_adjust``
                  hook so termination detection still converges — the
                  DROPPED work hangs, which is the point)
``dup_frame``     send a matching frame twice (receiver-side ``_fid``
                  dedup must recover)
``delay_frame``   hold a matching frame for ``ms`` before sending
                  (reorders it past later frames — the race amplifier)
``delay_recv``    hold a matching RECEIVED frame for ``ms`` before
                  dispatching its handler, while later frames from the
                  same (and every other) peer flow — reorder coverage
                  on the RECEIVE path, where send-side delays cannot
                  reach (a frame reordered by the network arrives
                  in-order per TCP stream; this reorders AFTER framing).
                  ``rank=<src>`` scopes to one source rank
``trunc_frame``   replace a matching frame with an undecodable one (the
                  receiver severs the connection: wire-corruption path)
``kill_rank``     ``<rank>@t+<sec>s`` — at ``sec`` seconds after the
                  engine came up, rank ``<rank>`` hard-closes every
                  socket (``mode=close``, default: EOF-detector path) or
                  goes silent with sockets open (``mode=hang``: only the
                  heartbeat timeout can see it)
``fail_task``     raise FaultInjected in a matching task body
                  (``key~substr`` matches ``str(task)``); exercises the
                  ``task_retry_max`` transient-retry path
``delay_dispatch``  sleep ``ms`` in the device manager before a launch
                  (perturbs manager/completer interleavings); with a
                  ``key~substr`` matcher the delay moves to the WORKER
                  right before a matching task's body runs instead —
                  the deterministic straggler injector the liveattr
                  anomaly tests replay (prof/liveattr.py)
``degrade``       ``rank=<r>,ms=<cap>,ramp=<sec>[,at=<sec>]`` — a rank
                  that is DYING, not dead: starting ``at`` seconds after
                  arming, every task body and every outbound frame
                  (heartbeats included) on rank ``r`` gains a delay that
                  ramps linearly from 0 to ``ms`` over ``ramp`` seconds,
                  with seeded ±10%% jitter.  Keep ``ms`` well under the
                  peer heartbeat timeout: the rank must stay ALIVE so
                  only the predictive health plane (prof/health.py) —
                  not the liveness detector — can see it.  This is the
                  drain-before-death validation workload

Field forms: ``tag:NAME`` (frame tag; default = any app tag),
``pm=<substr>`` (substring of ``repr(payload)``), ``p=<prob>``,
``n=<count>`` (fire at most n times), ``ms=<millis>``, ``key~<substr>``,
``<rank>@t+<sec>s``, ``mode=close|hang``, ``rank=<dst>`` (scope a frame
directive to frames bound for one destination rank).

Determinism: one ``random.Random(seed + 1000 * rank)`` per engine/rank,
so a plan replays the same decision stream per rank modulo thread
interleaving — the seeds vary the schedule, the plan bounds the blast
radius.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from parsec_tpu.utils.mca import params

params.register("fault_plan", "",
                "seeded fault-injection plan (see utils/faultinject.py "
                "for the grammar); empty = no faults, hook points "
                "compile to one module-flag check")

#: fast-path gate every hook site reads; True only while a plan is armed
ARMED = False

_PLAN: Optional["FaultPlan"] = None
_RUNTIME: Optional["RuntimeFaults"] = None
_lock = threading.Lock()

#: frame-tag name -> wire tag (mirrors comm/engine.py's TAG_* constants;
#: engine.py asserts the mapping at import so the two cannot drift)
TAG_NAMES: Dict[str, int] = {
    "ACT": 1, "ACTIVATE": 1, "GET_REQ": 2, "GET_REP": 3, "TERMDET": 4,
    "BARRIER": 5, "DTD": 6, "BATCH": 7, "UTRIG": 8, "PUT": 9,
    "GET1": 10, "GET1_REP": 11, "CLOCK": 12, "HB": 13, "REJOIN": 16,
    "RECOVER": 17,
}

#: application tags a tag-less frame matcher applies to (dropping the
#: detection plane itself — TERMDET tokens, barriers, heartbeats —
#: would break the algorithms whose job is to DETECT the fault)
_APP_TAGS = frozenset((1, 2, 3, 6, 7, 9, 10, 11))

_FRAME_KINDS = ("drop_frame", "dup_frame", "delay_frame", "trunc_frame")

#: receive-side directives (matched at the receiver, after framing)
_RECV_KINDS = ("delay_recv",)


class _Directive:
    __slots__ = ("kind", "tag", "p", "n", "ms", "rank", "at_s", "mode",
                 "key", "pm", "ramp", "fired", "lock")

    def __init__(self, kind: str):
        self.kind = kind
        self.tag: Optional[int] = None
        self.p = 1.0
        self.n: Optional[int] = None
        self.ms = 0.0
        self.rank: Optional[int] = None
        self.at_s = 0.0
        self.mode = "close"
        self.key: Optional[str] = None
        self.pm: Optional[str] = None
        self.ramp = 10.0
        self.fired = 0
        self.lock = threading.Lock()

    def take(self, rng: random.Random, text: Optional[str] = None) -> bool:
        """One match attempt: payload/probability/count gates, atomically
        counted so ``n=1`` fires exactly once across threads."""
        if self.pm is not None and (text is None or self.pm not in text):
            return False
        with self.lock:
            if self.n is not None and self.fired >= self.n:
                return False
            if self.p < 1.0 and rng.random() >= self.p:
                return False
            self.fired += 1
            return True


def _parse_field(d: _Directive, field: str) -> None:
    field = field.strip()
    if not field:
        return
    if field.startswith("tag:"):
        name = field[4:].strip().upper()
        d.tag = TAG_NAMES[name] if name in TAG_NAMES else int(name)
        return
    if "~" in field and "=" not in field.split("~", 1)[0]:
        k, v = field.split("~", 1)
        if k.strip() == "key":
            d.key = v
            return
    if "@" in field and "=" not in field.split("@", 1)[0]:
        # <rank>@t+<sec>s (kill_rank)
        r, at = field.split("@", 1)
        d.rank = int(r)
        at = at.strip().lower()
        if at.startswith("t+"):
            at = at[2:]
        d.at_s = float(at.rstrip("s"))
        return
    if "=" in field:
        k, v = field.split("=", 1)
        k = k.strip()
        if k == "p":
            d.p = float(v)
        elif k == "n":
            d.n = int(v)
        elif k == "ms":
            d.ms = float(v)
        elif k == "mode":
            d.mode = v.strip().lower()
        elif k == "pm":
            d.pm = v
        elif k == "rank":
            d.rank = int(v)
        elif k == "ramp":
            d.ramp = float(v.rstrip("s"))
        elif k == "at":
            d.at_s = float(v.rstrip("s"))
        else:
            raise ValueError(f"unknown fault-plan field {k!r}")
        return
    raise ValueError(f"unparseable fault-plan field {field!r}")


class FaultPlan:
    """A parsed plan: the seed plus its directives, grouped by kind."""

    def __init__(self, spec: str):
        self.spec = spec
        self.seed = 0
        self.directives: List[_Directive] = []
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            name, _, rest = part.partition("=")
            name = name.strip()
            if name == "seed":
                self.seed = int(rest)
                continue
            d = _Directive(name)
            for field in rest.split(","):
                _parse_field(d, field)
            self.directives.append(d)

    def of_kind(self, *kinds: str) -> List[_Directive]:
        return [d for d in self.directives if d.kind in kinds]


def _ramp_ms(d: _Directive, t0: float, rng: random.Random) -> float:
    """Current delay of a ``degrade`` directive: linear ramp from 0 at
    ``t0 + at_s`` to ``ms`` at ``t0 + at_s + ramp``, then held at the
    cap, with seeded ±10% jitter (the jitter IS a signal — inter-arrival
    variance is what a scrape-time health fold can see even after the
    ramp plateaus and the mean gap renormalizes)."""
    el = time.monotonic() - t0 - d.at_s
    if el <= 0.0:
        return 0.0
    frac = min(1.0, el / max(d.ramp, 1e-9))
    val = d.ms * frac
    if val <= 0.0:
        return 0.0
    return val * (0.9 + 0.2 * rng.random())


class CommFaults:
    """Per-engine (per-rank) comm-fault state: a seeded RNG plus the
    plan's frame and kill directives.  Created by ``comm_faults`` at
    transport construction; ``None`` when the plan has no comm
    directives, so the transport keeps a no-hook fast path."""

    def __init__(self, plan: FaultPlan, rank: int):
        self.rng = random.Random(plan.seed + 1000 * rank)
        self.frame_dirs = plan.of_kind(*_FRAME_KINDS)
        self.recv_dirs = plan.of_kind(*_RECV_KINDS)
        self.kill = next((d for d in plan.of_kind("kill_rank")
                          if d.rank == rank), None)
        self.degrade = next((d for d in plan.of_kind("degrade")
                             if d.rank is None or d.rank == rank), None)
        self._t0 = time.monotonic()

    def frame_action(self, tag: int, dst: int,
                     payload: Any) -> Optional[Tuple[str, float]]:
        """First matching frame directive's action for an outbound
        frame: ("drop"|"dup"|"trunc", 0) or ("delay", ms)."""
        text = None
        for d in self.frame_dirs:
            if d.rank is not None and d.rank != dst:
                continue   # rank= scopes a frame directive to one dst
            if d.tag is None:
                if tag not in _APP_TAGS:
                    continue
            elif d.tag != tag:
                continue
            if d.pm is not None and text is None:
                text = repr(payload)[:512] if payload is not None else ""
            if d.take(self.rng, text):
                return (d.kind[:-6], d.ms)   # strip "_frame"
        # degrade: every outbound frame — heartbeats included — gains
        # the ramped delay.  Explicit frame directives take precedence
        # above so composed plans keep their drop/dup/trunc semantics.
        dg = self.degrade
        if dg is not None:
            ms = _ramp_ms(dg, self._t0, self.rng)
            if ms >= 1.0:
                return ("delay", ms)
        return None

    def recv_delay_ms(self, tag: int, src: int,
                      payload: Any) -> Optional[float]:
        """First matching ``delay_recv`` directive's hold time for a
        just-received frame (``rank=`` scopes by SOURCE rank here), or
        None.  The transport re-delivers the frame after the hold —
        later frames dispatch first, which is the coverage."""
        text = None
        for d in self.recv_dirs:
            if d.rank is not None and d.rank != src:
                continue
            if d.tag is None:
                if tag not in _APP_TAGS:
                    continue
            elif d.tag != tag:
                continue
            if d.pm is not None and text is None:
                text = repr(payload)[:512] if payload is not None else ""
            if d.take(self.rng, text):
                return d.ms
        return None


class RuntimeFaults:
    """Process-wide task/device fault state (one Context per process in
    every supported deployment; rank 0 seeding)."""

    def __init__(self, plan: FaultPlan, rank: int = 0):
        self.rng = random.Random(plan.seed + 1000 * rank + 7)
        self.task_dirs = plan.of_kind("fail_task")
        self.disp_dirs = plan.of_kind("delay_dispatch")
        self.degrade = next((d for d in plan.of_kind("degrade")
                             if d.rank is None or d.rank == rank), None)
        self._t0 = time.monotonic()

    def task_fault(self, task) -> bool:
        for d in self.task_dirs:
            if d.key is not None and d.key not in str(task):
                continue
            if d.take(self.rng):
                return True
        return False

    def device_delay(self) -> None:
        for d in self.disp_dirs:
            if d.key is not None:
                continue   # keyed directives fire per task (task_delay)
            if d.take(self.rng) and d.ms > 0:
                time.sleep(d.ms * 1e-3)

    def task_delay(self, task) -> None:
        """Keyed ``delay_dispatch`` directives: stall a MATCHING task's
        body on the worker — a deterministic straggler whose class
        peers establish the baseline profile the detector arms from."""
        for d in self.disp_dirs:
            if d.key is None or d.key not in str(task):
                continue
            if d.take(self.rng) and d.ms > 0:
                time.sleep(d.ms * 1e-3)
        dg = self.degrade
        if dg is not None:
            ms = _ramp_ms(dg, self._t0, self.rng)
            if ms >= 1.0:
                time.sleep(ms * 1e-3)


def arm(spec: str) -> FaultPlan:
    """Arm a plan programmatically (tests, tools/chaos.py)."""
    global ARMED, _PLAN, _RUNTIME
    with _lock:
        _PLAN = FaultPlan(spec)
        _RUNTIME = None
        ARMED = bool(_PLAN.directives)
        return _PLAN


def disarm() -> None:
    global ARMED, _PLAN, _RUNTIME
    with _lock:
        ARMED = False
        _PLAN = None
        _RUNTIME = None


def refresh() -> None:
    """Re-read the MCA param (spawned workers arm from the inherited
    environment; a test that set the param after import calls this)."""
    spec = str(params.get("fault_plan", "") or "")
    if spec:
        arm(spec)
    elif ARMED and _PLAN is not None and _PLAN.spec != spec:
        disarm()


def comm_faults(rank: int) -> Optional[CommFaults]:
    """The transport's per-rank fault view, or None (no armed plan or no
    comm directives — the transport then skips every per-frame check)."""
    global _RANK
    _RANK = rank   # the transport learns the rank first; runtime() reuses it
    plan = _PLAN
    if plan is None:
        return None
    cf = CommFaults(plan, rank)
    if not cf.frame_dirs and not cf.recv_dirs and cf.kill is None \
            and cf.degrade is None:
        return None
    return cf


#: this process's rank as last reported by the transport (degrade
#: directives scope by rank on the TASK side too, and the task hooks
#: have no rank argument — the transport always constructs first)
_RANK = 0


def runtime(rank: Optional[int] = None) -> Optional[RuntimeFaults]:
    global _RUNTIME
    plan = _PLAN
    if plan is None:
        return None
    with _lock:
        if _RUNTIME is None:
            _RUNTIME = RuntimeFaults(plan, _RANK if rank is None else rank)
        return _RUNTIME


def task_fault(task) -> bool:
    """Hook: should this task body raise FaultInjected?  Call only
    behind an ``ARMED`` check."""
    rt = runtime()
    return rt is not None and rt.task_fault(task)


def device_delay() -> None:
    """Hook: pre-dispatch delay.  Call only behind an ``ARMED`` check."""
    rt = runtime()
    if rt is not None:
        rt.device_delay()


def task_delay(task) -> None:
    """Hook: keyed pre-body delay (straggler injection).  Call only
    behind an ``ARMED`` check."""
    rt = runtime()
    if rt is not None:
        rt.task_delay(task)


# spawned ranks inherit PARSEC_MCA_FAULT_PLAN through the environment:
# arming at import means a distributed child needs no explicit call
refresh()
