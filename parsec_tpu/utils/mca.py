"""MCA-style parameter system.

Rebuild of the reference's Modular Component Architecture parameter registry
(reference: parsec/utils/mca_param.c, mca_param.h): typed, hierarchically named
parameters ``<framework>_<component>_<param>`` sourced with the precedence

    registered default  <  keyval files  <  environment (PARSEC_MCA_<name>)
                        <  explicit/command-line (--mca <name> <value>)

and introspectable at runtime.  Component-selection strings (e.g.
``--mca sched lfq`` or ``--mca device_tpu_enabled 0``) drive module selection
exactly like the reference's MCA repository (parsec/mca/mca_repository.c).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

ENV_PREFIX = "PARSEC_MCA_"

# Source precedence, low to high (reference: mca_param.c lookup order).
SRC_DEFAULT = 0
SRC_FILE = 1
SRC_ENV = 2
SRC_OVERRIDE = 3

_SRC_NAMES = {SRC_DEFAULT: "default", SRC_FILE: "file", SRC_ENV: "env",
              SRC_OVERRIDE: "override"}


@dataclass
class _Param:
    name: str
    type_: type
    default: Any
    help: str = ""
    read_only: bool = False
    # values[src] = raw value from that source (already coerced)
    values: Dict[int, Any] = field(default_factory=dict)

    def current(self):
        for src in (SRC_OVERRIDE, SRC_ENV, SRC_FILE):
            if src in self.values:
                return self.values[src], src
        return self.default, SRC_DEFAULT


def _coerce(type_: type, raw: Any) -> Any:
    if isinstance(raw, type_) and not (type_ is int and isinstance(raw, bool)):
        return raw
    if type_ is bool:
        if isinstance(raw, str):
            return raw.strip().lower() in ("1", "true", "yes", "on", "y")
        return bool(raw)
    if type_ is int:
        if isinstance(raw, (bool, int, float)):
            return int(raw)
        s = str(raw).strip()
        try:
            return int(s, 0)       # accept 0x.., 0o.. forms
        except ValueError:
            return int(s, 10)      # base-0 rejects zero-padded decimals
    if type_ is float:
        return float(raw)
    return str(raw)


class ParamRegistry:
    """Process-wide named-parameter registry."""

    def __init__(self):
        self._lock = threading.RLock()
        self._params: Dict[str, _Param] = {}
        self._pending: Dict[str, Any] = {}   # set before registration
        self._pending_src: Dict[str, int] = {}
        self._watchers: Dict[str, List[Callable[[str, Any], None]]] = {}

    # -- registration ----------------------------------------------------
    def register(self, name: str, default: Any, help: str = "",
                 type_: Optional[type] = None, read_only: bool = False) -> str:
        """Register a parameter; returns its full name.

        Mirrors parsec_mca_param_reg_{int,string}_name: registering an
        already-registered name updates help text but keeps existing values.
        """
        if type_ is None:
            type_ = bool if isinstance(default, bool) else type(default)
        with self._lock:
            p = self._params.get(name)
            if p is None:
                p = _Param(name=name, type_=type_, default=default, help=help,
                           read_only=read_only)
                self._params[name] = p
                if read_only:
                    # Immutable params ignore env/pending overrides entirely.
                    self._pending.pop(name, None)
                    self._pending_src.pop(name, None)
                else:
                    env_raw = os.environ.get(ENV_PREFIX + name.upper(),
                                             os.environ.get(ENV_PREFIX + name))
                    if env_raw is not None:
                        p.values[SRC_ENV] = _coerce(type_, env_raw)
                    if name in self._pending:
                        src = self._pending_src.pop(name, SRC_OVERRIDE)
                        p.values[src] = _coerce(type_, self._pending.pop(name))
            else:
                if help:
                    p.help = help
            return name

    def reg_int(self, framework: str, component: str, param: str,
                default: int, help: str = "") -> str:
        return self.register(_join(framework, component, param), int(default), help)

    def reg_str(self, framework: str, component: str, param: str,
                default: str, help: str = "") -> str:
        return self.register(_join(framework, component, param), str(default), help)

    def reg_bool(self, framework: str, component: str, param: str,
                 default: bool, help: str = "") -> str:
        return self.register(_join(framework, component, param), bool(default),
                             help, type_=bool)

    # -- lookup ----------------------------------------------------------
    def get(self, name: str, default: Any = None) -> Any:
        with self._lock:
            p = self._params.get(name)
            if p is None:
                if name in self._pending:
                    return self._pending[name]
                env_raw = os.environ.get(ENV_PREFIX + name.upper())
                if env_raw is not None:
                    return env_raw
                return default
            return p.current()[0]

    def source_of(self, name: str) -> str:
        with self._lock:
            p = self._params.get(name)
            if p is None:
                return "unregistered"
            return _SRC_NAMES[p.current()[1]]

    # -- mutation --------------------------------------------------------
    def set(self, name: str, value: Any, src: int = SRC_OVERRIDE) -> None:
        """Set a parameter (``--mca name value``)."""
        with self._lock:
            p = self._params.get(name)
            if p is None:
                self._pending[name] = value
                self._pending_src[name] = src
                return
            if p.read_only:
                raise ValueError(f"MCA param {name!r} is read-only")
            p.values[src] = _coerce(p.type_, value)
            for cb in self._watchers.get(name, ()):
                cb(name, p.current()[0])

    def unset(self, name: str, src: int = SRC_OVERRIDE) -> None:
        with self._lock:
            p = self._params.get(name)
            if p is not None:
                p.values.pop(src, None)

    def watch(self, name: str, cb: Callable[[str, Any], None]) -> None:
        with self._lock:
            self._watchers.setdefault(name, []).append(cb)

    # -- files / CLI -----------------------------------------------------
    def load_keyval_file(self, path: str) -> int:
        """Load ``name = value`` lines (reference: utils/keyval_parse.c)."""
        n = 0
        with open(path) as f:
            for line in f:
                line = line.split("#", 1)[0].strip()
                if not line:
                    continue
                if "=" in line:
                    k, v = line.split("=", 1)
                elif " " in line:
                    k, v = line.split(None, 1)
                else:
                    continue
                self.set(k.strip(), v.strip().strip('"'), src=SRC_FILE)
                n += 1
        return n

    def parse_cmdline(self, argv: List[str]) -> List[str]:
        """Consume ``--mca <name> <value>`` pairs; return remaining argv.

        Reference: utils/mca_param_cmd_line.c.
        """
        out: List[str] = []
        i = 0
        while i < len(argv):
            a = argv[i]
            if a == "--mca":
                if i + 2 > len(argv) - 1:
                    raise ValueError("--mca requires <name> <value>")
                self.set(argv[i + 1], argv[i + 2])
                i += 3
            elif a.startswith("--mca="):
                kv = a[len("--mca="):]
                k, v = kv.split("=", 1)
                self.set(k, v)
                i += 1
            else:
                out.append(a)
                i += 1
        return out

    # -- introspection ---------------------------------------------------
    def dump(self) -> List[str]:
        """Human-readable dump (reference: parsec_mca_show_mca_params)."""
        with self._lock:
            lines = []
            for name in sorted(self._params):
                p = self._params[name]
                val, src = p.current()
                lines.append(f"{name}={val!r} (source: {_SRC_NAMES[src]}) # {p.help}")
            return lines

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._params)


def _join(framework: str, component: str, param: str) -> str:
    return "_".join(x for x in (framework, component, param) if x)


#: The process-global registry (reference keeps one global table too).
params = ParamRegistry()


# ---------------------------------------------------------------------------
# Component repository: open/select modules by framework+name
# (reference: parsec/mca/mca.h + mca_repository.c)
# ---------------------------------------------------------------------------

class ComponentRepository:
    """Static registry of pluggable components per framework.

    Frameworks: ``sched``, ``device``, ``termdet``, ``pins``, ``comm``.
    Selection honors the MCA string param ``<framework>`` — a comma-separated
    preference list, or a single name; empty means "best available by
    priority".
    """

    def __init__(self, registry: ParamRegistry):
        self._registry = registry
        self._components: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()

    def add(self, framework: str, name: str, component: Any,
            priority: int = 0) -> None:
        with self._lock:
            self._components.setdefault(framework, {})[name] = (priority, component)
        self._registry.register(framework, "", f"component selection for {framework}")

    def get(self, framework: str, name: str) -> Any:
        with self._lock:
            entry = self._components.get(framework, {}).get(name)
        if entry is None:
            raise KeyError(f"no MCA component {framework!r}/{name!r}")
        return entry[1]

    def available(self, framework: str) -> List[str]:
        with self._lock:
            comps = self._components.get(framework, {})
            return [n for n, _ in sorted(comps.items(),
                                         key=lambda kv: -kv[1][0])]

    def select(self, framework: str, requested: Optional[str] = None) -> Any:
        """Pick a component: explicit request > MCA param > highest priority."""
        if requested is None:
            requested = self._registry.get(framework, "")
        if requested:
            for name in str(requested).split(","):
                name = name.strip()
                try:
                    return name, self.get(framework, name)
                except KeyError:
                    continue
            raise KeyError(
                f"no usable component in {framework!r} from {requested!r}; "
                f"available: {self.available(framework)}")
        names = self.available(framework)
        if not names:
            raise KeyError(f"no components registered for {framework!r}")
        return names[0], self.get(framework, names[0])


#: Process-global component repository.
components = ComponentRepository(params)
