"""Extensible per-object info registry.

Rebuild of the reference's info system (reference: parsec/class/info.{c,h}
— ``parsec_info_t`` named-slot registries + ``parsec_info_object_array_t``
per-object storage with lazy constructors; used to hang user/device state
off taskpools and devices without changing their types).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional


class InfoSpace:
    """A named-slot registry (reference: parsec_info_t).  Each name is
    registered once and yields a dense integer id; object arrays index by
    that id."""

    def __init__(self, name: str = "info"):
        self.name = name
        self._lock = threading.Lock()
        self._ids: Dict[str, int] = {}
        self._ctors: List[Optional[Callable[[Any], Any]]] = []

    def register(self, name: str,
                 constructor: Optional[Callable[[Any], Any]] = None) -> int:
        """Register (or look up) a named slot; ``constructor(owner)``
        lazily builds the per-object value on first access
        (reference: parsec_info_register)."""
        with self._lock:
            iid = self._ids.get(name)
            if iid is not None:
                if constructor is not None:
                    self._ctors[iid] = constructor
                return iid
            iid = len(self._ctors)
            self._ids[name] = iid
            self._ctors.append(constructor)
            return iid

    def unregister(self, name: str) -> None:
        with self._lock:
            iid = self._ids.pop(name, None)
            if iid is not None:
                self._ctors[iid] = None

    def lookup(self, name: str) -> Optional[int]:
        with self._lock:
            return self._ids.get(name)

    def constructor_of(self, iid: int):
        with self._lock:
            return self._ctors[iid] if 0 <= iid < len(self._ctors) else None


class InfoObjectArray:
    """Per-object slot storage (reference: parsec_info_object_array_t)."""

    def __init__(self, space: InfoSpace, owner: Any = None):
        self.space = space
        self.owner = owner
        self._lock = threading.Lock()
        self._slots: Dict[int, Any] = {}

    def get(self, name_or_id, default: Any = None) -> Any:
        iid = self._resolve(name_or_id)
        if iid is None:
            return default
        with self._lock:
            if iid in self._slots:
                return self._slots[iid]
        ctor = self.space.constructor_of(iid)
        if ctor is None:
            return default
        value = ctor(self.owner)
        with self._lock:
            return self._slots.setdefault(iid, value)

    def set(self, name_or_id, value: Any) -> None:
        iid = self._resolve(name_or_id)
        if iid is None:
            raise KeyError(f"unregistered info {name_or_id!r}")
        with self._lock:
            self._slots[iid] = value

    def _resolve(self, name_or_id) -> Optional[int]:
        if isinstance(name_or_id, int):
            return name_or_id
        return self.space.lookup(name_or_id)


#: process-wide spaces mirroring the reference's pre-declared registries
#: (per-taskpool and per-device info; reference: parsec_per_stream_infos /
#: the device info arrays)
taskpool_info = InfoSpace("taskpool")
device_info = InfoSpace("device")
stream_info = InfoSpace("stream")
