"""Thread-local object pools.

Reference: parsec/mempool.{c,h} — per-thread freelists of task/repo/dep
objects to avoid allocator contention on the hot path.  In Python the win is
reduced GC churn for Task records; the native core uses real arenas.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List


class MemoryPool:
    """Per-thread freelist of reusable objects (parsec_mempool_t)."""

    def __init__(self, factory: Callable[[], Any],
                 reset: Callable[[Any], None] = None, max_cached: int = 4096):
        self._factory = factory
        self._reset = reset
        self._max = max_cached
        self._tls = threading.local()

    def _free_list(self) -> List[Any]:
        fl = getattr(self._tls, "free", None)
        if fl is None:
            fl = []
            self._tls.free = fl
        return fl

    def alloc(self) -> Any:
        fl = self._free_list()
        if fl:
            return fl.pop()
        return self._factory()

    def release(self, obj: Any) -> None:
        if self._reset is not None:
            self._reset(obj)
        fl = self._free_list()
        if len(fl) < self._max:
            fl.append(obj)
