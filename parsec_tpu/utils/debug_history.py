"""Debug-history ring buffer and paranoia tiers.

Rebuild of the reference's debugging defenses (reference:
parsec/utils/debug_marks.{c,h} — a ring buffer of protocol marks
(activation/data messages) dumped post-mortem — and the
PARSEC_DEBUG_PARANOID assertion tiers compiled into hot paths,
scheduling.c:290-316).  Here both are runtime-selected:

  --mca debug_paranoid 0    off (default: marks disabled, asserts off)
  --mca debug_paranoid 1    protocol marks recorded in the ring
  --mca debug_paranoid 2    + extra invariant assertions on hot paths

``paranoid()`` is the tier gate; ``mark()`` records; ``dump_history()``
returns the ring newest-last (and is printed on context error when
marks are enabled).
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import List

from parsec_tpu.utils.mca import params

params.register("debug_paranoid", 0,
                "debug tier: 0=off, 1=protocol marks, 2=+hot-path asserts")
params.register("debug_history_size", 1024,
                "entries in the debug-mark ring buffer")

_lock = threading.Lock()
_ring: List = []
_next = itertools.count()
#: cached tier, refreshed at most every 0.5s — paranoid() runs per dep
#: arrival and per wire message, so it must not take the MCA params lock
#: on the hot path when debugging is off
_cached = [0, 0.0]   # [level, expiry]


def refresh_tier() -> None:
    """Re-read the tier immediately (call after params.set at runtime;
    the cache otherwise refreshes every 0.5s)."""
    _cached[1] = 0.0


def paranoid(level: int = 1) -> bool:
    now = time.monotonic()
    if now >= _cached[1]:
        try:
            _cached[0] = int(params.get("debug_paranoid", 0))
        except (TypeError, ValueError):
            _cached[0] = 0
        _cached[1] = now + 0.5
    return _cached[0] >= level


def mark(fmt: str, *args) -> None:
    """Record one mark (cheap: formatted lazily at dump unless args are
    mutable).  Reference: parsec_debug_history_add."""
    if not paranoid(1):
        return
    size = int(params.get("debug_history_size", 1024))
    entry = (next(_next), time.monotonic(),
             threading.current_thread().name, fmt, args)
    with _lock:
        _ring.append(entry)
        if len(_ring) > size:
            del _ring[: len(_ring) - size]


def dump_history() -> List[str]:
    """Newest-last formatted marks (reference: parsec_debug_history_dump)."""
    with _lock:
        entries = list(_ring)
    out = []
    for seq, ts, thread, fmt, args in entries:
        try:
            text = fmt % args if args else fmt
        except (TypeError, ValueError):
            text = f"{fmt} {args!r}"
        out.append(f"[{seq}] {ts:.6f} {thread}: {text}")
    return out


def clear_history() -> None:
    with _lock:
        _ring.clear()
