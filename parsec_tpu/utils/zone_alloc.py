"""Zone (segment) allocator over a pre-reserved slab.

Rebuild of the reference's GPU-memory segment allocator
(reference: parsec/utils/zone_malloc.{c,h}): first-fit allocation of
fixed-unit segments from one contiguous zone with coalescing free.  On TPU
the "zone" is an HBM byte budget managed by the device module — XLA owns
physical allocation, so this tracks segments logically to drive LRU eviction
decisions exactly where the reference drove cudaMalloc'd slabs.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional


class ZoneAllocator:
    def __init__(self, total_bytes: int, unit_bytes: int = 512):
        if total_bytes <= 0 or unit_bytes <= 0:
            raise ValueError("zone size and unit must be positive")
        if total_bytes < unit_bytes:
            raise ValueError("zone smaller than one allocation unit")
        self.unit = unit_bytes
        self.nb_units = total_bytes // unit_bytes
        self._lock = threading.Lock()
        # segments: start_unit -> (nb_units, free?)
        self._segs: Dict[int, list] = {0: [self.nb_units, True]}

    def malloc(self, nbytes: int) -> Optional[int]:
        """Allocate; returns logical offset in bytes, or None if no room."""
        units = max(1, -(-nbytes // self.unit))
        with self._lock:
            for start in sorted(self._segs):
                n, free = self._segs[start]
                if free and n >= units:
                    if n > units:
                        self._segs[start + units] = [n - units, True]
                    self._segs[start] = [units, False]
                    return start * self.unit
            return None

    def free(self, offset: int) -> None:
        start = offset // self.unit
        with self._lock:
            seg = self._segs.get(start)
            if seg is None or seg[1]:
                raise ValueError(f"bad free at offset {offset}")
            seg[1] = True
            self._coalesce()

    def _coalesce(self) -> None:
        starts = sorted(self._segs)
        i = 0
        while i < len(starts) - 1:
            s, nxt = starts[i], starts[i + 1]
            n, free = self._segs[s]
            n2, free2 = self._segs[nxt]
            if free and free2 and s + n == nxt:
                self._segs[s] = [n + n2, True]
                del self._segs[nxt]
                starts.pop(i + 1)
            else:
                i += 1

    def free_bytes(self) -> int:
        with self._lock:
            return sum(n for n, free in self._segs.values() if free) * self.unit

    def used_bytes(self) -> int:
        with self._lock:
            return sum(n for n, free in self._segs.values() if not free) * self.unit

    def check_defrag(self) -> bool:
        """True if completely free (reference: zone_debug consistency)."""
        with self._lock:
            return len(self._segs) == 1 and self._segs.get(0, [0, False])[1]
