"""Properties dictionary: a runtime-queryable hierarchical key space.

Rebuild of the reference's properties dictionary (reference:
parsec/dictionary.{c,h} — a 1.3k-LoC hierarchical namespace of
taskpool/task properties that live tooling like tools/aggregator_visu
walks at runtime).  Here a property is a '/'-separated path bound to a
VALUE or a zero-arg PROVIDER evaluated at lookup time, so consumers
always read live state:

    space.register("runtime/devices/tpu:0/executed_tasks",
                   lambda: dev.stats.executed_tasks)
    space.lookup("runtime/devices/tpu:0/executed_tasks")  -> live count
    space.tree("runtime/devices")  -> {path: value, ...}

The Context exposes one per-process space at ``ctx.properties`` with the
runtime/device/scheduler namespaces pre-registered; taskpools attach
their per-class properties (flops weights, task counters) under
``taskpool/<name>/...`` when enqueued.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional


class PropertySpace:
    def __init__(self):
        self._lock = threading.Lock()
        self._props: Dict[str, Any] = {}

    @staticmethod
    def _norm(path: str) -> str:
        return "/".join(p for p in path.split("/") if p)

    def register(self, path: str, value: Any) -> None:
        """Bind ``path`` to a value or a zero-arg provider; re-registering
        replaces (the reference rebinds on taskpool re-enqueue)."""
        with self._lock:
            self._props[self._norm(path)] = value

    def unregister(self, path: str) -> None:
        with self._lock:
            self._props.pop(self._norm(path), None)

    def unregister_tree(self, prefix: str) -> int:
        """Drop every property under ``prefix`` (taskpool teardown)."""
        prefix = self._norm(prefix)
        with self._lock:
            doomed = [p for p in self._props
                      if p == prefix or p.startswith(prefix + "/")]
            for p in doomed:
                del self._props[p]
        return len(doomed)

    def lookup(self, path: str, default: Any = None) -> Any:
        with self._lock:
            v = self._props.get(self._norm(path), _MISSING)
        if v is _MISSING:
            return default
        return v() if callable(v) else v

    def paths(self, prefix: str = "") -> List[str]:
        prefix = self._norm(prefix)
        with self._lock:
            return sorted(p for p in self._props
                          if not prefix or p == prefix
                          or p.startswith(prefix + "/"))

    def tree(self, prefix: str = "") -> Dict[str, Any]:
        """Evaluate every property under ``prefix`` (the aggregator-GUI
        read pattern: walk a namespace, sample all gauges at once)."""
        out = {}
        for p in self.paths(prefix):
            out[p] = self.lookup(p)
        return out


_MISSING = object()


def install_runtime_properties(ctx) -> PropertySpace:
    """Pre-register the runtime namespaces on a context's space
    (reference: the runtime-level entries of dictionary.c)."""
    ps = ctx.properties
    ps.register("runtime/nranks", lambda: ctx.nranks)
    ps.register("runtime/rank", lambda: ctx.rank)
    ps.register("runtime/nb_cores", lambda: len(ctx.streams))
    ps.register("runtime/scheduler",
                lambda: type(ctx.scheduler).__name__)
    for d in ctx.device_registry.devices:
        base = f"runtime/devices/{d.name}"
        ps.register(f"{base}/kind", d.kind)
        for field in ("executed_tasks", "bytes_in", "bytes_out",
                      "faults", "evictions", "fused_launches",
                      "fused_tasks"):
            ps.register(f"{base}/{field}",
                        (lambda d=d, f=field: getattr(d.stats, f)))
        ps.register(f"{base}/load", lambda d=d: d.load)
    return ps


def install_taskpool_properties(ctx, tp) -> None:
    """Attach a taskpool's class properties + live counters under
    ``taskpool/<name>`` (reference: taskpool registration in
    dictionary.c; JDF-declared property expressions land in
    TaskClass.properties)."""
    base = f"taskpool/{tp.name}"
    ps = ctx.properties
    ps.register(f"{base}/nb_tasks",
                lambda tp=tp: getattr(tp, "nb_tasks", None))
    import inspect
    classes = getattr(tp, "task_classes", None) or {}
    for cname, tc in classes.items():
        for pname, pval in getattr(tc, "properties", {}).items():
            if callable(pval):
                # zero-arg callables are live providers (the dictionary
                # contract); parameterized per-task expressions (flops /
                # coaffinity lambdas over task locals) cannot be sampled
                # without an instance — register their description
                try:
                    sig = inspect.signature(pval)
                    needs_args = any(
                        p.default is p.empty and p.kind in
                        (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
                        for p in sig.parameters.values())
                except (TypeError, ValueError):
                    needs_args = False
                if needs_args:
                    ps.register(f"{base}/classes/{cname}/{pname}",
                                f"<per-task expression {pname}>")
                    continue
            ps.register(f"{base}/classes/{cname}/{pname}", pval)
