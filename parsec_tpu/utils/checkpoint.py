"""Checkpoint / restore of collection state.

The reference has NO runtime checkpointing (SURVEY §5.4: taskpool state
is never serialized; the closest mechanisms are completion callbacks and
data flush).  This layer implements the design SURVEY prescribes for the
TPU build: quiesce (pools complete, device pipelines drained, comm
settled), flush every authoritative device copy home, then snapshot the
collections' local tiles — one file per rank, restorable into freshly
built collections of the same shape/distribution.

Usage::

    ctx.wait()                                   # quiesce the DAGs
    checkpoint(ctx, [A, B, C], "/path/ckpt")     # rank-local snapshot
    ...
    restore(ctx, [A, B, C], "/path/ckpt")        # tiles + versions back

Checkpoint/restore are collective when a comm engine is attached: every
rank writes/reads its own shard and a barrier delimits the snapshot so
no rank can race ahead into mutating state another rank still saves.
"""

from __future__ import annotations

import os
from typing import Iterable, List

import numpy as np

from parsec_tpu.utils.output import debug_verbose

FORMAT_VERSION = 1


def _rank_path(context, path: str) -> str:
    rank = context.rank if context is not None else 0
    return f"{path}.r{rank}.npz"


def checkpoint(context, collections: Iterable, path: str) -> str:
    """Snapshot every local tile of ``collections`` (host-authoritative:
    device copies are flushed home first).  Returns the rank-local file.
    Call after ``context.wait()`` — a checkpoint of a running DAG is a
    torn checkpoint."""
    # drain device pipelines and push authoritative copies home
    for d in context.device_registry.accelerators:
        dsync = getattr(d, "sync", None)
        if dsync is not None:
            dsync()
    context.device_registry.flush_all()
    arrays = {}
    meta = {"format": FORMAT_VERSION, "rank": context.rank,
            "nranks": context.nranks}
    for dc in collections:
        for idx in dc.local_tiles():
            datum = dc.data_of(*idx)
            copy = datum.pull_to_host()
            key = ":".join([dc.name] + [str(i) for i in idx])
            arrays[key] = np.asarray(copy.payload)
    out = _rank_path(context, path)
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    np.savez(out, __meta__=np.array([meta["format"], meta["rank"],
                                     meta["nranks"]]), **arrays)
    if context.comm is not None:
        context.comm.ce.barrier()    # the snapshot is collective
    debug_verbose(3, "checkpoint: %d tiles -> %s", len(arrays), out)
    return out


def restore(context, collections: Iterable, path: str) -> int:
    """Load a snapshot back into ``collections`` (same shapes and
    distribution as at checkpoint time).  Host copies become the newest
    authoritative version; stale device copies invalidate.  Returns the
    number of tiles restored."""
    src = _rank_path(context, path)
    with np.load(src, allow_pickle=False) as zf:
        meta = zf["__meta__"]
        if int(meta[0]) != FORMAT_VERSION:
            raise ValueError(f"{src}: unsupported checkpoint format "
                             f"{int(meta[0])}")
        if int(meta[2]) != context.nranks:
            raise ValueError(
                f"{src}: checkpoint was taken on {int(meta[2])} ranks, "
                f"restoring on {context.nranks} (elastic restore is not "
                "supported — match the layout)")
        n = 0
        for dc in collections:
            for idx in dc.local_tiles():
                key = ":".join([dc.name] + [str(i) for i in idx])
                if key not in zf:
                    raise KeyError(f"{src}: missing tile {key}")
                datum = dc.data_of(*idx)
                datum.overwrite_host(zf[key])
                n += 1
    if context.comm is not None:
        context.comm.ce.barrier()
    debug_verbose(3, "restore: %d tiles <- %s", n, src)
    return n
