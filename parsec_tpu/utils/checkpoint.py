"""Checkpoint / restore of collection state.

The reference has NO runtime checkpointing (SURVEY §5.4: taskpool state
is never serialized; the closest mechanisms are completion callbacks and
data flush).  This layer implements the design SURVEY prescribes for the
TPU build: quiesce (pools complete, device pipelines drained, comm
settled), flush every authoritative device copy home, then snapshot the
collections' local tiles — one file per rank, restorable into freshly
built collections of the same shape/distribution.

Usage::

    ctx.wait()                                   # quiesce the DAGs
    checkpoint(ctx, [A, B, C], "/path/ckpt")     # rank-local snapshot
    ...
    restore(ctx, [A, B, C], "/path/ckpt")        # tiles + versions back

Checkpoint/restore are collective when a comm engine is attached: every
rank writes/reads its own shard and a barrier delimits the snapshot so
no rank can race ahead into mutating state another rank still saves.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from parsec_tpu.utils.mca import params
from parsec_tpu.utils.output import debug_verbose

#: format 2 adds per-tile version stamps ("v:" keys) so a shard can
#: serve as an exact-version replay cut for the recovery lineage
#: planner; format-1 shards (no stamps) still restore
FORMAT_VERSION = 2

params.register("recovery_checkpoint_interval_s", 0.0,
                "periodic incremental tile checkpoints for the recovery "
                "lineage planner (core/recovery.py): > 0 captures a "
                "version-stamped host copy of each dirty tile at most "
                "once per interval, riding the write-flow version bumps "
                "the lineage log already observes — the minimal-replay "
                "cut then lands on the most recent captured version "
                "instead of walking back to the pool-attach snapshot.  "
                "0 (default) disables the capture plane")
params.register("recovery_checkpoint_keep", 2,
                "captured versions retained per tile by the incremental "
                "checkpoint store (older captures evict; memory bound = "
                "keep x tile bytes per dirty tile)")


class TileCheckpointStore:
    """In-memory incremental tile checkpoints: version-stamped host
    copies captured on the write-flow completion path (the recovery
    lineage hook calls :meth:`note_write`), at most one capture per
    tile per ``recovery_checkpoint_interval_s``.

    This is the checkpoint-as-lineage tier: the minimal-replay planner
    (core/recovery.py) treats every captured ``(tile, version)`` as a
    MATERIALIZABLE cut, so a backward walk stops at the newest capture
    at-or-below the needed version instead of replaying from the
    pool-attach snapshot — bounded replay depth for long version
    chains.  Captures are torn-free by construction: they run after
    ``complete_write`` bumped the version and before any later writer
    of the same tile can start (the DAG serializes writers).
    """

    def __init__(self, interval_s: float, keep: int = 2):
        self.interval = float(interval_s)
        self.keep = max(1, int(keep))
        #: tile key -> [(version, ndarray)] newest-last
        #: (guarded-by: _lock)
        self._tiles: Dict[Tuple, List[Tuple[int, np.ndarray]]] = {}
        self._last: Dict[Tuple, float] = {}      # guarded-by: _lock
        self._lock = threading.Lock()
        self.captures = 0

    def note_write(self, key: Tuple, version: int, payload) -> None:
        """Capture ``payload`` (already host-resident) at ``version``
        when the tile's interval elapsed; cheap no-op otherwise."""
        now = time.monotonic()
        last = self._last.get(key)
        if last is not None and now - last < self.interval:
            return
        if not isinstance(payload, np.ndarray):
            return
        arr = payload.copy()
        with self._lock:
            self._last[key] = now
            lst = self._tiles.setdefault(key, [])
            lst.append((int(version), arr))
            del lst[:-self.keep]
            self.captures += 1

    def versions(self, key: Tuple) -> Tuple[int, ...]:
        with self._lock:
            return tuple(v for v, _ in self._tiles.get(key, ()))

    def get(self, key: Tuple, version: int) -> Optional[np.ndarray]:
        with self._lock:
            for v, arr in self._tiles.get(key, ()):
                if v == version:
                    return arr
        return None

    def drop(self, key: Tuple) -> None:
        with self._lock:
            self._tiles.pop(key, None)
            self._last.pop(key, None)

    def drop_owner(self, owner) -> None:
        """Evict every capture of one owning collection (keys are
        ``(owner, tile_key)`` — the recovery sweep calls this when a
        collection's recovery spec retires, so a later job's
        same-named tiles can never be served a previous job's bytes
        and a resident service does not accumulate captures forever)."""
        with self._lock:
            for k in [k for k in self._tiles if k[0] == owner]:
                del self._tiles[k]
            for k in [k for k in self._last if k[0] == owner]:
                del self._last[k]

    def clear(self) -> None:
        with self._lock:
            self._tiles.clear()
            self._last.clear()


def _rank_path(context, path: str) -> str:
    rank = context.rank if context is not None else 0
    return f"{path}.r{rank}.npz"


def _check_degraded(context, op: str) -> list:
    """Fail FAST under a degraded comm topology: the quiesce/barrier
    discipline below assumes every rank alive, and a checkpoint
    attempted with a dead peer used to wedge in the collective barrier
    until its timeout.  Dead peers a recovery EXCUSED (their partition
    re-mapped onto survivors) are fine — the barrier itself narrowed to
    the survivor set — but their absence is recorded in the shard
    metadata as an explicit marker.  Returns the excused ranks."""
    comm = getattr(context, "comm", None)
    if comm is None:
        return []
    ce = comm.ce
    excused = set(getattr(ce, "excused_peers", ()) or ())
    fatal = set(ce.dead_peers) - excused
    if fatal:
        from parsec_tpu.core.errors import CheckpointDegradedError
        raise CheckpointDegradedError(
            f"rank {context.rank}: {op} with dead peer(s) "
            f"{sorted(fatal)} — the collective barrier cannot complete "
            "(recover or rebuild the gang first)", ranks=fatal)
    return sorted(excused & set(ce.dead_peers))


def checkpoint(context, collections: Iterable, path: str) -> str:
    """Snapshot every local tile of ``collections`` (host-authoritative:
    device copies are flushed home first).  Returns the rank-local file.
    Call after ``context.wait()`` — a checkpoint of a running DAG is a
    torn checkpoint."""
    excused = _check_degraded(context, "checkpoint")
    # drain device pipelines and push authoritative copies home
    for d in context.device_registry.accelerators:
        dsync = getattr(d, "sync", None)
        if dsync is not None:
            dsync()
    context.device_registry.flush_all()
    arrays = {}
    meta = {"format": FORMAT_VERSION, "rank": context.rank,
            "nranks": context.nranks}
    for dc in collections:
        for idx in dc.local_tiles():
            datum = dc.data_of(*idx)
            copy = datum.pull_to_host()
            key = ":".join([dc.name] + [str(i) for i in idx])
            arrays[key] = np.asarray(copy.payload)
            # per-tile version stamp (format 2): the shard doubles as
            # an exact-version replay cut for the lineage planner
            arrays["v:" + key] = np.int64(datum.newest_version())
    out = _rank_path(context, path)
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    # excused dead ranks write no shard of their own; their adopted
    # tiles land in THIS shard (local_tiles routes through owner_of)
    # and the marker makes the absence explicit at restore time
    np.savez(out, __meta__=np.array([meta["format"], meta["rank"],
                                     meta["nranks"]]),
             __excused__=np.array(excused, dtype=np.int64), **arrays)
    if context.comm is not None:
        context.comm.ce.barrier()    # the snapshot is collective
    debug_verbose(3, "checkpoint: %d tiles -> %s", len(arrays), out)
    return out


def restore(context, collections: Iterable, path: str) -> int:
    """Load a snapshot back into ``collections`` (same shapes and
    distribution as at checkpoint time).  Host copies become the newest
    authoritative version; stale device copies invalidate.  Returns the
    number of tiles restored.

    Degraded topologies: under a recovery re-mapping, ``local_tiles``
    includes tiles ADOPTED from an excused dead rank — those were
    written to the DEAD rank's shard of a pre-death checkpoint, so
    missing keys fall back to the shard of the tile's original owner
    (``rank_of``, the pure distribution) before failing.  This is the
    checkpoint-as-lineage-base story: a survivor restores the whole
    re-mapped partition from the collective snapshot."""
    _check_degraded(context, "restore")
    src = _rank_path(context, path)
    sibling: dict = {}   # original-owner shards opened on demand

    def _sibling(rank: int):
        zf = sibling.get(rank)
        if zf is None:
            zf = sibling[rank] = np.load(
                f"{path}.r{rank}.npz", allow_pickle=False)
        return zf

    try:
        with np.load(src, allow_pickle=False) as zf:
            meta = zf["__meta__"]
            if int(meta[0]) not in (1, FORMAT_VERSION):
                raise ValueError(f"{src}: unsupported checkpoint format "
                                 f"{int(meta[0])}")
            if int(meta[2]) != context.nranks:
                raise ValueError(
                    f"{src}: checkpoint was taken on {int(meta[2])} "
                    f"ranks, restoring on {context.nranks} (elastic "
                    "restore is not supported — match the layout)")
            n = 0
            for dc in collections:
                for idx in dc.local_tiles():
                    key = ":".join([dc.name] + [str(i) for i in idx])
                    source = zf
                    if key not in zf:
                        owner = dc.rank_of(*idx)
                        if owner != context.rank:
                            try:
                                source = _sibling(owner)
                            except OSError:
                                raise KeyError(
                                    f"{src}: missing tile {key} (and "
                                    f"no shard of original owner rank "
                                    f"{owner})")
                        if key not in source:
                            raise KeyError(f"{src}: missing tile {key}")
                    datum = dc.data_of(*idx)
                    datum.overwrite_host(source[key])
                    n += 1
    finally:
        for zf in sibling.values():
            zf.close()
    if context.comm is not None:
        context.comm.ce.barrier()
    debug_verbose(3, "restore: %d tiles <- %s", n, src)
    return n


def shard_versions(path: str, rank: int) -> Dict[str, int]:
    """The per-tile version stamps of one rank's shard (format 2;
    empty for format-1 shards) — the replay-cut metadata the recovery
    cookbook reads when bounding replay depth against a collective
    checkpoint."""
    out: Dict[str, int] = {}
    with np.load(f"{path}.r{rank}.npz", allow_pickle=False) as zf:
        for key in zf.files:
            if key.startswith("v:"):
                out[key[2:]] = int(zf[key])
    return out
