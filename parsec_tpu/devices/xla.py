"""XLA device module: TPU (and any jax backend) task offload.

Rebuild of the reference's GPU device machinery on the XLA execution model
(reference: parsec/mca/device/device_gpu.{c,h} generic GPU base +
parsec/mca/device/cuda/device_cuda_module.c offload pipeline;
parsec/mca/device/template/ is the seam this module fills): each attached
jax device gets a manager thread (stage-in + kernel dispatch — the
reference's mutex-elected manager loop, device_cuda_module.c:2537-2763)
and a completer thread (the analog of CUDA-event polling in
progress_stream:1961).  Kernel dispatch through jax is asynchronous, so the
manager pipelines stage-in and launch while the completer blocks on the
oldest in-flight task's outputs, preserving the reference's
``PARSEC_HOOK_RETURN_ASYNC`` completion contract: the device owns the task
until it re-enters ``complete_execution``.

Device memory is a coherency-tracked cache of datum copies with LRU
eviction and byte accounting (reference: gpu_mem_lru + zone_malloc; here
XLA owns the actual HBM, we manage copy lifetime).  Kernels are pure jax
functions over flow payloads; they are jitted once per (shape, dtype)
signature with input buffers of written flows donated so XLA reuses their
HBM (the moral equivalent of in-place tile updates).

TPU notes: keep tiles MXU-friendly (multiples of 128, bf16/f32); the jit
cache means steady-state execution launches pre-compiled executables only.
"""

from __future__ import annotations

import threading
import weakref
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from parsec_tpu.core.task import HookReturn, Task
from parsec_tpu.data.data import (ACCESS_READ, ACCESS_WRITE, Coherency,
                                  DataCopy, FLAG_COW, FLAG_SCRATCH)
from parsec_tpu.devices.device import Device
from parsec_tpu.core.task import ToDesc
from parsec_tpu.utils import faultinject as _fi
from parsec_tpu.utils.mca import params
from parsec_tpu.utils.output import debug_verbose, warning


def _transient_compile_error(exc: Exception) -> bool:
    """A tunneled-TPU compile RPC that died mid-response (axon
    remote_compile flake): the program is valid and the server usually
    holds it in cache by the time a retry lands.  Anything else —
    OOM, invalid program, real device fault — is NOT transient."""
    s = str(exc)
    return ("remote_compile" in s or "response body closed" in s) \
        and "INTERNAL" in s

params.register("device_inflight_depth", 8,
                "max in-flight device tasks per XLA device")
params.register("device_fuse_bg", 1,
                "compile fused-width programs in a background thread "
                "and dispatch singles meanwhile (0 = compile "
                "synchronously on first use, stalling the wave)")
params.register("device_fuse_warm_wait_ms", 3000.0,
                "how long a wave waits for its fused-width program's "
                "background compile before falling back to de-fused "
                "singles: long enough to cover a server-cached compile "
                "(~1-3s), far below a cold tri_inv-class compile "
                "(minutes)")
params.register("device_fuse_window_ms", 0.0,
                "how long a manager waits for same-class siblings before "
                "launching a narrower-than-device_fuse wave (ms).  On "
                "tunneled TPUs each dispatched program costs ~10-15 ms "
                "of fixed overhead, so trading a few ms of batching "
                "window for 4-8x fewer programs wins whenever readiness "
                "arrives in bursts (eager dep release makes it so); "
                "0 = launch immediately (the right default off-tunnel)")
params.register("device_runahead", 256,
                "max eagerly-completed tasks with unmaterialized outputs "
                "before the completer blocks (memory safety valve; each "
                "blocking wait costs a full RPC round trip on tunneled "
                "TPUs, so keep this well above the DAG's width)")
params.register("device_mem_mb", 0,
                "device copy-cache capacity in MiB (0 = unlimited)")
params.register("device_donate", 1,
                "donate written-flow input buffers to XLA (TPU/GPU only)")
params.register("device_max_faults", 0,
                "disable a device after this many launch faults and fall "
                "back to other incarnations (0 = fail the context, like "
                "an unguarded run; reference: HOOK_RETURN_DISABLE)")
params.register("device_fuse", 8,
                "max same-class ready device tasks fused into ONE XLA "
                "launch (wavefront launch fusion: the TRSM panel or "
                "SYRK/GEMM trailing-update wave of a dense factorization "
                "rides a single dispatch, amortizing per-launch latency; "
                "1 disables)")
params.register("device_fuse_panel", 1,
                "cross-panel chain fusion: a task class carrying a "
                "'fuse_chain' property (POTRF->TRSM, GEQRT/TSQRT->TSQRT) "
                "is HELD at dispatch — its outputs become deferred "
                "placeholders, its deps release eagerly as usual — and "
                "its kernel is traced INTO the consumer wave's XLA "
                "program, so the factorization panel chain costs ONE "
                "dispatch round trip instead of one per link plus the "
                "Python scheduling latency between them (the measured "
                "potrf tunnel-state sensitivity).  0 restores the "
                "per-kernel panel path (the A/B attribution knob)")
params.register("device_fuse_donate", 1,
                "allow input-buffer donation inside CHAINED launches "
                "(device_fuse_panel programs).  Default ON since the "
                "ROADMAP-mandated soak (the slow "
                "test_fused_chain_donation_soak: 50+ fused-chain "
                "geqrf/potrf iterations under delay_dispatch load, 0 "
                "wrong results) — the r8 wrong-R aliasing was "
                "root-caused and fixed at the zero-copy device_put "
                "stage-in (device_put_private).  0 is the off-switch "
                "regression guard.  Plain launches donate regardless "
                "(device_donate)")
params.register("device_dispatchers", 2,
                "manager (launch) threads per XLA device: each dispatch "
                "blocks on the transport ack (milliseconds through a "
                "tunneled TPU), so overlapping independent launches keeps "
                "the device queue fed; ordering stays safe because a "
                "successor is only submitted after its producer's "
                "dispatch returned")


class XlaKernel:
    """Device incarnation spec: a pure jax function over flow payloads.

    The function's named arguments are bound from flow payloads (as jax
    arrays) and task parameters (passed as static arguments, so a kernel
    indexing by a parameter recompiles per value — keep parameters out of
    kernels on hot paths).  It returns the new values of the written flows:
    a dict {flow: array}, a tuple in written-flow declaration order, or a
    single array when exactly one flow is written.
    (reference: the BODY [type=CUDA] incarnation of a JDF task class,
    jdf2c.c:6556 GPU hook generation.)
    """

    _jit_lock = threading.Lock()

    def __init__(self, fn, arg_names: Sequence[str],
                 flow_names: Sequence[str], writable_flows: Sequence[str]):
        self.fn = fn
        self.arg_names = list(arg_names)
        self.flow_names = set(flow_names)
        self.writable = list(writable_flows)   # flow declaration order
        #: per-instance fast path: donate-flag -> jitted callable, dodging
        #: the lock + tuple rebuild on every launch (hot path)
        self._fast: Dict[bool, Any] = {}

    def jitted(self, donate: bool):
        jf = self._fast.get(donate)
        if jf is not None:
            return jf
        jf = self._jitted_slow(donate)
        self._fast[donate] = jf
        return jf

    def jitted_fused(self, donate: bool, n: int):
        """One XLA program applying the kernel to ``n`` independent task
        instances (wavefront launch fusion).  The traced body unrolls the
        n applications; XLA schedules them back-to-back on device, so a
        whole same-class wave costs one dispatch round trip instead of n.
        Compiled once per (n, donate) per shape signature."""
        key = (donate, n)
        jf = self._fast.get(key)
        if jf is not None:
            return jf
        jf = self._jitted_slow(donate, n)
        self._fast[key] = jf
        return jf

    def _jitted_slow(self, donate: bool, n: int = 1):
        # The jit cache lives ON the kernel function object, so its
        # lifetime is the function's: module-level kernels (apps memoize
        # theirs, e.g. gemm._kernels) share traced executables across
        # taskpool rebuilds, while per-build lambdas die with their pools
        # instead of pinning entries in a global table forever.
        k = len(self.arg_names)
        static1 = tuple(i for i, a in enumerate(self.arg_names)
                        if a not in self.flow_names)
        dn1 = tuple(i for i, a in enumerate(self.arg_names)
                    if a in self.flow_names and a in self.writable) \
            if donate else ()
        static = tuple(t * k + i for t in range(n) for i in static1)
        dn = tuple(t * k + i for t in range(n) for i in dn1)
        key = (static, dn, n)
        with XlaKernel._jit_lock:
            cache = getattr(self.fn, "__parsec_jit_cache__", None)
            if cache is None:
                cache = {}
                try:
                    self.fn.__parsec_jit_cache__ = cache
                except AttributeError:   # unsettable callable: no sharing
                    pass
            jf = cache.get(key)
            if jf is None:
                import jax
                if n == 1:
                    target = self.fn
                else:
                    fn = self.fn

                    def target(*flat):
                        return tuple(fn(*flat[t * k:(t + 1) * k])
                                     for t in range(n))
                jf = jax.jit(target, static_argnums=static, donate_argnums=dn)
                cache[key] = jf
            return jf

    def bind_outputs(self, result: Any) -> Dict[str, Any]:
        from parsec_tpu.core.task import normalize_body_outputs
        return normalize_body_outputs(result, self.writable, what="kernel")

    def fuse_ready(self, donate: bool, n: int, flat: Sequence[Any]) -> bool:
        """Whether the width-``n`` fused program may be dispatched NOW.

        First use of a fused width triggers a full XLA compile — minutes
        for tri_inv-class programs on tunneled TPUs — and which widths a
        run needs depends on nondeterministic wave scheduling, so a cold
        width mid-measurement stalls the whole pipeline (the r4 geqrf
        variance).  Instead of blocking, the first request WARMS the
        width in a background thread (shape-only lower+compile — the
        expensive XLA server compile lands in the server cache, so the
        eventual jit call is cheap) and the caller falls back to the
        already-compiled width-1 program."""
        if n <= 1:
            return True
        if not int(params.get("device_fuse_bg", 1)):
            return True    # kill-switch: compile widths synchronously
        import time as _time
        key = ("w", donate, n, tuple(
            (tuple(a.shape), str(a.dtype)) if hasattr(a, "shape") else a
            for a in flat))
        with XlaKernel._jit_lock:
            st = self._fast.get(key)
            if st is True:
                return True
            if st == "warming":
                return False
            if isinstance(st, tuple) and st[0] == "failed":
                if _time.monotonic() - st[1] < 60.0:
                    return False    # backoff: singles, no wait
                self._fast.pop(key, None)
            self._fast[key] = "warming"

        specs = []
        try:
            import jax
            for a in flat:
                specs.append(jax.ShapeDtypeStruct(a.shape, a.dtype)
                             if hasattr(a, "shape") else a)
        except Exception:
            with XlaKernel._jit_lock:
                self._fast.pop(key, None)
            return False
        _fuse_warmer.submit(self, key, donate, n, specs)
        # Bounded wait: when the program is server-cached (steady state,
        # earlier sessions), the background compile lands in ~1-3s and
        # dispatching FUSED is far cheaper than a de-fused singles rep
        # (measured: potrf lost 30% to eager singles).  A genuinely cold
        # tri_inv-class program blows past the bound and the wave takes
        # the singles path while the compile finishes in background.
        wait_s = float(params.get("device_fuse_warm_wait_ms", 3000.0)) \
            * 1e-3
        deadline = _time.monotonic() + wait_s
        while _time.monotonic() < deadline:
            with XlaKernel._jit_lock:
                st = self._fast.get(key)
            if st is True:
                return True
            if st != "warming":
                return False     # warm failed; singles this time
            _time.sleep(0.05)
        return False


class _FuseWarmer:
    """ONE background thread compiling fused-width programs serially:
    concurrent huge remote compiles pressure the tunnel's compile
    server (RESOURCE_EXHAUSTED observed with a free-for-all), and a
    single queue still warms every width well before steady state."""

    def __init__(self):
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._thread = None
        self._busy = 0

    def submit(self, spec, key, donate, n, arg_specs) -> None:
        with self._cv:
            self._q.append((spec, key, donate, n, arg_specs))
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="xla-fuse-warm")
                self._thread.start()
            self._cv.notify_all()

    def wait_idle(self, timeout: float = 600.0) -> bool:
        """Block until every queued width compile has finished — the
        bench-warmup hook: a timed rep must not run de-fused because
        its widths are still warming (see xla.wait_fuse_warm)."""
        import time as _time
        deadline = _time.monotonic() + timeout
        with self._cv:
            while self._q or self._busy:
                left = deadline - _time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(min(left, 0.2))
        return True

    def _run(self):
        while True:
            with self._cv:
                if not self._q:
                    # linger briefly for more work, then retire —
                    # clearing _thread UNDER THE LOCK first, so a
                    # submit() racing the unwind sees a dead warmer and
                    # restarts one (else its item would never compile)
                    self._cv.wait(5.0)
                    if not self._q:
                        self._thread = None
                        return
                spec, key, donate, n, arg_specs = self._q.popleft()
                self._busy += 1
            try:
                spec.jitted_fused(donate, n).lower(*arg_specs).compile()
                ok = True
            except Exception:
                ok = False
            import time as _time
            with XlaKernel._jit_lock:
                if ok:
                    spec._fast[key] = True
                else:
                    # failure memoization with backoff: a persistently
                    # failing width must not make every wave re-pay the
                    # bounded wait (fuse_ready checks the stamp)
                    spec._fast[key] = ("failed", _time.monotonic())
            with self._cv:
                self._busy -= 1
                self._cv.notify_all()


_fuse_warmer = _FuseWarmer()


def wait_fuse_warm(timeout: float = 600.0) -> bool:
    """Wait for all in-flight fused-width background compiles (benches
    call this between warmup and timed reps, then run ONE more warm
    pass so the newly-ready widths' client-side jit calls also land in
    cache — otherwise reps run de-fused singles while widths warm)."""
    return _fuse_warmer.wait_idle(timeout)


class Deferred:
    """Placeholder payload of a chain-held task's output (cross-panel
    fused dispatch; reference analog: the panel chains DPLASMA keeps on
    one CUDA stream so POTRF->TRSM never round-trips through the host).

    A held task's deps release eagerly — consumers instantiate and reach
    the device with Deferred payloads — and the held kernel is traced
    into the first consuming launch (XlaDevice._dispatch_chained), which
    resolves ``array`` for every other consumer.  Foreign consumers (a
    CPU body, another device, the ICI layer) call :meth:`force`, which
    dispatches the held chain on its owning device."""

    __slots__ = ("hold", "flow", "_shape", "_dtype", "array")

    #: duck-typing marker for layers that must not touch placeholder
    #: payloads (engine.stage_in_host, comm/ici.py)
    parsec_deferred = True

    def __init__(self, hold, flow, shape, dtype):
        self.hold = hold
        self.flow = flow
        self._shape = shape
        self._dtype = dtype
        self.array = None      # filled at resolution

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self._dtype

    @property
    def nbytes(self):
        if self.array is not None:
            return getattr(self.array, "nbytes", 0)
        try:
            n = 1
            for d in self._shape:
                n *= int(d)
            return n * np.dtype(self._dtype).itemsize
        except Exception:
            return 0

    def force(self):
        """Dispatch the held chain now (owning device) and return the
        real array."""
        if self.array is None:
            self.hold.device._force_deferred(self)
        return self.array

    def is_ready(self):
        a = self.array
        if a is None:
            return False
        r = getattr(a, "is_ready", None)
        try:
            return bool(r()) if r is not None else True
        except Exception:
            return True

    def block_until_ready(self):
        import jax
        a = self.array if self.array is not None else self.force()
        return jax.block_until_ready(a)


class _Hold:
    """One chain-held device task: staged inputs + deferred outputs.
    ``state`` moves held -> launching -> resolved under the device's
    ``_chain_cv``."""

    __slots__ = ("device", "task", "spec", "flat", "outputs", "state",
                 "seq")


_chain_jit_lock = threading.Lock()
#: (node structure, wave structure) -> jitted combined program.  Keys
#: hold the kernel function objects, so entries die only with the app's
#: memoized kernels; chain structures repeat per panel index, so steady
#: state compiles each shape once.
_chain_jit_cache: Dict[Any, Any] = {}


def _chain_jitted(key, node_specs, node_descs, wave_spec, wave_descs,
                  donate=()):
    """One XLA program executing the held chain nodes in topological
    order, then the consumer wave, wiring arguments by descriptor:
    ("l", i) = leaf input, ("n", j, flow) = node j's output, ("s", v) =
    static value closed over (part of the cache key)."""
    with _chain_jit_lock:
        jf = _chain_jit_cache.get(key)
        if jf is not None:
            return jf

    def resolve(d, leaves, node_outs):
        tag = d[0]
        if tag == "l":
            return leaves[d[1]]
        if tag == "n":
            return node_outs[d[1]][d[2]]
        return d[1]

    def prog(*leaves):
        node_outs = []
        for sp, ds in zip(node_specs, node_descs):
            args = [resolve(d, leaves, node_outs) for d in ds]
            node_outs.append(sp.bind_outputs(sp.fn(*args)))
        waves = []
        if wave_spec is not None:
            for ds in wave_descs:
                args = [resolve(d, leaves, node_outs) for d in ds]
                waves.append(wave_spec.bind_outputs(wave_spec.fn(*args)))
        return node_outs, waves

    import jax
    jf = jax.jit(prog, donate_argnums=tuple(donate))
    with _chain_jit_lock:
        return _chain_jit_cache.setdefault(key, jf)


def device_put_private(payload, jdev):   # lint: alias-wrapper
    """``jax.device_put`` that GUARANTEES a private buffer.

    On the CPU client (virtual multi-device meshes, tests, the dryrun)
    ``np.asarray`` of a device array is a zero-copy view and
    ``device_put`` of an aligned host buffer is zero-copy too — so a
    cross-device "copy" can silently ALIAS the source buffer.  Donation
    or an in-place update of either side then corrupts the other: the
    r8 root cause of the intermittent geqrf wrong-R (a consumer's staged
    tile changed under it when the producer-side buffer was donated).
    Real accelerator transfers never alias (and keep their direct D2D
    path here), so the pointer probe costs one comparison and the
    defensive copy never runs there."""
    import jax
    out = jax.device_put(payload, jdev)
    try:
        optr = out.unsafe_buffer_pointer()
    except Exception:
        return out   # probe unsupported on this backend: transfers copy
    sptr = _source_pointer(payload)
    if sptr is not None and optr == sptr:
        out = jax.device_put(np.asarray(payload).copy(), jdev)
    return out


def _source_pointer(payload):
    """Best-effort raw buffer pointer of a host/device payload (the
    alias probe shared by the private-put wrappers)."""
    try:
        return payload.unsafe_buffer_pointer()
    except Exception:
        iface = getattr(payload, "__array_interface__", None)
        return iface["data"][0] if iface is not None else None


def device_put_replicated_private(payload, sharding):   # lint: alias-wrapper
    """``jax.device_put`` onto a (replicating) sharding that GUARANTEES
    no shard aliases the source buffer — the multi-device sibling of
    :func:`device_put_private`.  On the CPU client the shard co-located
    with the host buffer can alias it, so a later in-place mutation or
    donation of the source would corrupt every consumer's replica (the
    same geqrf wrong-R hazard, through the broadcast path).  Real
    accelerator transfers never alias; there the probe is one pointer
    compare per shard and the defensive copy never runs."""
    import jax
    rep = jax.device_put(payload, sharding)
    sptr = _source_pointer(payload)
    if sptr is not None:
        try:
            aliased = any(s.data.unsafe_buffer_pointer() == sptr
                          for s in rep.addressable_shards)
        except Exception:
            aliased = False   # probe unsupported: transfers copy
        if aliased:
            rep = jax.device_put(np.asarray(payload).copy(), sharding)
    return rep


#: marks an LRU entry as an in-progress adopt claim (distinguishable from
#: a real accounted entry even at nbytes == 0)
_PLACEHOLDER = object()


class _Inflight:
    __slots__ = ("es", "task", "spec", "outputs", "pinned", "load",
                 "release_after", "prepublished")

    def __init__(self, es, task, spec, outputs, pinned, load, release_after):
        self.es = es
        self.task = task
        self.spec = spec
        self.outputs = outputs
        self.pinned = pinned
        self.load = load
        #: host arena copies to return to their freelist once the kernel
        #: (and therefore the H2D transfer reading them) has completed
        self.release_after = release_after
        #: chain-held tasks already planted their (Deferred) payloads at
        #: hold time; the completer must not overwrite the resolution
        self.prepublished = False


class XlaDevice(Device):
    """One jax device as a runtime device module."""

    kind = "xla"

    def __init__(self, jdev, weight: float = 1.0):
        super().__init__(f"{jdev.platform}:{jdev.id}")
        self.jdev = jdev
        self.platform = jdev.platform
        self.weight = weight
        # "axon" is the tunneled-TPU PJRT platform name
        self.kind = "tpu" if self.platform in ("tpu", "axon") else "xla"
        self._donate = (bool(params.get("device_donate", 1))
                        and self.platform in ("tpu", "axon", "gpu", "cuda",
                                              "rocm"))
        self._chain_donate = self._donate and \
            bool(int(params.get("device_fuse_donate", 1)))
        self._depth = max(1, int(params.get("device_inflight_depth", 8)))
        self._runahead = max(self._depth,
                             int(params.get("device_runahead", 256)))
        cap_mb = int(params.get("device_mem_mb", 0))
        self._capacity = cap_mb * (1 << 20) if cap_mb > 0 else None
        self._bytes_used = 0
        #: segment ledger over the HBM budget (reference: the GPU slab
        #: zone_malloc, utils/zone_malloc.c — XLA owns physical HBM, so
        #: the zone tracks logical segments to drive eviction exactly
        #: where the reference drove cudaMalloc'd slabs)
        if self._capacity is not None:
            self._zone = None
            try:
                from parsec_tpu.native import NativeZoneAllocator, available
                if available():
                    self._zone = NativeZoneAllocator(self._capacity)
            except Exception:
                pass
            if self._zone is None:
                from parsec_tpu.utils.zone_alloc import ZoneAllocator
                self._zone = ZoneAllocator(self._capacity)
        else:
            self._zone = None
        #: datum-id -> (weakref to device copy, nbytes, zone offset);
        #: insertion order = LRU order.  Weak so per-task temporaries
        #: (NEW-flow datums) do not accumulate here forever — a finalizer
        #: drops the accounting when the copy dies with its datum.
        self._lru: "OrderedDict[int, Tuple[Any, int, Any]]" = OrderedDict()
        self._pins: Dict[int, int] = {}
        # a Condition so adopt() can WAIT for a concurrent claim on the
        # same datum to resolve instead of polling (notified whenever a
        # placeholder resolves); plain `with self._mem_lock:` still works
        self._mem_lock = threading.Condition()

        self._pending: deque = deque()
        self._inflight: deque = deque()
        #: chain-held tasks (cross-panel fused dispatch): id(task) ->
        #: _Hold, resolved when a consumer launch traces them in
        self._held: "OrderedDict[int, _Hold]" = OrderedDict()
        self._chain_cv = threading.Condition()
        self._hold_seq = 0
        #: eagerly-completed tasks whose outputs are not yet materialized
        #: on device; finalized (pins/load/arena released) as they become
        #: ready, oldest-first
        self._retire: deque = deque()
        self._launching = 0
        self._completing = 0
        self._finalizing = 0
        self._cond = threading.Condition()
        self._stop = False
        self.es = None   # device execution stream, set on first submit
        self._managers = [
            threading.Thread(target=self._manager_loop,
                             name=f"xla-mgr-{self.name}-{i}", daemon=True)
            for i in range(max(1, int(params.get("device_dispatchers", 2))))]
        self._completer = threading.Thread(
            target=self._completer_loop, name=f"xla-fin-{self.name}",
            daemon=True)
        for m in self._managers:
            m.start()
        self._completer.start()

    # ------------------------------------------------------------------
    # submit: worker thread -> device ownership (HOOK_RETURN_ASYNC)
    # ------------------------------------------------------------------
    def submit(self, es, task: Task, spec: XlaKernel) -> HookReturn:
        flops = task.task_class.properties.get("flops", 1.0)
        load = float(flops(task.locals)) if callable(flops) else float(flops)
        self.load_add(load)
        with self._cond:
            if self.es is None:
                from parsec_tpu.core.context import ExecutionStream
                self.es = ExecutionStream(es.context, th_id=900 + self.space)
            self._pending.append((task, spec, load))
            self._cond.notify_all()
        return HookReturn.ASYNC

    # ------------------------------------------------------------------
    # manager: stage-in + dispatch (reference: parsec_cuda_kernel_push /
    # submit phases of the manager state machine)
    # ------------------------------------------------------------------
    def _manager_loop(self):
        while True:
            with self._cond:
                while not self._pending and not self._stop:
                    self._cond.wait(0.1)
                if self._stop and not self._pending:
                    return
                batch = self._pop_wave_locked()
                self._launching += 1
            try:
                if _fi.ARMED:
                    # fault plan delay_dispatch: perturb the manager /
                    # completer interleaving deterministically
                    _fi.device_delay()
                self._launch(batch)
            except Exception as exc:   # stage-in/compile failure
                from parsec_tpu.core import scheduling
                self.stats.faults += 1
                for _task, _spec, qload in batch:
                    self.load_sub(qload)
                rescued = self._degrade([t for t, _s, _l in batch], exc)
                if not rescued:
                    for t, _s, _l in batch:
                        self.es.context.record_error(exc, t)
                        scheduling.complete_execution(self.es, t, failed=True)
            finally:
                with self._cond:
                    self._launching -= 1
                    self._cond.notify_all()

    def _pop_wave_locked(self):
        """Pop the next task plus every queued same-class sibling it can
        fuse with (same kernel spec, equal non-flow args, matching
        payload shapes), up to ``device_fuse`` (wavefront launch fusion;
        reference analog: the GPU manager draining its pending FIFO into
        the exec streams, device_cuda_module.c:2697 — here the drain
        fuses the wave into one XLA program).  Non-matching entries keep
        their queue order.  Caller holds ``_cond``."""
        first = self._pending.popleft()
        limit = int(params.get("device_fuse", 8))
        if limit <= 1:
            return [first]
        task, spec, _load = first
        sig = self._fuse_sig(task, spec)
        if sig is None:
            return [first]
        window = float(params.get("device_fuse_window_ms", 0.0)) * 1e-3
        if not self._pending and window <= 0:
            return [first]
        batch = [first]
        rest = []
        import time as _time
        deadline = _time.monotonic() + window
        while True:
            # bound each scan at a small multiple of the fuse width: the
            # lock is shared with submit()/sync(), so an unbounded walk
            # over a deep mixed-class queue would serialize workers
            scan_budget = 4 * limit
            while self._pending and len(batch) < limit \
                    and scan_budget > 0:
                scan_budget -= 1
                cand = self._pending.popleft()
                if cand[1] is spec and \
                        self._fuse_sig(cand[0], spec) == sig:
                    batch.append(cand)
                else:
                    rest.append(cand)
            if len(batch) >= limit or window <= 0:
                break
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                break
            # sibling-batching window: readiness arrives in bursts
            # (deps release eagerly at dispatch), so a short wait
            # consolidates the burst into one wide program.  Requeue the
            # skipped non-matching entries BEFORE waiting — the wait
            # releases _cond and the other manager must be able to
            # dispatch those other-class tasks meanwhile.
            for item in reversed(rest):
                self._pending.appendleft(item)
            rest = []
            self._cond.wait(min(remaining, 0.002))
            if self._stop:
                break
        # quantize to the largest power of two <= wave size: each distinct
        # fused width is a separate XLA compile, so arbitrary widths would
        # keep tripping fresh compiles mid-run; powers of two cap the
        # variety at log2(device_fuse) per kernel
        quant = 1 << (len(batch).bit_length() - 1)
        # requeue order: skipped non-matching entries first (restoring
        # their queue positions), then the quantization extras IN FRONT so
        # they lead the next wave and can fuse with arriving siblings
        for item in reversed(rest):
            self._pending.appendleft(item)
        for item in reversed(batch[quant:]):
            self._pending.appendleft(item)
        batch = batch[:quant]
        return batch

    @staticmethod
    def _fuse_sig(task: Task, spec: XlaKernel):
        """Fusion compatibility signature: the values of non-flow kernel
        args (static argnums — they specialize the compile) and the
        shape/dtype of each flow payload.  None = not fusable (unbound
        or unhashable)."""
        sig = []
        try:
            for a in spec.arg_names:
                if a in spec.flow_names:
                    copy = task.data.get(a)
                    p = copy.payload if copy is not None else None
                    if p is None:
                        return None
                    sig.append((a, tuple(p.shape), str(p.dtype)))
                else:
                    v = task.locals.get(a, task.taskpool.globals.get(a))
                    hash(v)
                    sig.append((a, v))
        except Exception:
            return None
        return tuple(sig)

    def _degrade(self, tasks: List[Task], exc: Exception) -> bool:
        """Degraded mode (the reference's ONLY fault tolerance: device
        errors disable the device and push tasks back to the CPU
        incarnation, PARSEC_HOOK_RETURN_DISABLE /
        device_cuda_module.c:2757-2762).  After ``device_max_faults``
        launch failures the device disables itself and the failing tasks
        — plus everything still queued here — reschedule to fall
        through to the next incarnation.  Returns True when the tasks
        were rescued."""
        limit = int(params.get("device_max_faults", 0))
        if limit <= 0 or self.es is None:
            return False      # unguarded: the fault fails the context
        from parsec_tpu.core import scheduling
        from parsec_tpu.utils.output import warning
        rescued = list(tasks)
        with self._cond:
            if self.stats.faults >= limit and self.enabled:
                # past the limit: stop taking work and drain the queue
                # back to the scheduler for other incarnations
                self.enabled = False
                warning("device %s disabled after %d faults (%s); "
                        "falling back to other incarnations", self.name,
                        self.stats.faults, exc)
            if not self.enabled:
                while self._pending:
                    qtask, _spec, qload = self._pending.popleft()
                    self.load_sub(qload)
                    rescued.append(qtask)
        for t in rescued:
            t.status = scheduling.TaskStatus.READY
        scheduling.schedule(self.es, rescued)
        return True

    def _launch(self, batch) -> None:
        """Stage and dispatch one wave: a list of (task, spec, load) with
        a shared kernel spec (len 1 = the plain single-task launch).  The
        whole wave rides ONE jitted call (XlaKernel.jitted_fused), so a
        k-wide TRSM/SYRK/GEMM wavefront costs one dispatch round trip."""
        spec: XlaKernel = batch[0][1]
        n = len(batch)
        #: pins and deferred arena releases stay PER TASK: each inflight
        #: entry holds only its own, so finalizing one entry of a fused
        #: wave cannot unpin a sibling's datums before that sibling's
        #: completion ran (a concurrent dispatcher's _reserve would evict
        #: the still-live copy)
        pinned_per: List[List[Any]] = []
        release_per: List[List[DataCopy]] = []
        flat: List[Any] = []
        try:
            for task, _spec, _load in batch:
                tc = task.task_class
                staged: Dict[str, Any] = {}
                pinned: List[Any] = []
                release_after: List[DataCopy] = []
                pinned_per.append(pinned)
                release_per.append(release_after)
                # pin every datum this task touches before any eviction
                # decision
                for flow in tc.flows:
                    copy = task.data.get(flow.name)
                    if copy is not None and copy.data is not None:
                        self._pin(copy.data)
                        pinned.append(copy.data)
                for flow in tc.flows:
                    copy = task.data.get(flow.name)
                    if copy is None:
                        continue
                    dc = self._stage_in(copy, flow.access,
                                        pinned=flow.name in task.pinned_flows)
                    if dc is not copy and copy.device == 0 \
                            and copy.arena is not None:
                        # host arena temp fully superseded by the device
                        # copy: return it to the freelist once the kernel
                        # completes (the H2D transfer may still read it)
                        copy.data.detach_copy(0)
                        release_after.append(copy)
                    task.data[flow.name] = dc
                    staged[flow.name] = dc.payload
                for a in spec.arg_names:
                    if a in staged:
                        flat.append(staged[a])
                    elif a in task.locals:
                        flat.append(task.locals[a])
                    else:
                        flat.append(task.taskpool.globals.get(a))
            # already-resolved chain placeholders substitute transparently
            flat = [a.array if isinstance(a, Deferred)
                    and a.array is not None else a for a in flat]
            if n == 1 and spec.writable \
                    and self._chain_eligible(batch[0][0], spec):
                # chain head (POTRF(k), TSQRT(m,k)...): hold instead of
                # dispatching — deps release eagerly through the normal
                # completer path with Deferred payloads, and the kernel
                # is traced into the consumer wave's launch
                self._hold_task(batch[0], flat, pinned_per[0],
                                release_per[0])
                return
            if any(isinstance(a, Deferred) for a in flat):
                outs_per_task = self._dispatch_chained(spec, n, flat)
                fused = False
            else:
                fused, outs_per_task = self._dispatch_plain(spec, n, flat)
            if fused:
                # count only waves the fused program actually executed —
                # a de-fused n>1 wave (fuse_ready False) ran singles
                self.stats.fused_launches += 1
                self.stats.fused_tasks += n
        except Exception:
            for pinned in pinned_per:
                for d in pinned:
                    self._unpin(d)
            # arena copies already detached for deferred release would
            # otherwise leak on the failure path (ADVICE r1 low);
            # release_unheld: a chained NEW-flow buffer a predecessor's
            # repo entry still holds must wait for that retirement
            for release_after in release_per:
                for copy in release_after:
                    copy.arena.release_unheld(copy)
            raise
        self.stats.executed_tasks += n
        if self.es.context._device_spans:
            # device span opens at dispatch (the wave just entered the
            # accelerator pipeline); the matching device_done fires when
            # the outputs materialize (_finalize) — together the
            # dispatch->done device segment of the causal trace (and of
            # the flight recorder's incident ring).  The gate is
            # maintained by Context._recompute_ready_stamp, so a
            # recorder whose classes exclude 'device' costs nothing
            for task, _spec2, _load2 in batch:
                self.es.pins("device_dispatch", task)
        with self._cond:
            # gate on the WHOLE wave fitting under the inflight depth:
            # appending n entries after a <depth check would let the
            # window exceed device_inflight_depth by fuse-width-1 and
            # under-account HBM backpressure (ADVICE r3 low)
            room = max(self._depth - n, 0)   # n>depth: drain fully first
            while len(self._inflight) > room and not self._stop:
                self._cond.wait(0.1)
            for i, (task, _spec, load) in enumerate(batch):
                self._inflight.append(
                    _Inflight(self.es, task, spec, outs_per_task[i],
                              pinned_per[i], load, release_per[i]))
            self._cond.notify_all()

    def _dispatch_plain(self, spec: XlaKernel, n: int, flat: List[Any]):
        """The pre-existing dispatch path: one (possibly width-fused)
        jitted call over real arrays.  Returns (fused, bound outputs per
        task)."""
        donate = self._donate and not self._donation_hazard(spec, flat)

        def call1(fn, args):
            """One jitted call with the transient-flake retry AT THE
            CALL, never around a partially-executed sequence: an
            error naming remote_compile died in the COMPILE phase —
            nothing executed, donated inputs intact — so it retries
            even with donation; other transient shapes retry only
            when nothing was donated (a flake after donation leaves
            the inputs deleted).  Retrying per call keeps the
            singles-fallback path safe — already-executed siblings
            consumed their donated buffers and must not replay."""
            try:
                return fn(*args)
            except Exception as exc:
                if not _transient_compile_error(exc) or \
                        (donate and "remote_compile" not in str(exc)):
                    raise
                warning("%s: transient compile failure (%s); "
                        "retrying once", self.name, str(exc)[:120])
                return fn(*args)   # server-side cache warm now

        def dispatch():
            if n == 1:
                return False, [call1(spec.jitted(donate), flat)]
            if not spec.fuse_ready(donate, n, flat):
                # the fused width is still compiling in the
                # background (tri_inv-class programs take minutes
                # over the tunnel): dispatch singles now — the wave
                # fuses once the width is warm
                k = len(spec.arg_names)
                return False, [call1(spec.jitted(donate),
                                     flat[i * k:(i + 1) * k])
                               for i in range(n)]
            return True, list(call1(spec.jitted_fused(donate, n), flat))

        fused, results = dispatch()
        return fused, [spec.bind_outputs(r) for r in results]

    # ------------------------------------------------------------------
    # cross-panel chain fusion (device_fuse_panel): hold chain heads,
    # trace them into their consumer wave's launch
    # ------------------------------------------------------------------
    def _chain_eligible(self, task: Task, spec: XlaKernel) -> bool:
        """Whether this task may be chain-held: the knob is on, its
        class names a 'fuse_chain' (flow, successor class), the run is
        single-rank (remote activations must never see a Deferred
        payload), and the chain flow has at least one task successor to
        force the eventual launch."""
        try:
            if not int(params.get("device_fuse_panel", 1)):
                return False
        except (TypeError, ValueError):
            return False
        fc = task.task_class.properties.get("fuse_chain")
        if not fc:
            return False
        tp = task.taskpool
        ctx = getattr(tp, "context", None)
        if ctx is None or getattr(ctx, "nranks", 1) > 1:
            return False
        flow_name = fc[0] if isinstance(fc, (tuple, list)) else fc
        flow = task.task_class.flow(flow_name)
        if flow is None:
            return False
        from parsec_tpu.core.task import ToTask
        try:
            for dep in flow.active_outputs(task.locals):
                if isinstance(dep.end, ToTask):
                    for _ in dep.end.instances(task.locals):
                        return True
        except Exception:
            return False
        return False

    def _hold_task(self, item, flat, pinned, release_after) -> None:
        """Park a chain head: its outputs become Deferred payloads on
        the already-staged copies, and the task completes eagerly
        through the normal completer path (deps release, successors
        instantiate) without any dispatch."""
        task, spec, load = item
        h = _Hold()
        h.device = self
        h.task = task
        h.spec = spec
        h.flat = list(flat)
        h.state = "held"
        h.outputs = {}
        for fl in spec.writable:
            dc = task.data.get(fl)
            p = dc.payload if dc is not None else None
            d = Deferred(h, fl, tuple(getattr(p, "shape", ()) or ()),
                         getattr(p, "dtype", None))
            h.outputs[fl] = d
            if dc is not None:
                dc.payload = d
        with self._chain_cv:
            self._hold_seq += 1
            h.seq = self._hold_seq
            self._held[id(task)] = h
        inf = _Inflight(self.es, task, spec, h.outputs, pinned, load,
                        release_after)
        inf.prepublished = True
        with self._cond:
            room = max(self._depth - 1, 0)
            while len(self._inflight) > room and not self._stop:
                self._cond.wait(0.1)
            self._inflight.append(inf)
            self._cond.notify_all()

    def _claim_chain(self, roots: List[Deferred]) -> List[_Hold]:
        """Claim the transitive closure of held tasks the given
        placeholders depend on, all-or-nothing (two concurrent claimers
        can never wait on each other, so no deadlock): returns the
        claimed holds in topological (creation) order, or [] once
        everything resolved while waiting."""
        while True:
            with self._chain_cv:
                need: List[_Hold] = []
                seen = set()

                def visit(d):
                    if d.array is not None:
                        return
                    hd = d.hold
                    if id(hd) in seen or hd.state == "resolved":
                        return
                    seen.add(id(hd))
                    for a in hd.flat or ():
                        if isinstance(a, Deferred):
                            visit(a)
                    need.append(hd)   # post-order = dependencies first

                for d in roots:
                    visit(d)
                if not need:
                    return []
                if all(hd.state == "held" for hd in need):
                    for hd in need:
                        hd.state = "launching"
                    return sorted(need, key=lambda hd: hd.seq)
                # part of the chain is being launched by another thread:
                # wait for its resolution, then recompute the closure
                self._chain_cv.wait(0.1)

    def _run_chain(self, claimed: List[_Hold], wave_spec=None, n=0,
                   flat=None):
        """Trace the claimed chain (and optional consumer wave) into ONE
        jitted program and dispatch it.  A leaf is donated to XLA only
        when it feeds a WRITTEN flow position and appears exactly once
        in the whole program (the usage count is the chained analog of
        _donation_hazard) — in-place tile updates keep their HBM
        headroom on chained panel waves too."""
        leaves: List[Any] = []
        leaf_ix: Dict[int, int] = {}
        leaf_uses: Dict[int, int] = {}
        donatable: set = set()
        node_ix = {id(hd): i for i, hd in enumerate(claimed)}

        def desc(a, writable=False):
            if isinstance(a, Deferred):
                if a.array is not None:
                    a = a.array
                else:
                    return ("n", node_ix[id(a.hold)], a.flow)
            if hasattr(a, "shape") and hasattr(a, "dtype"):
                j = leaf_ix.get(id(a))
                if j is None:
                    j = leaf_ix[id(a)] = len(leaves)
                    leaves.append(a)
                leaf_uses[j] = leaf_uses.get(j, 0) + 1
                if writable:
                    donatable.add(j)
                return ("l", j)
            return ("s", a)

        def spec_descs(sp, args):
            wr = [a in sp.flow_names and a in sp.writable
                  for a in sp.arg_names]
            return tuple(desc(a, wr[i]) for i, a in enumerate(args))

        node_descs = [spec_descs(hd.spec, hd.flat) for hd in claimed]
        wave_descs = ()
        if wave_spec is not None and n:
            k = len(wave_spec.arg_names)
            wave_descs = tuple(
                spec_descs(wave_spec, flat[t * k:(t + 1) * k])
                for t in range(n))
        # REGRESSION GUARD (r8, the geqrf wrong-R flake): chained
        # launches donate NOTHING by default.  A/B under load +
        # delay_dispatch fault plans attributed the intermittent wrong
        # R to donation in chained programs (fuse=1/donate=1: 2 wrong
        # in 22 runs; fuse=1/donate=0 and fuse=0: 0 in 46) — a chain's
        # leaves were staged at HOLD time, long before this launch, and
        # the leaf-used-once rule cannot see every later reference the
        # way the plain path's same-instant _donation_hazard can.
        # device_fuse_donate=1 re-enables it for root-cause work.
        donate = tuple(sorted(j for j in donatable
                              if leaf_uses.get(j) == 1)) \
            if self._chain_donate else ()
        key = (tuple((hd.spec.fn, d)
                     for hd, d in zip(claimed, node_descs)),
               wave_spec.fn if wave_spec is not None else None,
               wave_descs, donate)
        hash(key)    # unhashable static -> the caller's failure path
        jf = _chain_jitted(key, [hd.spec for hd in claimed], node_descs,
                           wave_spec, wave_descs, donate)
        try:
            node_outs, wave_outs = jf(*leaves)
        except Exception as exc:
            # transient tunneled compile flake: an error naming
            # remote_compile died in the COMPILE phase — donated inputs
            # intact — so it retries even with donation; other transient
            # shapes retry only when nothing was donated (call1's rule)
            if not _transient_compile_error(exc) or \
                    (donate and "remote_compile" not in str(exc)):
                raise
            warning("%s: transient compile failure in chained launch "
                    "(%s); retrying once", self.name, str(exc)[:120])
            node_outs, wave_outs = jf(*leaves)
        self.stats.chained_launches += 1
        self.stats.chained_tasks += len(claimed) + \
            (n if wave_spec is not None else 0)
        return node_outs, wave_outs

    def _resolve_holds(self, claimed: List[_Hold], node_outs) -> None:
        """Publish a dispatched chain's outputs: fill every Deferred and
        swap the placeholder payloads for the real (asynchronous)
        arrays, then wake claim-waiters."""
        with self._chain_cv:
            for hd, outs in zip(claimed, node_outs):
                for fl, arr in outs.items():
                    d = hd.outputs.get(fl)
                    if d is not None:
                        d.array = arr
                    dc = hd.task.data.get(fl)
                    # identity check, not isinstance: on an RW chain the
                    # SAME copy carries successive holds' placeholders
                    # (TSQRT column T), and resolving an earlier link
                    # must not regress the payload over a later one
                    if dc is not None and dc.payload is d:
                        dc.payload = arr
                hd.state = "resolved"
                hd.flat = None          # release the leaf input buffers
                self._held.pop(id(hd.task), None)
            self._chain_cv.notify_all()

    def _unclaim(self, claimed: List[_Hold]) -> None:
        with self._chain_cv:
            for hd in claimed:
                if hd.state == "launching":
                    hd.state = "held"
            self._chain_cv.notify_all()

    def _dispatch_chained(self, spec: XlaKernel, n: int,
                          flat: List[Any]) -> List[Dict[str, Any]]:
        """Launch a wave whose inputs include unresolved chain
        placeholders: claim the chain, trace it in front of the wave in
        one program, resolve the held tasks' outputs from the same
        launch.  Returns the wave's bound outputs per task."""
        while True:
            claimed = self._claim_chain(
                [a for a in flat if isinstance(a, Deferred)
                 and a.array is None])
            # chains resolved while waiting substitute transparently
            flat = [a.array if isinstance(a, Deferred)
                    and a.array is not None else a for a in flat]
            if not claimed:
                if any(isinstance(a, Deferred) for a in flat):
                    continue          # raced a fresh hold: re-claim
                _f, outs = self._dispatch_plain(spec, n, flat)
                return outs
            try:
                node_outs, wave_outs = self._run_chain(claimed, spec, n,
                                                       flat)
            except Exception:
                self._unclaim(claimed)
                raise
            self._resolve_holds(claimed, node_outs)
            return wave_outs

    def _force_deferred(self, d: Deferred) -> None:
        """Dispatch the chain behind one placeholder without a consumer
        wave (foreign-device/CPU consumers, sync, teardown)."""
        while d.array is None:
            claimed = self._claim_chain([d])
            if not claimed:
                continue              # resolved concurrently
            try:
                node_outs, _ = self._run_chain(claimed)
            except Exception:
                self._unclaim(claimed)
                raise
            self._resolve_holds(claimed, node_outs)

    def _resolve_all_held(self) -> None:
        """Force every remaining hold (sync/teardown): consumers that
        never reached this device must not leave a panel chain
        undispatched."""
        import time as _time
        deadline = _time.monotonic() + 60.0
        while True:
            with self._chain_cv:
                pending = [hd for hd in self._held.values()
                           if hd.state == "held"]
                busy = any(hd.state == "launching"
                           for hd in self._held.values())
            if pending:
                # newest first: its closure covers its predecessors
                self._force_deferred(next(iter(pending[-1].outputs.values())))
                continue
            if not busy:
                return
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"device {self.name}: chain holds stuck in launch")
            _time.sleep(0.002)

    @staticmethod
    def _donation_hazard(spec: XlaKernel, flat: List[Any]) -> bool:
        """True when a to-be-donated buffer also appears as another
        argument of the same (possibly fused) call: two wave tasks
        sharing an operand where one donates it would hand XLA the same
        buffer as both alias-donated and live input.  Falling back to
        no-donation for the launch is always safe."""
        k = len(spec.arg_names)
        donatable = [i for i, a in enumerate(spec.arg_names)
                     if a in spec.flow_names and a in spec.writable]
        if not donatable:
            return False
        donated_ids = set()
        for t in range(len(flat) // k):
            for i in donatable:
                donated_ids.add(id(flat[t * k + i]))
        seen = {}
        for j, v in enumerate(flat):
            seen[id(v)] = seen.get(id(v), 0) + 1
        return any(seen.get(d, 0) > 1 for d in donated_ids)

    def _stage_in(self, copy: DataCopy, access: int,
                  pinned: bool = False) -> DataCopy:
        """Ensure a valid copy of ``copy``'s datum on this device
        (reference: parsec_gpu_data_stage_in, device_cuda_module.c:1261).

        A bound copy that a writeback replacement detached — or, for a
        task-fed (pinned) input, invalidated in place — is a
        version-pinned snapshot; it stages into a private standalone
        device copy without consulting the datum's coherency, which has
        moved on.  (A detached copy with payload None was merely evicted
        and re-stages from the datum's newest valid copy below.)"""
        import jax
        datum = copy.data
        p0 = copy.payload
        if isinstance(p0, Deferred):
            if p0.array is not None:
                copy.payload = p0.array     # resolved: unwrap in place
            elif p0.hold.device is not self:
                # produced by a chain held on ANOTHER device: force that
                # chain there, then stage the real array normally (D2D)
                copy.payload = p0.force()
            elif copy.flags & FLAG_COW or copy.is_pinned_snapshot(pinned):
                # snapshot/COW paths materialize private buffers from
                # the payload — they need the real array
                copy.payload = p0.force()
            # else: leave the placeholder — this device's launch traces
            # the chain into the consuming program (_dispatch_chained)
        if copy.flags & FLAG_SCRATCH and copy.version == 0 \
                and access & ACCESS_WRITE and copy.arena is not None:
            # NEW-flow scratch straight from the arena: the np.empty host
            # buffer's content is undefined, so materialize the copy
            # directly in device memory (zeros) instead of paying an H2D
            # transfer for garbage bytes — on tunneled TPUs that transfer
            # is the difference between noise and seconds per task
            import jax.numpy as jnp
            nbytes = getattr(copy.payload, "nbytes", 0)
            off = self._reserve(nbytes)
            dc = datum.copy_on(self.space)
            if dc is None:
                dc = datum.create_copy(self.space)
            shape = copy.payload.shape
            dtype = copy.payload.dtype
            dc.payload = jax.device_put(   # lint: private-ok (a fresh
                # jnp.zeros has no host-side owner to alias)
                jnp.zeros(shape, dtype=dtype), self.jdev)
            dc.version = copy.version
            datum.transfer_ownership(self.space, access)
            self._account(datum, dc, nbytes, off)
            self._touch(datum)
            return dc
        if (copy.flags & FLAG_COW) == 0 and copy.is_pinned_snapshot(pinned):
            from parsec_tpu.data.data import Data
            payload = copy.payload
            nbytes = getattr(payload, "nbytes", 0)
            off = self._reserve(nbytes)
            if self._on_this_device(payload):
                import jax.numpy as jnp
                staged = jnp.array(payload, copy=True)
            else:
                staged = device_put_private(payload, self.jdev)
                if copy.arena is not None:
                    # eager completion can retire (and recycle) the arena
                    # host buffer before this async H2D drains: wait it out
                    staged.block_until_ready()
            snap = Data(nb_elts=datum.nb_elts)
            dc = snap.create_copy(self.space, payload=staged,
                                  coherency=Coherency.SHARED,
                                  version=copy.version)
            self.stats.bytes_in += nbytes
            self._account(snap, dc, nbytes, off)
            return dc
        dc = datum.copy_on(self.space)
        fresh = dc is None
        if fresh:
            dc = datum.create_copy(self.space)
        src = datum.transfer_ownership(self.space, access)
        if src is not None or dc.payload is None:
            payload = src.payload if src is not None else copy.payload
            nbytes = getattr(payload, "nbytes", 0)
            # only a FRESH copy claims a zone segment: a re-staged copy
            # already owns one, and a surplus claim could evict victims
            # (or spuriously exhaust the budget) for nothing
            off = self._reserve(nbytes) if fresh else None
            if self._on_this_device(payload):
                # already resident (copy-on-write alias): device_put would
                # be a no-op sharing the buffer, which donation/in-place
                # update must not see — make a private HBM buffer
                import jax.numpy as jnp
                dc.payload = jnp.array(payload, copy=True)
            else:
                # cross-device/host staging must be private too: on the
                # CPU client a plain device_put ALIASES the source
                # buffer (see device_put_private — the r8 wrong-R root
                # cause)
                dc.payload = device_put_private(payload, self.jdev)
                if (src.arena if src is not None else copy.arena) \
                        is not None:
                    # see the snapshot path above: don't let an eager
                    # retirement recycle the arena buffer mid-H2D
                    dc.payload.block_until_ready()
            dc.version = src.version if src is not None else copy.version
            self.stats.bytes_in += nbytes
            if fresh:
                self._account(datum, dc, nbytes, off)
        if copy.flags & FLAG_COW and copy is not dc:
            # The COW alias's payload aliases the producer's buffer (for
            # DATA-fed fan-outs: the collection's backing array).  The
            # device copy above is private, so drop the alias from the
            # datum NOW — otherwise flush()/_evict() later treats this
            # datum's device copy as authoritative and pull_to_host
            # np.copyto's an intermediate result through the alias into
            # the shared storage (ADVICE r1 high: the stencil corruption).
            datum.detach_copy(copy.device)
            copy.payload = None
            copy.coherency = Coherency.INVALID
            copy.flags &= ~FLAG_COW
        self._touch(datum)
        return dc

    def _on_this_device(self, payload) -> bool:
        devs = getattr(payload, "devices", None)
        if devs is None:
            return False
        try:
            return self.jdev in devs()
        except TypeError:
            return False

    # ------------------------------------------------------------------
    # completer: EAGER completion on dispatch order (reference:
    # parsec_cuda_kernel_pop/epilog + progress_stream events — but where
    # the CUDA module must poll events before releasing deps, XLA
    # dispatch returns asynchronous arrays that successors may consume
    # directly: the dependency is enforced by dataflow ON DEVICE, so
    # deps are released immediately and the Python side runs ahead,
    # keeping the device pipeline full).  Pins, arena buffers and load
    # accounting are held until the outputs actually materialize
    # (_finalize), with a bounded run-ahead window of unmaterialized
    # tasks providing backpressure.
    # ------------------------------------------------------------------
    def _completer_loop(self):
        from parsec_tpu.core import scheduling
        while True:
            with self._cond:
                while not self._inflight and not self._stop:
                    self._cond.wait(0.1)
                if not self._inflight:
                    break       # _stop and drained
                inf = self._inflight.popleft()
                # _completing keeps the task visible to sync() between the
                # queue pop and the retire append: complete_execution below
                # is what wakes Context.wait, which may race into sync()
                self._completing += 1
                self._cond.notify_all()
            try:
                if not inf.prepublished:
                    # chain-held tasks planted their Deferred payloads at
                    # hold time; rewriting here could clobber a resolution
                    for fname, arr in inf.outputs.items():
                        dc = inf.task.data.get(fname)
                        if dc is not None:
                            dc.payload = arr
                scheduling.complete_execution(inf.es, inf.task)
            except Exception as exc:
                self.stats.faults += 1
                inf.es.context.record_error(exc, inf.task)
            with self._cond:
                self._retire.append(inf)
                self._completing -= 1
                self._cond.notify_all()
            try:
                self._drain_retired(max_unfinalized=self._runahead)
            except Exception as exc:   # the completer thread must survive
                self.stats.faults += 1
                inf.es.context.record_error(exc, inf.task)
        self._drain_retired(max_unfinalized=0)

    def _drain_retired(self, max_unfinalized: int) -> None:
        """Finalize retired tasks whose outputs are ready; when more than
        ``max_unfinalized`` are still pending, block on the oldest (the
        run-ahead memory valve).  The device queue is in-order, so ONE
        readiness probe of the newest entry covers the whole list —
        readiness probes and blocking waits are full RPC round trips on
        tunneled TPUs, so both are rationed."""
        while True:
            block = False
            with self._cond:
                if not self._retire:
                    return
                newest = self._retire[-1]
            # probe OUTSIDE the lock: is_ready() is a full RPC round trip
            # on tunneled TPUs, and submit()/manager/sync all contend on
            # _cond
            newest_ready = self._outputs_ready(newest)
            with self._cond:
                if not self._retire:
                    return
                if self._retire[-1] is not newest:
                    continue   # the list moved on; re-probe
                if newest_ready:
                    batch = list(self._retire)
                    self._retire.clear()
                elif len(self._retire) > max_unfinalized:
                    batch = [self._retire.popleft()]
                    block = True
                else:
                    return
                # popped entries stay visible to sync() until their
                # finalization lands (late errors must beat wait())
                self._finalizing += len(batch)
                self._cond.notify_all()
            try:
                for inf in batch:
                    self._finalize(inf, block=block)
            finally:
                with self._cond:
                    self._finalizing -= len(batch)
                    self._cond.notify_all()

    @staticmethod
    def _outputs_ready(inf: _Inflight) -> bool:
        for a in inf.outputs.values():
            r = getattr(a, "is_ready", None)
            if r is None:
                continue
            try:
                if not r():
                    return False
            except Exception as exc:
                if "deleted" in str(exc).lower():
                    # a successor kernel donated this buffer away — it
                    # was consumed, ordering is the device's problem now
                    continue
                # any OTHER probe failure must NOT report "ready": that
                # would finalize without blocking and swallow the error
                return False
        return True

    def _finalize(self, inf: _Inflight, block: bool) -> None:
        try:
            if block:
                import jax
                for a in inf.outputs.values():
                    try:
                        jax.block_until_ready(a)
                    except Exception as exc:
                        if "deleted" in str(exc).lower():
                            continue   # donated away — see _outputs_ready
                        raise
        except Exception as exc:
            # deps were already released at dispatch; a late device-side
            # failure surfaces as a context error (sync()/wait raise)
            self.stats.faults += 1
            inf.es.context.record_error(exc, inf.task)
        finally:
            if inf.es.context._device_spans:
                # outputs are materialized (or the failure surfaced):
                # close the dispatch->done device span
                inf.es.pins("device_done", inf.task)
            self.load_sub(inf.load)
            for d in inf.pinned:
                self._unpin(d)
            for copy in inf.release_after:
                # a predecessor's repo entry may still hold this
                # superseded host buffer for its OTHER consumers
                copy.arena.release_unheld(copy)

    def adopt(self, datum, dc: DataCopy) -> None:
        """Account a device copy attached by an EXTERNAL placer (the ICI
        engine's prebroadcast/preplace): claim its bytes against the HBM
        budget and enter it in the LRU so eviction can see it — an
        unaccounted attach would let collective placement overcommit the
        budget invisibly."""
        key = id(datum)
        nbytes = getattr(dc.payload, "nbytes", 0)
        with self._mem_lock:
            while True:
                ent = self._lru.get(key)
                if ent is None:
                    # placeholder claims the key atomically with the
                    # check, so a concurrent adopt/stage-in of the same
                    # datum cannot double-account; pinned so eviction
                    # skips the stub
                    self._lru[key] = (weakref.ref(dc), 0, _PLACEHOLDER)
                    self._pins[key] = self._pins.get(key, 0) + 1
                    break
                if ent[2] is not _PLACEHOLDER:
                    return      # already accounted (payload refresh)
                # another adopt of this datum is mid-reserve: wait for it
                # to resolve (account or fail) rather than piggy-backing
                # on a claim that may yet be rolled back (ADVICE r2 low)
                self._mem_lock.wait(0.05)
        def _drop_pin_locked():
            n = self._pins.get(key, 0) - 1
            if n <= 0:
                self._pins.pop(key, None)
            else:
                self._pins[key] = n

        try:
            off = self._reserve(nbytes)
        except BaseException:
            # roll the placeholder back, or every later adopt of this
            # datum early-returns "already accounted" and its bytes never
            # hit the budget (ADVICE r2 low)
            with self._mem_lock:
                ent = self._lru.get(key)
                if ent is not None and ent[2] is _PLACEHOLDER:
                    self._lru.pop(key)
                _drop_pin_locked()
                self._mem_lock.notify_all()
            raise
        with self._mem_lock:
            # entry lands and the claim pin drops under ONE lock hold: an
            # unpinned placeholder must never be visible to a concurrent
            # victim scan (it would _evict the just-adopted copy)
            self._lru[key] = (weakref.ref(dc), nbytes, off)
            self._bytes_used += nbytes
            _drop_pin_locked()
            self._mem_lock.notify_all()
        weakref.finalize(dc, self._forget, key, nbytes)
        self.stats.bytes_in += nbytes

    def sync(self, timeout: Optional[float] = None) -> None:
        """Drain the device: block until every dispatched kernel has
        materialized its outputs (the stream-synchronize at pool
        quiescence; reference: the GPU manager drains its exec and
        stage-out streams before epilog).  ``timeout`` bounds the wait
        for the dispatch queues; the final materialization block is
        unbounded, like a stream synchronize."""
        with self._cond:
            ok = self._cond.wait_for(
                lambda: (not self._pending and self._launching == 0
                         and self._completing == 0
                         and self._finalizing == 0
                         and not self._inflight) or self._stop,
                timeout=timeout)
            if not ok:
                raise TimeoutError(f"device {self.name}: sync timed out")
        # chain holds whose consumer never launched here (tail of the
        # last panel, cancelled pools) dispatch now — quiescence means
        # every held kernel has actually run
        self._resolve_all_held()
        with self._cond:
            entries = list(self._retire)
            self._retire.clear()
        if not entries:
            return
        # newest-first: the device queue is in-order, so one blocking
        # wait on the LAST dispatched outputs covers the earlier ones —
        # each avoided block or probe is a full RPC round trip on
        # tunneled TPUs
        self._finalize(entries[-1], block=True)
        for inf in entries[:-1]:
            self._finalize(inf, block=False)

    # ------------------------------------------------------------------
    # device memory cache management (reference: gpu_mem_lru / zone_malloc)
    # ------------------------------------------------------------------
    def _pin(self, datum) -> None:
        with self._mem_lock:
            self._pins[id(datum)] = self._pins.get(id(datum), 0) + 1

    def _unpin(self, datum) -> None:
        with self._mem_lock:
            n = self._pins.get(id(datum), 0) - 1
            if n <= 0:
                self._pins.pop(id(datum), None)
            else:
                self._pins[id(datum)] = n

    def _touch(self, datum) -> None:
        with self._mem_lock:
            if id(datum) in self._lru:
                self._lru.move_to_end(id(datum))

    def _account(self, datum, dc: DataCopy, nbytes: int,
                 offset: Any = None) -> None:
        key = id(datum)
        with self._mem_lock:
            self._lru[key] = (weakref.ref(dc), nbytes, offset)
            self._bytes_used += nbytes
        weakref.finalize(dc, self._forget, key, nbytes)

    def _forget(self, key: int, nbytes: int) -> None:
        """Finalizer: a device copy died with its (temporary) datum —
        drop its cache accounting.  Only removes the entry if it still
        refers to the dead copy (the key may have been reused by a
        re-staged copy of the same datum, or by a new datum at the same
        address)."""
        with self._mem_lock:
            ent = self._lru.get(key)
            if ent is not None and ent[0]() is None:
                self._lru.pop(key)
                self._bytes_used -= ent[1]
                self._zone_free(ent[2])

    def _zone_free(self, offset: Any) -> None:
        if self._zone is not None and offset is not None \
                and offset is not _PLACEHOLDER:
            self._zone.free(offset)

    def _reserve(self, nbytes: int) -> Any:
        """Claim a segment of the HBM budget, evicting LRU unpinned
        copies until it fits (reference:
        parsec_gpu_data_reserve_device_space, device_cuda_module.c:864,
        over the zone_malloc slab).  Returns the zone offset (None when
        the budget is unlimited); the caller threads it into _account or
        releases it via _zone_free if the copy turns out not to be
        fresh."""
        if self._zone is None:
            return None
        import time as _time
        deadline = _time.monotonic() + 30.0
        while True:
            with self._mem_lock:
                while True:
                    off = self._zone.malloc(nbytes)
                    if off is not None:
                        return off
                    victim = None
                    for key in self._lru.keys():
                        if self._pins.get(key, 0) > 0:
                            continue
                        dcv = self._lru[key][0]()
                        if dcv is not None and \
                                isinstance(dcv.payload, Deferred) and \
                                dcv.payload.array is None:
                            # an unresolved chain placeholder holds no
                            # bytes yet and its value exists nowhere
                            # else: never a victim
                            continue
                        victim = key
                        break
                    if victim is None:
                        break   # all pinned right now: wait outside
                    dcref, sz, voff = self._lru.pop(victim)
                    dc = dcref()
                    if dc is None:
                        self._bytes_used -= sz
                        self._zone_free(voff)
                        continue
                    self._evict(dc.data, dc, sz, voff)
            # every resident copy is transiently pinned by in-flight
            # tasks: wait for a finalization to unpin instead of failing
            # (the reference requeues, HOOK_RETURN_AGAIN, rather than
            # aborting)
            if _time.monotonic() > deadline:
                from parsec_tpu.utils.output import show_help
                raise MemoryError(show_help(
                    "device-oom", warn=False,
                    budget=(self._capacity or 0) >> 20, nbytes=nbytes))
            _time.sleep(0.001)

    def _evict(self, datum, dc: DataCopy, nbytes: int,
               offset: Any = None) -> None:
        """Write back if authoritative, then drop (caller holds _mem_lock)."""
        if dc.coherency in (Coherency.OWNED, Coherency.EXCLUSIVE) and \
                dc.version >= datum.newest_version():
            self._writeback_host(datum, dc)
        datum.detach_copy(self.space)
        dc.payload = None
        dc.coherency = Coherency.INVALID
        self._bytes_used -= nbytes
        self._zone_free(offset)
        self.stats.evictions += 1

    def _writeback_host(self, datum, dc: DataCopy) -> None:
        """Pull the datum home (one locked, version-guarded path:
        Data.pull_to_host), accounting the transfer."""
        host = datum.copy_on(0)
        if host is None or host.coherency == Coherency.INVALID or \
                host.version < dc.version:
            self.stats.bytes_out += getattr(dc.payload, "nbytes", 0)
        datum.pull_to_host()

    def discard_scratch(self) -> None:
        """Drop device copies of collection-less datums (NEW-flow arena
        temporaries) WITHOUT writeback, with full accounting — the
        quiescent-point twin of flush() for data nobody user-visible
        will ever read.  Benches call it before teardown so fini's
        flush does not D2H gigabytes of dead QR panels / potrf
        inverses through a slow link."""
        with self._mem_lock:
            for key in list(self._lru.keys()):
                dcref, sz, voff = self._lru[key]
                dc = dcref()
                if dc is None:
                    del self._lru[key]
                    self._bytes_used -= sz
                    self._zone_free(voff)
                    continue
                datum = dc.data
                if datum is None or datum.collection is not None:
                    continue   # user-visible data keeps flush semantics
                del self._lru[key]
                # _mem_lock -> datum._lock is the established order
                # (_reserve's eviction path writes back under it), so
                # taking the per-datum lock here is deadlock-free and
                # closes the window against concurrent flush/pull
                with datum._lock:
                    datum.detach_copy(self.space)
                    dc.payload = None
                    dc.coherency = Coherency.INVALID
                self._bytes_used -= sz
                self._zone_free(voff)

    def flush(self) -> None:
        """Push every authoritative device copy home (reference:
        parsec_dtd_data_flush_all / GPU w2r writeback tasks).  Flush is a
        quiescent point, so replaced host payloads re-link into their
        collection's user-visible backing storage."""
        with self._mem_lock:
            entries = [ref() for ref, _sz, _off in self._lru.values()]
        for dc in entries:
            if dc is None:
                continue
            datum = dc.data
            with datum._lock:
                if dc.payload is not None and \
                        dc.coherency in (Coherency.OWNED, Coherency.EXCLUSIVE) \
                        and dc.version >= datum.newest_version():
                    self._writeback_host(datum, dc)
            if datum.collection is not None:
                datum.collection.refresh_backing(datum)

    def fini(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        for m in self._managers:
            m.join(timeout=5)
        try:
            # undispatched chain holds would poison flush() with
            # placeholder payloads; the completer's final drain then
            # finds real arrays to block on
            self._resolve_all_held()
        except Exception as exc:
            warning("device %s: chain resolution at fini failed: %s",
                    self.name, exc)
        self._completer.join(timeout=5)
        self.flush()
        debug_verbose(5, "device %s: %s", self.name, self.stats.as_dict())
