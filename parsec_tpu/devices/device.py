"""Device registry and module base: accelerator seam of the runtime.

Rebuild of the reference's device MCA framework (reference:
parsec/mca/device/device.h:115-148 module vtable, device.c:79-140
``parsec_get_best_device`` and load counters device.h:159-162): devices
register with the runtime, carry a relative compute weight and a live load,
expose per-device statistics, and the engine picks the best device for a
task by data affinity first, then weighted load.

Memory spaces: space 0 is host RAM; each attached accelerator device gets
the next space index.  DataCopy.device is a memory-space index into this
registry.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from parsec_tpu.core.task import HookReturn, Task
from parsec_tpu.data.data import ACCESS_WRITE, Coherency


class DeviceStats:
    """Per-device counters (reference: device.h:132-137)."""

    __slots__ = ("executed_tasks", "bytes_in", "bytes_out", "faults",
                 "evictions", "fused_launches", "fused_tasks",
                 "chained_launches", "chained_tasks")

    def __init__(self):
        self.executed_tasks = 0
        self.bytes_in = 0
        self.bytes_out = 0
        self.faults = 0
        self.evictions = 0
        #: wavefront launch fusion counters: launches that carried >1 task,
        #: and how many tasks rode them (devices/xla.py manager batching)
        self.fused_launches = 0
        self.fused_tasks = 0
        #: cross-panel chain fusion counters: launches that traced a held
        #: panel chain into a consumer wave, and how many tasks (held +
        #: wave) rode them (devices/xla.py device_fuse_panel)
        self.chained_launches = 0
        self.chained_tasks = 0

    def as_dict(self) -> Dict[str, int]:
        return {k: getattr(self, k) for k in self.__slots__}


class Device:
    """Device module base (reference: parsec_device_module_t).

    ``space`` is the memory-space index (0 = host); ``weight`` is the
    relative throughput used for load balancing (reference: device
    gflops weights); ``load`` counts outstanding work units.
    """

    kind = "base"

    def __init__(self, name: str):
        self.name = name
        self.space = -1          # assigned by the registry
        self.weight = 1.0
        self.load = 0.0
        self._load_lock = threading.Lock()
        self.stats = DeviceStats()
        self.enabled = True
        #: extensible per-device info slots (reference: class/info.h
        #: object arrays on device modules)
        from parsec_tpu.utils.info import InfoObjectArray, device_info
        self.info = InfoObjectArray(device_info, owner=self)

    # -- load accounting (reference: parsec_device_load/sload) ------------
    def load_add(self, units: float) -> None:
        with self._load_lock:
            self.load += units

    def load_sub(self, units: float) -> None:
        with self._load_lock:
            self.load = max(0.0, self.load - units)

    # -- module vtable -----------------------------------------------------
    def submit(self, es, task: Task, spec: Any) -> HookReturn:
        """Take ownership of a device task; return ASYNC on success."""
        raise NotImplementedError

    def flush(self) -> None:
        """Write every dirty device copy back to its host datum."""

    def fini(self) -> None:
        """Stop device threads and release resources."""

    def __repr__(self):
        return f"<Device {self.name} space={self.space} load={self.load:.1f}>"


class HostDevice(Device):
    """Memory space 0: host RAM + inline CPU execution (reference: the
    implicit CPU device, PARSEC_DEV_CPU)."""

    kind = "cpu"

    def __init__(self):
        super().__init__("cpu")
        self.space = 0


class DeviceRegistry:
    """Process-wide device table (reference: parsec_mca_device_* in
    device.c)."""

    def __init__(self, context=None):
        self.context = context
        self.host = HostDevice()
        self.devices: List[Device] = [self.host]

    def attach(self, dev: Device) -> Device:
        """reference: parsec_mca_device_add (device.h:186)."""
        dev.space = len(self.devices)
        self.devices.append(dev)
        return dev

    @property
    def accelerators(self) -> List[Device]:
        return [d for d in self.devices[1:] if d.enabled]

    def get(self, space: int) -> Device:
        return self.devices[space]

    def best_device(self, task: Task) -> Optional[Device]:
        """Pick the execution device for a task (reference:
        parsec_get_best_device, device.c:79-140): honor the owner/preferred
        device of the task's written data when it is an accelerator,
        otherwise the enabled accelerator with the least weighted load.

        A pool carrying a serving-fabric carve stamp
        (``Taskpool.device_spaces``) restricts every choice — affinity
        hints included — to its carved subset, so concurrent tenants
        never share an exclusively-placed device."""
        allowed = getattr(task.taskpool, "device_spaces", None)
        accs = self.accelerators
        if allowed is not None:
            accs = [d for d in accs if d.space in allowed]
        if not accs:
            return None
        dev = self._coaffinity_device(task)
        if dev is not None and (allowed is None or dev.space in allowed):
            return dev
        for flow in task.task_class.flows:
            if not (flow.access & ACCESS_WRITE):
                continue
            copy = task.data.get(flow.name)
            if copy is None or copy.data is None:
                continue
            datum = copy.data
            pref = datum.preferred_device
            if pref is not None and 1 <= pref < len(self.devices) \
                    and self.devices[pref].enabled \
                    and (allowed is None or pref in allowed):
                return self.devices[pref]
            # residency affinity: the accelerator already holding the
            # newest valid copy of the written datum wins, avoiding a
            # cross-device migration per write
            v = datum.newest_version()
            for sp, c in datum.copies().items():
                if sp >= 1 and sp < len(self.devices) \
                        and c.coherency != Coherency.INVALID \
                        and c.version == v and c.payload is not None \
                        and self.devices[sp].enabled \
                        and (allowed is None or sp in allowed):
                    return self.devices[sp]
        return min(accs, key=lambda d: d.load / d.weight)

    def _coaffinity_device(self, task: Task) -> Optional[Device]:
        """Panel co-location hint: a task class carrying a 'coaffinity'
        property (locals -> data ref) prefers the device holding that
        datum — e.g. TRSM(m,k)/TSQRT(m,k) follow their panel's diagonal
        tile A(k,k), so the POTRF->TRSM / TSQRT column chain stays on
        ONE device and cross-panel chain fusion (devices/xla.py
        device_fuse_panel, which also gates this hint) can trace it into
        a single launch."""
        coaff = task.task_class.properties.get("coaffinity")
        if coaff is None:
            return None
        from parsec_tpu.utils.mca import params
        try:
            if not int(params.get("device_fuse_panel", 1)):
                return None
            datum = coaff(task.locals).resolve()
        except Exception:
            return None
        pref = datum.preferred_device
        if pref is not None and 1 <= pref < len(self.devices) \
                and self.devices[pref].enabled:
            return self.devices[pref]
        v = datum.newest_version()
        for sp, c in datum.copies().items():
            if 1 <= sp < len(self.devices) \
                    and c.coherency != Coherency.INVALID \
                    and c.version == v and c.payload is not None \
                    and self.devices[sp].enabled:
                return self.devices[sp]
        return None

    def flush_all(self) -> None:
        for d in self.devices[1:]:
            d.flush()

    def fini(self) -> None:
        for d in self.devices[1:]:
            d.fini()

    def dump_stats(self) -> Dict[str, Dict[str, int]]:
        """reference: parsec_mca_device_dump_and_reset_statistics."""
        return {d.name: d.stats.as_dict() for d in self.devices}
