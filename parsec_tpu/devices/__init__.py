"""Device layer: registry + XLA (TPU) device modules.

reference: parsec/mca/device/ — see device.py and xla.py in this package.
"""

from __future__ import annotations

from typing import Optional

from parsec_tpu.devices.device import Device, DeviceRegistry, DeviceStats
from parsec_tpu.utils.mca import params
from parsec_tpu.utils.output import debug_verbose, warning

params.register("device_enabled", 1, "attach XLA accelerator devices")
params.register("device_max", 0, "max XLA devices to attach (0 = all)")

# Relative throughput weights per platform, in rough TFLOPS (reference:
# the CUDA module's per-architecture flop-rate table,
# device_cuda_module.c:53).  Used only for load balancing ratios.
_PLATFORM_WEIGHTS = {"tpu": 100.0, "axon": 100.0, "gpu": 50.0,
                     "cuda": 50.0, "cpu": 1.0}


def init_devices(context) -> DeviceRegistry:
    """Attach every visible jax device as a runtime device module
    (reference: parsec_mca_device_init/attach, parsec.c:823-828)."""
    reg = DeviceRegistry(context)
    if not params.get("device_enabled", 1):
        return reg
    try:
        import jax
        jdevs = jax.devices()
    except Exception as exc:   # no jax / no backend: host-only runtime
        warning("device init: jax unavailable (%s); host-only", exc)
        return reg
    limit = int(params.get("device_max", 0))
    if limit > 0:
        jdevs = jdevs[:limit]
    from parsec_tpu.devices.xla import XlaDevice
    for jd in jdevs:
        w = _PLATFORM_WEIGHTS.get(jd.platform, 1.0)
        reg.attach(XlaDevice(jd, weight=w))
    debug_verbose(3, "attached %d XLA devices (%s)", len(jdevs),
                  jdevs[0].platform if jdevs else "-")
    return reg


__all__ = ["Device", "DeviceRegistry", "DeviceStats", "init_devices"]
