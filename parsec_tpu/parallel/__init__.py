"""Mesh/SPMD parallel layer: dataflow schedules lowered to XLA collectives.

The TPU-native realization of the reference's distributed machinery
(reference: parsec/remote_dep.c dataflow bcast trees + parsec_comm_engine.h
put/get seam — SURVEY.md §2.5/§5.8): where the reference moves tile
payloads with funnelled MPI driven by a comm thread, a pod slice moves
them with XLA collectives over ICI — all_gather for the bcast-tree fan-out,
psum_scatter for reductions, ppermute rings for neighbor pipelines.
"""

from parsec_tpu.parallel.spmd import (halo_stencil_fn, make_mesh,  # noqa: F401
                                      ring_reduce_gemm_fn, summa_gemm_fn)
