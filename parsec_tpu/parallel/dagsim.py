"""DAG scheduling-efficiency simulator.

The reference's headline scaling metric ("DAG scheduling efficiency
8→256 chips", BASELINE.json; the GFLOPS-vs-scale harness pattern of
reference tests/dsl/dtd/dtd_test_simple_gemm.c:659-666) needs more
chips than any build/bench host has.  The TPU-first answer mirrors what
the task-scheduling community does (DPLASMA/StarPU simulate with
simgrid): drive the REAL parameterized task graph — the same TaskClass
/ Flow / Dep structures the runtime executes, enumerated by the same
``iter_space``, placed by the same owner-computes affinity — through a
discrete-event list-scheduling simulation with measured kernel
durations and an alpha-beta ICI communication model.

What is simulated faithfully:
- the full dependency structure (guarded deps, range fan-outs, CTL
  edges) of the actual taskpool object;
- owner-computes placement from the collection's P x Q block-cyclic
  distribution (chip = the affinity datum's rank);
- priority-driven list scheduling per chip (highest task priority among
  ready tasks — the runtime's scheduler discipline);
- cross-chip edges charged alpha + bytes/beta, deduplicated per
  (producer, flow, destination chip) the way the runtime's collective
  bcast ships one payload per destination device.

What is abstracted: link contention (alpha-beta per edge, no shared-link
queueing) and memory capacity.  Durations and overheads are inputs —
the bench calibrates them on the real chip (bench.py eff mode).
"""

from __future__ import annotations

import heapq
import itertools
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional, Tuple

from parsec_tpu.core.task import FromTask, ToTask


class SimDag:
    """Static expansion of a ParameterizedTaskpool's DAG."""

    def __init__(self):
        self.nodes: Dict[Tuple, Dict[str, Any]] = {}
        #: src key -> list of (dst key, flow_name, bytes)
        self.succs: Dict[Tuple, List[Tuple[Tuple, str, int]]] = \
            defaultdict(list)
        self.preds_count: Dict[Tuple, int] = defaultdict(int)


def build_dag(tp, duration_fn: Callable[[str, Dict[str, int]], float],
              bytes_fn: Optional[Callable[[str, str], int]] = None,
              chip_fn: Optional[Callable] = None) -> SimDag:
    """Expand every task instance and task->task edge of ``tp``.

    ``duration_fn(class_name, locals) -> seconds``;
    ``bytes_fn(class_name, flow_name) -> payload bytes`` for the comm
    model (default 0); ``chip_fn(tc, locals) -> chip`` overrides the
    affinity rank (default: ``tc.rank_of``, i.e. the collection's own
    distribution — build the collection with nodes=n_chips).
    """
    dag = SimDag()
    for tc in tp.task_classes.values():
        for locals_ in tc.iter_space(tp.globals):
            key = tc.make_key(locals_)
            chip = (chip_fn(tc, locals_) if chip_fn is not None
                    else tc.rank_of(locals_))
            prio = tc.priority(locals_) if tc.priority else 0
            dag.nodes[key] = {
                "tc": tc.name, "locals": dict(locals_), "chip": int(chip),
                "prio": int(prio),
                "dur": float(duration_fn(tc.name, locals_)),
            }
    for tc in tp.task_classes.values():
        for locals_ in tc.iter_space(tp.globals):
            key = tc.make_key(locals_)
            for flow in tc.flows:
                nbytes = int(bytes_fn(tc.name, flow.name)) if bytes_fn \
                    else 0
                for dep in flow.active_outputs(locals_):
                    if not isinstance(dep.end, ToTask):
                        continue
                    dst_tc = tp.task_classes[dep.end.task_class]
                    for params in dep.end.instances(locals_):
                        # dep expressions carry free params only; fill
                        # derived locals before keying (JDF derived
                        # locals are single-valued TaskClass params)
                        params = dst_tc.complete_locals(params)
                        dkey = dst_tc.make_key(params)
                        if dkey in dag.nodes:
                            dag.succs[key].append((dkey, flow.name,
                                                   nbytes))
                            dag.preds_count[dkey] += 1
    return dag


def simulate(dag: SimDag, n_chips: int, alpha: float = 2e-6,
             beta: float = 4.5e10, overhead: float = 0.0) -> Dict[str, Any]:
    """Priority list-scheduling simulation of ``dag`` over ``n_chips``.

    ``alpha``/``beta``: per-message latency (s) and bandwidth (B/s) of a
    cross-chip edge (ICI-class defaults); ``overhead``: per-task runtime
    cost charged to the owning chip around the body (the measured
    scheduling overhead).  Returns makespan, busy time, efficiency
    (sum(durations) / (n_chips * makespan)) and per-chip utilization.
    """
    # per-chip: tasks whose deps resolved but whose data may still be in
    # flight (notyet, keyed by arrival time) vs runnable now (avail, by
    # descending priority)
    notyet: List[List] = [[] for _ in range(n_chips)]
    avail: List[List] = [[] for _ in range(n_chips)]
    chip_free = [0.0] * n_chips
    chip_busy = [0.0] * n_chips
    data_ready: Dict[Tuple, float] = defaultdict(float)
    pending = dict(dag.preds_count)
    seq = itertools.count()
    finish_at: Dict[Tuple, float] = {}

    events: List[Tuple[float, int, int]] = []   # (time, seq, chip)

    def enqueue(key, t_ready):
        node = dag.nodes[key]
        c = node["chip"] % n_chips
        heapq.heappush(notyet[c], (t_ready, -node["prio"], next(seq), key))
        heapq.heappush(events, (max(t_ready, chip_free[c]), next(seq), c))

    for key, node in dag.nodes.items():
        if pending.get(key, 0) == 0:
            enqueue(key, 0.0)

    done = 0
    makespan = 0.0
    while events:
        now, _, c = heapq.heappop(events)
        if chip_free[c] > now + 1e-18:
            # chip still running: defer to its free time (each deferral
            # moves strictly later, so progress is monotonic)
            heapq.heappush(events, (chip_free[c], next(seq), c))
            continue
        # surface everything that has arrived by `now`
        while notyet[c] and notyet[c][0][0] <= now + 1e-18:
            t_ready, nprio, s, key = heapq.heappop(notyet[c])
            heapq.heappush(avail[c], (nprio, s, key))
        if not avail[c]:
            if notyet[c]:
                heapq.heappush(events,
                               (max(notyet[c][0][0], chip_free[c]),
                                next(seq), c))
            continue
        _, _, key = heapq.heappop(avail[c])
        node = dag.nodes[key]
        start = max(now, chip_free[c])
        fin = start + overhead + node["dur"]
        chip_free[c] = fin
        chip_busy[c] += overhead + node["dur"]
        finish_at[key] = fin
        makespan = max(makespan, fin)
        done += 1
        # release successors; cross-chip edges pay alpha + bytes/beta
        # (no link-contention model — one bcast payload per dst chip and
        # per-edge latency coincide under that simplification)
        for dkey, flow_name, nbytes in dag.succs.get(key, ()):
            dst = dag.nodes[dkey]
            dc = dst["chip"] % n_chips
            if dc == node["chip"] % n_chips:
                arrival = fin
            else:
                arrival = fin + alpha + (nbytes / beta if beta else 0.0)
            data_ready[dkey] = max(data_ready[dkey], arrival)
            pending[dkey] -= 1
            if pending[dkey] == 0:
                enqueue(dkey, data_ready[dkey])
        if avail[c] or notyet[c]:
            heapq.heappush(events, (chip_free[c], next(seq), c))
    if done != len(dag.nodes):
        stuck = len(dag.nodes) - done
        raise RuntimeError(f"simulation deadlock: {stuck} tasks never ran "
                           "(cyclic or dangling deps)")
    total_work = sum(n["dur"] for n in dag.nodes.values()) \
        + overhead * len(dag.nodes)
    eff = total_work / (n_chips * makespan) if makespan > 0 else 1.0
    return {
        "n_chips": n_chips,
        "n_tasks": len(dag.nodes),
        "makespan_s": makespan,
        "total_work_s": total_work,
        "efficiency": eff,
        "chip_util": [b / makespan if makespan else 0.0
                      for b in chip_busy],
    }


def critical_path(dag: SimDag, overhead: float = 0.0) -> float:
    """Longest duration-weighted path (infinite-chip lower bound)."""
    memo: Dict[Tuple, float] = {}
    order: List[Tuple] = []
    pending = dict(dag.preds_count)
    stack = [k for k in dag.nodes if pending.get(k, 0) == 0]
    while stack:
        k = stack.pop()
        order.append(k)
        for dkey, _f, _b in dag.succs.get(k, ()):
            pending[dkey] -= 1
            if pending[dkey] == 0:
                stack.append(dkey)
    for k in reversed(order):
        best = 0.0
        for dkey, _f, _b in dag.succs.get(k, ()):
            best = max(best, memo.get(dkey, 0.0))
        memo[k] = dag.nodes[k]["dur"] + overhead + best
    return max(memo.values()) if memo else 0.0
