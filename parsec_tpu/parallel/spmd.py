"""SPMD schedules over a jax device mesh.

Each function here is a *dataflow schedule template*: the single-program
form of a task-graph pattern the runtime otherwise executes task by task.
They are what PTG dep patterns lower to on a TPU slice (SURVEY.md §5.8):

- ``summa_gemm_fn``  — owner-computes 2D GEMM; the A-row / B-column panel
  broadcasts are the reference's dataflow *bcast trees*
  (remote_dep.c:334-357 star/chain/binomial) realized as ``all_gather``
  over mesh axes (XLA picks the ICI-optimal tree/ring itself).
- ``ring_reduce_gemm_fn`` — contraction-sharded GEMM whose partial-sum
  combine is a ``psum_scatter`` ring: the reduction analog.
- ``halo_stencil_fn`` — neighbor exchange via ``ppermute``: the chain
  pipeline (Ex02/Ex04 chains, stencil halos) on the ICI torus.

All are pure jax functions built with shard_map over an explicit Mesh and
jit-compiled once; control flow is static (lax.fori_loop/scan) so XLA can
pipeline collectives with compute.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import numpy as np


def make_mesh(shape: Optional[Tuple[int, ...]] = None,
              axis_names: Sequence[str] = ("p", "q"),
              devices=None):
    """Build a Mesh over the visible devices.

    ``shape=None`` picks the most square 2D factorization of the device
    count (the PxQ process grid of the reference's 2D block-cyclic
    distribution, two_dim_rectangle_cyclic.h).
    """
    import jax
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    if shape is None:
        p = int(np.sqrt(n))
        while n % p:
            p -= 1
        shape = (p, n // p) if len(axis_names) == 2 else (n,)
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {shape} != {n} devices")
    arr = np.array(devs).reshape(shape)
    return jax.sharding.Mesh(arr, tuple(axis_names[:len(shape)]))


def summa_gemm_fn(mesh, precision: Optional[str] = None) -> Callable:
    """C = A@B with A, B, C block-distributed over a (p, q) mesh.

    Panel broadcast form of SUMMA: each rank all-gathers its A block row
    along ``q`` and its B block column along ``p``, then one local matmul
    produces its C block.  The all_gathers are the dataflow-broadcast
    edges of the tiled-GEMM PTG, batched per wavefront.
    """
    import jax
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    @jax.jit
    def sharded(a, b):
        def f(a_blk, b_blk):
            a_row = jax.lax.all_gather(a_blk, "q", axis=1, tiled=True)
            b_col = jax.lax.all_gather(b_blk, "p", axis=0, tiled=True)
            return jax.numpy.matmul(a_row, b_col, precision=precision)
        fm = shard_map(f, mesh=mesh,
                       in_specs=(P("p", "q"), P("p", "q")),
                       out_specs=P("p", "q"))
        return fm(a, b)

    return sharded


def ring_reduce_gemm_fn(mesh, axis: str = "p",
                        precision: Optional[str] = None) -> Callable:
    """C = A@B with the contraction (K) dimension sharded over ``axis``.

    Each rank computes a full-size partial product from its K shard; the
    partials combine with ``psum_scatter`` — a reduce-scatter ring over
    ICI — leaving C row-sharded.  This is the reduction-edge analog of
    the reference's dataflow collectives (BT_reduction.jdf pattern).
    """
    import jax
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    @jax.jit
    def sharded(a, b):
        def f(a_blk, b_blk):
            part = jax.numpy.matmul(a_blk, b_blk, precision=precision)
            return jax.lax.psum_scatter(part, axis, scatter_dimension=0,
                                        tiled=True)
        fm = shard_map(f, mesh=mesh,
                       in_specs=(P(None, axis), P(axis, None)),
                       out_specs=P(axis, None))
        return fm(a, b)

    return sharded


def halo_stencil_fn(mesh, axis: str = "p", radius: int = 1,
                    steps: int = 1) -> Callable:
    """1D 3-point stencil with ring halo exchange over ``axis``
    (reference: tests/apps/stencil 1D halo pattern; the neighbor sends are
    ``ppermute`` shifts on the ICI ring)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    n = mesh.shape[axis]
    fwd = [(i, (i + 1) % n) for i in range(n)]
    bwd = [(i, (i - 1) % n) for i in range(n)]

    @jax.jit
    def sharded(x):
        def f(x_blk):
            def step(u, _):
                left_halo = jax.lax.ppermute(u[-radius:], axis, fwd)
                right_halo = jax.lax.ppermute(u[:radius], axis, bwd)
                ext = jnp.concatenate([left_halo, u, right_halo])
                new = (ext[:-2 * radius] + ext[2 * radius:] + u) / 3.0
                return new, None
            u, _ = jax.lax.scan(step, x_blk, None, length=steps)
            return u
        fm = shard_map(f, mesh=mesh, in_specs=P(axis), out_specs=P(axis))
        return fm(x)

    return sharded
