"""Striped-lock concurrent hash table.

Rebuild of the reference's resizable bucket-locked hash table
(reference: parsec/class/parsec_hash_table.{c,h}) — the backbone of
dependency tracking, DTD tile lookup, and the taskpool registry.  Keeps the
reference's API shape: ``insert`` / ``find`` / ``remove`` plus the atomic
``find_or_insert`` (the reference's lock/insert-if-absent/unlock idiom) and
resizing driven by a max-collisions hint.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

_STRIPES = 64

#: Sentinel a ``mutate`` callback returns to delete the key atomically.
REMOVE = object()


class ConcurrentHashTable:
    def __init__(self, nb_bits: int = 8, max_collisions_hint: int = 16):
        # Python dicts already resize; we keep striped locks for concurrent
        # mutation and honor the API (nb_bits/max_collisions_hint accepted
        # for parity and sizing hints).
        self._locks = [threading.Lock() for _ in range(_STRIPES)]
        self._maps: List[Dict[Any, Any]] = [{} for _ in range(_STRIPES)]

    def _stripe(self, key: Any) -> int:
        return hash(key) % _STRIPES

    def insert(self, key: Any, value: Any) -> None:
        s = self._stripe(key)
        with self._locks[s]:
            self._maps[s][key] = value

    def find(self, key: Any, default: Any = None) -> Any:
        s = self._stripe(key)
        with self._locks[s]:
            return self._maps[s].get(key, default)

    def remove(self, key: Any) -> Any:
        s = self._stripe(key)
        with self._locks[s]:
            return self._maps[s].pop(key, None)

    def find_or_insert(self, key: Any, factory: Callable[[], Any]) -> Tuple[Any, bool]:
        """Atomically get existing value or insert factory().

        Returns (value, inserted).  Mirrors the reference's
        lock-bucket / find / insert-if-absent / unlock-bucket idiom
        (parsec_hash_table_lock_bucket, ...).
        """
        s = self._stripe(key)
        with self._locks[s]:
            if key in self._maps[s]:
                return self._maps[s][key], False
            v = factory()
            self._maps[s][key] = v
            return v, True

    def update_locked(self, key: Any, fn: Callable[[Any], Any],
                      default: Any = None) -> Any:
        """Apply fn to the current value under the bucket lock; store result.
        Returns the new value.  (The atomic read-modify-write the dep engine
        needs for arrival counters.)"""
        s = self._stripe(key)
        with self._locks[s]:
            cur = self._maps[s].get(key, default)
            new = fn(cur)
            self._maps[s][key] = new
            return new

    def mutate(self, key: Any, fn: Callable[[Any], Tuple[Any, Any]],
               default: Any = None) -> Any:
        """Atomic read-modify-write-or-remove under the bucket lock.

        ``fn(current)`` returns ``(new_value, result)``; if ``new_value`` is
        the REMOVE sentinel the key is deleted.  Returns ``result``.  This is
        the primitive the data-repo retirement protocol needs so an entry
        cannot be revived between its usage count reaching zero and its
        removal from the table.
        """
        s = self._stripe(key)
        with self._locks[s]:
            cur = self._maps[s].get(key, default)
            new, result = fn(cur)
            if new is REMOVE:
                self._maps[s].pop(key, None)
            else:
                self._maps[s][key] = new
            return result

    def pop_if(self, key: Any, pred: Callable[[Any], bool]) -> Optional[Any]:
        s = self._stripe(key)
        with self._locks[s]:
            v = self._maps[s].get(key)
            if v is not None and pred(v):
                del self._maps[s][key]
                return v
            return None

    def __contains__(self, key: Any) -> bool:
        s = self._stripe(key)
        with self._locks[s]:
            return key in self._maps[s]

    def __len__(self) -> int:
        return sum(len(m) for m in self._maps)

    def items(self) -> Iterator[Tuple[Any, Any]]:
        """Snapshot iteration (not linearizable across stripes)."""
        for s in range(_STRIPES):
            with self._locks[s]:
                snap = list(self._maps[s].items())
            yield from snap

    def for_all(self, fn: Callable[[Any, Any], None]) -> None:
        for k, v in self.items():
            fn(k, v)

    def clear(self) -> None:
        for s in range(_STRIPES):
            with self._locks[s]:
                self._maps[s].clear()
