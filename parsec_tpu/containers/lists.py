"""Concurrent list/queue containers.

Rebuild of the reference's lock-free containers
(reference: parsec/class/{lifo,fifo,list,dequeue}.{c,h}) as thread-safe
Python structures with the same API surface: LIFO, FIFO, Dequeue (push/pop at
both ends), and an ordered List supporting priority-sorted insertion — the
scheduler building blocks.  Items may be any object; priority ordering uses
``item.priority`` (higher first) like parsec_list's task rings.

A C++ backing (parsec_tpu/native) can replace these hot paths transparently;
the semantics defined here are the contract.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Iterable, List, Optional


def make_dequeue():
    """Scheduler-queue factory.  The native C++ dequeue is OPT-IN
    (``--mca native_queues 1``): for Python-object payloads the measured
    throughput is ~4x LOWER than the pure-Python deque (each op pays a
    ctypes FFI crossing plus id-parking under the GIL, which the queue
    cannot escape), so objects default to Python and the native queue
    serves payloads that are genuinely u64 handles end-to-end
    (reference seam: parsec_dequeue_t)."""
    from parsec_tpu.utils.mca import params
    params.register("native_queues", 0,
                    "use the native dequeue for scheduler object queues "
                    "(measured slower for Python payloads; see "
                    "containers.lists.make_dequeue)")
    try:
        if int(params.get("native_queues", 0)):
            from parsec_tpu.native import NativeDequeue, available
            if available():
                return NativeDequeue()
    except Exception:
        pass
    return Dequeue()


class Lifo:
    """LIFO stack (reference: parsec_lifo_t)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._items: List[Any] = []

    def push(self, item: Any) -> None:
        with self._lock:
            self._items.append(item)

    def push_chain(self, items: Iterable[Any]) -> None:
        with self._lock:
            self._items.extend(items)

    def pop(self) -> Optional[Any]:
        with self._lock:
            return self._items.pop() if self._items else None

    def try_pop(self) -> Optional[Any]:
        return self.pop()

    def is_empty(self) -> bool:
        with self._lock:
            return not self._items

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)


class Fifo:
    """FIFO queue (reference: parsec_fifo_t)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._items: collections.deque = collections.deque()

    def push(self, item: Any) -> None:
        with self._lock:
            self._items.append(item)

    def push_chain(self, items: Iterable[Any]) -> None:
        with self._lock:
            self._items.extend(items)

    def pop(self) -> Optional[Any]:
        with self._lock:
            return self._items.popleft() if self._items else None

    try_pop = pop

    def is_empty(self) -> bool:
        with self._lock:
            return not self._items

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)


class Dequeue:
    """Double-ended queue (reference: parsec_dequeue_t).

    Workers push back/pop back locally and steal from the front.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._items: collections.deque = collections.deque()

    def push_back(self, item: Any) -> None:
        with self._lock:
            self._items.append(item)

    def push_front(self, item: Any) -> None:
        with self._lock:
            self._items.appendleft(item)

    def pop_back(self) -> Optional[Any]:
        with self._lock:
            return self._items.pop() if self._items else None

    def pop_front(self) -> Optional[Any]:
        with self._lock:
            return self._items.popleft() if self._items else None

    def chain_back(self, items: Iterable[Any]) -> None:
        with self._lock:
            self._items.extend(items)

    def chain_front(self, items: Iterable[Any]) -> None:
        # extendleft inserts one-by-one; reverse first to splice in order.
        with self._lock:
            self._items.extendleft(reversed(list(items)))

    def is_empty(self) -> bool:
        with self._lock:
            return not self._items

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)


def _prio(item: Any) -> int:
    return getattr(item, "priority", 0) or 0


class OrderedList:
    """Priority-ordered list (reference: parsec_list_t with sorted insertion).

    Highest priority pops first; FIFO among equal priorities.  Like the
    reference, sorted insertion scans for the first lower-priority item, so
    mixing sorted and unsorted pushes stays locally correct.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._items: List[Any] = []

    def _insert_sorted(self, item: Any) -> None:
        p = _prio(item)
        for idx, other in enumerate(self._items):
            if _prio(other) < p:
                self._items.insert(idx, item)
                return
        self._items.append(item)

    def push_sorted(self, item: Any) -> None:
        with self._lock:
            self._insert_sorted(item)

    def chain_sorted(self, items: Iterable[Any]) -> None:
        """Insert a whole ring atomically (a concurrent consumer sees either
        none or all of it — the scheduler's ready-ring contract)."""
        with self._lock:
            for it in items:
                self._insert_sorted(it)

    def pop_front(self) -> Optional[Any]:
        with self._lock:
            return self._items.pop(0) if self._items else None

    def push_front(self, item: Any) -> None:
        with self._lock:
            self._items.insert(0, item)

    def push_back(self, item: Any) -> None:
        with self._lock:
            self._items.append(item)

    def pop_back(self) -> Optional[Any]:
        with self._lock:
            return self._items.pop() if self._items else None

    def is_empty(self) -> bool:
        with self._lock:
            return not self._items

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)


def ring_from(items: Iterable[Any]) -> List[Any]:
    """The reference threads ready tasks into 'rings' (parsec_list_item_ring);
    here a plain list is the ring representation used across the engine."""
    return list(items)


class HBBuffer:
    """Hierarchical bounded buffer (reference: parsec/hbbuffer.{c,h} —
    the scheduler building block: a fixed-capacity buffer whose pushes
    overflow to a PARENT store, forming per-thread -> per-group ->
    system chains; pops drain locally first, then pull from the
    parent).  ``parent`` is any object with push_back/pop_front (another
    HBBuffer, a Dequeue, ...)."""

    def __init__(self, capacity: int, parent: Any = None):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self.parent = parent
        self._lock = threading.Lock()
        self._items: collections.deque = collections.deque()

    def push_back(self, item: Any) -> None:
        with self._lock:
            if len(self._items) < self.capacity:
                self._items.append(item)
                return
        if self.parent is None:
            raise OverflowError("hbbuffer full and no parent store")
        self.parent.push_back(item)        # overflow up the hierarchy

    def chain_back(self, items: Iterable[Any]) -> None:
        for it in items:
            self.push_back(it)

    def pop_front(self, local_only: bool = False) -> Optional[Any]:
        """Drain locally, then walk up to the parent.  ``local_only``
        stops at this level — schedulers use it so the walk to the
        system store happens at ITS place in their fairness order
        (local -> steal -> system), not before stealing."""
        with self._lock:
            if self._items:
                return self._items.popleft()
        if self.parent is not None and not local_only:
            return self.parent.pop_front()
        return None

    def pop_back(self) -> Optional[Any]:
        """Steal end: local cold end only — thieves must not drain the
        victim's parent (the reference steals within one level)."""
        with self._lock:
            return self._items.pop() if self._items else None

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)
