"""Synchronization primitives: barrier, rwlock, atomic counter.

Reference: parsec/class/parsec_rwlock.*, parsec/barrier.c,
include/parsec/sys/atomic.h.  Python's GIL makes plain ints atomic for
single ops, but the engine's counters need read-modify-write atomicity, so
AtomicCounter wraps a lock explicitly (and maps onto std::atomic in the
native core).
"""

from __future__ import annotations

import threading


Barrier = threading.Barrier  # parsec_barrier_t


class AtomicCounter:
    """fetch_add/fetch_sub/cas counter (reference: parsec_atomic_fetch_*)."""

    __slots__ = ("_lock", "_value")

    def __init__(self, value: int = 0):
        self._lock = threading.Lock()
        self._value = value

    @property
    def value(self) -> int:
        with self._lock:
            return self._value

    def fetch_add(self, delta: int = 1) -> int:
        with self._lock:
            old = self._value
            self._value += delta
            return old

    def add_and_fetch(self, delta: int = 1) -> int:
        with self._lock:
            self._value += delta
            return self._value

    def fetch_sub(self, delta: int = 1) -> int:
        return self.fetch_add(-delta)

    def sub_and_fetch(self, delta: int = 1) -> int:
        return self.add_and_fetch(-delta)

    def cas(self, expected: int, desired: int) -> bool:
        with self._lock:
            if self._value == expected:
                self._value = desired
                return True
            return False

    def set(self, value: int) -> None:
        with self._lock:
            self._value = value


class RWLock:
    """Readers-writer lock (reference: parsec_rwlock, ticket-based)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    def acquire_read(self) -> None:
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1

    def release_read(self) -> None:
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire_write(self) -> None:
        with self._cond:
            self._writers_waiting += 1
            while self._writer or self._readers:
                self._cond.wait()
            self._writers_waiting -= 1
            self._writer = True

    def release_write(self) -> None:
        with self._cond:
            self._writer = False
            self._cond.notify_all()

    class _Guard:
        def __init__(self, lock, write):
            self._lock, self._write = lock, write

        def __enter__(self):
            (self._lock.acquire_write if self._write
             else self._lock.acquire_read)()

        def __exit__(self, *exc):
            (self._lock.release_write if self._write
             else self._lock.release_read)()

    def read(self):
        return RWLock._Guard(self, False)

    def write(self):
        return RWLock._Guard(self, True)
