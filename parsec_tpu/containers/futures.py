"""Futures and datacopy futures.

Rebuild of the reference's generic future + datacopy future
(reference: parsec/class/parsec_future.{c,h}, parsec_datacopy_future.c):
a thread-safe write-once cell with completion callbacks, and a specialized
future carrying a data copy produced by a triggered "reshape"/transform
callback — the primitive the reshape engine (layout conversion) is built on.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional


class Future:
    """Write-once future (reference: parsec_base_future_t).

    ``set`` may be called exactly once; ``get`` blocks; callbacks registered
    before or after completion all fire exactly once.
    """

    def __init__(self):
        self._cond = threading.Condition()
        self._done = False
        self._value: Any = None
        self._callbacks: List[Callable[[Any], None]] = []

    def is_ready(self) -> bool:
        with self._cond:
            return self._done

    def set(self, value: Any) -> None:
        with self._cond:
            if self._done:
                raise RuntimeError("future already completed")
            self._value = value
            self._done = True
            cbs, self._callbacks = self._callbacks, []
            self._cond.notify_all()
        for cb in cbs:
            cb(value)

    def get(self, timeout: Optional[float] = None) -> Any:
        with self._cond:
            if not self._cond.wait_for(lambda: self._done, timeout=timeout):
                raise TimeoutError("future wait timed out")
            return self._value

    def on_ready(self, cb: Callable[[Any], None]) -> None:
        with self._cond:
            if not self._done:
                self._callbacks.append(cb)
                return
            value = self._value
        cb(value)


class CountdownFuture(Future):
    """Completes after ``n`` contributions (used for quiescence joins)."""

    def __init__(self, n: int, value: Any = None):
        super().__init__()
        self._remaining = n
        self._final = value
        if n == 0:
            self.set(value)

    def contribute(self) -> None:
        fire = False
        with self._cond:
            self._remaining -= 1
            if self._remaining == 0:
                fire = True
        if fire:
            self.set(self._final)


class DataCopyFuture(Future):
    """Future of a data copy materialized on demand by a trigger.

    Reference: parsec_datacopy_future_t — created with a trigger callback
    that produces the target copy (e.g. a reshape/relayout) the first time a
    consumer requests it; multiple consumers share the single result and the
    future tracks how many still need it before the copy can be released.
    """

    def __init__(self, trigger: Callable[[Any], Any], spec: Any = None,
                 nb_consumers: int = 1,
                 cleanup: Optional[Callable[[Any], None]] = None):
        super().__init__()
        self._trigger = trigger
        self.spec = spec
        self._nb_consumers = nb_consumers
        self._cleanup = cleanup
        self._trigger_lock = threading.Lock()
        self._triggered = False

    def start(self) -> None:
        """Fire the trigger once (idempotent)."""
        with self._trigger_lock:
            if self._triggered:
                return
            self._triggered = True
        self.set(self._trigger(self.spec))

    def get_copy(self) -> Any:
        self.start()
        return self.get()

    def consume(self) -> None:
        """One consumer is done with the produced copy; release on last.

        If the trigger is still materializing (or fires later), cleanup is
        deferred to completion via on_ready so the copy is never leaked.
        """
        with self._trigger_lock:
            self._nb_consumers -= 1
            last = self._nb_consumers == 0
            triggered = self._triggered
        if last and self._cleanup is not None and triggered:
            self.on_ready(self._cleanup)
