"""PTG front-end: a Python-embedded JDF.

Rebuild of the reference's PTG/JDF interface (reference:
parsec/interfaces/ptg/ptg-compiler — grammar parsec.y/parsec.l, code
generator jdf2c.c).  Where the reference compiles a textual JDF into a
generated C taskpool, this front-end builds the same parameterized-task-
graph structures directly from Python declarations, preserving the JDF
concepts one-for-one:

  JDF                                  here
  ---------------------------------   ------------------------------------
  k = 0 .. NT-1                        k=Range(0, lambda NT: NT - 1)
  : A(k, k)        (partitioning)      .affinity(lambda k: A(k, k))
  RW T <- (k==0) ? A(k) : S(k-1)       .flow("T", "RW", IN(DATA(...),
        -> (k<NT-1) ? S(k+1) : A(k)        when=...), IN(TASK(...)), ...)
  -> TRSM(k+1..NT-1, k)                TASK("TRSM", "T", lambda k:
                                         [dict(m=m, k=k) for m in ...])
  BODY ... END                         .body(fn)  # named args by flow/param

All user lambdas take the task's parameters BY NAME (``lambda k, m: ...``);
bodies additionally receive flow payloads by flow name, plus the optional
``es`` and ``task`` magic names.  Taskpool globals (NT, ...) are visible to
Range bounds by name and to everything else via Python closures.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from parsec_tpu.core.task import (CTL as _CTL_FLOW, Dep, Flow, FromDesc,
                                  FromTask, HookReturn, New, Null, TaskClass,
                                  ToDesc, ToTask, normalize_body_outputs)
from parsec_tpu.core.taskpool import ParameterizedTaskpool
from parsec_tpu.data.arena import Arena
from parsec_tpu.data.collection import DataRef
from parsec_tpu.data.data import (ACCESS_NONE, ACCESS_READ, ACCESS_RW,
                                  ACCESS_WRITE)

_MODES = {"RW": ACCESS_RW, "READ": ACCESS_READ, "WRITE": ACCESS_WRITE,
          "CTL": ACCESS_NONE}


def _named(fn: Callable) -> Callable[[Dict[str, int]], Any]:
    """Adapt a named-parameter lambda to a locals-dict callable.

    Parameters with defaults (the ``lambda k, NB=NT: ...`` capture idiom)
    keep their defaults when the name is not a task parameter.
    """
    if fn is None:
        return None
    sig = [(p.name, p.default is not inspect.Parameter.empty)
           for p in inspect.signature(fn).parameters.values()
           if p.kind in (inspect.Parameter.POSITIONAL_OR_KEYWORD,
                         inspect.Parameter.KEYWORD_ONLY)]

    def wrapper(locals_: Dict[str, int]):
        kwargs = {}
        for name, has_default in sig:
            if name in locals_:
                kwargs[name] = locals_[name]
            elif not has_default:
                raise KeyError(
                    f"dep expression needs {name!r} but the task has "
                    f"params {sorted(locals_)}; capture globals with a "
                    f"default arg (lambda k, {name}={name}: ...)")
        return fn(**kwargs)
    return wrapper


def _resolve(v: Any, globals_: Dict[str, Any], locals_: Dict[str, int]) -> int:
    if callable(v):
        # signature introspection costs ~10us; Range bounds resolve once
        # per parameter per enumeration node, so memoize the name list
        # on the function itself (iter_space over an O(NT^3) space would
        # otherwise pay it millions of times)
        names = getattr(v, "_pt_argnames", None)
        if names is None:
            names = [p.name
                     for p in inspect.signature(v).parameters.values()]
            try:
                v._pt_argnames = names
            except AttributeError:
                pass   # builtins/bound methods: uncached, still correct
        scope = {**globals_, **locals_}
        return v(**{n: scope[n] for n in names})
    return int(v)


class Range:
    """JDF-style INCLUSIVE parameter range ``lo .. hi [.. step]``.
    Bounds may be ints or named lambdas over globals and earlier params."""

    def __init__(self, lo: Any, hi: Any, step: Any = 1):
        self.lo, self.hi, self.step = lo, hi, step

    def to_fn(self):
        def fn(globals_, locals_):
            lo = _resolve(self.lo, globals_, locals_)
            hi = _resolve(self.hi, globals_, locals_)
            st = _resolve(self.step, globals_, locals_)
            return range(lo, hi + (1 if st > 0 else -1), st)
        return fn


# -- dependency endpoint constructors ---------------------------------------

class _End:
    pass


class TASK(_End):
    """Reference to a peer task's flow: TASK("TRSM", "T", lambda k: dict(...))
    — a list-returning lambda expresses a JDF range dep."""

    def __init__(self, task_class: str, flow: str, params: Callable):
        self.task_class, self.flow = task_class, flow
        self.params = _named(params)


class DATA(_End):
    """Direct collection access: DATA(lambda k: A(k, k))."""

    def __init__(self, ref: Callable):
        self.ref = _named(ref)


class NEW(_End):
    """Fresh arena allocation (JDF NEW)."""

    def __init__(self, arena: str = "default"):
        self.arena = arena


class NULL_END(_End):
    """JDF NULL."""


def _to_core_end(e: Union[_End, Callable], is_input: bool):
    if isinstance(e, TASK):
        return (FromTask(e.task_class, e.flow, e.params) if is_input
                else ToTask(e.task_class, e.flow, e.params))
    if isinstance(e, DATA):
        return FromDesc(e.ref) if is_input else ToDesc(e.ref)
    if isinstance(e, NEW):
        if not is_input:
            # reference diagnostic (ptgpp output_NEW*.jdf golden cases)
            raise ValueError("Automatic data allocation with NEW only "
                             "supported in IN dependencies.")
        return New(e.arena)
    if isinstance(e, NULL_END) or e is NULL_END:
        if not is_input:
            # reference diagnostic (ptgpp output_NULL*.jdf golden cases)
            raise ValueError("NULL data only supported in IN dependencies.")
        return Null()
    if callable(e):   # bare lambda returning a DataRef == DATA shorthand
        return _to_core_end(DATA(e), is_input)
    raise TypeError(f"bad dependency endpoint {e!r}")


class IN:
    """Input dependency: IN(endpoint, when=guard, count=gather_multiplicity)."""

    def __init__(self, end, when: Optional[Callable] = None,
                 count: Optional[Callable] = None, dtt: Any = None):
        self.dep = Dep(_to_core_end(end, is_input=True), guard=_named(when),
                       count=_named(count), dtt=dtt)


class OUT:
    """Output dependency: OUT(endpoint, when=guard)."""

    def __init__(self, end, when: Optional[Callable] = None, dtt: Any = None):
        self.dep = Dep(_to_core_end(end, is_input=False), guard=_named(when),
                       dtt=dtt)


def _bind_body_outputs(task, ret: Any, writable: List[str]) -> None:
    """Store a functional body's return value(s) into the written flows'
    copies.  Host copies backed by collection storage are updated in place
    (np.copyto) so backing-array views stay linked."""
    outs = normalize_body_outputs(ret, writable, what=str(task))
    for name, value in outs.items():
        copy = task.data.get(name)
        if copy is None:
            raise RuntimeError(f"{task}: flow {name!r} has no bound copy")
        arr = np.asarray(value) if not hasattr(value, "devices") else value
        if isinstance(copy.payload, np.ndarray) \
                and isinstance(arr, np.ndarray) \
                and arr.shape == copy.payload.shape \
                and arr.dtype == copy.payload.dtype:
            np.copyto(copy.payload, arr)
        else:
            # shape/dtype change (a dtt edge layout, or a device array):
            # rebind the payload; the writeback path converts home
            copy.payload = arr


# -- task-class builder ------------------------------------------------------

class TaskBuilder:
    def __init__(self, ptg: "PTG", name: str, params: Dict[str, Any]):
        self._ptg = ptg
        self.name = name
        self._params = []
        for pname, r in params.items():
            if isinstance(r, Range):
                self._params.append((pname, r.to_fn()))
            elif callable(r):
                self._params.append((pname, r))
            else:
                raise TypeError(f"param {pname}: expected Range or callable")
        self._affinity = None
        self._priority = None
        self._key_fn = None
        self._flows: List[Flow] = []
        self._incarnations: List = []
        self._properties: Dict[str, Any] = {}

    def affinity(self, fn: Callable) -> "TaskBuilder":
        """JDF partitioning line ``: A(k, n)``."""
        self._affinity = _named(fn)
        return self

    def priority(self, fn: Callable) -> "TaskBuilder":
        self._priority = _named(fn)
        return self

    def make_key(self, fn: Callable) -> "TaskBuilder":
        """User-defined task key (reference: the ``[make_key_fn = ...]``
        task-class property, user-defined-functions/udf.jdf:46): ``fn``
        maps the task's named parameters to any hashable key, replacing
        the default parameter tuple in dep tracking and the repo."""
        self._key_fn = _named(fn)
        return self

    def flow(self, name: str, mode: str, *deps: Union[IN, OUT]) -> "TaskBuilder":
        ins = [d.dep for d in deps if isinstance(d, IN)]
        outs = [d.dep for d in deps if isinstance(d, OUT)]
        self._flows.append(Flow(name, _MODES[mode.upper()], ins, outs))
        return self

    def body(self, fn: Callable, device: str = "cpu") -> "TaskBuilder":
        """Register an incarnation.  The function's named args are bound
        from task params, flow payloads, and the magic names es/task.

        ``device="tpu"`` registers an XLA incarnation: ``fn`` must be a
        pure jax function over flow payloads (see XlaKernel); at runtime
        the task is handed to the best XLA device and completes
        asynchronously (reference: BODY [type=CUDA] bodies and the GPU
        hook of jdf2c.c:6556).  When no device is attached the incarnation
        declines (HookReturn.NEXT) and the next body — typically a cpu
        fallback declared after it — runs instead.
        """
        if device in ("tpu", "xla", "gpu"):
            return self._device_body(fn, device)
        flow_names = {f.name for f in self._flows}
        names = [p.name for p in inspect.signature(fn).parameters.values()]
        writable = [f.name for f in self._flows if f.access & ACCESS_WRITE]

        if not names and not writable:
            # zero-arg, zero-write body (CTL-only probes, barriers):
            # skip the kwargs binding loop — the empty-task hot path
            def hook(es, task):
                ret = fn()
                return ret if ret is None or isinstance(ret, HookReturn) \
                    else None
            hook.__ptg_fn__ = fn
            hook.__ptg_writable__ = writable
            self._incarnations.append((device, hook))
            return self

        def hook(es, task):
            kwargs = {}
            for n in names:
                if n == "es":
                    kwargs[n] = es
                elif n == "task":
                    kwargs[n] = task
                elif n in flow_names:
                    copy = task.data.get(n)
                    kwargs[n] = None if copy is None else copy.payload
                elif n in task.locals:
                    kwargs[n] = task.locals[n]
                elif n in self._ptg.globals_:
                    kwargs[n] = self._ptg.globals_[n]
                # else: the parameter's own default (capture idiom) applies
            ret = fn(**kwargs)
            # Functional bodies return the new written-flow values (same
            # convention as device kernels); in-place bodies return None.
            # Only HookReturn instances pass through as lifecycle codes —
            # a plain int/bool is a VALUE (silently eating it as a code
            # would drop the write).
            if ret is None or isinstance(ret, HookReturn):
                return ret
            if not writable:
                return None   # nothing to write; ignore the return value
            _bind_body_outputs(task, ret, writable)
            return None

        hook.__ptg_fn__ = fn            # raw body, for the PTG->DTD bridge
        hook.__ptg_writable__ = writable
        self._incarnations.append((device, hook))
        return self

    def _device_body(self, fn: Callable, device: str) -> "TaskBuilder":
        from parsec_tpu.core.task import HookReturn
        from parsec_tpu.devices.xla import XlaKernel
        names = [p.name for p in inspect.signature(fn).parameters.values()]
        flow_names = [f.name for f in self._flows]
        writable = [f.name for f in self._flows if f.access & ACCESS_WRITE]
        spec = XlaKernel(fn, names, flow_names, writable)

        def hook(es, task):
            reg = getattr(es.context, "device_registry", None)
            dev = reg.best_device(task) if reg is not None else None
            if dev is None:
                return HookReturn.NEXT
            return dev.submit(es, task, spec)

        self._incarnations.append((device, hook))
        return self

    def property(self, key: str, value: Any) -> "TaskBuilder":
        self._properties[key] = value
        return self

    def _build(self) -> TaskClass:
        return TaskClass(
            self.name, params=self._params, affinity=self._affinity,
            flows=self._flows, incarnations=self._incarnations,
            priority=self._priority, properties=self._properties,
            key_fn=self._key_fn)


class PTG:
    """A parameterized-task-graph taskpool under construction.

    ``PTG("name", NT=4, ...)`` declares globals; ``.task(...)`` declares
    task classes; ``.build()`` (or passing the PTG straight to
    Context.add_taskpool via ``.taskpool``) yields the runnable pool.
    """

    def __init__(self, name: str, **globals_):
        self.name = name
        self.globals_ = dict(globals_)
        self._tasks: List[TaskBuilder] = []
        self._arenas: Dict[str, Arena] = {}
        #: build a DynamicTaskpool instead (JDF ``%option dynamic = ON``):
        #: no startup enumeration; task classes seed via the
        #: ``startup_fn`` property and tasks are counted as discovered
        self.dynamic = False

    def task(self, name: str, **params) -> TaskBuilder:
        tb = TaskBuilder(self, name, params)
        self._tasks.append(tb)
        return tb

    def arena(self, name: str, shape: Sequence[int],
              dtype: Any = np.float32) -> "PTG":
        self._arenas[name] = Arena(tuple(shape), dtype)
        return self

    def build(self) -> ParameterizedTaskpool:
        if self.dynamic:
            from parsec_tpu.core.taskpool import DynamicTaskpool
            tp = DynamicTaskpool(self.name, globals_=self.globals_)
        else:
            tp = ParameterizedTaskpool(self.name, globals_=self.globals_)
        for aname, arena in self._arenas.items():
            tp.add_arena(aname, arena)
        for tb in self._tasks:
            tp.add_task_class(tb._build())
        return tp
