"""Textual JDF front-end: parse the reference's task-graph language into
the embedded PTG builder.

The reference's defining artifact is a compiler for the JDF language
(reference: parsec/interfaces/ptg/ptg-compiler/parsec.y grammar,
parsec.l:141-159 tokens, driver main.c) emitting C.  Here the same
surface syntax is parsed into the Python-embedded PTG builder
(dsl/ptg/api.py) — no code generation: globals become taskpool globals,
execution-space ranges become ``Range`` params, partitioning becomes
affinity, dependency lines become guarded IN/OUT deps, and ``BODY``
blocks are mapped to caller-supplied Python/JAX callables (inline C is
NOT executed; an unmapped body raises a clear error at execution).

Supported grammar subset (everything the reference's example corpus
uses — Ex01..Ex07, tests/apps/stencil/stencil_1D.jdf, and
tests/runtime/multichain.jdf, all golden-run in test_jdf_parser.py):

- ``extern "C" %{ ... %}`` prologue/epilogue blocks (captured verbatim,
  not executed),
- globals with ``[ type=... hidden=on default=... ]`` properties,
- task execution space: ``k = lo .. hi`` / ``lo .. hi .. step`` ranges
  and derived locals ``name = expr``,
- inline-C ``%{ ... return EXPR; %}`` blocks: a declaration /
  (compound-)assignment / return statement subset translates to one
  Python expression via sequenced assignment expressions; control flow
  is rejected,
- partitioning ``: data( exprs )``,
- flows ``RW|READ|WRITE|CTL name`` with guarded, possibly ternary
  endpoints ``(g) ? A Task(p) : B Other(p)``, range targets
  ``A Task( k, 0 .. NB .. 2 )``, ``NEW``/``NULL`` endpoints, and
  ``[ ... ]`` annotations (``type``/``type_remote`` looked up in the
  caller's ``dtts`` map),
- ``BODY [...] { ... } END`` (C source captured; annotation tolerated).

C expressions are translated to Python (&&/||/!/ternary/-> field
access), evaluated against the task's parameters, derived locals, and
the taskpool globals — the same binding rules the generated code uses
(reference: jdf2c.c expression evaluators, :2244).
"""

from __future__ import annotations

import ast
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from parsec_tpu.dsl.ptg.api import (DATA, IN, NEW, NULL_END, OUT, PTG,
                                    Range, TASK)


class JdfError(ValueError):
    pass


# ---------------------------------------------------------------------------
# C-expression -> Python translation
# ---------------------------------------------------------------------------

_INLINE_C = re.compile(r"%\{(.*?)%\}", re.S)


#: C declaration/assignment statement: ``[type] name [op]= expr``
_C_STMT = re.compile(
    r"^\s*(?:(?:unsigned\s+|signed\s+|const\s+)*"
    r"(?:int|long|short|float|double|char|size_t|uint\d+_t|int\d+_t)\s+)?"
    r"(\w+)\s*(\+|-|\*|/|%)?=(?!=)\s*(.+)$", re.S)


def _inline_c_expr(body: str) -> str:
    """Translate an inline-C block to ONE Python expression.

    ``%{ return EXPR; %}`` maps directly.  A small statement subset —
    declarations, (compound) assignments, then a final return
    (reference compiles arbitrary C, jdf2c.c:8163; this covers the
    idioms the corpus uses) — translates via assignment expressions
    sequenced in a tuple: ``int r = k+1; r *= 2; return r;`` becomes
    ``((r := (k+1)), (r := r * (2)), (r))[-1]``.  Anything else
    (loops, calls with side effects, conditionals) is rejected."""
    stmts = [s.strip() for s in _split_top(body, ";") if s.strip()]
    if not stmts:
        raise JdfError("empty inline C block")
    if not re.match(r"^return\b", stmts[-1]):
        raise JdfError(
            f"inline C must end in 'return EXPR;': {body.strip()[:60]!r}")
    final = stmts[-1][len("return"):].strip()
    if len(stmts) == 1:
        return final
    parts = []
    for s in stmts[:-1]:
        m = _C_STMT.match(s)
        if not m:
            raise JdfError(
                f"inline C statement outside the declaration/assignment/"
                f"return subset: {s[:60]!r}")
        name, op, rhs = m.group(1), m.group(2), m.group(3)
        if op:
            parts.append(f"({name} := {name} {op} ({rhs}))")
        else:
            parts.append(f"({name} := ({rhs}))")
    parts.append(f"({final})")
    # immediately-invoked lambda: walrus targets stay lambda-local (no
    # collision with range-dep comprehension variables) and the result
    # is legal anywhere an expression is — a bare walrus would be a
    # SyntaxError in a comprehension's iterable position
    return ("(lambda: (" + ", ".join(parts) + ")[-1])()")


def _translate_ternary(s: str) -> str:
    """C ternary ``a ? b : c`` -> Python conditional, recursively,
    splitting only at paren-depth 0."""
    depth = 0
    for i, ch in enumerate(s):
        if ch in "([":
            depth += 1
        elif ch in ")]":
            depth -= 1
        elif ch == "?" and depth == 0:
            cond = s[:i]
            rest = s[i + 1:]
            d2 = 0
            for j, c2 in enumerate(rest):
                if c2 in "([":
                    d2 += 1
                elif c2 in ")]":
                    d2 -= 1
                elif c2 == ":" and d2 == 0:
                    a, b = rest[:j], rest[j + 1:]
                    return (f"(({_translate_ternary(a)}) if "
                            f"({_translate_ternary(cond)}) else "
                            f"({_translate_ternary(b)}))")
            raise JdfError(f"ternary without ':' in {s!r}")
    return s


def _c_div(a, b):
    """C ``/`` semantics: truncation toward zero when both operands are
    integral (Python ``/`` would yield a float and silently build a
    wrong task space), true division otherwise."""
    if isinstance(a, (int, np.integer)) and isinstance(b, (int, np.integer)):
        q = abs(a) // abs(b)
        return -q if (a < 0) != (b < 0) else q
    return a / b


def _c_mod(a, b):
    """C ``%`` semantics: remainder truncates toward zero (Python floors)."""
    if isinstance(a, (int, np.integer)) and isinstance(b, (int, np.integer)):
        return a - _c_div(a, b) * b
    return a % b


#: names injected into every namespace that evaluates c2py() output
C_EVAL_HELPERS = {"_c_div": _c_div, "_c_mod": _c_mod}


class _CArithTransform(ast.NodeTransformer):
    """Rewrite ``a / b`` -> ``_c_div(a, b)`` and ``a % b`` ->
    ``_c_mod(a, b)`` so integral JDF index math keeps C semantics."""

    def visit_BinOp(self, node):
        self.generic_visit(node)
        fn = ("_c_div" if isinstance(node.op, ast.Div)
              else "_c_mod" if isinstance(node.op, ast.Mod) else None)
        if fn is None:
            return node
        return ast.copy_location(
            ast.Call(func=ast.Name(id=fn, ctx=ast.Load()),
                     args=[node.left, node.right], keywords=[]), node)


def _c_arith(expr: str) -> str:
    try:
        tree = ast.parse(expr, mode="eval")
    except SyntaxError:
        return expr     # let the later compile step surface the error
    if not any(isinstance(n, ast.BinOp) and
               isinstance(n.op, (ast.Div, ast.Mod))
               for n in ast.walk(tree)):
        return expr
    return ast.unparse(ast.fix_missing_locations(
        _CArithTransform().visit(tree)))


def c2py(expr: str) -> str:
    """Translate a C expression (as appearing in JDF ranges, guards and
    index expressions) to Python source."""
    expr = expr.strip()
    expr = _INLINE_C.sub(lambda m: "(" + _inline_c_expr(m.group(1)) + ")",
                         expr)
    expr = expr.replace("->", ".")
    expr = expr.replace("&&", " and ").replace("||", " or ")
    # logical not: '!' not part of '!='
    expr = re.sub(r"!(?!=)", " not ", expr)
    expr = _translate_ternary(expr)
    return _c_arith(expr.strip())


def _split_top(s: str, sep: str) -> List[str]:
    """Split on ``sep`` at paren-depth 0."""
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch in "([":
            depth += 1
        elif ch in ")]":
            depth -= 1
        if ch == sep and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    return parts


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------

class JdfGlobal:
    def __init__(self, name: str, props: Dict[str, str]):
        self.name = name
        self.props = props


class JdfEndpoint:
    """One side of a dependency arrow."""

    def __init__(self, kind: str, flow: Optional[str] = None,
                 target: Optional[str] = None,
                 args: Optional[List[str]] = None):
        self.kind = kind          # "task" | "data" | "new" | "null"
        self.flow = flow          # peer flow name (task kind)
        self.target = target      # task or data name
        self.args = args or []    # raw C argument expressions


class JdfDep:
    def __init__(self, direction: str, guard: Optional[str],
                 ep: JdfEndpoint, alt: Optional[JdfEndpoint],
                 props: Dict[str, str]):
        self.direction = direction            # "in" | "out"
        self.guard = guard                    # raw C guard or None
        self.ep = ep
        self.alt = alt                        # ':' branch of a ternary
        self.props = props                    # [ type=... ] annotations


class JdfFlow:
    def __init__(self, access: str, name: str):
        self.access = access                  # RW | READ | WRITE | CTL
        self.name = name
        self.deps: List[JdfDep] = []


class JdfTask:
    def __init__(self, name: str, params: List[str],
                 props: Optional[Dict[str, str]] = None):
        self.name = name
        self.params = params          # HEADER params: the free addressing
        self.props = props or {}      # [ make_key_fn=... startup_fn=... ]
        #: execution-space definitions in DECLARATION ORDER — ranges and
        #: derived locals interleave (BT_reduction.jdf: a local between
        #: two ranges feeds the later range's bounds)
        self.defs: List[Tuple] = []   # ("range", n, lo, hi, step) |
        #                               ("local", n, expr)
        self.partition: Optional[Tuple[str, List[str]]] = None
        self.flows: List[JdfFlow] = []
        self.body_src: str = ""
        self.body_props: Dict[str, str] = {}

    @property
    def ranges(self) -> List[Tuple[str, str, str, Optional[str]]]:
        return [(d[1], d[2], d[3], d[4]) for d in self.defs
                if d[0] == "range"]

    @property
    def locals(self) -> List[Tuple[str, str]]:
        return [(d[1], d[2]) for d in self.defs if d[0] == "local"]

    @property
    def def_names(self) -> List[str]:
        return [d[1] for d in self.defs]


class JdfFile:
    def __init__(self):
        self.externs: List[str] = []
        self.globals: List[JdfGlobal] = []
        self.tasks: List[JdfTask] = []
        self.options: Dict[str, str] = {}     # %option lines


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------

_PROPS = re.compile(r"(\w+)\s*=\s*(\"[^\"]*\"|\S+)")


def _parse_props(s: str) -> Dict[str, str]:
    out = {}
    for k, v in _PROPS.findall(s):
        out[k] = v.strip('"')
    return out


def _strip_comments(text: str) -> str:
    text = re.sub(r"/\*.*?\*/", "", text, flags=re.S)
    return re.sub(r"//[^\n]*", "", text)


def parse_jdf(text: str) -> JdfFile:
    """Parse JDF source into an AST (reference grammar: parsec.y)."""
    jdf = JdfFile()

    def grab_extern(m):
        jdf.externs.append(m.group(1))
        return ""
    text = re.sub(r"extern\s+\"C\"\s*%\{(.*?)%\}", grab_extern, text,
                  flags=re.S)
    # protect inline-C expressions from comment/line processing
    inlines: List[str] = []

    def protect(m):
        inlines.append(m.group(0))
        return f"\x00{len(inlines) - 1}\x00"
    text = _INLINE_C.sub(protect, text)
    text = _strip_comments(text)

    def unprotect(s: str) -> str:
        return re.sub(r"\x00(\d+)\x00", lambda m: inlines[int(m.group(1))],
                      s)

    # split off BODY blocks first (they contain arbitrary C)
    lines = text.splitlines()
    i = 0
    task: Optional[JdfTask] = None
    flow: Optional[JdfFlow] = None
    while i < len(lines):
        line = lines[i].strip()
        i += 1
        if not line:
            continue
        if line.startswith("BODY"):
            if task is None:
                raise JdfError("BODY outside a task")
            task.body_props = _parse_props(line[4:].strip(" []"))
            body: List[str] = []
            while i < len(lines):
                l2 = lines[i]
                i += 1
                if l2.strip() == "END":
                    break
                body.append(l2)
            else:
                raise JdfError(f"task {task.name}: BODY without END")
            task.body_src = unprotect("\n".join(body))
            flow = None
            continue
        # dependency continuation (<- / ->)
        if line.startswith("<-") or line.startswith("->"):
            if flow is None:
                raise JdfError(f"dangling dependency line: {line!r}")
            flow.deps.append(_parse_dep(unprotect(line)))
            continue
        # flow header: ACCESS name [deps...]
        m = re.match(r"^(RW|READ|WRITE|CTL)\s+(\w+)\s*(.*)$", line)
        if m and task is not None:
            flow = JdfFlow(m.group(1), m.group(2))
            task.flows.append(flow)
            rest = m.group(3).strip()
            if rest:
                flow.deps.append(_parse_dep(unprotect(rest)))
            continue
        # partitioning
        if line.startswith(":") and task is not None:
            mm = re.match(r":\s*(\w+)\s*\((.*)\)\s*$", line)
            if not mm:
                raise JdfError(f"bad partitioning line {line!r}")
            task.partition = (mm.group(1),
                              [unprotect(a.strip())
                               for a in _split_top(mm.group(2), ",")])
            continue
        # %option name = value (reference: parsec.y options rule; e.g.
        # "%option dynamic = ON", "%option no_taskpool_instance = true")
        if line.startswith("%option"):
            for k, v in _PROPS.findall(unprotect(line[len("%option"):])):
                jdf.options[k] = v.strip('"')
            continue
        # definition: name = range/expr
        m = re.match(r"^(\w+)\s*=\s*(.+)$", line)
        if m and task is not None:
            name, rhs = m.group(1), unprotect(m.group(2).strip())
            parts = [p.strip() for p in re.split(r"\.\.", rhs)]
            if name in task.params:
                if len(parts) == 2:
                    task.defs.append(("range", name, parts[0], parts[1],
                                      None))
                elif len(parts) == 3:
                    task.defs.append(("range", name, parts[0], parts[1],
                                      parts[2]))
                else:
                    raise JdfError(
                        f"task {task.name}: parameter {name} needs a "
                        f"'lo .. hi' range, got {rhs!r}")
            else:
                if len(parts) != 1:
                    raise JdfError(
                        f"task {task.name}: derived local {name} cannot "
                        f"be a range")
                task.defs.append(("local", name, rhs))
            continue
        # global: NAME [ props ]
        m = re.match(r"^(\w+)\s*\[(.*)\]\s*$", line)
        if m and task is None:
            jdf.globals.append(JdfGlobal(m.group(1),
                                         _parse_props(unprotect(m.group(2)))))
            continue
        # task header: Name(a, b) [ props... ] — the property block may
        # span lines (project_dyn.jdf:43-44)
        m = re.match(r"^(\w+)\s*\(([^)]*)\)\s*(\[.*)?$", line)
        if m:
            propsrc = m.group(3) or ""
            while propsrc.count("[") > propsrc.count("]") \
                    and i < len(lines):
                propsrc += " " + lines[i].strip()
                i += 1
            task = JdfTask(m.group(1),
                           [p.strip() for p in m.group(2).split(",")
                            if p.strip()],
                           props=_parse_props(
                               unprotect(propsrc.strip(" []"))))
            jdf.tasks.append(task)
            flow = None
            continue
        raise JdfError(f"unrecognized JDF line: {line!r}")
    return jdf


def _parse_dep(line: str) -> JdfDep:
    direction = "in" if line.startswith("<-") else "out"
    rest = line[2:].strip()
    props: Dict[str, str] = {}
    pm = re.search(r"\[([^\]]*)\]\s*$", rest)
    if pm:
        props = _parse_props(pm.group(1))
        rest = rest[:pm.start()].strip()
    guard = None
    alt = None
    # guard: any top-level '?' splits "<expr> ? endpoint [: alt]" — the
    # expression need not be parenthesized (project_dyn.jdf:52
    # "larger_than_thresh ? RL PROJECT(...)")
    depth = 0
    for i, ch in enumerate(rest):
        if ch in "([":
            depth += 1
        elif ch in ")]":
            depth -= 1
        elif ch == "?" and depth == 0:
            guard = rest[:i].strip()
            rest = rest[i + 1:].strip()
            break
    if guard is not None:
        branches = _split_top(rest, ":")
        if len(branches) == 2:
            ep = _parse_endpoint(branches[0].strip())
            alt = _parse_endpoint(branches[1].strip())
        else:
            ep = _parse_endpoint(rest)
    else:
        ep = _parse_endpoint(rest)
    return JdfDep(direction, guard, ep, alt, props)


def _parse_endpoint(s: str) -> JdfEndpoint:
    s = s.strip()
    if s == "NEW":
        return JdfEndpoint("new")
    if s == "NULL":
        return JdfEndpoint("null")
    m = re.match(r"^(\w+)\s+(\w+)\s*\((.*)\)\s*$", s)
    if m:
        return JdfEndpoint("task", flow=m.group(1), target=m.group(2),
                           args=[a.strip()
                                 for a in _split_top(m.group(3), ",")])
    m = re.match(r"^(\w+)\s*\((.*)\)\s*$", s)
    if m:
        return JdfEndpoint("data", target=m.group(1),
                           args=[a.strip()
                                 for a in _split_top(m.group(2), ",")])
    raise JdfError(f"unparseable dependency endpoint {s!r}")


# ---------------------------------------------------------------------------
# builder: AST -> embedded PTG
# ---------------------------------------------------------------------------

def _compile_fn(expr_py: str, params: List[str],
                derived: List[Tuple[str, str]], env: Dict[str, Any],
                list_wrap: Optional[List[Tuple[str, str, str, str]]] = None):
    """Build a real function ``f(params...)`` evaluating ``expr_py``
    after computing the task's derived locals (the JDF 'name = expr'
    definitions); ``list_wrap`` adds range-comprehension variables for
    range deps."""
    body = ["def _f(" + ", ".join(params) + "):"]
    for name, dexpr in derived:
        body.append(f"    {name} = ({c2py(dexpr)})")
    if list_wrap:
        comp = expr_py
        for var, lo, hi, step in list_wrap:
            comp += (f" for {var} in range(({c2py(lo)}), ({c2py(hi)}) + 1, "
                     f"({c2py(step) if step else 1}))")
        body.append(f"    return [{comp}]")
    else:
        body.append(f"    return ({expr_py})")
    ns: Dict[str, Any] = dict(env)
    exec("\n".join(body), ns)          # noqa: S102 — trusted build-time DSL
    return ns["_f"]


def _single_valued(vf, names: List[str]):
    """Derived local -> single-valued parameter range: the value function
    (over the preceding definition names) evaluated once per instance."""
    def fn(globals_, locals_):
        return [vf(*[locals_[n] for n in names])]
    return fn


def _missing_body(task_name: str):
    def body(*_a, **_k):
        raise RuntimeError(
            f"JDF task {task_name!r} has an inline-C body that was not "
            f"mapped to Python — pass bodies={{{task_name!r}: fn}} to "
            f"jdf_taskpool()")
    return body


def jdf_taskpool(source: str, *, globals: Optional[Dict[str, Any]] = None,
                 data: Optional[Dict[str, Any]] = None,
                 bodies: Optional[Dict[str, Any]] = None,
                 arenas: Optional[Dict[str, Tuple[Tuple[int, ...], Any]]]
                 = None,
                 dtts: Optional[Dict[str, Any]] = None,
                 funcs: Optional[Dict[str, Any]] = None,
                 name: Optional[str] = None):
    """Parse JDF ``source`` (text or a path ending in .jdf) and build a
    runnable taskpool.

    ``globals``: values for the JDF globals (collections included).
    ``data``: name -> data collection for partitioning/data endpoints
    (defaults to any collection-valued globals).
    ``bodies``: task name -> Python callable (or (device_kernel, cpu_fn)
    tuple) replacing the inline-C BODY.
    ``arenas``: arena name -> (shape, dtype) for NEW endpoints; a single
    ``"default"`` entry serves JDF NEW (which is untyped in the text).
    ``dtts``: annotation value (``type``/``type_remote``) -> dtt object.
    ``funcs``: C-function name -> Python callable for task-level
    properties (``make_key_fn`` over named params; ``startup_fn`` as
    ``fn(globals_, rank) -> iterable of seed param dicts`` for
    ``%option dynamic = ON`` pools — project_dyn.jdf:43-44,109-159).
    """
    if source.endswith(".jdf") and "\n" not in source:
        with open(source) as fh:
            text = fh.read()
        if name is None:
            name = re.sub(r"\.jdf$", "", source.rsplit("/", 1)[-1])
    else:
        text = source
    jdf = parse_jdf(text)
    gvals = dict(globals or {})
    for g in jdf.globals:
        if g.name in gvals:
            continue
        if data and g.name in data:
            gvals[g.name] = data[g.name]    # collection-typed global
        elif "default" in g.props:
            # defaults may reference earlier globals (kcyclic.jdf:111
            # "dA->super.mt-1"): evaluate against the values so far
            gvals[g.name] = eval(c2py(g.props["default"]),
                                 {**C_EVAL_HELPERS, **gvals}, {})
        else:
            raise JdfError(f"JDF global {g.name!r} has no value: pass "
                           f"globals={{{g.name!r}: ...}}")
    dmap = dict(data or {})
    for k, v in gvals.items():
        if hasattr(v, "data_of") and k not in dmap:
            dmap[k] = v
    env = dict(gvals)
    env.update(dmap)
    env["np"] = np
    env.update(C_EVAL_HELPERS)

    p = PTG(name or (jdf.tasks[0].name.lower() if jdf.tasks else "jdf"),
            **{k: v for k, v in gvals.items()
               if isinstance(v, (int, float, str, bool))})
    p.dynamic = str(jdf.options.get("dynamic", "")).lower() \
        in ("on", "true", "1", "yes")
    for aname, (shape, dtype) in (arenas or {}).items():
        p.arena(aname, shape, dtype)

    task_names = {t.name for t in jdf.tasks}

    for t in jdf.tasks:
        declared = [d[1] for d in t.defs if d[0] == "range"]
        for pname in t.params:
            if pname not in declared:
                raise JdfError(
                    f"task {t.name}: parameter {pname} has no range")
        # Execution space: EVERY definition — ranges and derived locals,
        # in declaration order — becomes a TaskClass parameter; derived
        # locals are single-valued ranges over the preceding names.  This
        # mirrors the reference exactly (locals live in
        # this_task->locals and bodies may overwrite them — the
        # project_dyn.jdf dynamic-pruning idiom), and lets later range
        # bounds use earlier derived locals (BT_reduction.jdf "s = 1 ..
        # sz" where sz derives from t).
        space: Dict[str, Any] = {}
        prior: List[str] = []
        for d in t.defs:
            if d[0] == "range":
                _, pname, lo, hi, step = d
                lo_f = _compile_fn(c2py(lo), list(prior), [], env)
                hi_f = _compile_fn(c2py(hi), list(prior), [], env)
                if step is not None:
                    space[pname] = Range(
                        lo_f, hi_f, _compile_fn(c2py(step), list(prior),
                                                [], env))
                else:
                    space[pname] = Range(lo_f, hi_f)
                prior.append(pname)
            else:
                _, lname, expr = d
                space[lname] = _single_valued(
                    _compile_fn(c2py(expr), list(prior), [], env),
                    list(prior))
                prior.append(lname)
        tb = p.task(t.name, **space)
        if "make_key_fn" in t.props:
            fn = (funcs or {}).get(t.props["make_key_fn"])
            if fn is None:
                raise JdfError(
                    f"task {t.name}: make_key_fn "
                    f"{t.props['make_key_fn']!r} not in funcs=")
            tb.make_key(fn)
        if "startup_fn" in t.props:
            fn = (funcs or {}).get(t.props["startup_fn"])
            if fn is None:
                raise JdfError(
                    f"task {t.name}: startup_fn "
                    f"{t.props['startup_fn']!r} not in funcs=")
            tb.property("startup_fn", fn)
        if t.partition is not None:
            dname, args = t.partition
            if dname not in dmap:
                raise JdfError(f"task {t.name}: partitioning data "
                               f"{dname!r} not provided")
            expr = f"{dname}(" + ", ".join(c2py(a) for a in args) + ")"
            tb.affinity(_compile_fn(expr, t.def_names, [], env))
        for f in t.flows:
            ends = []
            for dep in f.deps:
                ends.extend(_build_dep(t, f, dep, env, dmap, jdf.tasks,
                                       dtts or {}))
            tb.flow(f.name, f.access, *ends)
        body = (bodies or {}).get(t.name)
        if body is None:
            tb.body(_missing_body(t.name))
        elif isinstance(body, tuple):
            kern, cpu = body
            tb.body(kern, device="tpu")
            tb.body(cpu)
        else:
            tb.body(body)
    return p.build()


def _build_dep(t: JdfTask, f: JdfFlow, dep: JdfDep, env, dmap,
               all_tasks: List[JdfTask], dtts) -> List[Any]:
    """One JDF dependency line -> IN/OUT objects (a guarded ternary
    yields two, with complementary guards)."""
    task_names = {tt.name for tt in all_tasks}
    ctor = IN if dep.direction == "in" else OUT
    dtt = None
    for key in ("type_remote", "type"):
        if key in dep.props and dep.props[key] in dtts:
            dtt = dtts[dep.props[key]]
            break

    names = t.def_names   # guards/args see ranges AND derived locals,
    #                       all read from task.locals (body overwrites
    #                       of a local are visible to output guards)

    def one(ep: JdfEndpoint, guard_expr: Optional[str]):
        guard = _compile_fn(c2py(guard_expr), names, [], env) \
            if guard_expr is not None else None
        kw = {}
        if guard is not None:
            kw["when"] = guard
        if dtt is not None:
            kw["dtt"] = dtt
        if ep.kind == "new":
            if dep.direction != "in":
                raise JdfError(f"task {t.name}: NEW only valid on inputs")
            return ctor(NEW("default"), **kw)
        if ep.kind == "null":
            return ctor(NULL_END(), **kw)
        if ep.kind == "data":
            if ep.target not in dmap:
                raise JdfError(f"task {t.name}: data {ep.target!r} "
                               f"not provided")
            expr = (f"{ep.target}(" +
                    ", ".join(c2py(a) for a in ep.args) + ")")
            return ctor(DATA(_compile_fn(expr, names, [], env)), **kw)
        # task endpoint; range args become list-returning params fns
        if ep.target not in task_names:
            raise JdfError(f"task {t.name}: unknown peer task "
                           f"{ep.target!r}")
        tgt_params = next(tt.params for tt in all_tasks
                          if tt.name == ep.target)
        items = []
        wraps = []
        for pn, arg in zip(tgt_params, ep.args):
            parts = [x.strip() for x in re.split(r"\.\.", arg)]
            if len(parts) >= 2:
                var = f"__r_{pn}"
                wraps.append((var, parts[0], parts[1],
                              parts[2] if len(parts) > 2 else None))
                items.append(f"'{pn}': {var}")
            else:
                items.append(f"'{pn}': ({c2py(arg)})")
        expr = "{" + ", ".join(items) + "}"
        fn = _compile_fn(expr, names, [], env, list_wrap=wraps or None)
        return ctor(TASK(ep.target, ep.flow, fn), **kw)

    if dep.alt is not None:
        return [one(dep.ep, dep.guard),
                one(dep.alt, f" not ({dep.guard})")]
    return [one(dep.ep, dep.guard)]
