from parsec_tpu.dsl.ptg.api import (PTG, IN, OUT, Range, TASK, DATA, NEW,
                                    NULL_END)  # noqa: F401
