"""Dynamic task discovery: the insert_task programming model.

Rebuild of the reference's DTD interface (reference:
parsec/interfaces/dtd/insert_function.{c,h} — ``parsec_dtd_insert_task``
varargs API :3488, task creation :3220, last-writer dependency inference
``set_dependencies_for_function`` :2128, tile wrappers ``parsec_dtd_tile_of``
:1285, window throttling :131-141/:604, and the RAW/WAR/WAW successor
ordering of overlap_strategies.c:138): the application inserts tasks one by
one; the runtime discovers the DAG from how tasks touch *tiles* — for each
tile it tracks the last writer and the readers since, so

    RAW  — a reader depends on the last writer,
    WAR  — a writer depends on every reader since the last writer,
    WAW  — a writer depends on the previous writer (transitively via
           its readers when there are any).

Tasks whose dependencies are already satisfied schedule immediately; the
rest wake through the dynamic-release hook as predecessors complete.
Insertion throttles on a sliding window (reference: dtd_window_size) so a
fast producer cannot flood memory with pending tasks.

TPU notes: ``device="tpu"`` insertions run through the XLA device module
exactly like PTG device bodies (reference: parsec_dtd_gpu_task_submit →
parsec_cuda_kernel_scheduler, insert_function.c:2359-2399); tiles stay
device-resident between tasks and flush home on ``data_flush_all``.
"""

from __future__ import annotations

import inspect
import itertools
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from parsec_tpu.core import scheduling
from parsec_tpu.core.task import (Flow, HookReturn, Task, TaskClass,
                                  normalize_body_outputs)
from parsec_tpu.core.taskpool import Taskpool
from parsec_tpu.data.collection import DataCollection, DataRef
from parsec_tpu.data.data import (ACCESS_READ, ACCESS_RW, ACCESS_WRITE, Data,
                                  new_data)
from parsec_tpu.utils.mca import params

params.register("dtd_window_size", 2048,
                "max in-flight DTD tasks before insert_task throttles")
params.register("dtd_threshold_size", 1024,
                "resume insertion below this many in-flight tasks")


# -- argument modes (reference: insert_function.h:60-78 flags) --------------

class _Mode:
    def __init__(self, name: str, access: int):
        self.name = name
        self.access = access

    def __repr__(self):
        return self.name


INPUT = _Mode("INPUT", ACCESS_READ)
OUTPUT = _Mode("OUTPUT", ACCESS_WRITE)
INOUT = _Mode("INOUT", ACCESS_RW)
VALUE = _Mode("VALUE", 0)        # pass-by-value scalar
SCRATCH = _Mode("SCRATCH", 0)    # per-task temporary buffer
AFFINITY = _Mode("AFFINITY", 0)  # placement hint marker (modifier)
DONT_TRACK = _Mode("DONT_TRACK", 0)  # access data without dep tracking


class DTDTile:
    """Dep-tracking state of one datum (reference: parsec_dtd_tile_t —
    last_user / last_writer tracking)."""

    __slots__ = ("data", "last_writer", "readers")

    def __init__(self, data: Data):
        self.data = data
        self.last_writer: Optional["_DTDState"] = None
        self.readers: List["_DTDState"] = []


class _DTDState:
    """Runtime dep bookkeeping of one inserted task."""

    __slots__ = ("task", "remaining", "successors", "done", "affinity")

    def __init__(self, task: Task):
        self.task = task
        self.remaining = 0
        self.successors: List["_DTDState"] = []
        self.done = False
        self.affinity = None


_seq = itertools.count()


class DTDTaskpool(Taskpool):
    """Taskpool populated by ``insert_task`` calls
    (reference: parsec_dtd_taskpool_new, insert_function.c:1412)."""

    def __init__(self, name: str = "dtd"):
        super().__init__(name=name)
        self._dep_lock = threading.Lock()
        self._tiles: Dict[Any, DTDTile] = {}
        self._classes: Dict[Any, TaskClass] = {}
        self._inflight = 0
        self._window = threading.Condition(self._dep_lock)
        self._finished = False
        self.window_size = params.get("dtd_window_size", 2048)
        self.threshold = params.get("dtd_threshold_size", 1024)

    # -- lifecycle ---------------------------------------------------------
    def attach(self, context, termdet) -> None:
        super().attach(context, termdet)
        # hold the pool open until wait(): counters transiting 0 between
        # insertions must not terminate it (reference: DTD pools keep a
        # runtime action until parsec_dtd_taskpool_wait)
        termdet.taskpool_addto_runtime_actions(self, 1)

    def wait(self, timeout: Optional[float] = None) -> None:
        """Drain: all inserted tasks complete
        (reference: parsec_dtd_taskpool_wait, insert_function.c:691).
        Raises the first task error instead of hanging on a failed DAG."""
        if self.context is None:
            raise RuntimeError("taskpool not attached to a context")
        self.context.start()
        if not self._finished:
            self._finished = True
            self.termdet.taskpool_addto_runtime_actions(self, -1)
        import time
        deadline = None if timeout is None else time.monotonic() + timeout
        while not self.wait_local(0.1):
            self._raise_context_error()
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"{self} wait timed out")

    def _raise_context_error(self) -> None:
        errs = getattr(self.context, "_errors", None)
        if errs:
            exc, task = errs[0]
            raise RuntimeError(f"task {task} failed") from exc

    # -- tiles -------------------------------------------------------------
    def tile_of(self, dc: DataCollection, *indices) -> DTDTile:
        """Wrap a collection datum for dep tracking
        (reference: parsec_dtd_tile_of)."""
        key = (id(dc), dc.data_key(*indices))
        with self._dep_lock:
            t = self._tiles.get(key)
            if t is None:
                t = DTDTile(dc.data_of(*indices))
                self._tiles[key] = t
            return t

    def tile_new(self, shape: Tuple[int, ...], dtype: Any = np.float32,
                 key: Any = None) -> DTDTile:
        """A fresh unowned tile (reference: parsec_dtd_tile_new)."""
        datum = new_data(np.zeros(shape, dtype), key=key)
        t = DTDTile(datum)
        with self._dep_lock:
            self._tiles[("new", id(datum))] = t
        return t

    def data_flush_all(self) -> None:
        """Push every tracked tile home to its host copy
        (reference: parsec_dtd_data_flush_all)."""
        with self._dep_lock:
            tiles = list(self._tiles.values())
        for t in tiles:
            t.data.pull_to_host()

    # -- task classes ------------------------------------------------------
    def _class_for(self, fn: Callable, modes: Tuple[_Mode, ...],
                   device: str) -> TaskClass:
        # Closure-free functions dedupe by code object, so the common
        # "insert a fresh lambda per iteration" pattern reuses one class
        # (and one jitted kernel) instead of registering one per insert.
        if getattr(fn, "__closure__", True) is None:
            key = (fn.__code__, fn.__defaults__, modes, device)
        else:
            key = (fn, modes, device)
        tc = self._classes.get(key)
        if tc is not None:
            return tc
        fn_names = [p.name for p in inspect.signature(fn).parameters.values()]
        # AFFINITY args are markers, not function parameters: they do not
        # consume a name from the signature
        names: List[Optional[str]] = []
        cursor = 0
        for mode in modes:
            if mode is AFFINITY:
                names.append(None)
            else:
                names.append(fn_names[cursor] if cursor < len(fn_names)
                             else f"arg{cursor}")
                cursor += 1
        flows = []
        for i, mode in enumerate(modes):
            if mode in (INPUT, OUTPUT, INOUT, DONT_TRACK, SCRATCH):
                # SCRATCH/DONT_TRACK read-class: a scratch temp is not an
                # output flow (it would join the body's return contract
                # and get donated on device); in-place writes to it are
                # fine, its datum is throwaway
                access = mode.access if mode in (INPUT, OUTPUT, INOUT) \
                    else ACCESS_READ
                flows.append(Flow(names[i], access))
        writable = [f.name for f in flows if f.access & ACCESS_WRITE]
        bound = [n for n in names if n is not None]   # fn's actual args
        incarnations = []
        if device in ("tpu", "xla", "gpu"):
            incarnations.append((device, self._device_hook(fn, bound, flows,
                                                           writable)))
        incarnations.append(("cpu", self._cpu_hook(fn, bound, writable)))
        tc = TaskClass(fn.__name__ if hasattr(fn, "__name__") else "dtd_task",
                       params=[("tid", None)], flows=flows,
                       incarnations=incarnations)
        tc.dtd_names = names   # cached: insert_task must not re-inspect
        self.add_task_class_dynamic(tc)
        self._classes[key] = tc
        return tc

    def add_task_class_dynamic(self, tc: TaskClass) -> None:
        # DTD classes may share a name (same fn, different modes): key by id
        tc.task_class_id = len(self.task_classes)
        tc.taskpool = self
        self.task_classes[f"{tc.name}#{tc.task_class_id}"] = tc

    def _cpu_hook(self, fn: Callable, names: List[str],
                  writable: List[str]):
        def hook(es, task):
            args = []
            for i, n in enumerate(names):
                if n in task.data:
                    copy = task.data[n]
                    args.append(None if copy is None else copy.payload)
                elif n in task.locals:
                    args.append(task.locals[n])
            ret = fn(*args)
            if ret is None or isinstance(ret, HookReturn):
                return ret
            if not writable:
                return None
            outs = normalize_body_outputs(ret, writable, what=str(task))
            for fname, value in outs.items():
                copy = task.data.get(fname)
                if copy is None:
                    continue
                if isinstance(copy.payload, np.ndarray):
                    np.copyto(copy.payload, np.asarray(value))
                else:
                    copy.payload = value
            return None
        return hook

    def _device_hook(self, fn: Callable, names: List[str], flows, writable):
        from parsec_tpu.devices.xla import XlaKernel
        spec = XlaKernel(fn, names, [f.name for f in flows], writable)

        def hook(es, task):
            reg = getattr(es.context, "device_registry", None)
            dev = reg.best_device(task) if reg is not None else None
            if dev is None:
                return HookReturn.NEXT
            return dev.submit(es, task, spec)
        return hook

    # -- insertion ---------------------------------------------------------
    def insert_task(self, fn: Callable, *args, priority: int = 0,
                    device: str = "cpu") -> Task:
        """Insert one task; each arg is ``(value_or_tile, MODE)``
        (reference: parsec_dtd_insert_task, insert_function.c:3488).

        Tiles may be DTDTile, DataRef (``A(m, n)``), or Data.  VALUE args
        pass through; SCRATCH allocates a fresh buffer of the given shape.
        """
        if self.context is None:
            raise RuntimeError(
                "attach the DTD pool to a context before inserting")
        modes = tuple(m for _, m in args)
        tc = self._class_for(fn, modes, device)
        names = tc.dtd_names

        task = Task(tc, self, {"tid": next(_seq)})
        task.priority = priority
        state = _DTDState(task)
        task.dtd = state

        with self._window:
            # hysteresis: once the window fills, block until drained below
            # the threshold (reference: dtd_window_size/threshold,
            # insert_function.h:131-141)
            if self._inflight >= self.window_size:
                while self._inflight >= self.threshold:
                    self._raise_context_error()
                    self._window.wait(0.1)

        # parse/validate args FIRST: raising after the nb_tasks increment
        # would leave the count high forever and hang wait() (ADVICE r1)
        tracked: List[Tuple[DTDTile, _Mode]] = []
        for i, (value, mode) in enumerate(args):
            name = names[i]
            if mode is VALUE:
                task.locals[name] = value
            elif mode is AFFINITY:
                state.affinity = value   # placement hint (rank / tile)
            elif mode is SCRATCH:
                shape = value if isinstance(value, tuple) else (int(value),)
                datum = new_data(np.zeros(shape, np.float32))
                task.data[name] = datum.copy_on(0)
            elif mode in (INPUT, OUTPUT, INOUT, DONT_TRACK):
                tile = self._as_tile(value)
                task.data[name] = tile.data.copy_on(0)
                if mode is not DONT_TRACK:
                    tracked.append((tile, mode))
            else:
                raise TypeError(f"unsupported arg mode {mode!r}")

        self.termdet.taskpool_addto_nb_tasks(self, 1)
        with self._dep_lock:
            self._inflight += 1
            for tile, mode in tracked:
                self._track(state, tile, mode)
            # read under the lock: once released, a completing predecessor
            # may drive remaining to 0 and schedule the task itself —
            # checking outside would double-schedule
            ready_now = state.remaining == 0
        if ready_now:
            scheduling.schedule(self.context.streams[0], [task])
        return task

    def _as_tile(self, value) -> DTDTile:
        if isinstance(value, DTDTile):
            return value
        if isinstance(value, DataRef):
            return self.tile_of(value.dc, *value.indices)
        if isinstance(value, Data):
            key = ("data", id(value))
            with self._dep_lock:
                t = self._tiles.get(key)
                if t is None:
                    t = DTDTile(value)
                    self._tiles[key] = t
                return t
        raise TypeError(f"cannot interpret {value!r} as a tile")

    def _track(self, state: _DTDState, tile: DTDTile, mode: _Mode) -> None:
        """Register RAW/WAR/WAW edges against the tile's history (caller
        holds _dep_lock; reference: set_dependencies_for_function +
        parsec_dtd_ordering_correctly)."""
        def depend_on(pred: _DTDState):
            if pred is state or pred.done:
                return
            pred.successors.append(state)
            state.remaining += 1

        if mode is INPUT:
            if tile.last_writer is not None:
                depend_on(tile.last_writer)        # RAW
            tile.readers.append(state)
        else:  # OUTPUT / INOUT: this task becomes the tile's writer
            for r in tile.readers:                 # WAR
                depend_on(r)
            if tile.last_writer is not None:       # WAW (+ RAW for INOUT)
                depend_on(tile.last_writer)
            tile.last_writer = state
            tile.readers = []

    # -- dynamic release (called from engine.release_deps) ----------------
    def dynamic_release(self, es, task: Task) -> List[Task]:
        state = task.dtd
        if not isinstance(state, _DTDState):
            return []
        grapher = self.context.grapher if self.context else None
        ready: List[Task] = []
        with self._window:
            state.done = True
            self._inflight -= 1
            for succ in state.successors:
                if grapher is not None:
                    grapher.edge(task, succ.task.key, "dtd")
                succ.remaining -= 1
                if succ.remaining == 0:
                    ready.append(succ.task)
            if self._inflight < self.threshold:
                self._window.notify_all()
        return ready
