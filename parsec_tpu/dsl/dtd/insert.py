"""Dynamic task discovery: the insert_task programming model.

Rebuild of the reference's DTD interface (reference:
parsec/interfaces/dtd/insert_function.{c,h} — ``parsec_dtd_insert_task``
varargs API :3488, task creation :3220, last-writer dependency inference
``set_dependencies_for_function`` :2128, tile wrappers ``parsec_dtd_tile_of``
:1285, window throttling :131-141/:604, and the RAW/WAR/WAW successor
ordering of overlap_strategies.c:138): the application inserts tasks one by
one; the runtime discovers the DAG from how tasks touch *tiles* — for each
tile it tracks the last writer and the readers since, so

    RAW  — a reader depends on the last writer,
    WAR  — a writer depends on every reader since the last writer,
    WAW  — a writer depends on the previous writer (transitively via
           its readers when there are any).

Tasks whose dependencies are already satisfied schedule immediately; the
rest wake through the dynamic-release hook as predecessors complete.
Insertion throttles on a sliding window (reference: dtd_window_size) so a
fast producer cannot flood memory with pending tasks.

TPU notes: ``device="tpu"`` insertions run through the XLA device module
exactly like PTG device bodies (reference: parsec_dtd_gpu_task_submit →
parsec_cuda_kernel_scheduler, insert_function.c:2359-2399); tiles stay
device-resident between tasks and flush home on ``data_flush_all``.
"""

from __future__ import annotations

import inspect
import itertools
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from parsec_tpu.core import scheduling
from parsec_tpu.core.errors import PeerFailedError
from parsec_tpu.core.task import (Flow, HookReturn, Task, TaskClass,
                                  normalize_body_outputs)
from parsec_tpu.core.taskpool import Taskpool
from parsec_tpu.data.collection import DataCollection, DataRef
from parsec_tpu.data.data import (ACCESS_READ, ACCESS_RW, ACCESS_WRITE,
                                  Coherency, Data, new_data)
from parsec_tpu.utils.mca import params
from parsec_tpu.utils.output import warning


def _chain_val(arr) -> Optional[float]:
    """First element of a payload as a plain float — the 'chain value'
    the dtd_lane trace events carry so an ordering race's stale read is
    visible in the merged timeline."""
    try:
        a = np.asarray(arr)
        return float(a.flat[0]) if a.size else None
    except (TypeError, ValueError):
        return None


def _apply_payload(datum: Data, arr: np.ndarray,
                   slices: Optional[tuple] = None) -> None:
    """Land a network payload as the datum's new authoritative host
    value (the coherency transition lives in Data.overwrite_host).
    ``slices`` applies a region-lane payload into its sub-tile extent
    only (reference: per-region datatypes on the wire,
    insert_function.h:60-78) — a read-modify-write so concurrent
    disjoint-lane values survive."""
    if slices is None:
        datum.overwrite_host(arr)
        return
    copy = datum.pull_to_host()
    cur = np.array(copy.payload, copy=True)
    cur[tuple(slices)] = arr
    datum.overwrite_host(cur)

params.register("dtd_window_size", 2048,
                "max in-flight DTD tasks before insert_task throttles")
params.register("dtd_threshold_size", 1024,
                "resume insertion below this many in-flight tasks")


# -- argument modes (reference: insert_function.h:60-78 flags) --------------

class _Mode:
    def __init__(self, name: str, access: int, base: "_Mode" = None,
                 flags: frozenset = frozenset(), region: Any = None):
        self.name = name
        self.access = access
        self.base = base or self
        self.flags = flags
        self.region = region

    def __or__(self, other):
        """Compose with a modifier, mirroring the reference's OR'd flag
        words: ``INOUT | PUSHOUT``, ``INPUT | REGION_L``
        (reference: insert_function.h:60-78 PUSHOUT/PULLIN + region
        masks)."""
        if isinstance(other, _Flag):
            return _Mode(f"{self.name}|{other.name}", self.access,
                         base=self.base, flags=self.flags | {other.name},
                         region=self.region)
        if isinstance(other, Region):
            return _Mode(f"{self.name}|R({other.rid})", self.access,
                         base=self.base, flags=self.flags,
                         region=other)
        return NotImplemented

    def __repr__(self):
        return self.name


class _Flag:
    """Data-movement modifier OR'd onto an access mode."""

    def __init__(self, name: str):
        self.name = name

    def __repr__(self):
        return self.name


class Region:
    """Partial-tile dependency lane (reference: the region masks of
    insert_function.h — e.g. upper/lower/diagonal sub-tile regions).
    Accesses to DISTINCT regions of one tile do not conflict; a
    region-free access conflicts with every lane.

    ``slices`` (a tuple of python slices, e.g. ``(slice(0, 8),)`` for
    the tile's top half) declares the lane's byte extent.  With an
    extent, a remote lane write ships only the lane's sub-array and the
    receiver applies it read-modify-write, so concurrent writers of
    disjoint lanes on different ranks cannot
    clobber each other (the reference's per-region MPI datatypes).
    Ordering-only regions (no slices) also work across ranks: the lane
    id + version keep per-lane ORDERING on the wire, but each payload
    ships whole-tile (there is no extent to cut), so lanes of one tile
    written concurrently on DIFFERENT ranks merge at tile granularity —
    declare ``slices`` when byte-exact disjoint-lane merging matters
    (the reference's region masks always carry an MPI datatype,
    insert_function.h:60-78, which is exactly this extent)."""

    def __init__(self, rid: Any, slices: Optional[tuple] = None):
        self.rid = rid
        self.slices = tuple(slices) if slices is not None else None


INPUT = _Mode("INPUT", ACCESS_READ)
OUTPUT = _Mode("OUTPUT", ACCESS_WRITE)
INOUT = _Mode("INOUT", ACCESS_RW)
VALUE = _Mode("VALUE", 0)        # pass-by-value scalar
SCRATCH = _Mode("SCRATCH", 0)    # per-task temporary buffer
AFFINITY = _Mode("AFFINITY", 0)  # placement hint marker (modifier)
DONT_TRACK = _Mode("DONT_TRACK", 0)  # access data without dep tracking

#: force the produced tile home (host-authoritative) at task completion
#: instead of staying device/producer-resident until a flush
PUSHOUT = _Flag("PUSHOUT")
#: eager-fetch hint: the executing site pulls inputs at stage-in anyway
#: (always-correct on-demand movement), so PULLIN is accepted for API
#: parity and is satisfied by construction
PULLIN = _Flag("PULLIN")


def _norm(args):
    """Normalize each (value, mode) arg to (value, base_mode, flags,
    region): composed modes (``INOUT | PUSHOUT | Region(...)``) reduce to
    their base identity for the mode checks below."""
    out = []
    for value, mode in args:
        if not isinstance(mode, _Mode):
            raise TypeError(f"unsupported arg mode {mode!r}")
        out.append((value, mode.base, mode.flags, mode.region))
    return out


class DTDTile:
    """Dep-tracking state of one datum (reference: parsec_dtd_tile_t —
    last_user / last_writer tracking; ``version`` counts writers in the
    insertion stream, identically on every rank; ``wire_key`` names the
    tile on the wire)."""

    __slots__ = ("data", "last_writer", "readers", "home_rank", "version",
                 "wire_key", "v0_sent", "lanes", "applied_ver")

    def __init__(self, data: Data, home_rank: int = 0, wire_key: Any = None):
        self.data = data
        self.last_writer: Optional["_DTDState"] = None
        self.readers: List["_DTDState"] = []
        self.home_rank = home_rank
        self.version = 0
        self.wire_key = wire_key
        #: ranks already sent the pristine (version-0) home payload
        self.v0_sent: set = set()
        #: region dependency lanes (reference: region masks) — created
        #: lazily on the first region-flagged access; None = the tile is
        #: tracked whole (the fast default path)
        self.lanes: Optional[Dict[Any, "_Lane"]] = None
        #: highest WHOLE-COVERING version that actually LANDED on the
        #: datum — whole-tile or extent-less-lane payload applies, and
        #: completed local writes.  Distinct from ``version`` (bumped at
        #: INSERT time): whole-covering applies on disjoint lanes take
        #: no mutual dep edges, so an older payload (the v0 pristine
        #: pull, a delayed extent-less lane frame) can physically arrive
        #: after a newer value landed — and must not clobber it (the r6
        #: region-lane stale-read race, reproduced with
        #: ``delay_frame=tag:DTD,pm='ver': 0``)
        self.applied_ver = 0


class _Lane:
    """Per-region dependency history of one tile.  ``version`` is the
    tile-version of the lane's last write — what names that write's
    payload on the wire (distributed lanes)."""

    __slots__ = ("last_writer", "readers", "version")

    def __init__(self, last_writer=None, readers=None, version: int = 0):
        self.last_writer = last_writer
        self.readers: List["_DTDState"] = readers if readers is not None \
            else []
        self.version = version


class _DTDState:
    """Runtime dep bookkeeping of one inserted task.

    ``is_recv`` marks a *delivery surrogate*: the local stand-in for one
    (tile, version) produced by a task on another rank (reference: remote
    writers tracked as fake tasks, insert_function.c:3014-3163).  A
    surrogate joins the dep graph like a writer, but is only counted and
    scheduled once a local consumer *needs* that version; its body applies
    the network payload to the tile datum."""

    __slots__ = ("task", "remaining", "successors", "done", "affinity",
                 "rank", "is_recv", "needed", "tile", "version", "payload",
                 "remote_sends", "pushout", "region", "local_writes",
                 "insert_pos")

    def __init__(self, task: Optional[Task], rank: int = 0):
        self.task = task
        self.remaining = 0
        self.successors: List["_DTDState"] = []
        self.done = False
        self.affinity = None
        self.rank = rank
        self.is_recv = False
        self.needed = False
        self.pushout: List["DTDTile"] = []
        self.tile: Optional[DTDTile] = None
        self.version = 0
        self.payload: Optional[np.ndarray] = None
        #: region-lane id of a surrogate's write (None = whole tile):
        #: selects the slice extent its payload applies into
        self.region: Any = None
        #: (dst_rank, tile, version, lane) payloads to ship at completion
        self.remote_sends: set = set()
        #: (tile, version, lane) writes this task performs locally —
        #: dynamic_release advances each tile's applied_ver from them
        #: once the body has actually run
        self.local_writes: List[Tuple["DTDTile", int, Any]] = []
        #: SPMD insert-stream position (only stamped with the recovery
        #: lineage plane armed) — the unit of the cross-rank skip
        #: agreement: dynamic_release records it completed, and a
        #: restart's tid-gated replay filter skips agreed positions
        self.insert_pos: Optional[int] = None


_seq = itertools.count()


class DTDTaskpool(Taskpool):
    """Taskpool populated by ``insert_task`` calls
    (reference: parsec_dtd_taskpool_new, insert_function.c:1412)."""

    def __init__(self, name: str = "dtd"):
        super().__init__(name=name)
        self._dep_lock = threading.Lock()
        self._tiles: Dict[Any, DTDTile] = {}   # guarded-by: _dep_lock, _window
        #: guarded-by: _dep_lock, _window
        self._tiles_by_wire: Dict[Any, DTDTile] = {}
        #: region-lane byte extents, rid -> tuple of slices (populated
        #: identically on every rank by the SPMD insert stream — the
        #: wire carries only the rid)
        self._region_slices: Dict[Any, tuple] = {}
        #: tiles already warned about concurrent extent-less lane
        #: writers on different ranks (one warning per tile)
        self._extless_warned: set = set()
        #: serializes payload read-modify-write spans: two unordered
        #: disjoint-lane appliers interleaving pull/overwrite would lose
        #: one lane's bytes (whole-tile overwrite restores stale data)
        self._apply_lock = threading.Lock()
        self._dc_ids: Dict[int, int] = {}
        self._classes: Dict[Any, TaskClass] = {}
        self._inflight = 0                  # guarded-by: _dep_lock, _window
        self._window = threading.Condition(self._dep_lock)
        self._finished = False
        self.window_size = params.get("dtd_window_size", 2048)
        self.threshold = params.get("dtd_threshold_size", 1024)
        # distributed state (single-rank pools never touch it)
        self.myrank = 0
        self.nranks = 1
        self._new_seq = itertools.count()
        #: (wire_key, version) -> surrogate awaiting that payload
        #: (guarded-by: _dep_lock, _window)
        self._expected: Dict[Any, _DTDState] = {}
        #: early-arrived payloads nobody expects yet
        #: (guarded-by: _dep_lock, _window)
        self._received: Dict[Any, np.ndarray] = {}
        #: inbound tile-flush payloads queued until the local pool drains
        #: (guarded-by: _dep_lock, _window)
        self._flush_queue: List[Tuple[Any, np.ndarray]] = []
        self._drained = False
        self._recv_tc: Optional[TaskClass] = None
        # -- insert-stream lineage (core/recovery.py DTD skip agreement)
        # All of it gates on the shared lineage plane: with
        # PARSEC_MCA_RECOVERY_ENABLE=0 the pool's ``_lineage`` stays
        # None and every hook below is one attribute check.
        #: SPMD insert-stream position counter — bumped on EVERY
        #: insert_task call (local and remote placements alike), so it
        #: is identical on every rank by construction
        #: (guarded-by: _dep_lock, _window)
        self._insert_pos = 0
        #: stream positions placed on THIS rank / completed here
        #: (dynamic_release records completion post-body)
        #: (guarded-by: _dep_lock, _window)
        self._pos_local: set = set()
        self._pos_done: set = set()     # guarded-by: _dep_lock, _window
        #: (pos, wire_key) per tracked write, in stream order — the
        #: per-tile write ladder the skip-agreement coordinator cuts
        #: (guarded-by: _dep_lock, _window)
        self._wlog: List[Tuple[int, Any]] = []
        #: latched reason this pool can never skip (region lanes,
        #: tile_new wire keys, insert-log overflow) — the skip report
        #: then votes full instead of planning from partial evidence
        self._skip_note: Optional[str] = None
        #: a skip replay already ran this generation: a second death
        #: takes the full replay (the replayed wlog's placement went
        #: through the translation and is not holder-designation safe)
        self._skip_done = False
        #: armed by the RecoveryCoordinator between recovery_reset and
        #: the replay: {"prefix", "holders", "seeds", "vcut", "done"} —
        #: insert_task ghost-tracks positions below the agreed prefix
        #: and the finalize installs the holder writers/seeds
        self._dtd_skip: Optional[dict] = None

    # -- lifecycle ---------------------------------------------------------
    def attach(self, context, termdet) -> None:
        super().attach(context, termdet)
        # hold the pool open until wait(): counters transiting 0 between
        # insertions must not terminate it (reference: DTD pools keep a
        # runtime action until parsec_dtd_taskpool_wait)
        termdet.taskpool_addto_runtime_actions(self, 1)
        self.myrank = context.rank
        self.nranks = context.nranks
        if self.nranks > 1 and context.comm is not None:
            context.comm.dtd_drain_backlog(self)
            # flush home AT TERMINATION (before _taskpool_terminated
            # lets the quiescence ring see this rank idle): a flush
            # sent from wait() after local termination races global
            # quiescence — the home rank's ring could converge in the
            # completion→flush window and hand the application
            # pre-flush bytes (deterministically reproduced by the
            # kill-dtd-minimal chain's 100 ms keyed bodies)
            self.on_complete(self._flush_on_complete)

    def _flush_on_complete(self, tp) -> None:
        if not self.cancelled and self._finished:
            self._flush_home()

    def recovery_reset(self) -> None:
        """Recovery restart (core/recovery.py): drop every lane/window/
        surrogate structure of the torn generation on top of the base
        dep/repo reset.  The pool's ``recovery_replay`` then re-inserts
        the lost task stream against restored tiles — re-created
        ``tile_of`` wrappers resolve their home through the translated
        owner, so a single survivor replays the whole chain locally.

        Insert-stream lineage: with the recovery lineage plane armed,
        every insert stamps its SPMD stream position
        (``_DTDState.insert_pos``), ``dynamic_release`` records the
        completed positions, and ``_wlog`` keeps the per-tile write
        ladder — the evidence of the cross-rank SKIP AGREEMENT
        (core/recovery.py ``_plan_dtd_skip``): survivors agree on the
        largest common skippable prefix consistent with every rank's
        materializable ``(tile, version)`` cut, and the replay's
        tid-gated filter ghost-tracks the skipped prefix (versions and
        ordering advance, bodies do not run) while designated HOLDER
        ranks serve the cut values in place of the skipped producers'
        deliveries.  Any rank that cannot honor the prefix votes full
        and the PR 11 mode-agreement round falls the whole gang back
        symmetrically — SPMD insert streams provably never diverge."""
        super().recovery_reset()
        if not self._finished:
            # the attach-time wait() hold was zeroed with the counters;
            # re-take it so a wait() that has not happened yet finds
            # its decrement balanced
            self.termdet.taskpool_addto_runtime_actions(self, 1)
        with self._dep_lock:
            self._tiles.clear()
            self._tiles_by_wire.clear()
            self._expected.clear()
            self._received.clear()
            self._flush_queue.clear()
            self._inflight = 0
            self._drained = False
            # insert-stream lineage restarts with the new generation
            # (the pre-kill evidence was consumed by the skip plan);
            # _skip_note/_skip_done latches survive — a structurally
            # unskippable pool stays unskippable across restarts
            self._insert_pos = 0
            self._pos_local.clear()
            self._pos_done.clear()
            self._wlog = []
            self._dtd_skip = None
            self._window.notify_all()

    def wait(self, timeout: Optional[float] = None) -> None:
        """Drain: all inserted tasks complete
        (reference: parsec_dtd_taskpool_wait, insert_function.c:691).
        Raises the first task error instead of hanging on a failed DAG."""
        if self.context is None:
            raise RuntimeError("taskpool not attached to a context")
        self.context.start()
        if not self._finished:
            self._finished = True
            self.termdet.taskpool_addto_runtime_actions(self, -1)
        import time
        deadline = None if timeout is None else time.monotonic() + timeout
        while not self.wait_local(0.1):
            self._raise_context_error()
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"{self} wait timed out")
        # a failed task also DRAINS the pool (complete_execution runs on
        # the failure path), so the loop above can exit without ever
        # polling: surface the error instead of reporting success
        self._raise_context_error()
        if self.nranks > 1 and self.context.comm is not None:
            self._flush_home()

    def _flush_home(self) -> None:
        """Send each tile whose final writer ran here back to its owner
        rank, and apply queued inbound flushes (the distributed epilogue
        of parsec_dtd_data_flush_all: every tile's home datum holds the
        final value once all ranks pass Context.wait quiescence).
        Idempotent per generation: fired from the termination callback
        (so the outgoing sends are Safra-counted BEFORE the quiescence
        ring can see this rank idle) and again from ``wait()`` as a
        safety net; the second call is a no-op."""
        outgoing: List[Tuple[DTDTile, Any, int]] = []
        with self._dep_lock:
            if self._drained:
                return
            self._drained = True
            queued, self._flush_queue = self._flush_queue, []
            for tile in self._tiles.values():
                if tile.home_rank == self.myrank:
                    continue
                if tile.lanes is None:
                    lw = tile.last_writer
                    if lw is not None and not lw.is_recv:
                        outgoing.append((tile, None, tile.version))
                else:
                    # per-lane final writers may live on DIFFERENT
                    # ranks: each rank flushes home only the lanes it
                    # wrote last, as slice payloads
                    for lrid, lane in tile.lanes.items():
                        lw = lane.last_writer
                        if lw is not None and not lw.is_recv:
                            outgoing.append((tile, lrid, lane.version))
        for wire, arr, lane, ver in queued:
            tile = self._tiles_by_wire.get(wire)
            if tile is not None:
                self._apply_flush(tile, arr, lane, ver)
        for tile, lane, ver in outgoing:
            self._dtd_send_contained(
                tile.home_rank, self._wire_msg("flush", tile, ver, lane))

    def _dtd_send_contained(self, dst: int, msg: dict) -> None:
        """DTD send with recovery-aware containment: a task body that
        spans the instant a peer is DECLARED dead completes into a
        send the dead-peer guard rejects — that failure belongs to the
        pool (and is swallowed outright when a recovery restart
        already owns this pool's fate), never to the calling worker
        thread."""
        comm = self.context.comm
        try:
            comm.dtd_send(dst, msg)
        except PeerFailedError as exc:
            comm._contain_pool(self, exc)

    def _merge_payload(self, tile: DTDTile, arr: np.ndarray,
                       slices: Optional[tuple],
                       preserve: List[tuple]) -> None:
        """The one payload-landing primitive: write ``arr`` (into
        ``slices`` if given, else whole-tile) while restoring
        ``preserve`` extents from the current value.  The whole
        read-modify-write span holds _apply_lock — two unordered
        disjoint-lane appliers interleaving pull/overwrite would
        otherwise lose one lane's bytes."""
        with self._apply_lock:
            self._merge_payload_locked(tile, arr, slices, preserve)

    def _merge_payload_locked(self, tile: DTDTile, arr: np.ndarray,
                              slices: Optional[tuple],
                              preserve: List[tuple]) -> None:
        if slices is not None:
            _apply_payload(tile.data, arr, slices)
            return
        if not preserve:
            _apply_payload(tile.data, arr)
            return
        copy = tile.data.pull_to_host()
        cur = np.array(copy.payload, copy=True)
        new = np.asarray(arr).reshape(cur.shape).copy()
        for sl in preserve:
            new[tuple(sl)] = cur[tuple(sl)]
        tile.data.overwrite_host(new)

    def _apply_flush(self, tile: DTDTile, arr: np.ndarray, lane: Any,
                     ver: int) -> None:
        """Version-aware flush application: a flush carries the sender's
        final write version for its (lane) extent, and must not clobber
        extents this rank knows to be NEWER — e.g. a whole-tile write on
        rank A flushed home after rank B's later lane write (the lane's
        own flush, or the home rank's local value, carries the newer
        bytes)."""
        self._trace_lane("flush_apply", tile.wire_key, lane, ver,
                         arr=arr)
        if lane is not None:
            l = tile.lanes.get(lane) if tile.lanes else None
            if l is not None and l.version > ver:
                return          # a newer write to this lane supersedes
            sl = self._region_slices.get(lane)
            if sl is not None:
                self._merge_payload(tile, arr, sl, [])
                return
            # extent-less lane: the payload is whole-tile — fall through
            # to the whole-tile preserve logic so a NEWER sliced lane's
            # bytes survive this older snapshot of their extent
        preserve = []
        if tile.lanes:
            for lrid, l in tile.lanes.items():
                if lrid is not None and lrid != lane and l.version > ver:
                    sl = self._region_slices.get(lrid)
                    if sl is not None:
                        preserve.append(sl)
        with self._apply_lock:
            # same WHOLE-COVERING landing-order guard as _apply_data: a
            # flush-home payload delayed past a newer landing (an
            # extent-less lane whose tile.lanes entry a later whole-tile
            # write wiped slips the supersede check above, and the
            # preserve list only protects SLICED lanes) must be dropped
            # wholesale, not merged
            if ver < tile.applied_ver:
                self._trace_lane("flush_stale", tile.wire_key, lane, ver)
                return
            tile.applied_ver = ver
            self._merge_payload_locked(tile, arr, None, preserve)

    def _raise_context_error(self) -> None:
        errs = getattr(self.context, "_errors", None)
        if errs:
            exc, task = errs[0]
            raise RuntimeError(f"task {task} failed") from exc

    def _trace_lane(self, op: str, wire, lane, ver: int,
                    arr=None) -> None:
        """Lane/surrogate observability (the causal tracer's dtd_lane
        events): every dep-tracking transition and payload application
        lands in the trace with its lane id and chain value (the
        payload's first element, extracted from ``arr`` only once the
        tracer gate passed — untraced runs pay a single None check), so
        a region-ordering race shows up as an out-of-order apply in ONE
        merged timeline instead of needing rerun roulette."""
        ctx = self.context
        if ctx is None:
            return
        tr = getattr(ctx, "_causal_tracer", None)
        fr = getattr(ctx, "_flightrec", None)
        if fr is not None and "dtd" not in fr.classes:
            fr = None   # class-gated out: no numpy work on its account
        if tr is None and fr is None:
            return
        val = _chain_val(arr) if arr is not None else None
        for sink in (tr, fr):
            if sink is not None:
                sink.dtd_event(op, wire, lane, ver, val)

    # -- tiles -------------------------------------------------------------
    def tile_of(self, dc: DataCollection, *indices) -> DTDTile:
        """Wrap a collection datum for dep tracking
        (reference: parsec_dtd_tile_of).  Non-local tiles (owned by
        another rank) get a *shadow* datum: a local buffer of the tile's
        shape that receives forwarded versions and hosts locally-placed
        writes until the flush home."""
        if self.context is None:
            # home/shadow resolution needs the pool's rank: before attach
            # it would silently classify every tile as local (myrank=0 /
            # nranks=1) and skip the surrogate protocol (ADVICE r2 low)
            raise RuntimeError(
                "attach the DTD pool to a context before tile_of")
        key = (id(dc), dc.data_key(*indices))
        # owner_of, not rank_of: after a recovery re-mapping the dead
        # rank's tiles are home on their adopting survivor
        home = dc.owner_of(*indices)
        with self._dep_lock:
            t = self._tiles.get(key)
            if t is None:
                # wire-stable collection id: first-use order is identical
                # on every rank (SPMD insertion), and distinct collections
                # sharing a name= must not collide on the wire
                dcid = self._dc_ids.get(id(dc))
                if dcid is None:
                    dcid = self._dc_ids[id(dc)] = len(self._dc_ids)
                wire = ("c", dcid, dc.data_key(*indices))
                if home == self.myrank:
                    datum = dc.data_of(*indices)
                else:
                    if not hasattr(dc, "tile_shape"):
                        raise TypeError(
                            f"{type(dc).__name__} lacks tile_shape(): "
                            "distributed DTD needs it to shape the "
                            "shadow buffer of a remote-owned tile")
                    shape = dc.tile_shape(*indices)
                    datum = new_data(
                        np.zeros(shape, getattr(dc, "dtype", np.float32)),
                        key=("shadow",) + wire)
                t = DTDTile(datum, home_rank=home, wire_key=wire)
                self._tiles[key] = t
                self._tiles_by_wire[wire] = t
            return t

    def tile_new(self, shape: Tuple[int, ...], dtype: Any = np.float32,
                 key: Any = None, home_rank: int = 0) -> DTDTile:
        """A fresh unowned tile (reference: parsec_dtd_tile_new).
        Distributed pools must call this identically on every rank (SPMD
        insertion); ``home_rank`` owns the final flushed value."""
        datum = new_data(np.zeros(shape, dtype), key=key)
        wire = ("n", next(self._new_seq))
        t = DTDTile(datum, home_rank=home_rank, wire_key=wire)
        with self._dep_lock:
            if self._lineage is not None and self._skip_note is None:
                # _new_seq is not reset across a restart, so replayed
                # tile_new wires would not match the recorded ladder —
                # this pool's skip report votes full
                self._skip_note = "tile_new wire keys are not " \
                                  "replay-stable"
            self._tiles[("new", id(datum))] = t
            self._tiles_by_wire[wire] = t
        return t

    def data_flush_all(self) -> None:
        """Push every tracked tile home to its host copy
        (reference: parsec_dtd_data_flush_all).  Pulls device copies to
        the LOCAL host; the cross-rank flush home to each tile's owner
        happens at ``wait()`` (_flush_home), once no writer can still be
        in flight — flushing a tile another rank is mid-writing would be
        a torn flush."""
        with self._dep_lock:
            tiles = list(self._tiles.values())
        for t in tiles:
            t.data.pull_to_host()

    # -- insert-stream skip agreement (core/recovery.py DTD minimal
    # replay).  Everything below gates on the recovery lineage plane
    # (``self._lineage``); disabled, none of it runs.
    def _note_insert(self, pos: int, nargs, rank: int) -> None:
        """Record one insert's write ladder + placement (lineage armed
        only): the skip-agreement coordinator cuts the per-tile write
        positions, and the frontier is the contiguous completed prefix
        of the LOCAL positions."""
        cap = self._lineage.cap
        with self._window:
            if len(self._wlog) >= cap:
                if self._skip_note is None:
                    # a truncated ladder cannot prove a cut sound
                    self._skip_note = "insert log overflow"
                return
            for value, mode, _f, _r in nargs:
                if mode in (OUTPUT, INOUT):
                    self._wlog.append((pos, self._as_tile_locked(value)))
            if rank == self.myrank:
                self._pos_local.add(pos)

    def _as_tile_locked(self, value) -> Any:
        """wire_key of a tile value with _dep_lock already held (the
        _window condition shares the lock, so _as_tile/tile_of would
        self-deadlock)."""
        if isinstance(value, DTDTile):
            return value.wire_key
        if isinstance(value, DataRef):
            key = (id(value.dc), value.dc.data_key(*value.indices))
            t = self._tiles.get(key)
            if t is not None:
                return t.wire_key
            dcid = self._dc_ids.get(id(value.dc))
            if dcid is None:
                dcid = self._dc_ids[id(value.dc)] = len(self._dc_ids)
            return ("c", dcid, value.dc.data_key(*value.indices))
        if isinstance(value, Data):
            return ("d", id(value))
        raise TypeError(f"cannot interpret {value!r} as a tile")

    def _ghost_insert(self, nargs) -> None:
        """Dep-tracking-only replay of one agreed-skippable insert: its
        writes advance tile versions through DONE pass-through
        surrogates (ordering numbering stays identical to the original
        stream on every rank) and nothing is counted, scheduled, or
        executed — the values of the skipped prefix are served by the
        designated holder ranks (``_dtd_skip_finalize_locked``)."""
        writes = [(self._as_tile(value),
                   region.rid if region is not None else None)
                  for value, mode, _f, region in nargs
                  if mode in (OUTPUT, INOUT)]
        with self._dep_lock:
            for tile, rid in writes:
                self._surrogate_write(tile, rid)

    def dtd_arm_skip(self, prefix: int, holders: Dict[Any, int],
                     seeds: Dict[Any, np.ndarray],
                     vcut: Dict[Any, int]) -> None:
        """Arm the tid-gated replay filter (RecoveryCoordinator, after
        ``recovery_reset`` and before the replay callable runs)."""
        with self._dep_lock:
            self._dtd_skip = {"prefix": int(prefix),
                              "holders": dict(holders),
                              "seeds": dict(seeds),
                              "vcut": dict(vcut), "done": False}

    def _dtd_skip_finalize_locked(self) -> None:  # holds-lock: _dep_lock
        """Ghost prefix fully tracked: on each tile's designated HOLDER
        rank, replace the last ghost surrogate with a completed LOCAL
        writer over the seeded cut payload — local consumers read the
        datum directly, and the SPMD processing of a remote consumer's
        insert triggers the payload send exactly like a completed
        normal producer (``_insert_remote``'s ``lw.done`` path)."""
        sk = self._dtd_skip
        if sk is None or sk["done"]:
            return
        sk["done"] = True
        me = self.myrank
        for wire, holder in sk["holders"].items():
            tile = self._tiles_by_wire.get(wire)
            if tile is None:
                continue   # the replay stream never touched it
            vcut = sk["vcut"].get(wire, tile.version)
            if holder != me:
                # non-holders keep the done ghost surrogate; their
                # consumers revive it (_mark_needed) and the holder's
                # payload lands through the ordinary recv chain
                continue
            seed = sk["seeds"].get(wire)
            if seed is not None:
                tile.data.overwrite_host(np.asarray(seed))
            d = _DTDState(None, rank=me)
            d.done = True
            d.tile = tile
            d.version = vcut
            tile.last_writer = d
            tile.readers = []
            if tile.lanes:
                tile.lanes = {None: _Lane(d, version=vcut)}
            with self._apply_lock:
                if vcut > tile.applied_ver:
                    # the seeded bytes ARE the cut landing: an older
                    # stale payload must not clobber them
                    tile.applied_ver = vcut

    def dtd_skip_finish(self) -> None:
        """Replay stream done (RecoveryCoordinator): finalize (covers
        the all-skipped stream, where no post-prefix insert triggered
        it — the holder writers must still exist so ``_flush_home``
        ships the cut values home) and disarm.  A later death of this
        generation takes the full replay: the replayed ladder's
        placement went through the rank translation and is no longer
        holder-designation evidence."""
        with self._dep_lock:
            self._dtd_skip_finalize_locked()
            self._dtd_skip = None
            self._skip_done = True

    def dtd_skip_report(self) -> Dict[str, Any]:
        """This survivor's half of the skip agreement, computed AFTER
        the run_epoch fence and in-flight drain (the numbers are
        stable): either ``{"full": reason}`` — this rank votes full —
        or the insert-stream completion frontier plus the per-tile
        landed versions the coordinator cuts against.

        ``frontier`` = the largest K such that every LOCAL position
        < K completed; ``landed[wire]`` = the whole-covering version
        whose bytes this rank's datum actually holds
        (``DTDTile.applied_ver``) — the materializable cut evidence."""
        lin = self._lineage
        if lin is None or lin.overflow:
            return {"full": "evicted ring"}
        if self._skip_note is not None:
            return {"full": self._skip_note}
        if self._skip_done:
            return {"full": "skip already replayed this generation"}
        with self._window:
            frontier = self._insert_pos
            for p in sorted(self._pos_local):
                if p not in self._pos_done:
                    frontier = p
                    break
            landed = {t.wire_key: t.applied_ver
                      for t in self._tiles.values()}
            wlog = list(self._wlog)
        return {"frontier": frontier, "landed": landed, "writes": wlog}

    def dtd_capture_seeds(self, wires) -> Dict[Any, np.ndarray]:
        """Host copies of the agreed cut values this rank holds —
        captured BEFORE recovery_reset discards the shadow datums (an
        adopted tile's cut bytes may live only in the old shadow).
        Raises KeyError/ValueError-free: an unpullable payload returns
        a partial map and the caller falls back."""
        out: Dict[Any, np.ndarray] = {}
        with self._window:
            tiles = {w: self._tiles_by_wire.get(w) for w in wires}
        for wire, tile in tiles.items():
            if tile is None:
                continue
            copy = tile.data.pull_to_host()
            if copy is None or copy.payload is None:
                continue
            out[wire] = np.array(copy.payload, copy=True)
        return out

    def dtd_taint_stale(self, state: "_DTDState",
                        failed: bool = False) -> None:
        """Epoch-fence discard of a stale-generation body that RAN
        (core/scheduling.complete_execution): its in-place writes are
        LANDED bytes the skip report must see — advance applied_ver so
        the landed map can never claim an older version over mutated
        payloads (the DTD twin of the r13 stale-body version taint).
        A body that FAILED may have mutated its tiles PARTWAY: those
        bytes match no version at all, so the pool latches unskippable
        instead of claiming the write landed.

        The position is completion evidence too: the landed map must
        never run AHEAD of the frontier.  A body straddling the fence
        (claimed pre-restart, completed post-fence) that advanced
        applied_ver without recording its position would leave NO rank
        holding the frontier's cut bytes — the agreement would cut
        prefix 0 and force a full replay on a fully-completed write."""
        if failed:
            if state.local_writes and self._skip_note is None:
                self._skip_note = "stale body failed mid-write"
            return
        self._advance_applied(state.local_writes)
        if self._lineage is not None and state.insert_pos is not None:
            with self._window:
                self._pos_done.add(state.insert_pos)

    def _advance_applied(self, local_writes) -> None:
        """A completed body's WHOLE-COVERING writes are LANDED values:
        advance each tile's applied_ver monotonically (sliced region
        lanes stay out — their extent never names the whole tile).
        One helper for both landing sites (dynamic_release and the
        stale-discard taint) so the landing-order guard and the
        skip-agreement landed map can never diverge."""
        for wtile, wver, wrid in local_writes:
            if wrid is None or wrid not in self._region_slices:
                with self._apply_lock:
                    if wver > wtile.applied_ver:
                        wtile.applied_ver = wver

    # -- task classes ------------------------------------------------------
    def _class_for(self, fn: Callable, modes: Tuple[_Mode, ...],
                   device: str) -> TaskClass:
        # Closure-free functions dedupe by code object, so the common
        # "insert a fresh lambda per iteration" pattern reuses one class
        # (and one jitted kernel) instead of registering one per insert.
        if getattr(fn, "__closure__", True) is None:
            key = (fn.__code__, fn.__defaults__, modes, device)
        else:
            key = (fn, modes, device)
        tc = self._classes.get(key)
        if tc is not None:
            return tc
        fn_names = [p.name for p in inspect.signature(fn).parameters.values()]
        # AFFINITY args are markers, not function parameters: they do not
        # consume a name from the signature
        names: List[Optional[str]] = []
        cursor = 0
        for mode in modes:
            if mode is AFFINITY:
                names.append(None)
            else:
                names.append(fn_names[cursor] if cursor < len(fn_names)
                             else f"arg{cursor}")
                cursor += 1
        flows = []
        for i, mode in enumerate(modes):
            if mode in (INPUT, OUTPUT, INOUT, DONT_TRACK, SCRATCH):
                # SCRATCH/DONT_TRACK read-class: a scratch temp is not an
                # output flow (it would join the body's return contract
                # and get donated on device); in-place writes to it are
                # fine, its datum is throwaway
                access = mode.access if mode in (INPUT, OUTPUT, INOUT) \
                    else ACCESS_READ
                flows.append(Flow(names[i], access))
        writable = [f.name for f in flows if f.access & ACCESS_WRITE]
        bound = [n for n in names if n is not None]   # fn's actual args
        incarnations = []
        if device in ("tpu", "xla", "gpu"):
            incarnations.append((device, self._device_hook(fn, bound, flows,
                                                           writable)))
        incarnations.append(("cpu", self._cpu_hook(fn, bound, writable)))
        tc = TaskClass(fn.__name__ if hasattr(fn, "__name__") else "dtd_task",
                       params=[("tid", None)], flows=flows,
                       incarnations=incarnations)
        tc.dtd_names = names   # cached: insert_task must not re-inspect
        self.add_task_class_dynamic(tc)
        self._classes[key] = tc
        return tc

    def add_task_class_dynamic(self, tc: TaskClass) -> None:
        # DTD classes may share a name (same fn, different modes): key by id
        tc.task_class_id = len(self.task_classes)
        tc.taskpool = self
        self.task_classes[f"{tc.name}#{tc.task_class_id}"] = tc

    def create_task_class(self, name: str, arg_names: Sequence[str],
                          modes: Sequence[_Mode]) -> "DTDTaskClass":
        """Explicit task-class API (reference:
        parsec_dtd_create_task_classv, insert_function.c:2539 area):
        declare the argument layout once, attach one chore per device
        type with :meth:`DTDTaskClass.add_chore`, then pass the class to
        :meth:`insert_task` in place of a function.  One logical task
        can carry CPU and TPU chores; the runtime picks per execution
        (the incarnation iteration of scheduling.execute)."""
        if len(arg_names) != len(modes):
            raise ValueError("one name per argument mode")
        return DTDTaskClass(name, list(arg_names),
                            [m.base for m in modes])


    def _cpu_hook(self, fn: Callable, names: List[str],
                  writable: List[str]):
        def hook(es, task):
            args = []
            for i, n in enumerate(names):
                if n in task.data:
                    copy = task.data[n]
                    args.append(None if copy is None else copy.payload)
                elif n in task.locals:
                    args.append(task.locals[n])
            ret = fn(*args)
            if ret is None or isinstance(ret, HookReturn):
                return ret
            if not writable:
                return None
            outs = normalize_body_outputs(ret, writable, what=str(task))
            for fname, value in outs.items():
                copy = task.data.get(fname)
                if copy is None:
                    continue
                if isinstance(copy.payload, np.ndarray):
                    np.copyto(copy.payload, np.asarray(value))
                else:
                    copy.payload = value
            return None
        return hook

    def _device_hook(self, fn: Callable, names: List[str], flows, writable):
        from parsec_tpu.devices.xla import XlaKernel
        spec = XlaKernel(fn, names, [f.name for f in flows], writable)

        def hook(es, task):
            reg = getattr(es.context, "device_registry", None)
            if reg is None:
                return HookReturn.NEXT
            dev = None
            # an AFFINITY tile with a pinned device drives placement
            # (reference: data-affinity first, parsec_get_best_device)
            aff = getattr(task.dtd, "affinity", None) \
                if task.dtd is not None else None
            if aff is not None and not isinstance(aff, (int, np.integer)):
                try:
                    pref = self._as_tile(aff).data.preferred_device
                except TypeError:
                    pref = None
                if pref is not None and 1 <= pref < len(reg.devices) \
                        and reg.devices[pref].enabled:
                    dev = reg.devices[pref]
            if dev is None:
                dev = reg.best_device(task)
            if dev is None:
                return HookReturn.NEXT
            return dev.submit(es, task, spec)
        return hook

    # -- insertion ---------------------------------------------------------
    def insert_task(self, fn: Callable, *args, priority: int = 0,
                    device: str = "cpu") -> Optional[Task]:
        """Insert one task; each arg is ``(value_or_tile, MODE)``
        (reference: parsec_dtd_insert_task, insert_function.c:3488).

        Tiles may be DTDTile, DataRef (``A(m, n)``), or Data.  VALUE args
        pass through; SCRATCH allocates a fresh buffer of the given shape.

        Distributed pools insert SPMD: every rank calls insert_task with
        the same stream of tasks; each task executes on ONE rank — the
        AFFINITY arg's rank (an int, or a tile whose owner rank is used),
        else the owner of its first written tile (owner computes).  Other
        ranks track the task as a remote writer/reader only (reference:
        insert_function.c:3014-3163 fake remote tasks).  Insertion must
        come from a single thread per rank (the reference's main-thread
        model).  Returns None for tasks placed on other ranks.
        """
        if self.context is None:
            raise RuntimeError(
                "attach the DTD pool to a context before inserting")
        nargs = _norm(args)
        lin = self._lineage
        pos = None
        if lin is not None:
            # SPMD stream position: every rank's counter advances on
            # every insert call, so positions name the same logical
            # task cluster-wide (the skip-agreement unit)
            with self._window:
                pos = self._insert_pos
                self._insert_pos += 1
        for *_x, r in nargs:
            if r is not None and r.slices is not None:
                self._region_slices[r.rid] = r.slices
            if r is not None and lin is not None \
                    and self._skip_note is None:
                # region lanes track sub-tile writers whose landing
                # versions applied_ver cannot name — unskippable
                self._skip_note = "region lanes"
        args = [(v, b) for v, b, _f, _r in nargs]
        rank = self._task_rank(args) if self.nranks > 1 else self.myrank
        if lin is not None:
            sk = self._dtd_skip
            if sk is not None and pos < sk["prefix"]:
                # agreed-skippable prefix: ghost-track the write
                # ordering (versions advance, no body runs, no counts)
                self._ghost_insert(nargs)
                return None
            if sk is not None:
                # first post-prefix insert: install the holder writers
                # and seed the cut payloads BEFORE this insert tracks
                with self._dep_lock:
                    self._dtd_skip_finalize_locked()
            self._note_insert(pos, nargs, rank)
        if rank != self.myrank:
            self._insert_remote(nargs, rank)
            return None
        if isinstance(fn, DTDTaskClass):
            tc = fn.materialize(self)
            fn.validate_modes(tuple(b for _v, b in args))
        else:
            modes = tuple(m for _, m in args)
            tc = self._class_for(fn, modes, device)
        names = tc.dtd_names

        task = Task(tc, self, {"tid": next(_seq)})
        task.priority = priority
        state = _DTDState(task, rank=self.myrank)
        state.insert_pos = pos
        task.dtd = state

        with self._window:
            # hysteresis: once the window fills, block until drained below
            # the threshold (reference: dtd_window_size/threshold,
            # insert_function.h:131-141)
            if self._inflight >= self.window_size:
                while self._inflight >= self.threshold:
                    self._raise_context_error()
                    self._window.wait(0.1)

        # parse/validate args FIRST: raising after the nb_tasks increment
        # would leave the count high forever and hang wait() (ADVICE r1)
        tracked: List[Tuple[DTDTile, _Mode, Any]] = []
        for i, (value, mode, flags, region) in enumerate(nargs):
            name = names[i]
            if mode is VALUE:
                task.locals[name] = value
            elif mode is AFFINITY:
                state.affinity = value   # placement hint (rank / tile)
            elif mode is SCRATCH:
                shape = value if isinstance(value, tuple) else (int(value),)
                datum = new_data(np.zeros(shape, np.float32))
                task.data[name] = datum.copy_on(0)
            elif mode in (INPUT, OUTPUT, INOUT, DONT_TRACK):
                tile = self._as_tile(value)
                task.data[name] = tile.data.copy_on(0)
                if mode is not DONT_TRACK:
                    tracked.append((tile, mode,
                                    region.rid if region is not None
                                    else None))
                if "PUSHOUT" in flags and mode is not INPUT:
                    # force the result home at completion instead of
                    # staying producer/device-resident until a flush
                    # (reference: PARSEC_PUSHOUT)
                    state.pushout.append(tile)
            else:
                raise TypeError(f"unsupported arg mode {mode!r}")

        self.termdet.taskpool_addto_nb_tasks(self, 1)
        to_schedule: List[Task] = []
        with self._dep_lock:
            self._inflight += 1
            for tile, mode, region in tracked:
                self._track(state, tile, mode, to_schedule, region=region)
            # read under the lock: once released, a completing predecessor
            # may drive remaining to 0 and schedule the task itself —
            # checking outside would double-schedule
            if state.remaining == 0:
                to_schedule.append(task)
        if to_schedule:
            scheduling.schedule(self.context.streams[0], to_schedule)
        return task

    # -- distributed placement & remote tracking ---------------------------
    def _task_rank(self, args) -> int:
        """Execution rank of a task: AFFINITY wins (int rank or tile
        owner), else the owner of the first written tile, else the first
        read tile, else 0 — identical on every rank by construction.
        Routed through the pool's recovery translation so re-inserted
        work lands on the dead rank's adopter (tile home_ranks already
        resolve through the collection's owner_of at tile_of time)."""
        first = None
        rank = None
        for value, mode in args:
            if mode is AFFINITY:
                rank = int(value) if isinstance(value, (int, np.integer)) \
                    else self._as_tile(value).home_rank
                break
        if rank is None:
            for value, mode in args:
                if mode in (OUTPUT, INOUT):
                    rank = self._as_tile(value).home_rank
                    break
                if first is None and mode is INPUT:
                    first = self._as_tile(value)
        if rank is None:
            rank = first.home_rank if first is not None else 0
        t = getattr(self, "rank_translation", None)
        return t.get(rank, rank) if t else rank

    def _conflict_lanes(self, tile: DTDTile,
                        rid: Any) -> List[Tuple[Any, _Lane]]:
        """(lane rid, lane) pairs an access to ``rid`` conflicts with
        (caller holds _dep_lock; tile.lanes must exist): its own lane
        plus the whole-tile lane, or EVERY lane for a whole-tile
        access."""
        lanes = tile.lanes
        if rid is not None and rid not in lanes:
            lanes[rid] = _Lane()
        lanes.setdefault(None, _Lane())
        return [(rid, lanes[rid]), (None, lanes[None])] \
            if rid is not None else list(lanes.items())

    def _insert_remote(self, nargs, rank: int) -> None:
        """Track a task that executes on another rank: its reads of
        locally-produced versions trigger payload sends; its writes insert
        delivery surrogates so later local consumers chain correctly.
        Region-lane accesses conflict laneswise, and a lane write's
        payload is named (tile, version) with its lane rid riding along
        so the receiver applies only the lane's extent."""
        reads: List[Tuple[DTDTile, Any]] = []
        writes: List[Tuple[DTDTile, Any]] = []
        for value, mode, _f, region in nargs:
            if mode in (INPUT, OUTPUT, INOUT):
                tile = self._as_tile(value)
                rid = region.rid if region is not None else None
                if mode in (INPUT, INOUT):
                    reads.append((tile, rid))
                if mode in (OUTPUT, INOUT):
                    writes.append((tile, rid))
        sends: List[Tuple[int, DTDTile, int, Any]] = []
        with self._dep_lock:
            for tile, rid in reads:
                if tile.lanes is None and rid is None:
                    lws = [(tile.last_writer, None, tile.version)]
                    v0_needed = tile.last_writer is None
                else:
                    if tile.lanes is None:
                        tile.lanes = {None: _Lane(tile.last_writer,
                                                  list(tile.readers),
                                                  tile.version)}
                    lws = [(lane.last_writer, lrid, lane.version)
                           for lrid, lane in self._conflict_lanes(tile,
                                                                  rid)]
                    # mirrors _track_region's v0 rule EXACTLY (the SPMD
                    # streams keep lane states consistent, so sender and
                    # receiver reach the same verdict): the NONE lane
                    # writerless, and a lane-scoped read's own lane too
                    lanes = tile.lanes
                    v0_needed = lanes[None].last_writer is None \
                        and (rid is None
                             or lanes[rid].last_writer is None)
                if v0_needed and tile.home_rank == self.myrank \
                        and rank != self.myrank \
                        and rank not in tile.v0_sent:
                    # pristine home value: the owner forwards version 0
                    tile.v0_sent.add(rank)
                    sends.append((rank, tile, 0, None))
                for lw, lrid, lver in lws:
                    if lw is None or lw.is_recv or lw.rank != self.myrank:
                        continue   # a surrogate's rank serves its payload
                    key = (rank, tile, lver, lrid)
                    if key not in lw.remote_sends:
                        # recorded either way so N readers on one rank
                        # cost ONE payload on the wire
                        lw.remote_sends.add(key)
                        if lw.done:
                            sends.append(key)
            for tile, rid in writes:
                self._surrogate_write(tile, rid)
        for dst, tile, ver, lane in sends:
            self._send_payload(dst, tile, ver, lane)

    def _surrogate_write(self, tile: DTDTile, rid: Any = None) -> None:
        """Advance the tile's version past a remote write, leaving a
        delivery surrogate as (lane) last writer (caller holds _dep_lock).

        The WAW edge chains through EVERY surrogate — including unneeded
        ones — so WAR edges from still-pending readers of older versions
        survive skipped versions (the reference chains every fake remote
        writer, insert_function.c:3014-3163; ADVICE r2 high).  A
        surrogate whose ordering obligations are already met completes in
        place (``done``) instead of dangling; _edge then skips it."""
        tile.version += 1
        d = _DTDState(None, rank=self.myrank)
        d.is_recv = True
        d.tile = tile
        d.version = tile.version
        d.region = rid
        if tile.lanes is None and rid is None:
            for r in tile.readers:   # WAR: local readers finish first
                self._edge(r, d)
            lw = tile.last_writer    # WAW: order in-place datum writes
            if lw is not None:
                self._edge(lw, d)
            if d.remaining == 0:
                d.done = True        # no pending obligations: pass-through
            tile.last_writer = d
            tile.readers = []
            self._trace_lane("surrogate", tile.wire_key, None,
                             tile.version)
            return
        if tile.lanes is None:
            tile.lanes = {None: _Lane(tile.last_writer,
                                      list(tile.readers), tile.version)}
        lanes = tile.lanes
        self._warn_extentless_overlap(tile, rid, writer_is_recv=True)
        for _lrid, lane in self._conflict_lanes(tile, rid):
            for r in lane.readers:                     # WAR
                self._edge(r, d)
            if lane.last_writer is not None:           # WAW
                self._edge(lane.last_writer, d)
        if d.remaining == 0:
            d.done = True
        if rid is None:
            tile.lanes = {None: _Lane(d, version=tile.version)}
        else:
            lanes[rid].last_writer = d
            lanes[rid].readers = []
            lanes[rid].version = tile.version
        tile.last_writer = d
        tile.readers = []
        self._trace_lane("surrogate", tile.wire_key, rid, tile.version)

    @staticmethod
    def _edge(pred: "_DTDState", succ: "_DTDState") -> None:
        if pred is succ or pred.done:
            return
        pred.successors.append(succ)
        succ.remaining += 1

    def _mark_needed(self, d: "_DTDState",   # holds-lock: _dep_lock
                     to_schedule: List[Task]) -> None:
        """First local consumer of a surrogate's version: make it a real
        (counted, schedulable) task expecting the network payload (caller
        holds _dep_lock).

        A surrogate that completed IN PLACE (unneeded pass-through whose
        ordering obligations were already met — it is necessarily still
        the tile's last writer, with no successors) is revived here: its
        only remaining job is applying the payload before the new
        consumer runs."""
        if d.needed:
            return
        d.done = False               # revive a pass-through completion
        d.needed = True
        self._trace_lane("need", d.tile.wire_key, d.region, d.version)
        task = Task(self._recv_class(), self, {"tid": next(_seq)})
        task.dtd = d
        d.task = task
        key = (d.tile.wire_key, d.version)
        arr = self._received.pop(key, None)
        if arr is not None:
            d.payload = arr
        else:
            d.remaining += 1         # the payload arrival is a dependency
            self._expected[key] = d
        self.termdet.taskpool_addto_nb_tasks(self, 1)
        self._inflight += 1
        if d.remaining == 0:
            to_schedule.append(task)

    def _apply_data(self, tile: DTDTile, arr: np.ndarray, lane: Any,
                    ver: int) -> None:
        """Apply an in-run network payload.  A lane payload writes only
        its slice extent.  A whole-tile payload must not clobber extents
        of lanes with NEWER versions whose newest writer is a surrogate:
        that lane's bytes arrive via its own recv chain, which is
        UNORDERED relative to this one (disjoint lanes take no mutual
        edges) — preserving makes both arrival orders converge.  A
        newer lane whose newest writer is a LOCAL task is ordered after
        this recv (it conflicts transitively), so its extent still wants
        this payload's bytes and is NOT preserved."""
        self._trace_lane("apply", tile.wire_key, lane, ver,
                         arr=arr)
        if lane is not None:
            sl = self._region_slices.get(lane)
            if sl is not None:
                self._merge_payload(tile, arr, sl, [])
                return
            # extent-less lane payload = whole tile: preserve newer
            # sliced lanes below, exactly as a whole-tile payload would
        preserve = []
        with self._dep_lock:       # lanes mutate under the pool dep lock
            if tile.lanes:
                for lrid, l in tile.lanes.items():
                    if lrid is None or lrid == lane or l.version <= ver:
                        continue
                    lw = l.last_writer
                    # preserve the lane when its newer bytes arrive via
                    # an UNORDERED channel: a surrogate's own recv chain,
                    # or — for the version-0 pristine pull, which takes
                    # no edges on pre-existing lane writers — any write
                    # at all (a local one may have already landed)
                    if (lw is not None and lw.is_recv) or ver == 0:
                        sl = self._region_slices.get(lrid)
                        if sl is not None:
                            preserve.append(sl)
        with self._apply_lock:
            # WHOLE-COVERING landing order guard (the r6 region-lane
            # stale-read race): disjoint-lane appliers take no mutual
            # dep edges, and extent-less lanes have no byte extent the
            # preserve list could protect — so an apply that lost the
            # race to a NEWER whole-covering landing (payload apply or
            # completed local write) must be dropped wholesale, not
            # merged.  Checked and advanced atomically under the same
            # lock the merge holds: two racing appliers serialize here
            # and the older one sees the newer one's version.
            if ver < tile.applied_ver:
                self._trace_lane("apply_stale", tile.wire_key, lane, ver)
                return
            tile.applied_ver = ver
            self._merge_payload_locked(tile, arr, None, preserve)

    def _recv_class(self) -> TaskClass:
        if self._recv_tc is None:
            def _recv_hook(es, task):
                st = task.dtd
                if st.payload is not None:
                    self._apply_data(st.tile, st.payload, st.region,
                                     st.version)
                    st.payload = None
                return None
            tc = TaskClass("_dtd_recv", params=[("tid", None)], flows=[],
                           incarnations=[("cpu", _recv_hook)])
            self.add_task_class_dynamic(tc)
            self._recv_tc = tc
        return self._recv_tc

    def _wire_msg(self, kind: str, tile: DTDTile, ver: int,
                  lane: Any = None) -> dict:
        """Encode a tile payload message (pulls the tile home first).

        A region-lane write ships ONLY the lane's slice extent (the
        reference's per-region datatypes, insert_function.h:60-78); the
        lane rid rides the message and the receiver applies the payload
        into the same extent read-modify-write.

        Payloads over the eager limit travel by RENDEZVOUS: a snapshot
        registers as a serve-once region and only its handle rides the
        message; the consumer pulls via the CE's one-sided get
        (reference: the eager/rendezvous split of the remote-dep
        protocol applied to DTD traffic)."""
        from parsec_tpu.comm.engine import CommEngine
        copy = tile.data.pull_to_host()
        arr = np.asarray(copy.payload)
        base = {"tp": self.taskpool_id, "kind": kind,
                "tile": tile.wire_key, "ver": ver}
        if lane is not None:
            # extent-less (ordering-only) lanes ship the WHOLE tile with
            # the lane id + version riding for receiver-side ordering
            # (reference regions always carry a datatype,
            # insert_function.h:60-78; without one, whole-tile is the
            # only correct granularity)
            sl = self._region_slices.get(lane)
            if sl is not None:
                arr = np.ascontiguousarray(arr[tuple(sl)])
            base["lane"] = lane
        self._trace_lane("encode", tile.wire_key, lane, ver,
                         arr=arr)
        eager = int(params.get("comm_eager_limit", 65536))
        comm = self.context.comm if self.context is not None else None
        if comm is not None and arr.nbytes > eager:
            # snapshot: the datum may be rewritten by later local
            # writers before the consumer pulls
            rid = comm.ce.mem_register(arr.copy(), once=True)
            return {**base, "ref": rid, "from": self.myrank}
        return {**base, **CommEngine.pack(arr)}

    def _send_payload(self, dst: int, tile: DTDTile, ver: int,
                      lane: Any = None) -> None:
        self._dtd_send_contained(dst, self._wire_msg("data", tile, ver,
                                                     lane))

    def _dtd_incoming(self, src: int, msg: dict) -> None:
        """Comm-thread entry for DTD payload/flush messages."""
        from parsec_tpu.comm.engine import CommEngine
        if "ref" in msg:
            # rendezvous: pull the registered snapshot from the producer
            # (the pending-pull count was taken atomically with the
            # message credit in RemoteDepEngine._dtd_cb)
            comm = self.context.comm

            def on_data(arr, msg=msg, comm=comm):
                try:
                    if arr is None:
                        self.context.record_error(RuntimeError(
                            f"DTD rendezvous pull of {msg['tile']} "
                            f"v{msg['ver']} from rank {msg['from']} "
                            "failed"), None)
                        return
                    self._dtd_payload(msg, arr)
                finally:
                    comm.dtd_ref_done((msg.get("tp"),
                                       msg.get("pe", 0)))

            comm.ce.get(msg["from"], msg["ref"], on_data)
            return
        self._dtd_payload(msg, CommEngine.unpack(msg))

    def _dtd_payload(self, msg: dict, arr: np.ndarray) -> None:
        wire = tuple(msg["tile"])
        self._trace_lane("payload", wire, msg.get("lane"), msg["ver"],
                         arr=arr)
        if msg["kind"] == "data":
            key = (wire, msg["ver"])
            to_schedule: List[Task] = []
            with self._dep_lock:
                d = self._expected.pop(key, None)
                if d is None:
                    self._received[key] = arr
                else:
                    d.payload = arr
                    d.remaining -= 1
                    if d.remaining == 0:
                        to_schedule.append(d.task)
            if to_schedule:
                scheduling.schedule(self.context.streams[0], to_schedule)
        elif msg["kind"] == "flush":
            lane = msg.get("lane")
            with self._dep_lock:
                if not self._drained:
                    self._flush_queue.append((wire, arr, lane,
                                              msg["ver"]))
                    return
                tile = self._tiles_by_wire.get(wire)
            if tile is not None:
                self._apply_flush(tile, arr, lane, msg["ver"])

    def _as_tile(self, value) -> DTDTile:
        if isinstance(value, DTDTile):
            return value
        if isinstance(value, DataRef):
            return self.tile_of(value.dc, *value.indices)
        if isinstance(value, Data):
            key = ("data", id(value))
            with self._dep_lock:
                t = self._tiles.get(key)
                if t is None:
                    if self._lineage is not None \
                            and self._skip_note is None:
                        # id()-based wire keys are neither rank- nor
                        # replay-stable: a skip plan over them would
                        # exchange meaningless landed evidence — vote
                        # full up front (the tile_new latch's twin)
                        self._skip_note = "raw Data wire keys are " \
                                          "not replay-stable"
                    # raw Data has no owner rank: local-only tile
                    t = DTDTile(value, home_rank=self.myrank,
                                wire_key=("d", id(value)))
                    self._tiles[key] = t
                return t
        raise TypeError(f"cannot interpret {value!r} as a tile")

    def _track(self, state: _DTDState, tile: DTDTile, mode: _Mode,
               to_schedule: List[Task], region: Any = None) -> None:
        """Register RAW/WAR/WAW edges against the tile's history (caller
        holds _dep_lock; reference: set_dependencies_for_function +
        parsec_dtd_ordering_correctly).  Versions produced on other ranks
        appear as delivery surrogates; consuming one marks it needed.

        ``region`` selects a partial-tile dependency lane (reference:
        the region masks of insert_function.h): distinct regions of one
        tile do not conflict; a region-free access conflicts with every
        lane."""
        if region is not None or tile.lanes is not None:
            self._track_region(state, tile, mode, region, to_schedule)
            return
        me = self.myrank
        lw = tile.last_writer
        if mode is INPUT:
            if lw is None and tile.home_rank != me and self.nranks > 1:
                # pristine remote-home value: pull version 0
                d = _DTDState(None, rank=me)
                d.is_recv, d.tile, d.version = True, tile, 0
                tile.last_writer = lw = d
            if lw is not None:
                if lw.is_recv:
                    # revives an in-place-completed (pass-through)
                    # surrogate; a needed one that already ran is kept
                    self._mark_needed(lw, to_schedule)
                self._edge(lw, state)              # RAW
            tile.readers.append(state)
            self._trace_lane("read", tile.wire_key, None, tile.version)
        else:  # OUTPUT / INOUT: this task becomes the tile's writer
            for r in tile.readers:                 # WAR
                self._edge(r, state)
            if lw is None and mode is INOUT and tile.home_rank != me \
                    and self.nranks > 1:
                d = _DTDState(None, rank=me)
                d.is_recv, d.tile, d.version = True, tile, 0
                tile.last_writer = lw = d
            if lw is not None:                     # WAW (+ RAW for INOUT)
                if lw.is_recv and mode is INOUT:
                    # INOUT reads the surrogate's version: needs payload
                    self._mark_needed(lw, to_schedule)
                # chain WAW through every writer, surrogates included —
                # _edge skips only a DONE one, whose ordering obligations
                # (WAR from pending readers, earlier WAW) are all met
                # (ADVICE r2 high)
                self._edge(lw, state)
            tile.version += 1
            state.version = tile.version
            state.local_writes.append((tile, tile.version, None))
            tile.last_writer = state
            tile.readers = []
            self._trace_lane("write", tile.wire_key, None, tile.version)

    def _warn_extentless_overlap(self, tile: DTDTile, rid: Any,
                                 writer_is_recv: bool) -> None:
        """Extent-less lanes merge across ranks at WHOLE-TILE granularity
        (no byte extent to cut), so two such lanes of one tile with
        concurrent writers on different ranks can lose one lane's
        update.  Make that LOUD at insert time — the r4 guard's
        diagnostic value without banning the legal serialized patterns
        (caller holds _dep_lock)."""
        if self.nranks <= 1 or rid is None or rid in self._region_slices:
            return
        for lrid, lane in (tile.lanes or {}).items():
            if lrid is None or lrid == rid \
                    or lrid in self._region_slices:
                continue
            lw = lane.last_writer
            if lw is None or lw.done or lw.is_recv == writer_is_recv:
                continue
            if tile.wire_key in self._extless_warned:
                return
            self._extless_warned.add(tile.wire_key)
            warning(
                "tile %s: extent-less region lanes %r and %r have "
                "concurrent writers on different ranks; payloads ship "
                "whole-tile, so one lane's bytes may be lost — declare "
                "Region(..., slices=...) for byte-exact disjoint "
                "merging", tile.wire_key, rid, lrid)
            return

    def _track_region(self, state: _DTDState, tile: DTDTile, mode: _Mode,
                      rid: Any, to_schedule: List[Task]) -> None:
        """Region-lane dependency tracking.  The first region-flagged
        access migrates the tile's whole-tile history into the ``None``
        lane; thereafter a region access conflicts with its own lane
        plus the whole-tile lane, and a whole-tile access conflicts with
        every lane.  Versions produced on other ranks appear as lane
        surrogates (same machinery as whole-tile distributed tracking);
        consuming one marks it needed and its payload applies into the
        lane's slice extent only."""
        me = self.myrank
        if tile.lanes is None:
            tile.lanes = {None: _Lane(tile.last_writer,
                                      list(tile.readers), tile.version)}
        lanes = tile.lanes
        conflict = self._conflict_lanes(tile, rid)
        if mode is INPUT or mode is INOUT:
            # pristine remote-home tile: materialize the v0 pull in the
            # whole-tile lane (mirrors _track's surrogate-on-demand).
            # Keyed on the NONE lane being writerless — another lane
            # having a writer must not suppress it, or a whole-tile read
            # after a lone OUTPUT lane write would read uninitialized
            # extents; the v0 apply preserves every written lane's bytes
            if self.nranks > 1 and tile.home_rank != me \
                    and lanes[None].last_writer is None \
                    and (rid is None
                         or lanes[rid].last_writer is None):
                d = _DTDState(None, rank=me)
                d.is_recv, d.tile, d.version = True, tile, 0
                lanes[None].last_writer = d
        mine = lanes[rid] if rid is not None else None
        if mode is INPUT:
            for _lrid, lane in conflict:
                lw = lane.last_writer
                if lw is not None:
                    if lw.is_recv:
                        self._mark_needed(lw, to_schedule)
                    self._edge(lw, state)                      # RAW
            (mine if mine is not None else lanes[None]).readers.append(
                state)
            self._trace_lane("read", tile.wire_key, rid, tile.version)
        else:
            self._warn_extentless_overlap(tile, rid, writer_is_recv=False)
            for _lrid, lane in conflict:
                for r in lane.readers:                         # WAR
                    self._edge(r, state)
                lw = lane.last_writer
                if lw is not None:                             # WAW
                    if lw.is_recv and mode is INOUT:
                        # INOUT reads the surrogate's version
                        self._mark_needed(lw, to_schedule)
                    self._edge(lw, state)
            tile.version += 1
            state.version = tile.version
            state.region = rid
            state.local_writes.append((tile, tile.version, rid))
            if rid is None:
                # whole-tile write supersedes every lane's history
                tile.lanes = {None: _Lane(state, version=tile.version)}
            else:
                mine.last_writer = state
                mine.readers = []
                mine.version = tile.version
            # keep the legacy fields coherent for flush/debug paths
            tile.last_writer = state
            tile.readers = []
            self._trace_lane("write", tile.wire_key, rid, tile.version)

    # -- dynamic release (called from engine.release_deps) ----------------
    def dynamic_release(self, es, task: Task) -> List[Task]:
        state = task.dtd
        if not isinstance(state, _DTDState):
            return []
        # the body has run: its whole-covering writes are LANDED values
        # now — advance each tile's applied_ver so an older whole-
        # covering payload racing in from an unordered lane cannot
        # clobber them (see _apply_data's landing-order guard)
        self._advance_applied(state.local_writes)
        for tile in state.pushout:
            # PUSHOUT: force the produced version home now (reference:
            # PARSEC_PUSHOUT — eager writeback instead of lazy residency)
            try:
                tile.data.pull_to_host()
                if tile.data.collection is not None:
                    tile.data.collection.refresh_backing(tile.data)
            except Exception as exc:
                self.context.record_error(exc, task)
        grapher = self.context.grapher if self.context else None
        ready: List[Task] = []
        outgoing: List[Tuple[int, dict]] = []
        # Encode payloads outside the pool lock — a 64MB D2H pull under
        # _dep_lock would stall the insertion and comm threads — but
        # BEFORE marking the task done: a later writer inserted while we
        # encode still takes an edge on us (done tasks are skipped by
        # _edge) and cannot run until the successor decrements below, so
        # the datum is stable.  Readers inserted mid-encode append to
        # remote_sends, hence the delta loop (reference: delayed dep
        # release + per-peer sends, remote_dep_mpi.c:519).
        encoded: set = set()
        while True:
            with self._window:
                delta = [e for e in state.remote_sends if e not in encoded]
                if not delta:
                    state.done = True
                    self._inflight -= 1
                    break
            for dst, tile, ver, lane in sorted(
                    delta, key=lambda e: (e[0], e[2])):
                outgoing.append((dst, self._wire_msg("data", tile, ver,
                                                     lane)))
                encoded.add((dst, tile, ver, lane))
        with self._window:
            if self._lineage is not None and state.insert_pos is not None:
                # insert-stream completion evidence: the skip report's
                # frontier is the contiguous prefix of these positions
                self._pos_done.add(state.insert_pos)
            # worklist: an unneeded surrogate whose last obligation clears
            # completes IN PLACE (no task to run) and propagates to its
            # own successors immediately — the ordering chain through
            # skipped versions stays intact (ADVICE r2 high)
            pending = [(state, s) for s in state.successors]
            while pending:
                pred, succ = pending.pop()
                if grapher is not None and succ.task is not None \
                        and pred.task is not None:
                    # cascaded edges (pred = an in-place-completed
                    # surrogate, task None) are not drawn: attributing
                    # them to the outer task would fabricate DAG edges
                    grapher.edge(pred.task, succ.task.key, "dtd")
                succ.remaining -= 1
                if succ.remaining != 0:
                    continue
                if succ.is_recv and not succ.needed:
                    succ.done = True
                    pending.extend((succ, s) for s in succ.successors)
                elif succ.task is not None:
                    ready.append(succ.task)
            if self._inflight < self.threshold:
                self._window.notify_all()
        for dst, msg in outgoing:
            self._dtd_send_contained(dst, msg)
        return ready


class DTDTaskClass:
    """User-declared DTD task class with explicit per-device chores
    (reference: parsec_dtd_create_task_classv + parsec_dtd_add_chore)."""

    def __init__(self, name: str, arg_names: List[str],
                 modes: List[_Mode]):
        self.name = name
        self.arg_names = arg_names
        self.modes = modes
        self.chores: List[Tuple[str, Callable]] = []
        self._tc: Optional[TaskClass] = None

    def add_chore(self, device: str, fn: Callable) -> "DTDTaskClass":
        if self._tc is not None:
            raise RuntimeError("add_chore after the class was first "
                               "inserted (chore table is frozen)")
        self.chores.append((device, fn))
        return self

    def validate_modes(self, modes: Tuple[_Mode, ...]) -> None:
        if tuple(modes) != tuple(self.modes):
            raise TypeError(
                f"task class {self.name!r}: insert arg modes {modes} do "
                f"not match the declared {tuple(self.modes)}")

    def materialize(self, pool: DTDTaskpool) -> TaskClass:
        if self._tc is not None:
            if self._tc.taskpool is not pool:
                raise RuntimeError(
                    f"task class {self.name!r} is bound to another pool")
            return self._tc
        if not self.chores:
            raise RuntimeError(f"task class {self.name!r} has no chores")
        names: List[Optional[str]] = [
            None if mode is AFFINITY else self.arg_names[i]
            for i, mode in enumerate(self.modes)]
        flows = []
        for i, mode in enumerate(self.modes):
            if mode in (INPUT, OUTPUT, INOUT, DONT_TRACK, SCRATCH):
                access = mode.access if mode in (INPUT, OUTPUT, INOUT) \
                    else ACCESS_READ
                flows.append(Flow(names[i], access))
        writable = [f.name for f in flows if f.access & ACCESS_WRITE]
        bound = [n for n in names if n is not None]
        incarnations = []
        for device, fn in self.chores:
            if device in ("tpu", "xla", "gpu"):
                incarnations.append(
                    (device, pool._device_hook(fn, bound, flows, writable)))
            else:
                incarnations.append(
                    ("cpu", pool._cpu_hook(fn, bound, writable)))
        tc = TaskClass(self.name, params=[("tid", None)], flows=flows,
                       incarnations=incarnations)
        tc.dtd_names = names
        pool.add_task_class_dynamic(tc)
        self._tc = tc
        return tc
