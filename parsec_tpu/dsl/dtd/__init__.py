"""DTD front-end: dynamic task discovery (insert_task).

reference: parsec/interfaces/dtd/ — see insert.py in this package.
"""

from parsec_tpu.dsl.dtd.insert import (AFFINITY, DONT_TRACK, INOUT,  # noqa: F401
                                       INPUT, OUTPUT, PULLIN, PUSHOUT,
                                       SCRATCH, VALUE, DTDTaskClass,
                                       DTDTaskpool, DTDTile, Region)
