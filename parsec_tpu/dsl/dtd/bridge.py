"""PTG -> DTD bridge: replay a parameterized task graph through dynamic
task discovery.

Rebuild of the reference's ptg_to_dtd converter (reference:
mca/pins/ptg_to_dtd/pins_ptg_to_dtd_module.c — a PINS module that turns a
PTG taskpool into runtime ``insert_task`` calls so the DTD engine can be
validated against PTG-defined graphs).  The bridge enumerates the PTG's
instances, linearizes them in topological dep order, and inserts one DTD
task per instance:

- collection endpoints (``<- A(m, n)`` / ``-> A(m, n)``) become DTD tile
  accesses with the flow's access mode, so DTD's last-writer inference
  reproduces the PTG's RAW/WAR/WAW structure (fan-outs with a writing
  consumer serialize by WAR ordering where PTG hands out COW copies —
  same values, legal schedule);
- task-fed edges (``-> A TASK(...)``) ride the producer instance's tile
  for that flow — pure dataflow through DTD versioning;
- CTL edges become 1-element synthetic tiles written by the producer and
  read by the consumer (gathers read one per incoming edge), preserving
  control ordering;
- NEW flows allocate a synthetic tile shaped by the arena.

Limitations (enforced with clear errors): functional CPU bodies only, no
``es``/``task`` magic args, and NULL-forwarding flows are not preserved.
"""

from __future__ import annotations

import inspect
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from parsec_tpu.core.task import (FromDesc, FromTask, New, Null, Task,
                                  TaskClass, ToTask,
                                  normalize_body_outputs)
from parsec_tpu.data.data import ACCESS_NONE, ACCESS_READ, ACCESS_WRITE
from parsec_tpu.dsl.dtd.insert import (DTDTaskpool, INOUT, INPUT, OUTPUT,
                                       VALUE)


def _raw_body(tc: TaskClass):
    for dev, hook in tc.incarnations:
        fn = getattr(hook, "__ptg_fn__", None)
        if fn is not None and dev == "cpu":
            return fn
    raise TypeError(
        f"{tc.name}: the PTG->DTD bridge needs a functional CPU body "
        "(declared via TaskBuilder.body)")


def _instances(tp) -> List[Tuple[TaskClass, Dict[str, int]]]:
    return [(tc, dict(locals_))
            for tc in tp.task_classes.values()
            for locals_ in tc.iter_space(tp.globals)]


def _succ_locals(end: ToTask, loc, stc: TaskClass):
    # fill derived single-valued params: dep expressions name peers by
    # their free params only (mirrors engine.release_deps)
    return [stc.complete_locals(s) for s in end.instances(loc)]


def _src_locals(end: FromTask, loc,
                stc: TaskClass) -> List[Dict[str, int]]:
    return [stc.complete_locals(s) for s in end.instances(loc)]


def _topo_order(tp, instances):
    """Kahn topological sort over task-fed dep edges."""
    idx = {tc.make_key(loc): i for i, (tc, loc) in enumerate(instances)}
    preds = [0] * len(instances)
    succs: List[List[int]] = [[] for _ in instances]
    for i, (tc, loc) in enumerate(instances):
        for flow in tc.flows:
            for dep in flow.active_outputs(loc):
                end = dep.end
                if not isinstance(end, ToTask):
                    continue
                stc = tp.task_classes[end.task_class]
                for sloc in _succ_locals(end, loc, stc):
                    j = idx.get(stc.make_key(sloc))
                    if j is not None:
                        succs[i].append(j)
                        preds[j] += 1
    order: List[int] = []
    queue = [i for i, p in enumerate(preds) if p == 0]
    while queue:
        i = queue.pop()
        order.append(i)
        for j in succs[i]:
            preds[j] -= 1
            if preds[j] == 0:
                queue.append(j)
    if len(order) != len(instances):
        raise ValueError("PTG graph has a task-fed dependency cycle")
    return [instances[i] for i in order]


def _make_body(tc: TaskClass, data_names: List[str], n_ctl: int,
               param_names: List[str], writable: List[str]):
    """Generate a DTD body with a REAL named signature (insert_task
    binds task.data by the function's parameter names) that forwards to
    the raw PTG body and re-emits written flows as a dict."""
    fn = _raw_body(tc)
    sig = [p.name for p in inspect.signature(fn).parameters.values()]
    if "es" in sig or "task" in sig:
        raise TypeError(
            f"{tc.name}: bodies using es/task magic args cannot be "
            "bridged to DTD")
    args = (list(data_names) + [f"_ctl{i}" for i in range(n_ctl)]
            + list(param_names))
    ns: Dict[str, Any] = {"_fn": fn, "_sig": sig, "_wr": writable,
                          "_norm": normalize_body_outputs}
    src = (f"def _bridge_body({', '.join(args)}):\n"
           f"    _bound = dict({', '.join(f'{a}={a}' for a in args)})\n"
           "    _ret = _fn(**{n: _bound[n] for n in _sig if n in _bound})\n"
           "    if _ret is None or not _wr:\n"
           "        return None\n"
           "    return {k: v for k, v in _norm(_ret, _wr).items()}\n")
    exec(src, ns)
    body = ns["_bridge_body"]
    body.__name__ = f"ptg2dtd_{tc.name}"
    return body


def run_ptg_as_dtd(src_tp, dtd_tp: DTDTaskpool) -> None:
    """Insert every instance of ``src_tp`` (a built ParameterizedTaskpool)
    into ``dtd_tp`` in topological order; call ``dtd_tp.wait()`` after
    (reference: the ptg_to_dtd PINS module's runtime conversion)."""
    out_tiles: Dict[Tuple, Any] = {}
    bodies: Dict[Tuple, Any] = {}

    for tc, loc in _topo_order(src_tp, _instances(src_tp)):
        key = tc.make_key(loc)
        data_args: List[Tuple[Any, Any]] = []
        data_names: List[str] = []
        ctl_args: List[Tuple[Any, Any]] = []
        writable: List[str] = []
        for flow in tc.flows:
            is_ctl = flow.access == ACCESS_NONE
            if is_ctl:
                # consumer side: one synthetic-tile read per incoming
                # edge — CTL gathers apply several deps at once
                for dep in flow.inputs:
                    if not dep.applies(loc):
                        continue
                    end = dep.end
                    if not isinstance(end, FromTask):
                        continue
                    stc = src_tp.task_classes[end.task_class]
                    for sloc in _src_locals(end, loc, stc):
                        t = out_tiles.get((stc.make_key(sloc), end.flow))
                        if t is not None:
                            ctl_args.append((t, INPUT))
                # producer side: a fresh 1-elt tile successors will read
                if any(isinstance(d.end, ToTask)
                       for d in flow.active_outputs(loc)):
                    t = dtd_tp.tile_new((1,), key=("ctl", key, flow.name))
                    ctl_args.append((t, OUTPUT))
                    out_tiles[(key, flow.name)] = t
                continue
            dep = flow.active_input(loc)
            end = dep.end if dep is not None else None
            if isinstance(end, FromDesc):
                ref = end.ref_fn(loc)
                tile = dtd_tp.tile_of(ref.dc, *ref.indices)
            elif isinstance(end, FromTask):
                tile = None
                stc = src_tp.task_classes[end.task_class]
                for sloc in _src_locals(end, loc, stc):
                    tile = out_tiles.get((stc.make_key(sloc), end.flow))
                    if tile is not None:
                        break
                if tile is None:
                    raise ValueError(
                        f"{tc.name}{loc}: task-fed flow {flow.name} has "
                        "no recorded producer tile (unsupported pattern)")
            elif isinstance(end, New):
                arena = src_tp.arenas.get(end.arena_name)
                if arena is None:
                    raise ValueError(
                        f"bridge: unknown arena {end.arena_name!r}")
                tile = dtd_tp.tile_new(tuple(arena.shape),
                                       key=("new", key, flow.name))
            elif isinstance(end, Null) or end is None:
                raise ValueError(
                    f"{tc.name}{loc}: NULL flows are not bridgeable")
            else:
                raise TypeError(f"unsupported input endpoint {end!r}")
            if flow.access & ACCESS_WRITE:
                mode = INOUT if flow.access & ACCESS_READ else OUTPUT
                writable.append(flow.name)
            else:
                mode = INPUT
            data_args.append((tile, mode))
            data_names.append(flow.name)
            if any(isinstance(d.end, ToTask)
                   for d in flow.active_outputs(loc)):
                out_tiles[(key, flow.name)] = tile
        value_args = [(loc[p], VALUE) for p, _ in tc.params]
        bkey = (id(tc), tuple(data_names), len(ctl_args))
        body = bodies.get(bkey)
        if body is None:
            body = bodies[bkey] = _make_body(
                tc, data_names, len(ctl_args),
                [p for p, _ in tc.params], writable)
        dtd_tp.insert_task(body, *(data_args + ctl_args + value_args))