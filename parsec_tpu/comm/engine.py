"""Comm-engine: transport-neutral active messages + one-sided emulation.

Rebuild of the reference's comm-engine seam (reference:
parsec/parsec_comm_engine.h:161-183 ``parsec_comm_engine_t`` vtable — AM
tag register/send, put/get with memory handles, progress, capabilities;
the funnelled MPI module parsec_mpi_funnelled.c is its only in-tree
implementation).  ``SocketCE`` implements the vtable over localhost TCP:
one listener per rank (port base+rank), lazily-connected peer sockets,
length-prefixed pickled frames, and one receiver thread per peer
dispatching AM callbacks — the threading stands in for the reference's
dedicated comm thread; sends are multi-threaded behind per-peer locks
(capability CE_MT in the reference's terms).

On a TPU pod the same vtable would sit on DCN (host network) for control
while payloads ride ICI collectives; the socket module doubles as that
bootstrap path and as the test transport (SURVEY.md §4: the reference
tests multi-node with mpiexec on one node).
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from parsec_tpu.utils.debug_history import mark
from parsec_tpu.utils.mca import params
from parsec_tpu.utils.output import debug_verbose, warning

params.register("comm_port_base", 0,
                "TCP port base for the socket comm engine (0 = from env "
                "PARSEC_COMM_PORT_BASE or 23500)")
params.register("comm_hosts", "",
                "comma-separated per-rank host list for multi-host (DCN) "
                "runs — rank i listens on 0.0.0.0 and peers dial "
                "hosts[i]; empty = single-node loopback (also read from "
                "env PARSEC_COMM_HOSTS)")

# AM tag space (reference: parsec_comm_engine.h:29-38)
TAG_ACTIVATE = 1
TAG_GET_REQ = 2
TAG_GET_REP = 3
TAG_TERMDET = 4
TAG_BARRIER = 5
TAG_DTD = 6       # distributed DTD data/flush traffic
TAG_BATCH = 7     # aggregated same-destination messages [(tag, payload)...]
TAG_UTRIG = 8     # user-trigger termination declaration
TAG_PUT = 9       # one-sided put into a registered region
TAG_GET1 = 10     # one-sided get request
TAG_GET1_REP = 11
TAG_USER = 16     # first tag available to applications

#: frame header: (tag, pickle length, out-of-band buffer count).  Large
#: array payloads ride OUT OF BAND (pickle protocol 5): the pickle holds
#: only metadata while each buffer is scatter-gathered onto the socket
#: unserialized and received straight into its own bytearray — the
#: dataflow-bandwidth path does no full-payload serialization copy
_LEN = struct.Struct("!IQI")
_BUFLEN = struct.Struct("!Q")

#: wire-format guard (VERDICT r2: a malformed or cross-version frame
#: must fail its CONNECTION with a cause, not corrupt the recv thread):
#: connections handshake magic+version+rank; frames are bounded and
#: undecodable ones sever the peer
_HANDSHAKE = struct.Struct("!4sII")   # (magic, proto version, rank)
_WIRE_MAGIC = b"PTCE"
_WIRE_VERSION = 2   # v2: protocol-5 out-of-band buffer frames

params.register("comm_max_frame_mb", 4096,
                "largest acceptable frame payload in MiB; a length field "
                "beyond this is treated as stream corruption and severs "
                "the connection")


params.register("comm_sockbuf_mb", 4,
                "SO_SNDBUF/SO_RCVBUF request per peer socket in MiB "
                "(0 = system default).  The r5 bw breakdown measured "
                "the 8MB-payload recv at ~1.1GB/s under default-sized "
                "buffers — sender/receiver ping-pong on a small window; "
                "MB-class buffers let the kernel stream the frame")


def _bump_sockbufs(s: socket.socket) -> None:
    mb = int(params.get("comm_sockbuf_mb", 4))
    if mb <= 0:
        return
    for opt in (socket.SO_SNDBUF, socket.SO_RCVBUF):
        try:
            s.setsockopt(socket.SOL_SOCKET, opt, mb << 20)
        except OSError:
            pass    # best-effort: the kernel clamps to its limits


def wire_dtype(dtype) -> str:
    """A dtype string that round-trips over the wire.  Extension dtypes
    (ml_dtypes bfloat16 & friends) have a ``.str`` of raw void bytes —
    their NAME is the parseable spelling."""
    import numpy as _np
    dt = _np.dtype(dtype)
    s = dt.str
    try:
        if _np.dtype(s) == dt:
            return s
    except TypeError:
        pass
    return dt.name


def parse_dtype(spec: str):
    import numpy as _np
    try:
        return _np.dtype(spec)
    except TypeError:
        import ml_dtypes  # noqa: F401  (registers bfloat16 et al.)
        return _np.dtype(spec)


class CommEngine:
    """Vtable (reference: parsec_comm_engine_t — AM tag register/send,
    registered-memory one-sided put/get, pack/unpack, progress, sync,
    capability flags parsec_comm_engine.h:161-183)."""

    #: capability flags (reference: the CE capabilities the remote-dep
    #: layer queries to pick eager vs rendezvous and threading mode)
    CAP_ONESIDED = True     # put/get over registered regions
    CAP_MT = True           # sends are thread-safe

    def __init__(self, rank: int, nranks: int):
        self.rank = rank
        self.nranks = nranks
        #: registered memory regions: id -> writable numpy view
        #: (reference: memory registration handles of ce.mem_register)
        self._regions: Dict[int, Any] = {}
        self._once_regions: Dict[int, float] = {}   # rid -> registered-at
        self._region_seq = 0
        self._reg_lock = threading.Lock()
        #: completion callbacks of outstanding one-sided ops
        self._osc: Dict[int, Callable] = {}
        self._osc_seq = 0
        self._callbacks: Dict[int, Callable] = {}
        #: messages for tags nobody registered yet — replayed on register
        #: (the reference posts persistent recvs per tag at init; here a
        #: peer may send before this rank finishes wiring its handlers)
        self._undelivered: Dict[int, List] = {}
        self._cb_lock = threading.Lock()
        # message counters (engine-level stats; the remote-dep layer keeps
        # its own application-message counters for termination detection)
        self.sent_msgs = 0
        self.recv_msgs = 0
        #: set by the remote-dep layer: fatal handler errors fail the rank
        #: fast instead of silently dropping the message
        self.on_error: Optional[Callable[[Exception], None]] = None
        #: ranks whose connection died mid-run (failure detection);
        #: barrier and quiescence waiters observe this and fail fast
        self.dead_peers: set = set()

    def tag_register(self, tag: int, cb: Callable[[int, Any], None]) -> None:
        """cb(src_rank, payload) runs on the comm receive thread."""
        with self._cb_lock:
            self._callbacks[tag] = cb
            backlog = self._undelivered.pop(tag, [])
        for src, payload in backlog:
            cb(src, payload)

    def tag_unregister(self, tag: int) -> None:
        with self._cb_lock:
            self._callbacks.pop(tag, None)

    def send_am(self, tag: int, dst: int, payload: Any) -> None:
        raise NotImplementedError

    def barrier(self) -> None:
        raise NotImplementedError

    def fini(self) -> None:
        pass

    # -- pack/unpack (reference: ce.pack/unpack) ------------------------
    @staticmethod
    def pack(arr) -> dict:
        """Snapshot an array payload for the wire.  ONE owned copy here
        — the snapshot contract: the source tile may be mutated in place
        by later tasks before the comm thread serializes the frame, so
        the payload must be frozen at encode time.  The copy stays an
        ndarray and ships OUT OF BAND (pickle protocol 5 + gather-send),
        so this is the only copy on the send path (tobytes + in-band
        pickling + the join used to make three)."""
        import numpy as np
        a = np.array(np.asarray(arr), order="C", copy=True)
        return {"buf": a, "dtype": wire_dtype(a.dtype),
                "shape": a.shape}

    @staticmethod
    def unpack(msg: dict):
        import numpy as np
        buf = msg["buf"]
        if isinstance(buf, np.ndarray):
            # out-of-band delivery: the array already views the freshly
            # received (private, writable) buffer — no copy needed
            return np.asarray(buf, dtype=parse_dtype(msg["dtype"])) \
                .reshape(msg["shape"])
        return np.frombuffer(buf, dtype=parse_dtype(msg["dtype"])) \
            .reshape(msg["shape"]).copy()

    # -- registered memory + one-sided put/get (reference: ce.mem_register
    # / ce.put:793 / ce.get:896 of parsec_mpi_funnelled.c — emulated over
    # two-sided AM exactly like the reference's MPI module) --------------
    def mem_register(self, array, once: bool = False) -> int:
        """Expose a writable array to one-sided access; returns the
        region handle peers name in put/get.  ``once`` auto-unregisters
        after the first successful GET (rendezvous payloads: exactly one
        consumer pulls, then the region is gone)."""
        with self._reg_lock:
            self._region_seq += 1
            rid = self._region_seq
            self._regions[rid] = array
            if once:
                self._once_regions[rid] = time.monotonic()
        return rid

    def mem_unregister(self, rid: int) -> None:
        with self._reg_lock:
            self._regions.pop(rid, None)
            self._once_regions.pop(rid, None)

    def purge_once_regions(self, ttl: float) -> int:
        """Drop serve-once regions nobody pulled within ``ttl`` seconds
        (a consumer that died or errored out must not strand the
        producer's payload snapshot forever); returns the count purged.
        Driven by the comm-progress purge alongside the rendezvous
        handle GC."""
        now = time.monotonic()
        purged = 0
        with self._reg_lock:
            for rid, born in list(self._once_regions.items()):
                if now - born > ttl:
                    del self._once_regions[rid]
                    self._regions.pop(rid, None)
                    purged += 1
        if purged:
            warning("rank %d: dropped %d unclaimed serve-once region(s) "
                    "after %.0fs", self.rank, purged, ttl)
        return purged

    def _register_onesided(self) -> None:
        """Wire the put/get emulation tags (called by subclasses once
        transport recv is up)."""
        self.tag_register(TAG_PUT, self._put_cb)
        self.tag_register(TAG_GET1, self._get1_cb)
        self.tag_register(TAG_GET1_REP, self._get1_rep_cb)

    def put(self, dst: int, local_array, remote_rid: int,
            on_complete: Optional[Callable] = None) -> None:
        """Write ``local_array`` into peer ``dst``'s registered region;
        ``on_complete(error=None)`` runs on the comm thread once the
        remote copy landed — or failed (reference: mpi_no_thread_put)."""
        with self._reg_lock:
            self._osc_seq += 1
            op = self._osc_seq
            if on_complete is not None:
                self._osc[op] = ("put", on_complete)
        self.send_am(TAG_PUT, dst, {"rid": remote_rid, "op": op,
                                    "from": self.rank,
                                    **self.pack(local_array)})

    def get(self, dst: int, remote_rid: int,
            on_data: Callable) -> None:
        """Fetch peer ``dst``'s registered region; ``on_data(array)``
        runs on the comm thread (``None`` on failure; reference:
        mpi_no_thread_get)."""
        with self._reg_lock:
            self._osc_seq += 1
            op = self._osc_seq
            self._osc[op] = ("get", on_data)
        self.send_am(TAG_GET1, dst, {"rid": remote_rid, "op": op,
                                     "from": self.rank})

    def _osc_fail(self, dst: int, op: int, why: str) -> None:
        """An op that cannot complete still gets a terminal reply — a
        silent drop would leak the originator's callback and hang its
        waiter."""
        self.send_am(TAG_GET1_REP, dst, {"op": op, "error": why})

    def _put_cb(self, src: int, msg: dict) -> None:
        import numpy as np
        # hold the lock across the copy: concurrent put/get on one
        # region from different peer recv threads must not tear
        with self._reg_lock:
            target = self._regions.get(msg["rid"])
            if target is not None:
                tgt = np.asarray(target)
                try:
                    # zero-copy source view straight into the region
                    src_view = np.frombuffer(
                        msg["buf"],
                        dtype=parse_dtype(msg["dtype"])).reshape(tgt.shape)
                    np.copyto(tgt, src_view)
                except (TypeError, ValueError) as exc:
                    self._osc_fail(msg["from"], msg["op"], str(exc))
                    return
        if target is None:
            warning("rank %d: PUT into unknown region %s", self.rank,
                    msg["rid"])
            self._osc_fail(msg["from"], msg["op"], "unknown region")
            return
        self.send_am(TAG_GET1_REP, msg["from"],
                     {"op": msg["op"], "ack": True})

    def _get1_cb(self, src: int, msg: dict) -> None:
        with self._reg_lock:
            target = self._regions.get(msg["rid"])
            packed = self.pack(target) if target is not None else None
            if packed is not None and msg["rid"] in self._once_regions:
                del self._once_regions[msg["rid"]]
                del self._regions[msg["rid"]]
        if packed is None:
            warning("rank %d: GET of unknown region %s", self.rank,
                    msg["rid"])
            self._osc_fail(msg["from"], msg["op"], "unknown region")
            return
        self.send_am(TAG_GET1_REP, msg["from"],
                     {"op": msg["op"], **packed})

    def _get1_rep_cb(self, src: int, msg: dict) -> None:
        with self._reg_lock:
            ent = self._osc.pop(msg["op"], None)
        if ent is None:
            return
        kind, cb = ent
        err = msg.get("error")
        if err is not None:
            warning("rank %d: one-sided op %d failed at peer %d: %s",
                    self.rank, msg["op"], src, err)
        if kind == "put":
            cb(err)
        else:
            cb(None if err is not None else self.unpack(msg))

    def _dispatch(self, tag: int, src: int, payload: Any) -> None:
        mark("recv tag=%d src=%d", tag, src)
        with self._cb_lock:
            cb = self._callbacks.get(tag)
            if cb is None:
                self._undelivered.setdefault(tag, []).append((src, payload))
                return
        cb(src, payload)


class SocketCE(CommEngine):
    """TCP active-message engine (the mpi_funnelled analog)."""

    def __init__(self, rank: int, nranks: int,
                 port_base: Optional[int] = None):
        super().__init__(rank, nranks)
        if port_base is None:
            port_base = int(params.get("comm_port_base", 0)) or \
                int(os.environ.get("PARSEC_COMM_PORT_BASE", 23500))
        self.port_base = port_base
        # multi-host address book (the DCN story: one rank per host, the
        # same engine; reference: the MPI module gets this from mpiexec)
        hosts = str(params.get("comm_hosts", "") or
                    os.environ.get("PARSEC_COMM_HOSTS", "")).strip()
        self._hosts = [h.strip() for h in hosts.split(",")] if hosts else []
        if self._hosts and len(self._hosts) != nranks:
            raise ValueError(
                f"comm_hosts names {len(self._hosts)} hosts for "
                f"{nranks} ranks")
        self._peers: Dict[int, socket.socket] = {}
        self._send_locks: Dict[int, threading.Lock] = {}
        self._plock = threading.Lock()
        self._stop = False
        self._threads: List[threading.Thread] = []
        self._bar_lock = threading.Lock()
        self._bar_cond = threading.Condition(self._bar_lock)
        self._bar_gen = 0
        self._bar_arrived: Dict[int, int] = {}
        self._bar_released: set = set()
        self.tag_register(TAG_BARRIER, self._barrier_cb)
        self._register_onesided()

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # buffer size must be set BEFORE listen(): accepted sockets
        # inherit it, and the receive window is negotiated at the
        # handshake (man 7 tcp)
        _bump_sockbufs(self._listener)
        self._listener.bind(("0.0.0.0" if self._hosts else "127.0.0.1",
                             self.port_base + rank))
        self._listener.listen(nranks)
        t = threading.Thread(target=self._accept_loop,
                             name=f"ce-accept-{rank}", daemon=True)
        t.start()
        self._threads.append(t)
        # Deterministic connection direction: the HIGHER rank initiates to
        # each lower rank, eagerly at init, so a pair can never cross-
        # connect simultaneously and close each other's canonical socket.
        for dst in range(rank):
            self._connect(dst)

    # -- connection management -------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            _bump_sockbufs(conn)
            # peer announces magic + protocol version + rank first: a
            # stranger or cross-version peer fails ITS connection here
            hdr = self._recv_exact(conn, _HANDSHAKE.size)
            if hdr is None:
                conn.close()
                continue
            magic, ver, src = _HANDSHAKE.unpack(hdr)
            if magic != _WIRE_MAGIC or ver != _WIRE_VERSION:
                warning("rank %d: rejected connection with bad handshake "
                        "(magic=%r version=%r)", self.rank, magic, ver)
                conn.close()
                continue
            with self._plock:
                self._peers.setdefault(src, conn)
                self._send_locks.setdefault(src, threading.Lock())
            t = threading.Thread(target=self._recv_loop, args=(conn, src),
                                 name=f"ce-recv-{self.rank}<-{src}",
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _connect(self, dst: int) -> socket.socket:
        with self._plock:
            s = self._peers.get(dst)
            if s is not None:
                return s
        if dst > self.rank:
            # the higher rank owns the initiation: wait for its inbound
            deadline = time.monotonic() + 30
            while True:
                with self._plock:
                    s = self._peers.get(dst)
                if s is not None:
                    return s
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"rank {self.rank}: no connection from {dst}")
                time.sleep(0.01)
        peer_host = self._hosts[dst] if self._hosts else "127.0.0.1"
        deadline = time.monotonic() + 30
        s = None
        while True:
            try:
                # buffers must be sized BEFORE connect() so the window
                # is negotiated large (man 7 tcp) — hence no
                # create_connection here
                s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                _bump_sockbufs(s)
                s.settimeout(5)
                s.connect((peer_host, self.port_base + dst))
                s.settimeout(None)
                break
            except OSError:
                # socket() itself may have raised, leaving s unbound for
                # this iteration — a bare close() would turn the retry
                # into a NameError escaping the deadline logic
                try:
                    if s is not None:
                        s.close()
                except OSError:
                    pass
                s = None
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.sendall(_HANDSHAKE.pack(_WIRE_MAGIC, _WIRE_VERSION, self.rank))
        with self._plock:
            self._peers[dst] = s
            self._send_locks.setdefault(dst, threading.Lock())
        t = threading.Thread(target=self._recv_loop, args=(s, dst),
                             name=f"ce-recv-{self.rank}<-{dst}", daemon=True)
        t.start()
        self._threads.append(t)
        return s

    # -- framing -----------------------------------------------------------
    @staticmethod
    def _recv_exact(conn: socket.socket, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            try:
                chunk = conn.recv(n - len(buf))
            except OSError:
                return None
            if not chunk:
                return None
            buf += chunk
        return buf

    @staticmethod
    def _recv_into(conn: socket.socket, n: int) -> Optional[bytearray]:
        """Receive ``n`` bytes straight into one buffer (no quadratic
        bytes-concatenation; the out-of-band payload path)."""
        buf = bytearray(n)
        view = memoryview(buf)
        got = 0
        while got < n:
            try:
                r = conn.recv_into(view[got:], n - got)
            except OSError:
                return None
            if r == 0:
                return None
            got += r
        return buf

    def _recv_loop(self, conn: socket.socket, src: int) -> None:
        max_ln = int(params.get("comm_max_frame_mb", 4096)) << 20
        while not self._stop:
            hdr = self._recv_exact(conn, _LEN.size)
            if hdr is None:
                self._peer_lost(src)
                return
            tag, ln, nbufs = _LEN.unpack(hdr)
            if ln > max_ln or nbufs > 4096:
                # corrupt stream (or hostile length): sever THIS
                # connection with a cause instead of trying to consume
                # an absurd frame — the guard VERDICT r2 asked for
                self._peer_corrupt(src, conn,
                                   f"frame length {ln}/{nbufs} bufs "
                                   f"exceeds the {max_ln >> 20} MiB "
                                   f"bound (tag={tag})")
                return
            data = self._recv_exact(conn, ln) if ln else b""
            if data is None:
                self._peer_lost(src)
                return
            oob: List[bytearray] = []
            corrupt = None
            for _ in range(nbufs):
                bhdr = self._recv_exact(conn, _BUFLEN.size)
                if bhdr is None:
                    self._peer_lost(src)
                    return
                (bln,) = _BUFLEN.unpack(bhdr)
                if bln > max_ln:
                    corrupt = f"oob buffer length {bln} (tag={tag})"
                    break
                buf = self._recv_into(conn, bln)
                if buf is None:
                    self._peer_lost(src)
                    return
                oob.append(buf)
            if corrupt is not None:
                self._peer_corrupt(src, conn, corrupt)
                return
            self.recv_msgs += 1
            try:
                payload = pickle.loads(data, buffers=oob) if data else None
            except Exception as exc:
                # undecodable frame = wire corruption: fail the
                # connection, not the handler path
                self._peer_corrupt(src, conn,
                                   f"undecodable frame tag={tag}: {exc}")
                return
            try:
                self._dispatch(tag, src, payload)
            except Exception as exc:   # handler error must not kill recv,
                warning("rank %d: AM handler tag=%d failed: %s",
                        self.rank, tag, exc)
                if self.on_error is not None:   # ...but must fail the rank
                    self.on_error(exc)

    def _peer_corrupt(self, src: int, conn: socket.socket,
                      why: str) -> None:
        warning("rank %d: protocol corruption from rank %d: %s",
                self.rank, src, why)
        try:
            conn.close()
        except OSError:
            pass
        self._peer_lost(src)

    def _peer_lost(self, src: int) -> None:
        """Failure detection: a peer's socket closed while we are still
        running (the reference has NO fault tolerance — it aborts; here
        the loss surfaces as a context error AND wakes barrier/
        quiescence waiters so they fail fast with a cause instead of
        hanging to their timeouts)."""
        if self._stop:
            return             # orderly shutdown closes sockets
        warning("rank %d: lost connection to rank %d", self.rank, src)
        self.dead_peers.add(src)
        cond = getattr(self, "_bar_cond", None)   # SocketCE's barrier
        if cond is not None:
            with cond:
                cond.notify_all()
        if self.on_error is not None:
            self.on_error(ConnectionError(
                f"rank {self.rank}: peer rank {src} disconnected "
                "mid-run"))

    def send_am(self, tag: int, dst: int, payload: Any = None) -> None:
        mark("send_am tag=%d dst=%d", tag, dst)
        if dst == self.rank:
            # local delivery short-circuit (counts as a message so the
            # termination balance stays symmetric)
            self.sent_msgs += 1
            self.recv_msgs += 1
            self._dispatch(tag, self.rank, payload)
            return
        bufs: List[Any] = []
        raws: List[Any] = []
        if payload is not None:
            data = pickle.dumps(payload, protocol=5,
                                buffer_callback=bufs.append)
            try:
                raws = [pb.raw() for pb in bufs]
            except BufferError:
                # a non-contiguous exporter: fall back to in-band
                data = pickle.dumps(payload, protocol=5)
                raws = []
        else:
            data = b""
        parts: List[Any] = [_LEN.pack(tag, len(data), len(raws)), data]
        for raw in raws:
            parts.append(_BUFLEN.pack(raw.nbytes))
            parts.append(raw)
        s = self._connect(dst)
        with self._send_locks[dst]:
            self.sent_msgs += 1
            self._sendmsg_all(s, parts)

    @staticmethod
    def _sendmsg_all(s: socket.socket, parts: List[Any]) -> None:
        """Gather-send every part (scatter-gather keeps large array
        buffers out of any join copy); loops on partial sends."""
        views = [memoryview(p) for p in parts if len(p)]
        while views:
            sent = s.sendmsg(views)
            while sent and views:
                head = views[0]
                if sent >= head.nbytes:
                    sent -= head.nbytes
                    views.pop(0)
                else:
                    views[0] = head[sent:]
                    sent = 0

    # -- collective: flat barrier, generation-numbered (gather-to-0 +
    # release; reference: ce.sync) -----------------------------------------
    def _barrier_cb(self, src: int, payload: Any) -> None:
        kind, gen = payload
        with self._bar_cond:
            if kind == "arrive":
                self._bar_arrived[gen] = self._bar_arrived.get(gen, 0) + 1
            else:
                self._bar_released.add(gen)
            self._bar_cond.notify_all()

    def barrier(self, timeout: float = 60.0) -> None:
        self._bar_gen += 1
        gen = self._bar_gen
        if self.nranks == 1:
            return
        if self.rank == 0:
            with self._bar_cond:
                ok = self._bar_cond.wait_for(
                    lambda: self._bar_arrived.get(gen, 0) == self.nranks - 1
                    or self.dead_peers,
                    timeout=timeout)
                if self.dead_peers and \
                        self._bar_arrived.get(gen, 0) != self.nranks - 1:
                    raise ConnectionError(
                        f"rank 0: barrier with dead peer(s) "
                        f"{sorted(self.dead_peers)}")
                if not ok:
                    raise TimeoutError("rank 0: barrier timeout")
                del self._bar_arrived[gen]
            for r in range(1, self.nranks):
                try:
                    self.send_am(TAG_BARRIER, r, ("release", gen))
                except OSError:
                    # a rank that arrived and then died must not strand
                    # the release of later-ranked survivors
                    warning("rank 0: barrier release to dead rank %d "
                            "skipped", r)
        else:
            self.send_am(TAG_BARRIER, 0, ("arrive", gen))
            with self._bar_cond:
                ok = self._bar_cond.wait_for(
                    lambda: gen in self._bar_released or self.dead_peers,
                    timeout=timeout)
                if self.dead_peers and gen not in self._bar_released:
                    raise ConnectionError(
                        f"rank {self.rank}: barrier with dead peer(s) "
                        f"{sorted(self.dead_peers)}")
                if not ok:
                    raise TimeoutError(f"rank {self.rank}: barrier timeout")
                self._bar_released.discard(gen)

    def fini(self) -> None:
        self._stop = True
        try:
            self._listener.close()
        except OSError:
            pass
        with self._plock:
            for s in self._peers.values():
                try:
                    s.close()
                except OSError:
                    pass
            self._peers.clear()
        debug_verbose(5, "rank %d CE down: sent=%d recv=%d",
                      self.rank, self.sent_msgs, self.recv_msgs)
