"""Comm-engine: transport-neutral active messages + one-sided emulation.

Rebuild of the reference's comm-engine seam (reference:
parsec/parsec_comm_engine.h:161-183 ``parsec_comm_engine_t`` vtable — AM
tag register/send, put/get with memory handles, progress, capabilities;
the funnelled MPI module parsec_mpi_funnelled.c is its only in-tree
implementation).  ``SocketCE`` implements the vtable over localhost TCP:
one listener per rank (port base+rank), lazily-connected peer sockets,
length-prefixed pickled frames, and one receiver thread per peer
dispatching AM callbacks — the threading stands in for the reference's
dedicated comm thread; sends are multi-threaded behind per-peer locks
(capability CE_MT in the reference's terms).

On a TPU pod the same vtable would sit on DCN (host network) for control
while payloads ride ICI collectives; the socket module doubles as that
bootstrap path and as the test transport (SURVEY.md §4: the reference
tests multi-node with mpiexec on one node).
"""

from __future__ import annotations

import os
import pickle
import select
import selectors
import socket
import struct
import threading
import time
from collections import deque
from itertools import islice
from typing import Any, Callable, Dict, List, Optional

from parsec_tpu.core.errors import PeerFailedError
from parsec_tpu.utils import faultinject
from parsec_tpu.utils.debug_history import mark
from parsec_tpu.utils.mca import params
from parsec_tpu.utils.output import debug_verbose, warning

params.register("comm_port_base", 0,
                "TCP port base for the socket comm engine (0 = from env "
                "PARSEC_COMM_PORT_BASE or 23500)")
params.register("comm_hosts", "",
                "comma-separated per-rank host list for multi-host (DCN) "
                "runs — rank i listens on 0.0.0.0 and peers dial "
                "hosts[i]; empty = single-node loopback (also read from "
                "env PARSEC_COMM_HOSTS)")

# AM tag space (reference: parsec_comm_engine.h:29-38)
TAG_ACTIVATE = 1
TAG_GET_REQ = 2
TAG_GET_REP = 3
TAG_TERMDET = 4
TAG_BARRIER = 5
TAG_DTD = 6       # distributed DTD data/flush traffic
TAG_BATCH = 7     # aggregated same-destination messages [(tag, payload)...]
TAG_UTRIG = 8     # user-trigger termination declaration
TAG_PUT = 9       # one-sided put into a registered region
TAG_GET1 = 10     # one-sided get request
TAG_GET1_REP = 11
TAG_CLOCK = 12    # clock-offset ping/pong (causal-trace alignment)
TAG_HB = 13       # heartbeat (active failure detection of HUNG peers)
TAG_METRICS = 14  # telemetry pull/push (cross-rank /metrics aggregation)
TAG_FLIGHT = 15   # flight-recorder incident dump request (prof/flightrec)
TAG_REJOIN = 16   # elastic-rejoin handshake of a restarted incarnation
TAG_RECOVER = 17  # recovery control lane (dead-set agreement, replay needs)
TAG_USER = 18     # first tag available to applications

# the fault injector names tags without importing this module (it is
# below us in the layering); a drift between the two maps would
# silently mistarget every tag-matched fault directive.  An explicit
# raise, not assert: python -O would compile the guard away
for _name, _tag in (("ACT", TAG_ACTIVATE), ("DTD", TAG_DTD),
                    ("GET_REP", TAG_GET_REP), ("HB", TAG_HB),
                    ("REJOIN", TAG_REJOIN), ("RECOVER", TAG_RECOVER)):
    if faultinject.TAG_NAMES[_name] != _tag:
        raise RuntimeError(
            f"faultinject.TAG_NAMES[{_name!r}] drifted from "
            "comm/engine.py's wire tags — every tag-matched fault "
            "directive would silently mistarget")
del _name, _tag

#: frame header: (tag, pickle length, out-of-band buffer count).  Large
#: array payloads ride OUT OF BAND (pickle protocol 5): the pickle holds
#: only metadata while each buffer is scatter-gathered onto the socket
#: unserialized and received straight into its own bytearray — the
#: dataflow-bandwidth path does no full-payload serialization copy
_LEN = struct.Struct("!IQI")
_BUFLEN = struct.Struct("!Q")

#: wire-format guard (VERDICT r2: a malformed or cross-version frame
#: must fail its CONNECTION with a cause, not corrupt the recv thread):
#: connections handshake magic+version+rank; frames are bounded and
#: undecodable ones sever the peer
_HANDSHAKE = struct.Struct("!4sII")   # (magic, proto version, rank)
_WIRE_MAGIC = b"PTCE"
_WIRE_VERSION = 2   # v2: protocol-5 out-of-band buffer frames

params.register("comm_max_frame_mb", 4096,
                "largest acceptable frame payload in MiB; a length field "
                "beyond this is treated as stream corruption and severs "
                "the connection")


params.register("comm_sockbuf_mb", 4,
                "SO_SNDBUF/SO_RCVBUF request per peer socket in MiB "
                "(0 = system default).  The r5 bw breakdown measured "
                "the 8MB-payload recv at ~1.1GB/s under default-sized "
                "buffers — sender/receiver ping-pong on a small window; "
                "MB-class buffers let the kernel stream the frame")

params.register("comm_sockbuf_bytes", 0,
                "exact SO_SNDBUF/SO_RCVBUF request in BYTES (overrides "
                "comm_sockbuf_mb when > 0).  Test hook: a tiny send "
                "buffer forces the event-loop transport through its "
                "partial-write resume path")

params.register("comm_clock_samples", 4,
                "ping samples per clock-offset probe round; the "
                "minimum-RTT sample's midpoint estimate wins (error "
                "bounded by that sample's rtt/2 under asymmetric delay)")

params.register("comm_peer_timeout_s", 15.0,
                "declare a peer dead after this many seconds of total "
                "wire silence (heartbeats ride the control lane at "
                "timeout/3, piggybacking on the TAG_CLOCK probe "
                "machinery, so a HUNG peer — open socket, nothing "
                "flowing — is detected, not just a closed one; "
                "0 disables active detection)")

params.register("comm_epoch", 0,
                "incarnation epoch of this process's comm engine: a "
                "rank RESTARTED after a death rejoins with a bumped "
                "epoch (TAG_REJOIN handshake) so survivors can fence "
                "stale frames of the previous incarnation out of the "
                "protocol (core/recovery.py elastic rejoin); 0 = first "
                "incarnation")

params.register("comm_transport", "evloop",
                "socket transport module: 'evloop' (single-threaded "
                "nonblocking event loop owning every peer socket — the "
                "reference's dedicated-comm-thread analog) or 'threads' "
                "(one blocking receiver thread per peer + per-peer send "
                "locks; the pre-r6 path, kept for A/B attribution)")


def _bump_sockbufs(s: socket.socket) -> None:
    nbytes = int(params.get("comm_sockbuf_bytes", 0))
    if nbytes <= 0:
        mb = int(params.get("comm_sockbuf_mb", 4))
        if mb <= 0:
            return
        nbytes = mb << 20
    for opt in (socket.SO_SNDBUF, socket.SO_RCVBUF):
        try:
            s.setsockopt(socket.SOL_SOCKET, opt, nbytes)
        except OSError:
            pass    # best-effort: the kernel clamps to its limits


def wire_dtype(dtype) -> str:
    """A dtype string that round-trips over the wire.  Extension dtypes
    (ml_dtypes bfloat16 & friends) have a ``.str`` of raw void bytes —
    their NAME is the parseable spelling."""
    import numpy as _np
    dt = _np.dtype(dtype)
    s = dt.str
    try:
        if _np.dtype(s) == dt:
            return s
    except TypeError:
        pass
    return dt.name


def parse_dtype(spec: str):
    import numpy as _np
    try:
        return _np.dtype(spec)
    except TypeError:
        import ml_dtypes  # noqa: F401  (registers bfloat16 et al.)
        return _np.dtype(spec)


def clock_offset_estimate(samples):
    """Peer clock offset (``clock_peer - clock_mine``, seconds) and rtt
    from ping samples ``[(t0, t1, t2), ...]`` — t0 = ping send and t2 =
    pong arrival on OUR clock, t1 = the peer's stamp on ITS clock.  The
    minimum-RTT sample's midpoint estimate ``t1 - (t0 + t2) / 2`` wins:
    queuing delay only ever inflates rtt, so the tightest round trip is
    the closest to symmetric, and the estimate's error is bounded by
    that sample's rtt/2 even under fully asymmetric path delay (the
    NTP/Cristian bound)."""
    best = min(samples, key=lambda s: s[2] - s[0])
    t0, t1, t2 = best
    return t1 - (t0 + t2) / 2.0, t2 - t0


class CommStats:
    """Transport-level counters (both transports bump them), the wire
    side of the bench's bw/rtt protocol breakdown."""

    FIELDS = ("frames_sent", "frames_recv", "bytes_sent", "bytes_recv",
              "syscalls_send", "syscalls_recv", "partial_writes",
              "wakeups", "frames_parsed_native")

    __slots__ = FIELDS

    def __init__(self):
        for f in self.FIELDS:
            setattr(self, f, 0)

    def as_dict(self) -> Dict[str, int]:
        return {f: getattr(self, f) for f in self.FIELDS}


def _dial_peer(host: str, port: int, myrank: int,
               deadline_s: float = 30.0) -> socket.socket:
    """Connect-with-retry + handshake write — the wire setup shared by
    BOTH transports (buffers sized BEFORE connect so the TCP window
    negotiates large; the peer may not be listening yet)."""
    deadline = time.monotonic() + deadline_s
    s = None
    while True:
        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            _bump_sockbufs(s)
            s.settimeout(5)
            s.connect((host, port))
            s.settimeout(None)
            break
        except OSError:
            # socket() itself may have raised, leaving s unbound for
            # this iteration — a bare close() would turn the retry
            # into a NameError escaping the deadline logic
            try:
                if s is not None:
                    s.close()
            except OSError:
                pass
            s = None
            if time.monotonic() > deadline:
                raise
            time.sleep(0.05)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    s.sendall(_HANDSHAKE.pack(_WIRE_MAGIC, _WIRE_VERSION, myrank))
    return s


_nat_parts = None
_nat_parts_tried = False


def _native_parts():
    """commext.frame_parts when the native frame path is on and builds
    (resolved once per process — the A/B knob is read at first frame)."""
    global _nat_parts, _nat_parts_tried
    if not _nat_parts_tried:
        _nat_parts_tried = True
        from parsec_tpu.comm.frames import params as _p
        if int(_p.get("comm_frame_native", 1)):
            from parsec_tpu.native import load_commext
            cx = load_commext()
            if cx is not None:
                _nat_parts = cx.frame_parts
    return _nat_parts


def _frame_parts(tag: int, payload: Any) -> List[Any]:
    """Serialize one AM into its wire parts (header, pickle body, then
    per-buffer length + raw buffer).  Large array payloads ride OUT OF
    BAND (pickle protocol 5) — no full-payload serialization copy.
    The part-list assembly (every length header) is one C call when
    the native frame path is armed (commext.frame_parts)."""
    bufs: List[Any] = []
    raws: List[Any] = []
    if payload is not None:
        data = pickle.dumps(payload, protocol=5,
                            buffer_callback=bufs.append)
        try:
            raws = [pb.raw() for pb in bufs]
        except BufferError:
            # a non-contiguous exporter: fall back to in-band
            data = pickle.dumps(payload, protocol=5)
            raws = []
    else:
        data = b""
    nat = _native_parts()
    if nat is not None:
        return nat(tag, data, raws)
    parts: List[Any] = [_LEN.pack(tag, len(data), len(raws)), data]
    for raw in raws:
        parts.append(_BUFLEN.pack(raw.nbytes))
        parts.append(raw)
    return parts


class CommEngine:
    """Vtable (reference: parsec_comm_engine_t — AM tag register/send,
    registered-memory one-sided put/get, pack/unpack, progress, sync,
    capability flags parsec_comm_engine.h:161-183)."""

    #: capability flags (reference: the CE capabilities the remote-dep
    #: layer queries to pick eager vs rendezvous and threading mode)
    CAP_ONESIDED = True     # put/get over registered regions
    CAP_MT = True           # sends are thread-safe
    #: transport name recorded in stats()/bench protocol breakdowns
    TRANSPORT = "base"

    def __init__(self, rank: int, nranks: int):
        self.rank = rank
        self.nranks = nranks
        #: registered memory regions: id -> writable numpy view
        #: (reference: memory registration handles of ce.mem_register;
        #: guarded-by: _reg_lock)
        self._regions: Dict[int, Any] = {}
        self._once_regions: Dict[int, float] = {}   # guarded-by: _reg_lock
        self._region_seq = 0                        # guarded-by: _reg_lock
        self._reg_lock = threading.Lock()
        #: completion callbacks of outstanding one-sided ops
        #: (guarded-by: _reg_lock)
        self._osc: Dict[int, Callable] = {}
        self._osc_seq = 0                           # guarded-by: _reg_lock
        self._callbacks: Dict[int, Callable] = {}   # guarded-by: _cb_lock
        #: messages for tags nobody registered yet — replayed on register
        #: (the reference posts persistent recvs per tag at init; here a
        #: peer may send before this rank finishes wiring its handlers;
        #: guarded-by: _cb_lock)
        self._undelivered: Dict[int, List] = {}
        self._cb_lock = threading.Lock()
        # message counters (engine-level stats; the remote-dep layer keeps
        # its own application-message counters for termination detection)
        self.sent_msgs = 0
        self.recv_msgs = 0
        self.stats = CommStats()
        # flat generation-numbered barrier state (gather-to-0 + release;
        # reference: ce.sync) — shared by every transport
        self._bar_lock = threading.Lock()
        self._bar_cond = threading.Condition(self._bar_lock)
        self._bar_gen = 0                        # guarded-by: _bar_cond
        #: gen -> set of arrived SOURCE ranks (not a bare count: a
        #: rank that arrived and then died+was-excused must not satisfy
        #: the shrunk survivor quorum in its place; guarded-by: _bar_cond)
        self._bar_arrived: Dict[int, set] = {}
        self._bar_released: set = set()          # guarded-by: _bar_cond
        self._bar_aborted: set = set()           # guarded-by: _bar_cond
        # registered HERE, next to the state it serves: a transport
        # that forgot the registration would hang every barrier to its
        # timeout with nothing pointing at the cause
        self.tag_register(TAG_BARRIER, self._barrier_cb)
        #: per-peer clock alignment (causal traces): rank ->
        #: {offset (clock_peer - clock_mine, perf_counter seconds),
        #:  rtt, drift (s/s), measured_at (monotonic)} — fed by the
        #: TAG_CLOCK ping exchange, re-probed periodically by the
        #: remote-dep progress/event loop (guarded-by: _clock_lock)
        self.clock: Dict[int, Dict[str, float]] = {}
        self._clock_lock = threading.Lock()
        self._clock_pend: Dict[int, List] = {}   # guarded-by: _clock_lock
        self.tag_register(TAG_CLOCK, self._clock_cb)
        #: set by the remote-dep layer: fatal handler errors fail the rank
        #: fast instead of silently dropping the message
        self.on_error: Optional[Callable[[Exception], None]] = None
        #: set by the remote-dep layer: peer-death containment — routes a
        #: PeerFailedError to the taskpools that touch the dead rank
        #: instead of poisoning the whole context; falls back to on_error
        self.on_peer_dead: Optional[Callable[[int, Exception], None]] = None
        #: ranks whose connection died mid-run (failure detection);
        #: barrier and quiescence waiters observe this and fail fast
        self.dead_peers: set = set()
        #: dead ranks the RECOVERY plane routed around (core/recovery):
        #: barriers, quiescence and checkpoints run over the survivors
        #: instead of failing — empty unless a recovery engaged, so the
        #: containment-only behavior is reproduced exactly by default
        self.excused_peers: set = set()
        #: this engine's incarnation epoch (comm_epoch): restarted
        #: ranks rejoin with a bumped value; receivers fence older ones
        self.epoch = int(params.get("comm_epoch", 0))
        #: elastic rejoin: gate on reconnections from dead ranks (set by
        #: the recovery coordinator; default keeps the PR 3 zombie
        #: rejection) and the survivor-side handshake validator
        self.rejoin_allowed = False
        self.on_rejoin: Optional[Callable[[int, dict],
                                          Optional[dict]]] = None
        self._rejoin_cond = threading.Condition()
        self._rejoin_ack: Optional[dict] = None   # guarded-by: _rejoin_cond
        self.tag_register(TAG_REJOIN, self._rejoin_cb)
        #: recovery control lane (core/recovery.py): dead-set agreement
        #: reports/broadcasts and minimal-replay need/ack messages all
        #: ride one tag, dispatched to the coordinator's handler
        self.on_recover: Optional[Callable[[int, dict], None]] = None
        self.tag_register(TAG_RECOVER, self._recover_cb)
        #: set when an injected kill_rank fired on THIS rank: its own
        #: containment must not be "recovered" into a split brain
        self.fault_killed = False
        #: failure detection: monotonic stamp of the last frame each peer
        #: delivered (ANY tag counts as liveness; TAG_HB only guarantees
        #: a floor of traffic on an otherwise-quiet control lane)
        self._last_heard: Dict[int, float] = {}
        self._hb_check_at = time.monotonic()
        #: fault injection (utils/faultinject.py): None compiles every
        #: per-frame hook to a single attribute check
        self._fault = faultinject.comm_faults(rank) \
            if faultinject.ARMED else None
        #: Safra reconcile hook: the remote-dep layer adjusts its message
        #: balance (global AND per-destination — the recovery reconcile
        #: subtracts a dead rank's whole contribution, so the two must
        #: move together) when the injector drops/duplicates an app frame
        self.on_frame_fault: Optional[Callable[[str, int, Any, int],
                                               None]] = None
        #: kill_rank mode=hang: a muted engine neither sends nor
        #: processes frames (sockets stay open — the silent-hang fault)
        self._muted = False
        self.tag_register(TAG_HB, self._hb_cb)
        #: telemetry plane (prof/metrics.py): a provider returns this
        #: rank's sample list for TAG_METRICS pulls; replies to OUR
        #: pulls land in _metrics_replies keyed by request id
        self.metrics_provider: Optional[Callable[[], Any]] = None
        #: every ACCEPTED clock-probe round trip feeds the frame-RTT
        #: histogram (control-lane protocol latency over time, not
        #: just the latest per-peer gauge)
        self.on_clock_rtt: Optional[Callable[[float], None]] = None
        self._metrics_cond = threading.Condition()
        self._metrics_replies: Dict[int, Dict[int, Any]] = {}  # guarded-by: _metrics_cond
        self._metrics_req = 0                    # guarded-by: _metrics_cond
        self.tag_register(TAG_METRICS, self._metrics_cb)
        #: control-plane journal (prof/journal.py): the Context's
        #: journal attaches here so barrier/death events land in it,
        #: and a provider serves cross-rank journal pulls riding the
        #: SAME TAG_METRICS req/reply machinery (zero new wire tags)
        self.journal = None
        self.journal_provider: Optional[Callable[[], Any]] = None
        #: flight recorder (prof/flightrec.py): a peer's incident
        #: broadcast asks this rank to dump its ring into the bundle
        self.on_flight_dump: Optional[Callable[[str], None]] = None
        self.tag_register(TAG_FLIGHT, self._flight_cb)
        #: starved-checker rebase accounting (observability of the
        #: failure detector): per-peer silence-clock rebases; written
        #: only by the single thread running check_peer_timeouts
        self.hb_rebase_total = 0
        self._hb_rebases: Dict[int, int] = {}
        #: heartbeat inter-arrival tracking (predictive health plane,
        #: prof/health.py): per-peer EWMA of TAG_HB gaps plus a
        #: mean-absolute-deviation jitter estimate.  Written only on
        #: the comm receive thread (_hb_cb); read at scrape time
        #: (hb_stats) — a degrading-but-alive peer shows up here as
        #: gap inflation long before the silence timeout fires
        self._hb_arrivals: Dict[int, Dict[str, float]] = {}

    def tag_register(self, tag: int, cb: Callable[[int, Any], None]) -> None:
        """cb(src_rank, payload) runs on the comm receive thread."""
        with self._cb_lock:
            self._callbacks[tag] = cb
            backlog = self._undelivered.pop(tag, [])
        for src, payload in backlog:
            cb(src, payload)

    def tag_unregister(self, tag: int) -> None:
        with self._cb_lock:
            self._callbacks.pop(tag, None)

    def send_am(self, tag: int, dst: int, payload: Any) -> None:
        raise NotImplementedError

    def fini(self) -> None:
        pass

    # -- collective: flat barrier, generation-numbered (gather-to-0 +
    # release; reference: ce.sync) --------------------------------------
    # lint: on-loop (AM callback: runs on the comm loop/recv thread)
    def _barrier_cb(self, src: int, payload: Any) -> None:
        kind, gen = payload
        with self._bar_cond:
            if kind == "arrive":
                self._bar_arrived.setdefault(gen, set()).add(src)
            elif kind == "abort":
                self._bar_aborted.add(gen)
            else:
                self._bar_released.add(gen)
            self._bar_cond.notify_all()

    def _bar_fatal(self) -> set:
        """Dead peers a barrier must FAIL on: excused ranks (a recovery
        routed around them — core/recovery.py) narrowed the collective
        to the survivors, every other death still aborts the round.
        Empty excused set == the pre-recovery semantics exactly."""
        return self.dead_peers - self.excused_peers

    def _bar_live(self) -> List[int]:
        """Barrier participants: every rank not EXCUSED (self included).
        A non-excused dead rank stays a participant — its absence fails
        the round exactly as before recovery existed; only a recovery's
        excusal narrows the collective.  The root is the lowest
        participant, so survivor-only barriers keep working when rank 0
        itself died and was excused."""
        return [r for r in range(self.nranks)
                if r == self.rank or r not in self.excused_peers]

    def _journal_barrier(self, gen: int, root: int, outcome: str) -> None:
        """Journal one barrier round's terminal state (the generation
        numbers are protocol state the rejoin handshake re-syncs — a
        divergent generation is exactly a black-box question)."""
        jr = self.journal
        if jr is not None:
            jr.emit("barrier", gen=gen, outcome=outcome, root=root,
                    peers=self._bar_live())

    def barrier(self, timeout: float = 60.0) -> None:
        with self._bar_cond:
            # under the lock: two app threads racing barrier() must not
            # read the same generation number (found by PCL-LOCK when
            # the guarded-by annotations landed)
            self._bar_gen += 1
            gen = self._bar_gen
        if self.nranks == 1:
            return
        live = self._bar_live()
        root = live[0]
        if len(live) == 1:
            # every peer is dead; with all of them excused this is a
            # survivor-of-one barrier (trivially met), otherwise the
            # fatal check below raises as before
            if self._bar_fatal():
                self._journal_barrier(gen, root, "dead")
                raise ConnectionError(
                    f"rank {self.rank}: barrier with dead peer(s) "
                    f"{sorted(self.dead_peers)}")
            self._journal_barrier(gen, root, "ok")
            return
        with self._bar_cond:
            # GC residue of past generations (stragglers landing after a
            # waiter gave up re-add entries nobody will consume — a
            # resident engine must not accumulate them across failed
            # rounds)
            self._bar_arrived = {g: c for g, c in self._bar_arrived.items()
                                 if g >= gen}
            self._bar_released = {g for g in self._bar_released if g >= gen}
            self._bar_aborted = {g for g in self._bar_aborted if g >= gen}
        if self.rank == root:
            # arrivals needed re-evaluate per wakeup: a participant
            # dying AND being excused mid-round shrinks the quorum
            # instead of stranding the root; an unexcused death keeps
            # the quorum unreachable so the fatal path aborts the round
            def quorum() -> int:
                return sum(1 for r in range(self.nranks)
                           if r != self.rank
                           and r not in self.excused_peers)

            def arrived() -> int:
                # live arrivals only: an arrival from a since-excused
                # rank must not stand in for a survivor still working
                return len(set(self._bar_arrived.get(gen, ()))
                           - self.excused_peers)
            with self._bar_cond:
                ok = self._bar_cond.wait_for(
                    lambda: arrived() >= quorum() or self._bar_fatal(),
                    timeout=timeout)
                failed = (self._bar_fatal() and arrived() < quorum())
                if not failed:
                    if not ok:
                        self._bar_arrived.pop(gen, None)
                        self._journal_barrier(gen, root, "timeout")
                        raise TimeoutError(
                            f"rank {self.rank}: barrier timeout")
                    self._bar_arrived.pop(gen, None)
                else:
                    # failure paths must not leak this generation (a
                    # resident service keeps the engine alive across
                    # failed barriers)
                    self._bar_arrived.pop(gen, None)
            if failed:
                # a peer died before arriving: fail the SURVIVORS fast
                # too — an abort releases their wait with the cause
                # instead of letting them ride out the full timeout
                for r in range(self.nranks):
                    if r == self.rank or r in self.dead_peers:
                        continue
                    try:
                        self.send_am(TAG_BARRIER, r, ("abort", gen))
                    except OSError:
                        pass
                self._journal_barrier(gen, root, "dead")
                raise ConnectionError(
                    f"rank {self.rank}: barrier with dead peer(s) "
                    f"{sorted(self.dead_peers)}")
            for r in range(self.nranks):
                if r == self.rank or r in self.dead_peers:
                    continue
                try:
                    self.send_am(TAG_BARRIER, r, ("release", gen))
                except OSError:
                    # a rank that arrived and then died must not strand
                    # the release of later-ranked survivors
                    warning("rank %d: barrier release to dead rank %d "
                            "skipped", self.rank, r)
            self._journal_barrier(gen, root, "ok")
        else:
            self.send_am(TAG_BARRIER, root, ("arrive", gen))
            with self._bar_cond:
                # A SIBLING that passed this barrier and exited before
                # our release arrived is orderly shutdown (final-barrier
                # race), so sibling death alone does not fail us — the
                # root aborts the round if a sibling died mid-barrier,
                # and only the root's own (unexcused) death can strand
                # our release.
                # the captured root dying fails this round FAST whether
                # or not a recovery later excuses it: an excused root
                # still sends neither release nor abort for a round it
                # entered dead — only barriers ENTERED after the
                # excusal re-elect a live root
                ok = self._bar_cond.wait_for(
                    lambda: gen in self._bar_released
                    or gen in self._bar_aborted
                    or root in self.dead_peers,
                    timeout=timeout)
                if gen not in self._bar_released and \
                        (gen in self._bar_aborted
                         or root in self.dead_peers):
                    aborted = gen in self._bar_aborted
                    self._bar_aborted.discard(gen)
                    self._journal_barrier(
                        gen, root, "abort" if aborted else "dead")
                    raise ConnectionError(
                        f"rank {self.rank}: barrier with dead peer(s) "
                        f"{sorted(self.dead_peers)}"
                        + (" (aborted by the root)" if aborted else ""))
                if not ok:
                    self._bar_released.discard(gen)
                    self._bar_aborted.discard(gen)
                    self._journal_barrier(gen, root, "timeout")
                    raise TimeoutError(
                        f"rank {self.rank}: barrier timeout "
                        f"(dead peers: {sorted(self.dead_peers) or None})")
                self._bar_released.discard(gen)
                self._bar_aborted.discard(gen)
                self._journal_barrier(gen, root, "ok")

    # -- clock alignment (causal traces): Cristian-style ping exchange --
    # lint: on-loop (periodic hook on the comm loop/progress thread)
    def probe_clocks(self, samples: Optional[int] = None) -> int:
        """Fire one offset-probe round at every live peer: ``samples``
        pings whose pongs fold into ``self.clock`` asynchronously (the
        estimator keeps the minimum-RTT sample).  TAG_CLOCK rides the
        control lane (_CTL_TAGS) so a ping measures protocol latency,
        not the bulk queue it would otherwise sit behind.  Returns the
        number of peers probed — the threaded progress loop retries
        quickly until the FIRST round actually went out."""
        if self.nranks == 1:
            return 0
        n = samples if samples is not None \
            else max(1, int(params.get("comm_clock_samples", 4)))
        probed = 0
        for r in range(self.nranks):
            if r == self.rank or r in self.dead_peers:
                continue
            probed += 1
            for _ in range(n):
                try:
                    self.send_am(TAG_CLOCK, r,
                                 {"k": "ping", "n": n,
                                  "t0": time.perf_counter()})
                except OSError:
                    break
        return probed

    # lint: on-loop (AM callback)
    def _clock_cb(self, src: int, msg: dict) -> None:
        if msg.get("k") == "ping":
            try:
                self.send_am(TAG_CLOCK, src,
                             {"k": "pong", "n": msg.get("n", 1),
                              "t0": msg["t0"],
                              "t1": time.perf_counter()})
            except OSError:
                pass
            return
        t2 = time.perf_counter()
        with self._clock_lock:
            pend = self._clock_pend.setdefault(src, [])
            pend.append((msg["t0"], msg["t1"], t2))
            if len(pend) < msg.get("n", 1):
                return
            samples, self._clock_pend[src] = list(pend), []
        self._clock_update(src, samples)

    def _clock_update(self, src: int, samples: List) -> None:
        off, rtt = clock_offset_estimate(samples)
        now = time.monotonic()
        accepted = True
        with self._clock_lock:
            st = self.clock.get(src)
            if st is None:
                self.clock[src] = {"offset": off, "rtt": rtt,
                                   "drift": 0.0, "measured_at": now}
            else:
                dt = now - st["measured_at"]
                # a round whose best rtt is much worse than what we
                # have seen is congestion, not clock motion — keep the
                # old estimate unless it has gone stale (then anything
                # beats extrapolating a minute-old offset)
                if rtt > 2.0 * st["rtt"] and dt < 60.0:
                    accepted = False
                else:
                    if dt > 1.0:
                        st["drift"] = (off - st["offset"]) / dt
                    st["offset"] = off
                    # the ACCEPTED sample's rtt, not an all-time
                    # minimum: the recorded value must bound the
                    # stored offset's error (rtt/2), and a ratcheted
                    # floor would make the congestion veto above
                    # monotonically stricter as host load rises
                    st["rtt"] = rtt
                    st["measured_at"] = now
        if not accepted:
            return
        cb = self.on_clock_rtt
        if cb is not None:
            try:
                cb(rtt)
            except Exception:   # telemetry must never hurt clock sync
                pass

    def clock_table(self) -> Dict[int, Dict[str, float]]:
        """Snapshot of the per-peer alignment state (trace headers)."""
        with self._clock_lock:
            return {r: dict(st) for r, st in self.clock.items()}

    # -- telemetry plane: TAG_METRICS pull/push + TAG_FLIGHT dumps ------
    # lint: on-loop (AM callback: builds a snapshot — short lock holds
    # in the registry — and replies on the control lane)
    def _metrics_cb(self, src: int, msg: dict) -> None:
        if msg.get("k") in ("pull", "jpull"):
            # "pull" = telemetry snapshot, "jpull" = control-plane
            # journal snapshot; both reply with a req-correlated push
            # so one reply/wait machinery serves both
            provider = self.metrics_provider if msg["k"] == "pull" \
                else self.journal_provider
            try:
                samples = provider() if provider is not None else []
            except Exception:   # a broken provider must not kill the loop
                samples = []
            try:
                self.send_am(TAG_METRICS, src,
                             {"k": "push", "req": msg.get("req"),
                              "rank": self.rank, "samples": samples})
            except OSError:
                pass   # puller died; its gather times out
            return
        with self._metrics_cond:
            pend = self._metrics_replies.get(msg.get("req"))
            if pend is not None:
                pend[int(msg.get("rank", src))] = msg.get("samples") or []
                self._metrics_cond.notify_all()

    def _gather(self, kind: str, timeout: float) -> Dict[int, Any]:
        """One req-correlated pull round at every live peer (the shared
        machinery under gather_metrics/gather_journals).  Blocks the
        CALLER — scrape threads (service/server.py), never the comm
        loop itself."""
        targets = [r for r in range(self.nranks)
                   if r != self.rank and r not in self.dead_peers]
        if not targets:
            return {}
        with self._metrics_cond:
            self._metrics_req += 1
            req = self._metrics_req
            self._metrics_replies[req] = {}
        reached = []
        for r in targets:
            try:
                self.send_am(TAG_METRICS, r, {"k": kind, "req": req})
                reached.append(r)
            except OSError:
                pass   # died since the dead_peers check: don't wait on it
        with self._metrics_cond:
            if reached:
                self._metrics_cond.wait_for(
                    lambda: len(self._metrics_replies[req])
                    >= len(reached),
                    timeout=timeout)
            return self._metrics_replies.pop(req, {})

    def gather_metrics(self, timeout: float = 2.0) -> Dict[int, Any]:
        """Pull every live peer's telemetry snapshot over TAG_METRICS;
        returns rank -> sample list (missing ranks timed out or died)."""
        return self._gather("pull", timeout)

    def gather_journals(self, timeout: float = 2.0) -> Dict[int, Any]:
        """Pull every live peer's control-plane journal snapshot (the
        job-port ``{"op": "journal"}`` surface and the hang autopsy's
        clock-aligned tail both ride this); rank -> snapshot dict."""
        out = self._gather("jpull", timeout)
        return {r: snap for r, snap in out.items()
                if isinstance(snap, dict) and snap}

    # lint: on-loop (AM callback — hands the dump to a timer thread so
    # file I/O never stalls the comm loop)
    def _flight_cb(self, src: int, msg: dict) -> None:
        cb = self.on_flight_dump
        if cb is None:
            return
        t = threading.Timer(0.0, cb, args=(
            str((msg or {}).get("reason", f"peer rank {src}")),))
        t.daemon = True
        t.start()

    # -- active failure detection: heartbeats + silence timeout ---------
    # lint: on-loop (AM callback)
    def _hb_cb(self, src: int, payload: Any) -> None:
        # receipt alone is the LIVENESS signal (_note_heard at the
        # framer); the arrival TIME additionally feeds the health
        # plane: per-peer inter-arrival EWMA + jitter, folded here at
        # heartbeat cadence (a handful of floats per period — nowhere
        # near the task hot path) and read by prof/health.py scrapes
        now = time.monotonic()
        st = self._hb_arrivals.get(src)
        if st is None:
            self._hb_arrivals[src] = {"at": now, "ewma": 0.0,
                                      "jit": 0.0, "n": 0.0}
            return
        gap = now - st["at"]
        st["at"] = now
        if st["n"] < 1.0:
            st["ewma"] = gap
        else:
            st["ewma"] += 0.3 * (gap - st["ewma"])
            st["jit"] += 0.3 * (abs(gap - st["ewma"]) - st["jit"])
        st["n"] += 1.0

    def hb_stats(self) -> Dict[int, Dict[str, float]]:
        """Per-peer heartbeat inter-arrival estimates for the health
        plane: smoothed gap (``ewma_s``), mean-absolute-deviation
        jitter (``jitter_s``), sample count and current silence age.
        Scrape-time accessor; the fold itself runs in _hb_cb."""
        now = time.monotonic()
        out: Dict[int, Dict[str, float]] = {}
        for r, st in list(self._hb_arrivals.items()):
            out[r] = {"ewma_s": round(st["ewma"], 6),
                      "jitter_s": round(st["jit"], 6),
                      "n": int(st["n"]),
                      "age_s": round(now - st["at"], 6)}
        return out

    def _note_heard(self, src: Optional[int]) -> None:
        if src is not None:
            self._last_heard[src] = time.monotonic()

    # lint: on-loop (periodic hook)
    def heartbeat_tick(self) -> None:
        """One heartbeat round at every live peer; rides the control
        lane so it measures protocol liveness, not bulk-queue depth.
        Driven by the remote-dep progress machinery on the TAG_CLOCK
        probe cadence (capped at comm_peer_timeout_s / 3)."""
        if self.nranks == 1 or self._muted:
            return
        for r in range(self.nranks):
            if r == self.rank or r in self.dead_peers:
                continue
            try:
                self._hb_send(r)
            except OSError:
                pass

    def _hb_send(self, r: int) -> None:
        """One heartbeat frame to ``r``.  Transports whose send path can
        BLOCK must override with a nonblocking discipline: the caller is
        the single progress thread that also runs check_peer_timeouts,
        and a detector wedged behind a hung peer's full send buffer (or
        a not-yet-dialed-in rank's 30s connect wait) cannot detect the
        very hang it exists to catch."""
        self.send_am(TAG_HB, r, None)

    # lint: on-loop (periodic hook)
    def check_peer_timeouts(self) -> None:
        """Declare peers silent past ``comm_peer_timeout_s`` dead — the
        detector for HUNG peers, whose sockets never close.  A starved
        checker (GIL/compile storm froze US, not them) rebases instead
        of declaring: our own silence proves nothing about theirs.

        The rebase is PER PEER (the PR 5 tradeoff refined): only peers
        whose last frame predates the stall window restart their
        silence clock — we were frozen for their whole silence, so it
        proves nothing.  A peer heard DURING the stall (socket recv
        threads, or the loop between stalls, kept stamping
        ``_last_heard``) keeps its real silence age, so one wedged
        SO_SNDTIMEO send no longer resets every OTHER peer's detection
        latency.  Rebases are counted per peer (``hb_rebase_total`` /
        ``hb_rebases``) so the detector's own behavior is observable
        in the metrics plane."""
        timeout = float(params.get("comm_peer_timeout_s", 15.0))
        if timeout <= 0 or self.nranks == 1 or self._muted:
            return
        now = time.monotonic()
        stall_start = self._hb_check_at
        starved = now - stall_start > timeout
        self._hb_check_at = now
        for r, at in list(self._last_heard.items()):
            if r in self.dead_peers:
                continue
            if starved:
                # a starved round never DECLARES — a process-wide
                # freeze (GIL/compile storm) may have parked unread
                # frames in the kernel, so every age is suspect.  But
                # only peers whose last frame predates the stall
                # restart their clock; one heard DURING the stall
                # keeps its true age, and the next healthy check —
                # one period away — declares on it if the silence is
                # real
                if at <= stall_start:
                    self._last_heard[r] = now
                    self.hb_rebase_total += 1
                    self._hb_rebases[r] = self._hb_rebases.get(r, 0) + 1
                continue
            if now - at > timeout:
                self.declare_peer_dead(r, PeerFailedError(
                    r, f"rank {self.rank}: no frames from rank {r} for "
                       f"{now - at:.1f}s (comm_peer_timeout_s="
                       f"{timeout:g})", detector="heartbeat"))

    def hb_rebases(self) -> Dict[int, int]:
        """Per-peer starved-checker rebase counts (metrics export)."""
        return dict(self._hb_rebases)

    # -- recovery plane (core/recovery.py) -------------------------------
    def excuse_peer(self, r: int) -> None:
        """Mark a dead rank ROUTED-AROUND: collectives and quiescence
        proceed over the survivors instead of failing on it."""
        first = r not in self.excused_peers
        self.excused_peers.add(r)
        with self._bar_cond:
            self._bar_cond.notify_all()
        jr = self.journal
        if jr is not None and first:
            jr.emit("peer_excused", peer=r)

    def peer_rejoined(self, r: int, epoch: int) -> None:
        """A restarted incarnation of ``r`` completed the TAG_REJOIN
        handshake: clear the death marks so traffic flows again (its
        transport connection was re-established at handshake time)."""
        self.dead_peers.discard(r)
        self.excused_peers.discard(r)
        self._note_heard(r)
        with self._bar_cond:
            self._bar_cond.notify_all()

    # lint: on-loop (AM callback)
    def _rejoin_cb(self, src: int, msg: Any) -> None:
        if not isinstance(msg, dict):
            return
        k = msg.get("k")
        if k == "req":
            cb = self.on_rejoin
            reply = None
            if cb is not None:
                try:
                    reply = cb(src, msg)
                except Exception as exc:
                    warning("rank %d: rejoin validation failed: %s",
                            self.rank, exc)
            if reply is None:
                reply = {"k": "deny"}
            try:
                self.send_am(TAG_REJOIN, src, reply)
            except OSError:
                pass   # the rejoiner vanished again; nothing to do
        elif k == "ack":
            with self._rejoin_cond:
                self._rejoin_ack = msg
                self._rejoin_cond.notify_all()
        # denies are NOT stashed: one fast deny (a survivor with a
        # higher fence) must not mask a later ack from a survivor that
        # already validated us and flipped peer_rejoined — the waiter
        # keeps waiting for an ack until its timeout

    # lint: on-loop (AM callback)
    def _recover_cb(self, src: int, msg: Any) -> None:
        """Recovery control lane: hand the message to the coordinator's
        handler (dead-set agreement + minimal-replay needs).  Handlers
        must not block — they store and signal only."""
        cb = self.on_recover
        if cb is not None and isinstance(msg, dict):
            try:
                cb(src, msg)
            except Exception as exc:
                warning("rank %d: recovery control message from %d "
                        "failed: %s", self.rank, src, exc)

    def wait_rejoin_ack(self, timeout: float) -> Optional[dict]:
        """Block for a rejoin ACK (restarted-rank side); None when no
        survivor acknowledged within the timeout (all denied or
        unreachable)."""
        with self._rejoin_cond:
            self._rejoin_cond.wait_for(
                lambda: self._rejoin_ack is not None, timeout=timeout)
            ack = self._rejoin_ack
            self._rejoin_ack = None
        return ack

    def declare_peer_dead(self, r: int, exc: Exception) -> None:
        """Shared death path (EOF, corruption, heartbeat silence): mark,
        drop the transport state, wake barrier waiters, and route the
        failure through containment."""
        if r in self.dead_peers or self._stop_requested():
            return
        warning("rank %d: declaring rank %d dead: %s", self.rank, r, exc)
        jr = self.journal
        if jr is not None:
            jr.emit("peer_dead", peer=r,
                    detector=getattr(exc, "detector", "unknown"))
        self.dead_peers.add(r)
        self._drop_peer(r)
        with self._bar_cond:
            self._bar_cond.notify_all()
        self._peer_failure(r, exc)

    def _stop_requested(self) -> bool:
        return bool(getattr(self, "_stop", False))

    def _drop_peer(self, r: int) -> None:
        pass   # transports close the peer's socket / clear its queues

    def _peer_failure(self, r: int, exc: Exception) -> None:
        cb = self.on_peer_dead
        if cb is not None:
            try:
                cb(r, exc)
                return
            except Exception as route_exc:   # containment must not mask
                warning("rank %d: peer-death containment failed: %s",
                        self.rank, route_exc)
        if self.on_error is not None:
            self.on_error(exc)

    def peer_debug(self) -> Dict[int, Dict[str, Any]]:
        """Per-peer liveness/queue snapshot for the hang autopsy."""
        now = time.monotonic()
        out: Dict[int, Dict[str, Any]] = {}
        for r, at in list(self._last_heard.items()):   # recv threads insert
            out[r] = {"last_heard_age_s": round(now - at, 3),
                      "dead": r in self.dead_peers}
            reb = self._hb_rebases.get(r)
            if reb:
                out[r]["hb_rebases"] = reb
        for r in list(self.dead_peers):
            out.setdefault(r, {"dead": True})
        return out

    # -- fault injection (utils/faultinject.py hook points) -------------
    def _arm_kill(self) -> None:
        """Schedule this rank's kill_rank directive, if any."""
        if self._fault is None or self._fault.kill is None:
            return
        k = self._fault.kill
        t = threading.Timer(max(0.0, k.at_s), self.fault_kill,
                            args=(k.mode,))
        t.daemon = True
        t.start()

    def fault_kill(self, mode: str = "close") -> None:
        """Injected rank death.  ``close`` hard-closes every socket (the
        EOF detector path); ``hang`` goes silent with sockets open (only
        the heartbeat timeout can see it)."""
        warning("rank %d: FAULT INJECTION kill_rank fired (mode=%s)",
                self.rank, mode)
        #: the recovery plane must never "recover" the killed rank's own
        #: view of its peers — that would split-brain the gang
        self.fault_killed = True
        if mode == "hang":
            self._muted = True
            return
        self._kill_close()

    def _kill_close(self) -> None:
        raise NotImplementedError

    def _fault_frame(self, tag: int, dst: int, payload: Any) -> bool:
        """Apply a matching frame directive to an outbound frame;
        returns True when the frame was consumed (drop/delay/trunc) —
        dup sends the extra copy and falls through to the normal send."""
        act = self._fault.frame_action(tag, dst, payload)
        if act is None:
            return False
        kind, ms = act
        debug_verbose(3, "rank %d: FAULT %s_frame tag=%d dst=%d",
                      self.rank, kind, tag, dst)
        if kind == "drop":
            if self.on_frame_fault is not None:
                self.on_frame_fault("drop", tag, payload, dst)
            return True
        if kind == "delay":
            def _delayed_send():
                try:
                    self.send_am(tag, dst, payload, _nofault=True)
                except OSError:
                    # the lane died while the frame was held: reconcile
                    # like a drop, or the Safra balance leaks the held
                    # frame's count forever
                    if self.on_frame_fault is not None:
                        self.on_frame_fault("drop", tag, payload, dst)
            t = threading.Timer(ms * 1e-3, _delayed_send)
            t.daemon = True
            t.start()
            return True
        if kind == "dup":
            if self.on_frame_fault is not None:
                self.on_frame_fault("dup", tag, payload, dst)
            self.send_am(tag, dst, payload, _nofault=True)
            return False
        if kind == "trunc":
            # an undecodable frame: the receiver severs the connection
            # (the wire-corruption detector); the frame's message never
            # arrives, so reconcile the balance like a drop
            if self.on_frame_fault is not None:
                self.on_frame_fault("drop", tag, payload, dst)
            try:
                self._send_raw_parts(
                    dst, [_LEN.pack(tag, 8, 0), b"\xde\xad\xbe\xef" * 2])
            except OSError:
                pass
            return True
        return False

    def _send_raw_parts(self, dst: int, parts: List[Any]) -> None:
        raise NotImplementedError

    def _recv_fault_hold(self, tag: int, src: int, payload: Any) -> bool:
        """Recv-side delay injection (utils/faultinject ``delay_recv``):
        hold a just-received, already-decoded frame for its directive's
        ``ms`` while LATER frames — same peer and others — dispatch
        first.  This is reorder coverage the send-side ``delay_frame``
        cannot provide: TCP delivers each stream in order, so only a
        post-framing hold reorders the RECEIVE path.  Returns True when
        the frame was consumed (redelivery is scheduled); callers then
        skip their normal dispatch.  Counters stay honest: the frame
        was received (frames_recv already bumped), and the handler-side
        Safra credit lands at the delayed dispatch — the in-flight
        window is visible to the termination balance."""
        f = self._fault
        if f is None:
            return False
        ms = f.recv_delay_ms(tag, src, payload)
        if ms is None:
            return False
        debug_verbose(3, "rank %d: FAULT delay_recv tag=%d src=%d ms=%g",
                      self.rank, tag, src, ms)
        t = threading.Timer(ms * 1e-3, self._deliver_held,
                            args=(tag, src, payload))
        t.daemon = True
        t.start()
        return True

    def _deliver_held(self, tag: int, src: int, payload: Any) -> None:
        """Timer-thread redelivery of a held frame.  Fine as-is on the
        threaded transport (handlers already run on per-peer recv
        threads); the funnelled event loop overrides to re-post onto
        its loop thread."""
        try:
            self._dispatch(tag, src, payload)
        except Exception as exc:
            warning("rank %d: held-frame handler tag=%d failed: %s",
                    self.rank, tag, exc)
            if self.on_error is not None:
                self.on_error(exc)

    # -- pack/unpack (reference: ce.pack/unpack) ------------------------
    @staticmethod
    def pack(arr) -> dict:
        """Snapshot an array payload for the wire.  ONE owned copy here
        — the snapshot contract: the source tile may be mutated in place
        by later tasks before the comm thread serializes the frame, so
        the payload must be frozen at encode time.  The copy stays an
        ndarray and ships OUT OF BAND (pickle protocol 5 + gather-send),
        so this is the only copy on the send path (tobytes + in-band
        pickling + the join used to make three)."""
        import numpy as np
        a = np.array(np.asarray(arr), order="C", copy=True)
        return {"buf": a, "dtype": wire_dtype(a.dtype),
                "shape": a.shape}

    @staticmethod
    def unpack(msg: dict):
        import numpy as np
        buf = msg["buf"]
        if isinstance(buf, np.ndarray):
            # out-of-band delivery: the array already views the freshly
            # received (private, writable) buffer — no copy needed
            return np.asarray(buf, dtype=parse_dtype(msg["dtype"])) \
                .reshape(msg["shape"])
        return np.frombuffer(buf, dtype=parse_dtype(msg["dtype"])) \
            .reshape(msg["shape"]).copy()

    # -- registered memory + one-sided put/get (reference: ce.mem_register
    # / ce.put:793 / ce.get:896 of parsec_mpi_funnelled.c — emulated over
    # two-sided AM exactly like the reference's MPI module) --------------
    def mem_register(self, array, once: bool = False) -> int:
        """Expose a writable array to one-sided access; returns the
        region handle peers name in put/get.  ``once`` auto-unregisters
        after the first successful GET (rendezvous payloads: exactly one
        consumer pulls, then the region is gone)."""
        with self._reg_lock:
            self._region_seq += 1
            rid = self._region_seq
            self._regions[rid] = array
            if once:
                self._once_regions[rid] = time.monotonic()
        return rid

    def mem_unregister(self, rid: int) -> None:
        with self._reg_lock:
            self._regions.pop(rid, None)
            self._once_regions.pop(rid, None)

    def purge_once_regions(self, ttl: float) -> int:
        """Drop serve-once regions nobody pulled within ``ttl`` seconds
        (a consumer that died or errored out must not strand the
        producer's payload snapshot forever); returns the count purged.
        Driven by the comm-progress purge alongside the rendezvous
        handle GC."""
        now = time.monotonic()
        purged = 0
        with self._reg_lock:
            for rid, born in list(self._once_regions.items()):
                if now - born > ttl:
                    del self._once_regions[rid]
                    self._regions.pop(rid, None)
                    purged += 1
        if purged:
            warning("rank %d: dropped %d unclaimed serve-once region(s) "
                    "after %.0fs", self.rank, purged, ttl)
        return purged

    def _register_onesided(self) -> None:
        """Wire the put/get emulation tags (called by subclasses once
        transport recv is up)."""
        self.tag_register(TAG_PUT, self._put_cb)
        self.tag_register(TAG_GET1, self._get1_cb)
        self.tag_register(TAG_GET1_REP, self._get1_rep_cb)

    def put(self, dst: int, local_array, remote_rid: int,
            on_complete: Optional[Callable] = None) -> None:
        """Write ``local_array`` into peer ``dst``'s registered region;
        ``on_complete(error=None)`` runs on the comm thread once the
        remote copy landed — or failed (reference: mpi_no_thread_put)."""
        with self._reg_lock:
            self._osc_seq += 1
            op = self._osc_seq
            if on_complete is not None:
                self._osc[op] = ("put", on_complete)
        self.send_am(TAG_PUT, dst, {"rid": remote_rid, "op": op,
                                    "from": self.rank,
                                    **self.pack(local_array)})

    def get(self, dst: int, remote_rid: int,
            on_data: Callable) -> None:
        """Fetch peer ``dst``'s registered region; ``on_data(array)``
        runs on the comm thread (``None`` on failure; reference:
        mpi_no_thread_get)."""
        with self._reg_lock:
            self._osc_seq += 1
            op = self._osc_seq
            self._osc[op] = ("get", on_data)
        self.send_am(TAG_GET1, dst, {"rid": remote_rid, "op": op,
                                     "from": self.rank})

    def _osc_fail(self, dst: int, op: int, why: str) -> None:
        """An op that cannot complete still gets a terminal reply — a
        silent drop would leak the originator's callback and hang its
        waiter."""
        self.send_am(TAG_GET1_REP, dst, {"op": op, "error": why})

    # lint: on-loop (AM callback)
    def _put_cb(self, src: int, msg: dict) -> None:
        import numpy as np
        # hold the lock across the copy: concurrent put/get on one
        # region from different peer recv threads must not tear
        with self._reg_lock:
            target = self._regions.get(msg["rid"])
            if target is not None:
                tgt = np.asarray(target)
                try:
                    # zero-copy source view straight into the region
                    src_view = np.frombuffer(
                        msg["buf"],
                        dtype=parse_dtype(msg["dtype"])).reshape(tgt.shape)
                    np.copyto(tgt, src_view)
                except (TypeError, ValueError) as exc:
                    self._osc_fail(msg["from"], msg["op"], str(exc))
                    return
        if target is None:
            warning("rank %d: PUT into unknown region %s", self.rank,
                    msg["rid"])
            self._osc_fail(msg["from"], msg["op"], "unknown region")
            return
        self.send_am(TAG_GET1_REP, msg["from"],
                     {"op": msg["op"], "ack": True})

    # lint: on-loop (AM callback)
    def _get1_cb(self, src: int, msg: dict) -> None:
        with self._reg_lock:
            target = self._regions.get(msg["rid"])
            packed = self.pack(target) if target is not None else None
            if packed is not None and msg["rid"] in self._once_regions:
                del self._once_regions[msg["rid"]]
                del self._regions[msg["rid"]]
        if packed is None:
            warning("rank %d: GET of unknown region %s", self.rank,
                    msg["rid"])
            self._osc_fail(msg["from"], msg["op"], "unknown region")
            return
        self.send_am(TAG_GET1_REP, msg["from"],
                     {"op": msg["op"], **packed})

    # lint: on-loop (AM callback)
    def _get1_rep_cb(self, src: int, msg: dict) -> None:
        with self._reg_lock:
            ent = self._osc.pop(msg["op"], None)
        if ent is None:
            return
        kind, cb = ent
        err = msg.get("error")
        if err is not None:
            warning("rank %d: one-sided op %d failed at peer %d: %s",
                    self.rank, msg["op"], src, err)
        if kind == "put":
            cb(err)
        else:
            cb(None if err is not None else self.unpack(msg))

    def _dispatch(self, tag: int, src: int, payload: Any) -> None:
        mark("recv tag=%d src=%d", tag, src)
        with self._cb_lock:
            cb = self._callbacks.get(tag)
            if cb is None:
                self._undelivered.setdefault(tag, []).append((src, payload))
                return
        cb(src, payload)

    def _safe_dispatch(self, tag: int, src: int, payload: Any) -> None:
        try:
            self._dispatch(tag, src, payload)
        except Exception as exc:   # handler error must not kill the loop,
            warning("rank %d: AM handler tag=%d failed: %s",
                    self.rank, tag, exc)
            if self.on_error is not None:   # ...but must fail the rank
                self.on_error(exc)

    def _deliver_frames(self, frames, src: int, native: bool,
                        sever: Callable[[str], None],
                        alive: Callable[[], bool]) -> bool:
        """Shared delivery of parser-completed frames (the evloop and
        shm transports' one dispatch loop): stats, unpickle, recv-side
        fault holds, dispatch.  ``sever(why)`` is the transport's
        corruption path; ``alive()`` says whether to keep dispatching
        after a handler ran (it may have torn the peer down).  Returns
        False when the caller must stop reading this peer."""
        for tag, body, oob in frames:
            self.recv_msgs += 1
            self.stats.frames_recv += 1
            if native:
                self.stats.frames_parsed_native += 1
            self._note_heard(src)
            if body is not None:
                try:
                    payload = pickle.loads(body, buffers=oob)
                except Exception as exc:
                    sever(f"undecodable frame tag={tag}: {exc}")
                    return False
            else:
                payload = None
            if self._fault is not None and \
                    self._recv_fault_hold(tag, src, payload):
                if not alive():
                    return False
                continue   # redelivery scheduled; later frames flow
            self._safe_dispatch(tag, src, payload)
            if not alive():
                return False
        return True


class SocketCE(CommEngine):
    """TCP active-message engine (the mpi_funnelled analog)."""

    TRANSPORT = "threads"

    def __init__(self, rank: int, nranks: int,
                 port_base: Optional[int] = None):
        super().__init__(rank, nranks)
        if port_base is None:
            port_base = int(params.get("comm_port_base", 0)) or \
                int(os.environ.get("PARSEC_COMM_PORT_BASE", 23500))
        self.port_base = port_base
        # multi-host address book (the DCN story: one rank per host, the
        # same engine; reference: the MPI module gets this from mpiexec)
        hosts = str(params.get("comm_hosts", "") or
                    os.environ.get("PARSEC_COMM_HOSTS", "")).strip()
        self._hosts = [h.strip() for h in hosts.split(",")] if hosts else []
        if self._hosts and len(self._hosts) != nranks:
            raise ValueError(
                f"comm_hosts names {len(self._hosts)} hosts for "
                f"{nranks} ranks")
        #: canonical peer sockets + per-peer send serialization; both
        #: resized by accept/connect/death paths on different threads
        #: (guarded-by: _plock)
        self._peers: Dict[int, socket.socket] = {}
        self._send_locks: Dict[int, threading.Lock] = {}  # guarded-by: _plock
        self._plock = threading.Lock()
        self._stop = False
        self._threads: List[threading.Thread] = []
        self._register_onesided()

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # buffer size must be set BEFORE listen(): accepted sockets
        # inherit it, and the receive window is negotiated at the
        # handshake (man 7 tcp)
        _bump_sockbufs(self._listener)
        self._listener.bind(("0.0.0.0" if self._hosts else "127.0.0.1",
                             self.port_base + rank))
        self._listener.listen(nranks)
        t = threading.Thread(target=self._accept_loop,
                             name=f"ce-accept-{rank}", daemon=True)
        t.start()
        self._threads.append(t)
        # Deterministic connection direction: the HIGHER rank initiates to
        # each lower rank, eagerly at init, so a pair can never cross-
        # connect simultaneously and close each other's canonical socket.
        for dst in range(rank):
            self._connect(dst)
        self._arm_kill()

    # -- connection management -------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            _bump_sockbufs(conn)
            self._bound_send(conn)
            # peer announces magic + protocol version + rank first: a
            # stranger or cross-version peer fails ITS connection here
            hdr = self._recv_exact(conn, _HANDSHAKE.size)
            if hdr is None:
                conn.close()
                continue
            magic, ver, src = _HANDSHAKE.unpack(hdr)
            if magic != _WIRE_MAGIC or ver != _WIRE_VERSION:
                warning("rank %d: rejected connection with bad handshake "
                        "(magic=%r version=%r)", self.rank, magic, ver)
                conn.close()
                continue
            if src in self.dead_peers and not self.rejoin_allowed:
                # no rejoin protocol armed: a dead rank's reconnection
                # would be a half-connected zombie (frames dispatched
                # while every reply is refused by the dead-peer guard)
                warning("rank %d: rejected reconnection from dead rank "
                        "%d", self.rank, src)
                conn.close()
                continue
            if src in self.dead_peers:
                warning("rank %d: reconnection from dead rank %d "
                        "accepted pending TAG_REJOIN handshake",
                        self.rank, src)
            with self._plock:
                self._peers.setdefault(src, conn)
                self._send_locks.setdefault(src, threading.Lock())
            self._note_heard(src)
            t = threading.Thread(target=self._recv_loop, args=(conn, src),
                                 name=f"ce-recv-{self.rank}<-{src}",
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _connect(self, dst: int) -> socket.socket:
        with self._plock:
            s = self._peers.get(dst)
            if s is not None:
                return s
        if dst > self.rank:
            # the higher rank owns the initiation: wait for its inbound
            deadline = time.monotonic() + 30
            while True:
                with self._plock:
                    s = self._peers.get(dst)
                if s is not None:
                    return s
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"rank {self.rank}: no connection from {dst}")
                time.sleep(0.01)
        peer_host = self._hosts[dst] if self._hosts else "127.0.0.1"
        s = _dial_peer(peer_host, self.port_base + dst, self.rank)
        self._bound_send(s)
        with self._plock:
            self._peers[dst] = s
            self._send_locks.setdefault(dst, threading.Lock())
        self._note_heard(dst)
        t = threading.Thread(target=self._recv_loop, args=(s, dst),
                             name=f"ce-recv-{self.rank}<-{dst}", daemon=True)
        t.start()
        self._threads.append(t)
        return s

    # -- framing -----------------------------------------------------------
    def _bound_send(self, s: socket.socket) -> None:
        """Bound blocking sends with SO_SNDTIMEO (send-only; recv loops
        keep blocking indefinitely by design): a hung peer that stopped
        draining must not wedge the single progress thread — which also
        runs check_peer_timeouts — inside sendmsg forever.  2x the
        detection timeout: a lane that cannot drain one frame in that
        long is dead for every practical purpose."""
        pt = float(params.get("comm_peer_timeout_s", 15.0))
        if pt <= 0:
            return
        t = 2.0 * pt
        try:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                         struct.pack("ll", int(t), int((t % 1.0) * 1e6)))
        except OSError:
            pass

    def _recv_exact(self, conn: socket.socket, n: int,
                    src: Optional[int] = None) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            try:
                chunk = conn.recv(n - len(buf))
            except OSError:
                return None
            if not chunk:
                return None
            self.stats.syscalls_recv += 1
            self.stats.bytes_recv += len(chunk)
            # liveness per CHUNK, not per completed frame: a frame whose
            # transmission outlasts comm_peer_timeout_s must not get its
            # actively-sending peer declared dead mid-transfer
            self._note_heard(src)
            buf += chunk
        return buf

    def _recv_into(self, conn: socket.socket, n: int,
                   src: Optional[int] = None) -> Optional[bytearray]:
        """Receive ``n`` bytes straight into one buffer (no quadratic
        bytes-concatenation; the out-of-band payload path)."""
        buf = bytearray(n)
        view = memoryview(buf)
        got = 0
        while got < n:
            try:
                r = conn.recv_into(view[got:], n - got)
            except OSError:
                return None
            if r == 0:
                return None
            self.stats.syscalls_recv += 1
            self.stats.bytes_recv += r
            self._note_heard(src)   # per chunk (see _recv_exact)
            got += r
        return buf

    def _recv_loop(self, conn: socket.socket, src: int) -> None:
        max_ln = int(params.get("comm_max_frame_mb", 4096)) << 20
        while not self._stop:
            if self._muted:
                # injected silent hang: stop consuming (data piles up in
                # the kernel buffer; our socket stays open and mute)
                time.sleep(0.05)
                continue
            hdr = self._recv_exact(conn, _LEN.size, src)
            if hdr is None:
                self._peer_lost(src)
                return
            tag, ln, nbufs = _LEN.unpack(hdr)
            if ln > max_ln or nbufs > 4096:
                # corrupt stream (or hostile length): sever THIS
                # connection with a cause instead of trying to consume
                # an absurd frame — the guard VERDICT r2 asked for
                self._peer_corrupt(src, conn,
                                   f"frame length {ln}/{nbufs} bufs "
                                   f"exceeds the {max_ln >> 20} MiB "
                                   f"bound (tag={tag})")
                return
            data = self._recv_exact(conn, ln, src) if ln else b""
            if data is None:
                self._peer_lost(src)
                return
            oob: List[bytearray] = []
            corrupt = None
            for _ in range(nbufs):
                bhdr = self._recv_exact(conn, _BUFLEN.size, src)
                if bhdr is None:
                    self._peer_lost(src)
                    return
                (bln,) = _BUFLEN.unpack(bhdr)
                if bln > max_ln:
                    corrupt = f"oob buffer length {bln} (tag={tag})"
                    break
                buf = self._recv_into(conn, bln, src)
                if buf is None:
                    self._peer_lost(src)
                    return
                oob.append(buf)
            if corrupt is not None:
                self._peer_corrupt(src, conn, corrupt)
                return
            self.recv_msgs += 1
            self.stats.frames_recv += 1
            self._note_heard(src)
            try:
                payload = pickle.loads(data, buffers=oob) if data else None
            except Exception as exc:
                # undecodable frame = wire corruption: fail the
                # connection, not the handler path
                self._peer_corrupt(src, conn,
                                   f"undecodable frame tag={tag}: {exc}")
                return
            if self._fault is not None and \
                    self._recv_fault_hold(tag, src, payload):
                continue   # redelivery scheduled; later frames flow
            try:
                self._dispatch(tag, src, payload)
            except Exception as exc:   # handler error must not kill recv,
                warning("rank %d: AM handler tag=%d failed: %s",
                        self.rank, tag, exc)
                if self.on_error is not None:   # ...but must fail the rank
                    self.on_error(exc)

    def _peer_corrupt(self, src: int, conn: socket.socket,
                      why: str) -> None:
        try:
            conn.close()
        except OSError:
            pass
        self.declare_peer_dead(src, PeerFailedError(
            src, f"rank {self.rank}: protocol corruption from rank "
                 f"{src}: {why}", detector="corrupt"))

    def _peer_lost(self, src: int) -> None:
        """Failure detection: a peer's socket closed while we are still
        running (the reference has NO fault tolerance — it aborts; here
        the loss surfaces as a contained PeerFailedError AND wakes
        barrier/quiescence waiters so they fail fast with a cause
        instead of hanging to their timeouts)."""
        self.declare_peer_dead(src, PeerFailedError(
            src, f"rank {self.rank}: peer rank {src} disconnected "
                 "mid-run"))

    def _drop_peer(self, r: int) -> None:
        with self._plock:
            s = self._peers.pop(r, None)
        if s is not None:
            try:
                s.close()
            except OSError:
                pass

    def _kill_close(self) -> None:
        """Injected hard death: every socket closes abruptly (peers see
        EOF); the engine object stays nominally alive."""
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        with self._plock:
            peers, self._peers = dict(self._peers), {}
        for s in peers.values():
            try:
                s.close()
            except OSError:
                pass

    def _send_raw_parts(self, dst: int, parts: List[Any]) -> None:
        s = self._connect(dst)
        with self._send_locks[dst]:
            self._sendmsg_all(s, parts)

    def probe_clocks(self, samples: Optional[int] = None) -> int:
        # clock pings ride send_am, and send_am to an undialed higher
        # rank parks in _connect's 30s wait — which would starve the
        # progress thread that also runs the failure detectors (the
        # _hb_send lesson).  Probe ESTABLISHED peers only; a peer still
        # dialing in gets its first round once the progress loop sees
        # it established (the fast first-round retry).
        if self.nranks == 1 or self._muted:
            return 0
        n = samples if samples is not None \
            else max(1, int(params.get("comm_clock_samples", 4)))
        with self._plock:
            established = [r for r in self._peers
                           if r != self.rank and
                           r not in self.dead_peers]
        for r in established:
            for _ in range(n):
                try:
                    self.send_am(TAG_CLOCK, r,
                                 {"k": "ping", "n": n,
                                  "t0": time.perf_counter()})
                except OSError:
                    break
        return len(established)

    def _hb_send(self, r: int) -> None:
        # NEVER block the progress thread on a heartbeat: only beat
        # ESTABLISHED connections (send_am to an undialed higher rank
        # parks in _connect's 30s wait), skip when a send is already in
        # flight on the lane, and skip when the kernel buffer is full —
        # a hung peer that stopped draining would otherwise wedge the
        # thread that runs check_peer_timeouts behind a blocking
        # sendmsg.  A skipped beat only delays the PEER's view of us by
        # one tick; our own view of them rides _last_heard regardless.
        if self._muted:
            return
        with self._plock:
            s = self._peers.get(r)
        lock = self._send_locks.get(r)
        if s is None or lock is None or not lock.acquire(blocking=False):
            return
        try:
            try:
                if hasattr(select, "poll"):
                    # poll has no FD_SETSIZE: select.select raises
                    # ValueError for fds >= 1024 (a resident service
                    # holds thousands) and that would kill the thread
                    po = select.poll()
                    po.register(s.fileno(), select.POLLOUT)
                    writable = bool(po.poll(0))
                else:
                    writable = bool(select.select([], [s], [], 0)[1])
            except (OSError, ValueError):
                return
            if not writable:
                return   # send buffer full: beating it would block
            self.sent_msgs += 1
            self.stats.frames_sent += 1
            self._sendmsg_all(s, _frame_parts(TAG_HB, None))
        finally:
            lock.release()

    def send_am(self, tag: int, dst: int, payload: Any = None,
                _nofault: bool = False) -> None:
        mark("send_am tag=%d dst=%d", tag, dst)
        if dst == self.rank:
            # local delivery short-circuit (counts as a message so the
            # termination balance stays symmetric)
            self.sent_msgs += 1
            self.recv_msgs += 1
            self._dispatch(tag, self.rank, payload)
            return
        if self._muted:
            return   # injected silent hang swallows every outbound frame
        if dst in self.dead_peers:
            # the closed socket used to raise OSError from sendmsg; now
            # that death drops the peer entry, raise the same class
            # rather than re-dialing a corpse for 30s
            raise OSError(f"peer rank {dst} is dead")
        if self._fault is not None and not _nofault \
                and self._fault_frame(tag, dst, payload):
            return
        parts = _frame_parts(tag, payload)
        s = self._connect(dst)
        with self._send_locks[dst]:
            self.sent_msgs += 1
            self.stats.frames_sent += 1
            try:
                self._sendmsg_all(s, parts)
            except (socket.timeout, BlockingIOError):
                # SO_SNDTIMEO fired (_bound_send): the peer stopped
                # draining for 2x comm_peer_timeout_s and the frame is
                # torn mid-stream — fail the lane like an EOF so the
                # progress thread (which also runs the hung-peer
                # detector) is never wedged inside sendmsg
                try:
                    s.close()
                except OSError:
                    pass
                self._peer_lost(dst)
                raise OSError(
                    f"rank {self.rank}: send to rank {dst} timed out "
                    "(peer not draining)")

    def _sendmsg_all(self, s: socket.socket, parts: List[Any]) -> None:
        """Gather-send every part (scatter-gather keeps large array
        buffers out of any join copy); loops on partial sends."""
        views = [memoryview(p) for p in parts if len(p)]
        while views:
            sent = s.sendmsg(views)
            self.stats.syscalls_send += 1
            self.stats.bytes_sent += sent
            while sent and views:
                head = views[0]
                if sent >= head.nbytes:
                    sent -= head.nbytes
                    views.pop(0)
                else:
                    views[0] = head[sent:]
                    sent = 0

    def fini(self) -> None:
        self._stop = True
        try:
            # close() alone leaves the port LISTENING while the accept
            # thread is blocked in accept() (the kernel socket ref is
            # held by the syscall): shutdown() wakes it so the port is
            # actually released before fini returns
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        with self._plock:
            for s in self._peers.values():
                try:
                    s.close()
                except OSError:
                    pass
            self._peers.clear()
        for t in self._threads:
            if t is not threading.current_thread():
                t.join(timeout=1)
        debug_verbose(5, "rank %d CE down: sent=%d recv=%d",
                      self.rank, self.sent_msgs, self.recv_msgs)


# ---------------------------------------------------------------------------
# event-loop transport (the single-threaded comm engine)
# ---------------------------------------------------------------------------

#: control-plane tags jump the per-peer output queue ahead of bulk data
#: frames (a termination token or GET request must not wait behind a
#: multi-MB payload drain); a partially-written frame is never preempted
_CTL_TAGS = frozenset((TAG_TERMDET, TAG_BARRIER, TAG_GET_REQ, TAG_UTRIG,
                       TAG_CLOCK, TAG_HB, TAG_METRICS, TAG_FLIGHT,
                       TAG_RECOVER))

#: receive state machine stages
_ST_HS, _ST_HDR, _ST_BODY, _ST_BLEN, _ST_BUF = range(5)

_IOV_CAP = 64          # views gathered per sendmsg (Linux IOV_MAX=1024)
_RECV_BUDGET = 4 << 20  # bytes drained per readable event before yielding
_EWMA = 0.2            # feedback smoothing for the adaptive protocol


class _EvPeer:
    """Per-connection state of the event loop: an incremental receive
    parser (frames assemble across partial reads, large payloads
    ``recv_into`` their own preallocated buffer directly) plus
    priority-ordered output queues with partial-write resume."""

    __slots__ = (
        "rank", "sock", "born", "registered",
        # receive state machine
        "r_stage", "r_want", "r_got", "r_view", "r_buf", "r_small",
        "r_tag", "r_ln", "r_nbufs", "r_body", "r_oob",
        # native frame parser (comm/frames.py make_parser): when set,
        # the receive path feeds it instead of the inline machinery
        "fparser", "fp_native",
        # send side: queued frames -> wire-committed views -> kernel
        "q_ctl", "q_bulk", "wire", "marks", "out_bytes", "want_write",
        # adaptive-protocol feedback (updated as frames drain)
        "delay_ewma", "rate_ewma",
    )

    def __init__(self, rank: Optional[int], sock: Optional[socket.socket]):
        self.rank = rank
        self.sock = sock
        self.born = time.monotonic()
        self.registered = False
        self.r_small = bytearray(_LEN.size)
        self.r_stage = _ST_HDR
        self.r_want = _LEN.size
        self.r_got = 0
        self.r_view = memoryview(self.r_small)
        self.r_buf: Optional[bytearray] = None
        self.r_tag = self.r_ln = self.r_nbufs = 0
        self.r_body: Any = b""
        self.r_oob: List[bytearray] = []
        self.fparser = None
        self.fp_native = False
        self.q_ctl: deque = deque()
        self.q_bulk: deque = deque()
        self.wire: deque = deque()   # memoryviews committed to wire order
        self.marks: deque = deque()  # [bytes_left, t_enq, total] per frame
        self.out_bytes = 0
        self.want_write = False
        self.delay_ewma: Optional[float] = None
        self.rate_ewma: Optional[float] = None


class EventLoopCE(CommEngine):
    """Single-threaded nonblocking event-loop transport: ONE comm thread
    owns accept, recv, AND send for every peer socket through a
    ``selectors`` loop — the reference's dedicated-comm-thread model
    (parsec_remote_dep.c progress thread making nonblocking MPI progress
    over all peers) rebuilt over TCP.  A 2-rank exchange on one core
    costs zero cross-thread wakeups on the data path: the AM callback
    runs on the loop thread, and a handler's reply frames go straight to
    ``sendmsg`` from the same stack.

    Cross-thread sends (workers flushing activations, user code) ride a
    lock-free command ring (``collections.deque``) with one self-pipe
    wakeup, written only when the loop is parked in ``select``.  Sends
    become per-peer priority-ordered output queues drained on EPOLLOUT
    with vectored ``sendmsg`` gather writes (many small frames coalesce
    into one syscall) and explicit backpressure: a partial write parks
    the remaining views in per-peer resume state and registers write
    interest instead of spinning.

    The remote-dep layer detects ``FUNNELLED`` and folds its progress
    loop in here (no separate progress thread, no per-peer recv
    threads); ``post``/``add_periodic`` are its hooks.
    """

    FUNNELLED = True   # callbacks + sends are funnelled onto ONE thread
    CAP_MT = True      # send_am remains thread-safe (via the ring)
    TRANSPORT = "evloop"

    def __init__(self, rank: int, nranks: int,
                 port_base: Optional[int] = None):
        super().__init__(rank, nranks)
        if port_base is None:
            port_base = int(params.get("comm_port_base", 0)) or \
                int(os.environ.get("PARSEC_COMM_PORT_BASE", 23500))
        self.port_base = port_base
        hosts = str(params.get("comm_hosts", "") or
                    os.environ.get("PARSEC_COMM_HOSTS", "")).strip()
        self._hosts = [h.strip() for h in hosts.split(",")] if hosts else []
        if self._hosts and len(self._hosts) != nranks:
            raise ValueError(
                f"comm_hosts names {len(self._hosts)} hosts for "
                f"{nranks} ranks")
        self._max_frame = int(params.get("comm_max_frame_mb", 4096)) << 20
        self._peers: Dict[int, _EvPeer] = {}
        self._anon: set = set()          # accepted, handshake pending
        self._stop = False
        self._sel = selectors.DefaultSelector()
        self._ring: deque = deque()
        self._sleeping = False
        rfd, wfd = os.pipe()
        os.set_blocking(rfd, False)
        os.set_blocking(wfd, False)
        self._wake_r, self._wake_w = rfd, wfd
        self._scratch = bytearray(256 << 10)
        self._scratch_mv = memoryview(self._scratch)
        self._timers: List[list] = []
        self._register_onesided()

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        _bump_sockbufs(self._listener)
        self._listener.bind(("0.0.0.0" if self._hosts else "127.0.0.1",
                             self.port_base + rank))
        self._listener.listen(nranks)
        self._listener.setblocking(False)
        self._sel.register(self._listener, selectors.EVENT_READ,
                           ("accept", None))
        self._sel.register(rfd, selectors.EVENT_READ, ("wake", None))
        self._thread = threading.Thread(target=self._loop,
                                        name=f"ce-loop-{rank}", daemon=True)
        self._thread.start()
        # Deterministic connection direction (same as the threaded
        # transport): the HIGHER rank initiates to each lower rank at
        # init; a send to a not-yet-dialed-in higher rank just queues.
        self._post(("timer", self._check_unconnected, 5.0))
        try:
            for dst in range(rank):
                self._dial(dst)
        except OSError:
            # a failed dial must not abandon a half-built engine: the
            # loop thread, selector, pipe fds, and the bound listener
            # would leak (and block a rebind of this port)
            self.fini()
            raise
        self._arm_kill()

    # -- public loop hooks (the remote-dep layer's progress seam) -------
    def post(self, fn: Callable, *args) -> None:
        """Run ``fn(*args)`` on the loop thread (the reference's
        dep_cmd_queue analog)."""
        self._post(("call", fn, args))

    def add_periodic(self, fn: Callable[[], None], period: float) -> None:
        """Run ``fn()`` on the loop thread every ``period`` seconds
        (handle GC, flush windows)."""
        self._post(("timer", fn, float(period)))

    def peer_feedback(self, dst: int) -> Optional[Dict[str, Any]]:
        """Adaptive-protocol feedback: queued bytes not yet on the wire,
        EWMA of frame queue->wire latency, EWMA drain rate (bytes/s)."""
        peer = self._peers.get(dst)
        if peer is None:
            return None
        return {"out_bytes": peer.out_bytes,
                "delay_ewma": peer.delay_ewma,
                "rate_ewma": peer.rate_ewma}

    def peer_debug(self) -> Dict[int, Dict[str, Any]]:
        out = super().peer_debug()
        for r, peer in list(self._peers.items()):
            ent = out.setdefault(r, {})
            ent["out_bytes"] = peer.out_bytes
            ent["connected"] = peer.sock is not None
        return out

    # -- command ring ----------------------------------------------------
    def _post(self, cmd: tuple) -> None:
        self._ring.append(cmd)
        if self._sleeping and self._wake_w >= 0:
            try:
                os.write(self._wake_w, b"\0")
                self.stats.wakeups += 1
            except (BlockingIOError, OSError):
                pass   # pipe full = wakeups already pending

    def _drain_ring(self) -> None:
        ring = self._ring
        while ring:
            try:
                cmd = ring.popleft()
            except IndexError:
                return
            op = cmd[0]
            try:
                if op == "send":
                    self._send_now(cmd[1], cmd[2], cmd[3])
                elif op == "call":
                    cmd[1](*cmd[2])
                elif op == "local":
                    self.recv_msgs += 1
                    self._safe_dispatch(cmd[1], self.rank, cmd[2])
                elif op == "adopt":
                    self._adopt(cmd[1], cmd[2])
                elif op == "timer":
                    self._timers.append(
                        [time.monotonic() + cmd[2], cmd[2], cmd[1]])
                elif op == "stop":
                    self._stop = True
            except Exception as exc:
                self._handler_error(exc)

    def _handler_error(self, exc: Exception) -> None:
        warning("rank %d: comm-loop command failed: %s", self.rank, exc)
        if self.on_error is not None:
            self.on_error(exc)

    # -- the loop --------------------------------------------------------
    def _loop(self) -> None:
        sel = self._sel
        mute_done = False
        while not self._stop:
            if self._muted and not mute_done:
                # injected silent hang: deafen the selector once (a
                # level-triggered readable socket we refuse to read
                # would otherwise busy-spin the loop)
                mute_done = True
                for peer in list(self._peers.values()) + list(self._anon):
                    if peer.sock is not None and peer.registered:
                        try:
                            sel.unregister(peer.sock)
                        except (KeyError, ValueError, OSError):
                            pass
                        peer.registered = False
                try:
                    sel.unregister(self._listener)
                except (KeyError, ValueError, OSError):
                    pass
            self._drain_ring()
            if self._stop:
                break
            self._run_timers()
            self._sleeping = True
            if self._ring:
                self._sleeping = False
                continue
            try:
                events = sel.select(self._next_timeout())
            except OSError:
                self._sleeping = False
                continue
            self._sleeping = False
            for key, mask in events:
                kind, peer = key.data
                try:
                    if kind == "accept":
                        self._on_accept()
                    elif kind == "wake":
                        try:
                            os.read(self._wake_r, 4096)
                        except (BlockingIOError, OSError):
                            pass
                    else:
                        if mask & selectors.EVENT_WRITE and \
                                peer.sock is not None:
                            self._flush(peer)
                        if mask & selectors.EVENT_READ and \
                                peer.sock is not None:
                            self._on_read(peer)
                except Exception as exc:   # the loop must survive
                    self._handler_error(exc)
        self._shutdown_drain()

    def _shutdown_drain(self, deadline: float = 5.0) -> None:
        """Orderly shutdown ships what is already queued (a barrier
        release posted just before the stop flag flipped must reach the
        peers — the threaded transport sent it synchronously), bounded
        so dead peers cannot hang teardown."""
        if self._muted:
            return   # a hung rank ships nothing, by definition
        end = time.monotonic() + deadline
        while time.monotonic() < end:
            self._drain_ring()
            pending = [p for p in self._peers.values()
                       if p.sock is not None and
                       (p.wire or p.q_ctl or p.q_bulk)]
            if not pending and not self._ring:
                return
            for p in pending:
                self._flush(p)
            # post-stop bounded drain: the loop is already exiting and
            # nothing else runs on this thread
            time.sleep(0.002)   # lint: allow-blocking (teardown drain)

    def _next_timeout(self) -> float:
        if not self._timers:
            return 0.5
        now = time.monotonic()
        due = min(t[0] for t in self._timers) - now
        return min(0.5, max(0.0, due))

    def _run_timers(self) -> None:
        if not self._timers:
            return
        now = time.monotonic()
        for t in self._timers:
            if now >= t[0]:
                t[0] = now + t[1]
                try:
                    t[2]()
                except Exception as exc:
                    self._handler_error(exc)

    def _check_unconnected(self) -> None:
        """A peer with queued frames that never dialed in is a failure,
        not a silent stall (the threaded transport's 30s connect
        deadline, ported to the nonblocking world)."""
        now = time.monotonic()
        for rank, peer in list(self._peers.items()):
            if peer.sock is None and peer.out_bytes and \
                    now - peer.born > 30 and rank not in self.dead_peers:
                self._clear_peer_queues(peer)
                # the shared death sequence (mark, wake barrier
                # waiters, containment route) — one path per detector
                self.declare_peer_dead(rank, PeerFailedError(
                    rank, f"rank {self.rank}: no connection from rank "
                          f"{rank} after 30s (frames queued)",
                    detector="connect"))

    # -- connection management ------------------------------------------
    def _dial(self, dst: int) -> None:   # lint: off-loop (init thread)
        """Blocking connect + handshake (init thread), then hand the
        socket to the loop."""
        peer_host = self._hosts[dst] if self._hosts else "127.0.0.1"
        s = _dial_peer(peer_host, self.port_base + dst, self.rank)
        s.setblocking(False)
        self._post(("adopt", s, dst))

    def _attach_parser(self, peer: _EvPeer) -> None:
        """Arm the native frame parser for a post-handshake stream
        (comm_frame_native); None keeps the inline Python machinery —
        which IS the A/B fallback path here."""
        from parsec_tpu.comm.frames import make_parser
        peer.fparser, peer.fp_native = make_parser(self._max_frame)

    def _adopt(self, sock: socket.socket, rank: int) -> None:
        peer = self._peers.get(rank)
        if peer is not None and peer.sock is None:
            peer.sock = sock       # frames queued before connect: keep
            peer.born = time.monotonic()
        else:
            peer = _EvPeer(rank, sock)
            self._peers[rank] = peer
        # outbound stream: WE sent the handshake, the peer's bytes are
        # frames from the first one — parse natively when available
        self._attach_parser(peer)
        self._sel.register(sock, selectors.EVENT_READ, ("peer", peer))
        peer.registered = True
        self._note_heard(rank)
        self._flush(peer)

    def _on_accept(self) -> None:
        while True:
            try:
                conn, _ = self._listener.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            _bump_sockbufs(conn)
            conn.setblocking(False)
            peer = _EvPeer(None, conn)
            peer.r_stage = _ST_HS
            peer.r_want = _HANDSHAKE.size
            peer.r_got = 0
            peer.r_buf = None
            peer.r_view = memoryview(peer.r_small)
            self._anon.add(peer)
            self._sel.register(conn, selectors.EVENT_READ, ("peer", peer))
            peer.registered = True

    def _close_peer(self, peer: _EvPeer) -> None:
        sock = peer.sock
        peer.sock = None
        self._anon.discard(peer)
        if sock is not None:
            if peer.registered:
                try:
                    self._sel.unregister(sock)
                except (KeyError, ValueError, OSError):
                    pass
                peer.registered = False
            try:
                sock.close()
            except OSError:
                pass

    @staticmethod
    def _clear_peer_queues(peer: _EvPeer) -> None:
        # frames can never reach a dead peer: drop them (and stop
        # accumulating — _send_now discards for dead ranks), else a
        # resident service leaks every later token/activation to it
        peer.q_ctl.clear()
        peer.q_bulk.clear()
        peer.wire.clear()
        peer.marks.clear()
        peer.out_bytes = 0

    def _peer_down(self, peer: _EvPeer, cause: Optional[str],
                   detector: str = "close") -> None:
        """Failure detection: the connection fails WITH its cause — the
        engine contract.  Local transport teardown happens here; the
        shared death sequence (mark, wake barrier waiters, containment
        route) is declare_peer_dead's — ONE path for every detector."""
        self._close_peer(peer)
        self._clear_peer_queues(peer)
        src = peer.rank
        if src is None:
            return   # a stranger that never handshook has no identity
        self.declare_peer_dead(src, PeerFailedError(
            src, f"rank {self.rank}: peer rank {src} disconnected mid-run"
            + (f": {cause}" if cause else ""), detector=detector))

    def _sever(self, peer: _EvPeer, why: str) -> None:
        warning("rank %d: protocol corruption from rank %s: %s",
                self.rank, peer.rank, why)
        self._peer_down(peer, why, detector="corrupt")

    def _drop_peer(self, r: int) -> None:
        """Close a declared-dead peer's transport state (declare_peer_dead
        contract); hops onto the loop thread when called off it."""
        if threading.current_thread() is not self._thread:
            self._post(("call", self._drop_peer, (r,)))
            return
        peer = self._peers.get(r)
        if peer is not None:
            self._close_peer(peer)
            self._clear_peer_queues(peer)

    def _kill_close(self) -> None:
        """Injected hard death: close everything abruptly on the loop
        thread; each dropped connection surfaces on OUR side too, so the
        killed rank's own context fails structurally instead of
        wedging."""
        def doit():
            try:
                self._listener.close()
            except OSError:
                pass
            for peer in list(self._peers.values()):
                if peer.sock is not None:
                    self._peer_down(peer, "fault_kill (injected)")
        self.post(doit)

    def _send_raw_parts(self, dst: int, parts: List[Any]) -> None:
        views = [memoryview(p) for p in parts if len(p)]
        nbytes = sum(v.nbytes for v in views)

        def doit():
            peer = self._peers.get(dst)
            if peer is None or peer.sock is None:
                return
            peer.q_bulk.append((time.monotonic(), nbytes, views))
            peer.out_bytes += nbytes
            self._flush(peer)
        self.post(doit)

    # -- send path -------------------------------------------------------
    def send_am(self, tag: int, dst: int, payload: Any = None,
                _nofault: bool = False) -> None:
        mark("send_am tag=%d dst=%d", tag, dst)
        if self._muted and dst != self.rank:
            return   # injected silent hang swallows every outbound frame
        if self._fault is not None and not _nofault and dst != self.rank \
                and self._fault_frame(tag, dst, payload):
            return
        if dst == self.rank:
            # local delivery short-circuit (counts as a message so the
            # termination balance stays symmetric); same posted-FIFO
            # rule as the remote branch below
            self.sent_msgs += 1
            if threading.current_thread() is self._thread and \
                    not self._ring:
                self.recv_msgs += 1
                self._dispatch(tag, self.rank, payload)
            else:
                self._post(("local", tag, payload))
            return
        if threading.current_thread() is self._thread:
            # per-destination FIFO across threads: a loop-thread send
            # (handler reply) must not overtake worker sends already
            # POSTED but not yet drained — the DTD lane protocol owes
            # its total write-chain order to this
            if self._ring:
                self._ring.append(("send", tag, dst, payload))
            else:
                self._send_now(tag, dst, payload)
        else:
            self._post(("send", tag, dst, payload))

    def _send_now(self, tag: int, dst: int, payload: Any) -> None:
        if dst in self.dead_peers:
            return        # undeliverable; the loss already surfaced
        peer = self._peers.get(dst)
        if peer is None:
            # not yet dialed in (higher-rank peer owns the initiation):
            # frames queue on a placeholder and flush at adoption
            peer = self._peers[dst] = _EvPeer(dst, None)
        self.sent_msgs += 1
        self.stats.frames_sent += 1
        self._enqueue(peer, tag, payload)
        if peer.sock is not None:
            self._flush(peer)

    def _enqueue(self, peer: _EvPeer, tag: int, payload: Any) -> None:
        parts = _frame_parts(tag, payload)
        views = [memoryview(p) for p in parts if len(p)]
        nbytes = sum(v.nbytes for v in views)
        q = peer.q_ctl if tag in _CTL_TAGS else peer.q_bulk
        q.append((time.monotonic(), nbytes, views))
        peer.out_bytes += nbytes

    def _flush(self, peer: _EvPeer) -> None:
        sock = peer.sock
        if sock is None or self._muted:
            return
        stats = self.stats
        while True:
            # commit queued frames to wire order (control first; a
            # partially-sent frame is never preempted)
            while len(peer.wire) < _IOV_CAP and (peer.q_ctl or peer.q_bulk):
                t_enq, nb, views = (peer.q_ctl.popleft() if peer.q_ctl
                                    else peer.q_bulk.popleft())
                peer.wire.extend(views)
                peer.marks.append([nb, t_enq, nb])
            if not peer.wire:
                self._set_write(peer, False)
                return
            iov = list(islice(peer.wire, _IOV_CAP))
            try:
                sent = sock.sendmsg(iov)
            except (BlockingIOError, InterruptedError):
                stats.partial_writes += 1
                self._set_write(peer, True)
                return
            except OSError as exc:
                self._peer_down(peer, f"send failed: {exc}")
                return
            stats.syscalls_send += 1
            stats.bytes_sent += sent
            peer.out_bytes -= sent
            short = sent < sum(v.nbytes for v in iov)
            self._consume(peer, sent)
            if short:
                # kernel send buffer full mid-frame: park the resume
                # state, drain the rest on EPOLLOUT (backpressure)
                stats.partial_writes += 1
                self._set_write(peer, True)
                return

    def _consume(self, peer: _EvPeer, sent: int) -> None:
        wire = peer.wire
        while sent:
            head = wire[0]
            if sent >= head.nbytes:
                sent -= head.nbytes
                self._mark_drained(peer, head.nbytes)
                wire.popleft()
            else:
                wire[0] = head[sent:]
                self._mark_drained(peer, sent)
                sent = 0

    @staticmethod
    def _mark_drained(peer: _EvPeer, n: int) -> None:
        marks = peer.marks
        while n and marks:
            m = marks[0]
            take = n if n < m[0] else m[0]
            m[0] -= take
            n -= take
            if m[0] == 0:
                marks.popleft()
                dt = time.monotonic() - m[1]
                # feedback for the adaptive eager protocol: observed
                # frame queue->wire latency and drain rate
                if dt > 0:
                    rate = m[2] / dt
                    peer.rate_ewma = rate if peer.rate_ewma is None \
                        else (1 - _EWMA) * peer.rate_ewma + _EWMA * rate
                peer.delay_ewma = dt if peer.delay_ewma is None \
                    else (1 - _EWMA) * peer.delay_ewma + _EWMA * dt

    def _set_write(self, peer: _EvPeer, want: bool) -> None:
        if peer.want_write == want or peer.sock is None:
            return
        peer.want_write = want
        ev = selectors.EVENT_READ | \
            (selectors.EVENT_WRITE if want else 0)
        try:
            self._sel.modify(peer.sock, ev, ("peer", peer))
        except (KeyError, ValueError, OSError):
            pass

    # -- receive path ----------------------------------------------------
    def _on_read(self, peer: _EvPeer) -> None:
        if self._muted:
            return   # injected silent hang: stop consuming
        if peer.fparser is not None:
            self._on_read_native(peer)
            return
        budget = _RECV_BUDGET
        scratch = self._scratch
        smv = self._scratch_mv
        stats = self.stats
        while budget > 0 and peer.sock is not None:
            rem = peer.r_want - peer.r_got
            if peer.r_buf is not None and rem >= len(scratch):
                # bulk stage: receive straight into the frame's own
                # preallocated buffer (zero-copy out-of-band path)
                want = rem if rem < budget else budget
                try:
                    n = peer.sock.recv_into(
                        peer.r_view[peer.r_got:peer.r_got + want])
                except (BlockingIOError, InterruptedError):
                    return
                except OSError as exc:
                    self._peer_down(peer, f"recv failed: {exc}")
                    return
                if n == 0:
                    self._eof(peer)
                    return
                stats.syscalls_recv += 1
                stats.bytes_recv += n
                # liveness per chunk, not per completed frame: a bulk
                # frame outlasting comm_peer_timeout_s on the wire must
                # not get its actively-sending peer declared dead
                self._note_heard(peer.rank)
                peer.r_got += n
                budget -= n
                if peer.r_got == peer.r_want and not self._advance(peer):
                    return
                if n < want:
                    return        # socket drained
            else:
                # buffered stage: one read, then carve every complete
                # small frame out of it (frames/syscall coalescing)
                try:
                    n = peer.sock.recv_into(scratch)
                except (BlockingIOError, InterruptedError):
                    return
                except OSError as exc:
                    self._peer_down(peer, f"recv failed: {exc}")
                    return
                if n == 0:
                    self._eof(peer)
                    return
                stats.syscalls_recv += 1
                stats.bytes_recv += n
                self._note_heard(peer.rank)   # per chunk (see above)
                budget -= n
                if not self._feed(peer, smv[:n]):
                    return
                if n < len(scratch):
                    return        # socket drained

    def _on_read_native(self, peer: _EvPeer) -> None:
        """Receive path over the native frame parser: the per-frame
        state machine runs in ONE C crossing per read (commext.c), and
        an in-progress large payload is recv_into'd straight into the
        parser's own buffer — the zero-copy out-of-band path."""
        budget = _RECV_BUDGET
        scratch = self._scratch
        smv = self._scratch_mv
        stats = self.stats
        fp = peer.fparser
        while budget > 0 and peer.sock is not None:
            tgt = fp.bulk_target()
            want = len(tgt) if tgt is not None else len(scratch)
            try:
                n = peer.sock.recv_into(tgt if tgt is not None
                                        else scratch)
            except (BlockingIOError, InterruptedError):
                return
            except OSError as exc:
                self._peer_down(peer, f"recv failed: {exc}")
                return
            if n == 0:
                self._eof(peer)
                return
            stats.syscalls_recv += 1
            stats.bytes_recv += n
            # liveness per chunk, not per completed frame (see the
            # fallback path's rationale)
            self._note_heard(peer.rank)
            budget -= n
            try:
                frames = fp.bulk_commit(n) if tgt is not None \
                    else fp.feed(smv[:n])
            except ValueError as exc:
                self._sever(peer, str(exc))
                return
            if frames and not self._dispatch_frames(peer, frames):
                return
            if n < want:
                return        # socket drained

    def _dispatch_frames(self, peer: _EvPeer, frames) -> bool:
        """Deliver parser-completed frames; False = stop reading this
        peer (severed / handed off by a handler)."""
        return self._deliver_frames(
            frames, peer.rank, peer.fp_native,
            lambda why: self._sever(peer, why),
            lambda: peer.sock is not None)

    def _feed(self, peer: _EvPeer, mv: memoryview) -> bool:
        while len(mv):
            if peer.fparser is not None:
                # the handshake completed inside this read and armed
                # the parser: the remaining bytes are frame stream
                try:
                    frames = peer.fparser.feed(mv)
                except ValueError as exc:
                    self._sever(peer, str(exc))
                    return False
                return self._dispatch_frames(peer, frames) if frames \
                    else peer.sock is not None
            take = peer.r_want - peer.r_got
            if take > len(mv):
                take = len(mv)
            peer.r_view[peer.r_got:peer.r_got + take] = mv[:take]
            peer.r_got += take
            mv = mv[take:]
            if peer.r_got == peer.r_want and not self._advance(peer):
                return False
        return True

    def _expect_hdr(self, peer: _EvPeer) -> None:
        peer.r_stage = _ST_HDR
        peer.r_want = _LEN.size
        peer.r_got = 0
        peer.r_buf = None
        peer.r_view = memoryview(peer.r_small)

    def _advance(self, peer: _EvPeer) -> bool:
        """One receive stage filled; returns False when the peer was
        severed or the socket handed off (stop reading it)."""
        st = peer.r_stage
        if st == _ST_HS:
            magic, ver, src = _HANDSHAKE.unpack_from(peer.r_small)
            if magic != _WIRE_MAGIC or ver != _WIRE_VERSION:
                warning("rank %d: rejected connection with bad handshake "
                        "(magic=%r version=%r)", self.rank, magic, ver)
                self._close_peer(peer)
                return False
            peer.rank = src
            if src in self.dead_peers and not self.rejoin_allowed:
                # no rejoin protocol armed: accepting a dead rank would
                # create a half-connected zombie (its frames dispatched
                # and Safra-counted while _send_now drops every reply)
                warning("rank %d: rejected reconnection from dead rank "
                        "%d", self.rank, src)
                self._close_peer(peer)
                return False
            if src in self.dead_peers:
                # elastic rejoin (core/recovery.py): adopt the stream —
                # the rank stays dead (sends still refused, app frames
                # fenced by incarnation epoch) until its TAG_REJOIN
                # handshake validates, which flips peer_rejoined
                warning("rank %d: reconnection from dead rank %d "
                        "accepted pending TAG_REJOIN handshake",
                        self.rank, src)
            existing = self._peers.get(src)
            if existing is not None and existing is not peer:
                if existing.sock is None:
                    # frames queued before the peer dialed in: adopt
                    peer.q_ctl.extend(existing.q_ctl)
                    peer.q_bulk.extend(existing.q_bulk)
                    peer.out_bytes += existing.out_bytes
                else:
                    warning("rank %d: duplicate connection from rank %d "
                            "rejected", self.rank, src)
                    self._close_peer(peer)
                    return False
            self._peers[src] = peer
            self._anon.discard(peer)
            self._note_heard(src)
            self._expect_hdr(peer)
            # handshake done: the rest of the stream is frames — hand
            # it to the native parser (any bytes that followed the
            # handshake in this same read are routed by _feed)
            self._attach_parser(peer)
            self._flush(peer)
            return peer.sock is not None
        if st == _ST_HDR:
            tag, ln, nbufs = _LEN.unpack_from(peer.r_small)
            if ln > self._max_frame or nbufs > 4096:
                self._sever(peer, f"frame length {ln}/{nbufs} bufs "
                                  f"exceeds the {self._max_frame >> 20} "
                                  f"MiB bound (tag={tag})")
                return False
            peer.r_tag, peer.r_ln, peer.r_nbufs = tag, ln, nbufs
            peer.r_body = b""
            peer.r_oob = []
            if ln:
                buf = bytearray(ln)
                peer.r_buf = buf
                peer.r_view = memoryview(buf)
                peer.r_stage = _ST_BODY
                peer.r_want = ln
                peer.r_got = 0
                return True
            return self._next_buf(peer)
        if st == _ST_BODY:
            peer.r_body = peer.r_buf
            return self._next_buf(peer)
        if st == _ST_BLEN:
            (bln,) = _BUFLEN.unpack_from(peer.r_small)
            if bln > self._max_frame:
                self._sever(peer, f"oob buffer length {bln} "
                                  f"(tag={peer.r_tag})")
                return False
            if bln == 0:
                peer.r_oob.append(bytearray(0))
                return self._next_buf(peer)
            buf = bytearray(bln)
            peer.r_buf = buf
            peer.r_view = memoryview(buf)
            peer.r_stage = _ST_BUF
            peer.r_want = bln
            peer.r_got = 0
            return True
        if st == _ST_BUF:
            peer.r_oob.append(peer.r_buf)
            return self._next_buf(peer)
        return True

    def _next_buf(self, peer: _EvPeer) -> bool:
        if len(peer.r_oob) < peer.r_nbufs:
            peer.r_stage = _ST_BLEN
            peer.r_want = _BUFLEN.size
            peer.r_got = 0
            peer.r_buf = None
            peer.r_view = memoryview(peer.r_small)
            return True
        return self._frame_done(peer)

    def _frame_done(self, peer: _EvPeer) -> bool:
        self.recv_msgs += 1
        self.stats.frames_recv += 1
        self._note_heard(peer.rank)
        tag = peer.r_tag
        body, oob = peer.r_body, peer.r_oob
        src = peer.rank
        self._expect_hdr(peer)   # reset BEFORE dispatch (handlers send)
        if body:
            try:
                payload = pickle.loads(body, buffers=oob)
            except Exception as exc:
                self._sever(peer, f"undecodable frame tag={tag}: {exc}")
                return False
        else:
            payload = None
        if self._fault is not None and \
                self._recv_fault_hold(tag, src, payload):
            return peer.sock is not None   # redelivery scheduled
        self._safe_dispatch(tag, src, payload)
        return peer.sock is not None

    def _deliver_held(self, tag: int, src: int, payload: Any) -> None:
        # funnelled contract: handlers run ONLY on the loop thread — a
        # Timer-thread dispatch (the base-class redelivery) would race
        # every lock-free structure the loop owns
        self._post(("call", self._safe_dispatch, (tag, src, payload)))

    def _eof(self, peer: _EvPeer) -> None:
        if peer.fparser is not None:
            if peer.fparser.idle():
                self._peer_down(peer, None)  # closed between frames
            else:
                self._peer_down(peer, "peer died mid-frame")
            return
        if peer.r_stage == _ST_HDR and peer.r_got == 0:
            self._peer_down(peer, None)      # closed between frames
        elif peer.r_stage == _ST_HS:
            self._close_peer(peer)           # stranger never handshook
        else:
            self._peer_down(
                peer, f"peer died mid-frame (stage={peer.r_stage}, "
                      f"{peer.r_got}/{peer.r_want} bytes of tag="
                      f"{peer.r_tag})")

    # -- teardown --------------------------------------------------------
    def fini(self) -> None:
        self._stop = True
        self._post(("stop",))
        wake_w = self._wake_w
        if wake_w >= 0:
            try:
                os.write(wake_w, b"\0")
            except OSError:
                pass
        if threading.current_thread() is not self._thread:
            self._thread.join(timeout=5)
        try:
            self._listener.close()
        except OSError:
            pass
        for peer in list(self._peers.values()) + list(self._anon):
            sock = peer.sock
            peer.sock = None
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
        try:
            self._sel.close()
        except (OSError, RuntimeError):
            pass
        # invalidate BEFORE closing: a second fini must not write to or
        # close a recycled fd number belonging to someone else
        fds = (self._wake_r, self._wake_w)
        self._wake_r = self._wake_w = -1
        for fd in fds:
            if fd >= 0:
                try:
                    os.close(fd)
                except OSError:
                    pass
        debug_verbose(5, "rank %d CE down: sent=%d recv=%d %s",
                      self.rank, self.sent_msgs, self.recv_msgs,
                      self.stats.as_dict())


def make_ce(rank: int, nranks: int,
            port_base: Optional[int] = None) -> CommEngine:
    """Transport factory: ``comm_transport`` MCA knob (env
    ``PARSEC_MCA_COMM_TRANSPORT``) selects ``evloop`` (default),
    ``threads`` (the pre-event-loop path kept selectable for A/B
    attribution), or ``shm`` (same-host mmap ring pairs, comm/shm.py;
    multi-host address books fall back to evloop with a warning)."""
    transport = str(params.get("comm_transport", "evloop")
                    or "evloop").lower()
    if transport in ("threads", "thread", "socketce"):
        return SocketCE(rank, nranks, port_base)
    if transport in ("shm", "sharedmem", "ring"):
        hosts = str(params.get("comm_hosts", "") or
                    os.environ.get("PARSEC_COMM_HOSTS", "")).strip()
        if hosts:
            warning("comm_transport=shm is same-host only but "
                    "comm_hosts is set: using evloop")
        else:
            from parsec_tpu.comm.shm import ShmCE
            return ShmCE(rank, nranks, port_base)
    elif transport not in ("evloop", "eventloop", "select"):
        warning("unknown comm_transport %r: using evloop", transport)
    return EventLoopCE(rank, nranks, port_base)
