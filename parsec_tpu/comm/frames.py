"""Frame-stream parsing shared by the byte-stream transports.

The event-loop TCP transport (comm/engine.py) and the shared-memory
ring transport (comm/shm.py) speak one wire format: a 16-byte header
(``!IQI``: tag, pickle length, out-of-band buffer count), the pickle
body, then per-buffer length (``!Q``) + raw bytes.  This module is the
parser seam: ``make_parser`` hands out the native incremental parser
(parsec_tpu/native/commext.c — one C crossing consumes a whole read and
returns the completed frames) behind the ``comm_frame_native`` A/B
knob, with ``PyFrameParser`` as the always-available Python twin.

Parser API (both implementations):

  ``feed(buf) -> [(tag, body|None, [oob, ...]), ...]`` — consume bytes,
      return completed frames; raises ValueError on a bound violation
      (the caller severs the connection: wire corruption).
  ``bulk_target() -> memoryview | None`` — writable view of an
      in-progress large payload's remaining region, so the transport
      can ``recv_into`` it directly (the zero-copy out-of-band path);
      commit with ``bulk_commit(n) -> [frames...]``.
  ``idle() -> bool`` — True exactly between frames (EOF here is a
      clean close; anywhere else the peer died mid-frame).
  ``stats() -> int`` — frames completed through this parser.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Tuple

from parsec_tpu.utils.mca import params

params.register("comm_frame_native", 1,
                "parse comm frames with the native (C) incremental "
                "parser when it builds (0 = the Python state machine; "
                "the frame-path A/B knob).  Applies to the evloop and "
                "shm transports; the threads transport keeps its "
                "blocking per-peer recv loops either way")

_LEN = struct.Struct("!IQI")
_BUFLEN = struct.Struct("!Q")

#: below this many remaining bytes, copying through feed() beats a
#: dedicated recv_into (mirrors commext.c BULK_MIN)
_BULK_MIN = 65536

_ST_HDR, _ST_BODY, _ST_BLEN, _ST_BUF = range(4)


class PyFrameParser:
    """Pure-Python twin of commext.FrameParser (same API, same wire
    semantics); the fallback when the extension does not build and the
    reference implementation its tests diff against."""

    __slots__ = ("_max", "_stage", "_want", "_got", "_small", "_target",
                 "_tag", "_ln", "_nbufs", "_body", "_oob", "_frames")

    def __init__(self, max_frame: int):
        self._max = int(max_frame)
        self._small = bytearray(_LEN.size)
        self._frames = 0
        self._expect_hdr()

    def _expect_hdr(self) -> None:
        self._stage = _ST_HDR
        self._want = _LEN.size
        self._got = 0
        self._target = None
        self._body = None
        self._oob: List[bytearray] = []

    def idle(self) -> bool:
        return self._stage == _ST_HDR and self._got == 0

    def stats(self) -> int:
        return self._frames

    def feed(self, data) -> List[Tuple[int, Optional[bytearray], list]]:
        out: List = []
        mv = memoryview(data)
        while len(mv):
            take = self._want - self._got
            if take > len(mv):
                take = len(mv)
            tgt = self._target if self._target is not None else self._small
            tgt[self._got:self._got + take] = mv[:take]
            self._got += take
            mv = mv[take:]
            if self._got == self._want:
                self._advance(out)
        return out

    def bulk_target(self):
        if self._target is None or self._want - self._got < _BULK_MIN:
            return None
        return memoryview(self._target)[self._got:]

    def bulk_commit(self, n: int) -> List:
        if self._target is None or n < 0 or self._got + n > self._want:
            raise ValueError("bulk_commit outside an in-progress payload")
        out: List = []
        self._got += n
        if self._got == self._want:
            self._advance(out)
        return out

    def _advance(self, out: List) -> None:
        st = self._stage
        if st == _ST_HDR:
            tag, ln, nbufs = _LEN.unpack_from(self._small)
            if ln > self._max or nbufs > 4096:
                raise ValueError(
                    f"frame length {ln}/{nbufs} bufs exceeds the bound "
                    f"(tag={tag})")
            self._tag, self._ln, self._nbufs = tag, ln, nbufs
            self._body = None
            self._oob = []
            if ln:
                self._target = bytearray(ln)
                self._stage = _ST_BODY
                self._want = ln
                self._got = 0
                return
        elif st == _ST_BODY:
            self._body = self._target
            self._target = None
        elif st == _ST_BLEN:
            (bln,) = _BUFLEN.unpack_from(self._small)
            if bln > self._max:
                raise ValueError(
                    f"oob buffer length {bln} (tag={self._tag})")
            if bln:
                self._target = bytearray(bln)
                self._stage = _ST_BUF
                self._want = bln
                self._got = 0
                return
            self._oob.append(bytearray(0))
        elif st == _ST_BUF:
            self._oob.append(self._target)
            self._target = None
        if len(self._oob) < self._nbufs:
            self._stage = _ST_BLEN
            self._want = _BUFLEN.size
            self._got = 0
            self._target = None
            return
        out.append((self._tag, self._body, self._oob))
        self._frames += 1
        self._expect_hdr()


def make_parser(max_frame: int, require: bool = False):
    """The frame parser for one peer stream: ``(parser, is_native)``.

    ``require=False`` (the evloop caller) returns ``(None, False)``
    when the native parser is off/unavailable — the transport keeps its
    own inline Python machinery, which IS the A/B fallback there.
    ``require=True`` (the shm transport, which has no inline path)
    falls back to PyFrameParser instead.
    """
    if int(params.get("comm_frame_native", 1)):
        from parsec_tpu.native import load_commext
        cx = load_commext()
        if cx is not None:
            return cx.FrameParser(int(max_frame)), True
    if require:
        return PyFrameParser(int(max_frame)), False
    return None, False
