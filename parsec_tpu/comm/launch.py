"""Multiprocess SPMD launcher for distributed runs and tests.

The mpiexec analog (reference: tests run distributed cases under
``${MPI_TEST_CMD_LIST} <nranks>`` = mpiexec -n N on one node,
CMakeLists.txt:921-952): spawns N python processes, wires each into a
SocketCE + RemoteDepEngine + Context, runs ``fn(ctx, rank, nranks)``
SPMD, and gathers per-rank results (or the first traceback).

Children force jax onto CPU (set ``PARSEC_LAUNCH_PLATFORM`` to override)
so distributed tests run anywhere, mirroring the reference's
multi-process-on-one-node strategy.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import random
import socket
import traceback
from typing import Any, Callable, List, Optional


def _probe_port_base(nranks: int, tries: int = 32) -> int:
    """Pick a base port with every rank's port currently bindable: an
    in-use port would make SocketCE.bind fail or cross-talk with an
    unrelated listener (ADVICE r1 low)."""
    for _ in range(tries):
        base = random.randrange(20000, 60000 - nranks)
        socks = []
        try:
            for r in range(nranks):
                s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                s.bind(("127.0.0.1", base + r))
                socks.append(s)
            return base
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
    return random.randrange(20000, 60000 - nranks)


def _worker(rank: int, nranks: int, port_base: int, nb_cores: int,
            fn: Callable, args: tuple, outq) -> None:
    os.environ.setdefault("PARSEC_COMM_PORT_BASE", str(port_base))
    platform = os.environ.get("PARSEC_LAUNCH_PLATFORM", "cpu")
    os.environ["JAX_PLATFORMS"] = platform
    try:
        try:
            import jax
            jax.config.update("jax_platforms", platform)
        except Exception:
            pass
        from parsec_tpu.comm.engine import make_ce
        from parsec_tpu.comm.remote_dep import RemoteDepEngine
        from parsec_tpu.core.context import Context

        # transport selected by PARSEC_MCA_COMM_TRANSPORT (inherited by
        # the spawned children): evloop (default) or threads (the old
        # per-peer-thread path, kept for A/B attribution)
        ce = make_ce(rank, nranks, port_base)
        ctx = Context(nb_cores=nb_cores, rank=rank, nranks=nranks)
        rde = RemoteDepEngine(ce, ctx)
        ce.barrier()   # every rank's handlers are wired before user code
        try:
            result = fn(ctx, rank, nranks, *args)
            ce.barrier()
            # past the final barrier every rank is done: peers closing
            # their sockets now (possibly while we still serialize the
            # result below) is orderly shutdown, not a failure
            ce._stop = True
            outq.put((rank, None, result))
        finally:
            ce._stop = True
            ctx.fini()
            rde.fini()
    except Exception:
        outq.put((rank, traceback.format_exc(), None))


def run_distributed(fn: Callable, nranks: int, args: tuple = (),
                    nb_cores: int = 2, timeout: float = 120.0,
                    port_base: Optional[int] = None,
                    tolerate_ranks=()) -> List[Any]:
    """Run ``fn(ctx, rank, nranks, *args)`` on ``nranks`` processes;
    returns the per-rank results in rank order.

    ``tolerate_ranks``: ranks whose failure is EXPECTED (chaos kill
    victims under recovery — the survivors' completion is the result
    that matters); their slot in the returned list is None when they
    errored.  An error on any other rank still fails the run."""
    if port_base is None:
        port_base = _probe_port_base(nranks)
    mpctx = mp.get_context("spawn")
    outq = mpctx.Queue()
    procs = [mpctx.Process(target=_worker,
                           args=(r, nranks, port_base, nb_cores, fn, args,
                                 outq),
                           daemon=True)
             for r in range(nranks)]
    # Children must NOT initialize real accelerator plugins: a TPU tunnel
    # admits one claimant, so N spawned ranks racing for it hang or crawl.
    # Env is inherited at spawn — patch, start, restore.
    saved = {k: os.environ.get(k)
             for k in ("JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS")}
    os.environ["JAX_PLATFORMS"] = \
        os.environ.get("PARSEC_LAUNCH_PLATFORM", "cpu")
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    try:
        for p in procs:
            p.start()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    tolerate = set(tolerate_ranks)
    results: dict = {}
    errors: List[str] = []
    try:
        for _ in range(nranks):
            rank, err, res = outq.get(timeout=timeout)
            if err is not None and rank in tolerate:
                results[rank] = None   # expected casualty (chaos kill)
            elif err is not None:
                errors.append(f"rank {rank}:\n{err}")
            else:
                results[rank] = res
    except Exception as exc:
        for p in procs:
            p.terminate()
        raise TimeoutError(
            f"distributed run incomplete ({len(results)}/{nranks} ranks): "
            f"{errors or exc}")
    finally:
        for p in procs:
            p.join(timeout=10)
            if p.is_alive():
                p.terminate()
    if errors:
        raise RuntimeError("distributed run failed:\n" + "\n".join(errors))
    return [results[r] for r in range(nranks)]
