"""Remote dependencies: dataflow activation across ranks.

Rebuild of the reference's remote-dep protocol (reference:
parsec/remote_dep.c + remote_dep_mpi.c — activation message carrying task
id + output data (remote_dep_wire_activate_t, remote_dep.h:41-48), eager
payload inlining vs receiver-initiated GET (remote_dep_mpi_get_start:1963),
delayed activations for not-yet-known taskpools (:1831), and collective
propagation along virtual topologies re-rooted at the source — star,
chain pipeline, binomial tree (remote_dep.c:334-357, selected by MCA
``runtime_comm_coll_bcast``)).

Flow: a completing task's release_deps finds successors on other ranks →
activations are buffered per (flow, payload), grouped by destination rank,
and flushed once per task as ONE message down the chosen bcast tree; each
receiving rank delivers its local successor deps (engine.deliver_dep) and
re-forwards to its tree children.  Large payloads travel by rendezvous:
the activation carries a handle, the receiver pulls with GET_REQ and the
source serves GET_REP from a refcounted handle table.

Global quiescence uses Safra's token algorithm over the message counters
(the counterpart of the reference's fourcounter termdet module,
mca/termdet/fourcounter): rank 0 circulates (color, balance); a clean
white round with zero balance means no task and no message is in flight
anywhere, and TERMINATE is broadcast.
"""

from __future__ import annotations

import itertools
import queue as queue_mod
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from parsec_tpu.comm.engine import (CommEngine, TAG_ACTIVATE, TAG_BATCH,
                                    TAG_DTD, TAG_GET_REP, TAG_GET_REQ,
                                    TAG_TERMDET, TAG_UTRIG)
from parsec_tpu.core import scheduling
from parsec_tpu.core.engine import deliver_dep
from parsec_tpu.core.errors import PeerFailedError
from parsec_tpu.utils.mca import params
from parsec_tpu.utils.output import warning

params.register("comm_eager_limit", 64 * 1024,
                "payloads up to this many bytes ride inside the activation")
params.register("comm_coll_bcast", "binomial",
                "activation fan-out topology: star | chain | binomial")
params.register("comm_adaptive_eager", True,
                "adapt the eager/rendezvous threshold per peer from the "
                "transport's observed frame latency vs drain rate "
                "(starts at comm_eager_limit; backpressure halves it, a "
                "fast-draining pipe raises it toward the cap).  Only "
                "active on transports exporting peer_feedback (evloop)")
params.register("comm_eager_min", 4096,
                "adaptive floor: the per-peer eager threshold never "
                "drops below min(this, comm_eager_limit)")
params.register("comm_eager_cap_mult", 4,
                "adaptive ceiling: per-peer threshold may rise to "
                "comm_eager_limit * this when the peer's pipe drains "
                "fast (payloads that size skip the rendezvous "
                "round-trip)")
params.register("comm_backpressure_ms", 2.0,
                "projected per-peer queue drain delay above which "
                "payloads are demoted to rendezvous (the adaptive "
                "protocol's latency budget)")
params.register("comm_flush_window_ms", 0.0,
                "cross-TASK activation flush window in milliseconds: "
                "same-destination activations of tasks completing "
                "within the window pack into one framed batch "
                "(0 = off: coalescing stays per-task)")
params.register("comm_clock_sync", 1,
                "estimate per-peer clock offset + drift via a TAG_CLOCK "
                "ping exchange (re-probed periodically); recorded into "
                "causal-trace headers for cross-rank merge alignment "
                "(0 = off)")
params.register("comm_clock_probe_s", 5.0,
                "seconds between clock-offset probe rounds")

_handle_seq = itertools.count(1)


def _encode(arr) -> Tuple[bytes, str, Tuple[int, ...]]:
    p = CommEngine.pack(arr)
    return p["buf"], p["dtype"], p["shape"]


def _decode(buf: bytes, dtype: str, shape) -> np.ndarray:
    return CommEngine.unpack({"buf": buf, "dtype": dtype, "shape": shape})


params.register("comm_handle_timeout", 600.0,
                "seconds before an unclaimed rendezvous handle is dropped "
                "(a receiver that never GETs — eager race, dead peer — "
                "must not strand the payload forever; a GET after the "
                "purge fails the RECEIVER with a clear miss, not the "
                "serving rank)")

params.register("comm_rdv_retry_s", 2.0,
                "initial rendezvous retry backoff: a GET_REQ with no "
                "reply is re-sent after this many seconds, doubling per "
                "attempt (the serving side keeps answered handles "
                "around for a grace period, so a duplicate pull is "
                "idempotent)")

params.register("comm_rdv_timeout_s", 60.0,
                "terminal rendezvous deadline: a pull still unanswered "
                "after this many seconds fails ITS taskpool with a "
                "structured PeerFailedError instead of waiting forever")

#: answered (refs == 0) rendezvous handles linger this long so a
#: retransmitted GET_REQ — retry backoff, duplicated frame — can be
#: re-served instead of surfacing a spurious miss.  Sized past the
#: default backoff horizon (comm_rdv_retry_s doubling: 2+4+8+16 = 30s)
#: so every retry a 60s comm_rdv_timeout_s allows finds the handle
_HANDLE_GRACE_S = 30.0


def _msg_nbytes(msg: dict) -> int:
    """Best-effort payload byte count of an app message (trace events)."""
    d = msg.get("data")
    if isinstance(d, tuple) and len(d) >= 2 and hasattr(d[1], "nbytes"):
        return int(d[1].nbytes)
    b = msg.get("buf")
    if hasattr(b, "nbytes"):
        return int(b.nbytes)
    return 0


class _Handle:
    __slots__ = ("data", "refs", "lock", "born", "dead_at", "served")

    def __init__(self, data, refs: int):
        self.data = data
        self.refs = refs
        self.lock = threading.Lock()
        self.born = time.monotonic()
        #: stamped when the last expected ref was served; the handle
        #: then lingers for _HANDLE_GRACE_S (idempotent re-serves)
        self.dead_at: Optional[float] = None
        #: ranks already served once: a RETRANSMITTED pull (retry
        #: backoff stamps a fresh _fid, so dedup cannot see it) must not
        #: consume another requester's ref
        self.served: set = set()


class RemoteDepEngine:
    """Attached to a Context as ``ctx.comm`` (reference: the remote_dep
    layer driven by the comm thread, remote_dep_mpi.c:461)."""

    def __init__(self, ce: CommEngine, context):
        self.ce = ce
        self.context = context
        context.comm = self
        # the inline-poll auto probe must see the affinity of NOW: a
        # fabric-carved worker is re-pinned between Context build and
        # comm attach, and a stale 1-core reading would never arm the
        # spare-core poll (context._recompute_db_spin)
        recompute = getattr(context, "_recompute_db_spin", None)
        if recompute is not None:
            recompute()
        self.rank = ce.rank
        self.nranks = ce.nranks
        self.eager = int(params.get("comm_eager_limit", 65536))
        self.bcast = params.get("comm_coll_bcast", "binomial")
        #: rendezvous handle table (guarded-by: _hlock)
        self._handles: Dict[int, _Handle] = {}
        self._hlock = threading.Lock()
        #: activations buffered during one task's release_deps
        #: (guarded-by: _outbox_lock)
        self._outbox: Dict[int, List] = {}
        self._outbox_lock = threading.Lock()
        #: activations for taskpools not yet registered locally
        #: (guarded-by: _dlock)
        self._delayed: List[Tuple[int, dict]] = []
        self._dlock = threading.Lock()
        # Safra token state (reference counterpart: termdet fourcounter).
        # Only ACTIVATE/GET traffic counts toward the balance; token and
        # barrier messages are part of the detection algorithm itself.
        self._color_black = False           # guarded-by: _term_lock
        self._term_lock = threading.Lock()
        self._terminated = threading.Event()
        self._app_sent = 0                  # guarded-by: _term_lock
        self._app_recv = 0                  # guarded-by: _term_lock
        #: per-peer twins of the Safra counters: a RECOVERY subtracts a
        #: dead rank's whole contribution in one critical section so
        #: the token balance reflects survivor traffic only
        #: (core/recovery.py; guarded-by: _term_lock)
        self._sent_to: Dict[int, int] = {}
        self._recv_from: Dict[int, int] = {}
        #: incarnation fencing: frames from ``src`` whose ``_ep`` is
        #: below the fence are stale traffic of a dead incarnation —
        #: dropped BEFORE the Safra credit (their sender's counters were
        #: reconciled away; guarded-by: _term_lock)
        self._fence_epoch: Dict[int, int] = {}
        self._peer_epoch: Dict[int, int] = {}   # guarded-by: _term_lock
        #: this engine's own incarnation (comm_epoch): stamped into app
        #: frames past epoch 0 so survivors can tell a rejoined rank's
        #: traffic from its dead predecessor's
        self._epoch = int(getattr(ce, "epoch", 0))
        self._retry_pending = False         # guarded-by: _dlock
        #: dynamic taskpools holding a runtime action until the
        #: pool-scoped quiescence round proves global drain (the
        #: reference's dynamic/fourcounter termdet role for
        #: %option dynamic pools; guarded-by: _term_lock)
        self._dyn_holds: List = []
        self._dyn_released = threading.Event()
        ce.on_error = self._on_handler_error
        #: peer-death containment: route a dead rank into the taskpools
        #: that touch it (per-pool error sinks) instead of poisoning the
        #: whole context
        ce.on_peer_dead = self._on_peer_dead
        #: Safra reconcile for injected frame faults (utils/faultinject):
        #: a dropped app frame un-counts its send, a duplicated one
        #: counts twice — the token balance stays convergent either way
        ce.on_frame_fault = self._on_frame_fault
        #: per-message wire id (origin_rank, seq): receivers drop
        #: duplicate deliveries (retransmits, injected dups) after
        #: crediting them in the Safra balance.  The sequence starts at
        #: epoch << 48 so a rejoined incarnation can never collide with
        #: ids its predecessor already burned into peers' dedup windows
        self._fid_seq = itertools.count(1 + (self._epoch << 48))
        self._seen_fids: set = set()
        self._fid_order: "deque" = deque()
        #: causal tracer (prof/causal.py) and flight recorder
        #: (prof/flightrec.py), attached through the ``tracer`` /
        #: ``flightrec`` properties below; ``_sinks`` is the maintained
        #: fan-out tuple every trace site iterates — one shape to
        #: extend when the next sink arrives.  Empty = zero tracing
        #: work on every send/recv path
        self._tracer = None
        self._flightrec = None
        self._sinks: Tuple = ()
        #: protocol counters (exported through stats() -> bench bw/rtt;
        #: guarded-by: _proto_lock)
        self.proto: Dict[str, int] = {
            "act_eager": 0, "act_rdv": 0, "act_inline": 0,
            "eager_bytes": 0, "rdv_bytes": 0,
            "coalesced_batches": 0, "coalesced_msgs": 0,
            "eager_downshift": 0, "eager_upshift": 0,
        }
        #: per-peer adaptive eager state: dst -> {"eager": cur, "base":..}
        #: (guarded-by: _proto_lock)
        self._proto_peer: Dict[int, Dict[str, int]] = {}
        # adaptive-law constants cached off the task-retire hot path
        # (each params.get is a registry-lock round trip); the
        # comm_adaptive_eager SWITCH stays a live lookup so tests and
        # operators can flip it mid-run
        self._bp_budget = float(params.get("comm_backpressure_ms",
                                           2.0)) * 1e-3
        self._eager_floor_cfg = int(params.get("comm_eager_min", 4096))
        self._eager_cap_mult = max(
            1, int(params.get("comm_eager_cap_mult", 4)))
        #: guards proto counters + _proto_peer read-modify-writes:
        #: flush_activations runs concurrently on every worker stream
        self._proto_lock = threading.Lock()
        #: cross-task flush window: dst -> [(tag, msg), ...]
        #: (guarded-by: _flush_lock)
        self._flushbox: Dict[int, List] = {}
        self._flush_lock = threading.Lock()
        self._flush_deadline: Optional[float] = None  # guarded-by: _flush_lock
        # Progress model (reference: the comm thread + dep_cmd_queue,
        # remote_dep_mpi.c:461-503).  On a FUNNELLED transport (evloop)
        # the dep-engine work runs directly on the transport's single
        # loop thread: AM callbacks dispatch the handlers in place and
        # sends ride the loop's command ring — zero cross-thread
        # wakeups on the recv->handler->send data path.  On the
        # threaded transport, socket recv threads only ENQUEUE and one
        # dedicated comm-progress thread drains the command queue with
        # per-peer send aggregation (the pre-r6 path, selectable via
        # PARSEC_MCA_COMM_TRANSPORT=threads for A/B attribution).
        self.funnelled = bool(getattr(ce, "FUNNELLED", False))
        self._cmdq: "queue_mod.Queue" = queue_mod.Queue()
        self._stop = False
        if self.funnelled:
            ce.tag_register(TAG_ACTIVATE, self._activate_cb)
            ce.tag_register(TAG_GET_REQ, self._get_req_cb)
            ce.tag_register(TAG_GET_REP, self._get_rep_cb)
            ce.tag_register(TAG_DTD, self._dtd_cb)
        else:
            ce.tag_register(TAG_ACTIVATE, self._enq_cb("activate"))
            ce.tag_register(TAG_GET_REQ, self._enq_cb("get_req"))
            ce.tag_register(TAG_GET_REP, self._enq_cb("get_rep"))
            ce.tag_register(TAG_DTD, self._enq_cb("dtd"))
        ce.tag_register(TAG_TERMDET, self._termdet_cb)
        ce.tag_register(TAG_BATCH, self._batch_cb)
        ce.tag_register(TAG_UTRIG, self._utrig_cb)
        #: pending GET completions: handle -> (tp_id, deliveries)
        self._pending_gets: Dict[Tuple[int, int], dict] = {}
        #: DTD messages that raced their pool's registration on this rank
        #: (guarded-by: _dlock)
        self._dtd_backlog: Dict[int, List] = {}
        #: outstanding DTD rendezvous pulls (Safra-visible in-flight work:
        #: the one-sided GET itself rides uncounted CE messages;
        #: guarded-by: _term_lock)
        self.dtd_refs_pending = 0
        #: per-pool share of dtd_refs_pending, so a recovery restart can
        #: forget exactly its pool's parked pulls (guarded-by: _term_lock)
        self._dtd_refs_tp: Dict[int, int] = {}
        self._recv_handlers = {
            "activate": self._activate_cb,
            "get_req": self._get_req_cb,
            "get_rep": self._get_rep_cb,
            "dtd": self._dtd_cb,
        }
        #: cross-task flush window, cached at init (run-scoped knob)
        self._flush_window = float(params.get("comm_flush_window_ms", 0.0))
        #: clock alignment: probe every peer's offset at attach and
        #: periodically after (drift), through the transport's own
        #: progress machinery (the event loop / the progress thread)
        self._clock_on = bool(int(params.get("comm_clock_sync", 1))) \
            and self.nranks > 1
        self._clock_period = max(0.5,
                                 float(params.get("comm_clock_probe_s",
                                                  5.0)))
        #: active failure detection: TAG_HB heartbeats piggyback on the
        #: TAG_CLOCK probe cadence (capped at timeout/3 so a silent peer
        #: is declared within ~2x the timeout even with drifty timers)
        self._peer_timeout = float(params.get("comm_peer_timeout_s",
                                              15.0))
        self._hb_period = max(0.2, min(self._clock_period,
                                       self._peer_timeout / 3.0)) \
            if self._peer_timeout > 0 else 0.0
        self._hb_on = self._peer_timeout > 0 and self.nranks > 1
        #: rendezvous retry/backoff state (see _retry_rendezvous)
        self._rdv_retry = max(0.05, float(params.get("comm_rdv_retry_s",
                                                     2.0)))
        self._rdv_timeout = float(params.get("comm_rdv_timeout_s", 60.0))
        # telemetry plane wiring — BEFORE the progress machinery arms:
        # the first clock-probe round fires at attach on the loop
        # thread, and its accepted RTT must find on_clock_rtt wired
        # (the always-on registry serves TAG_METRICS pulls, an armed
        # flight recorder answers TAG_FLIGHT dump requests)
        m = getattr(context, "metrics", None)
        if m is not None:
            ce.metrics_provider = m.samples
            ce.on_clock_rtt = m.comm_frame_rtt.observe
        jr = getattr(context, "journal", None)
        if jr is not None:
            # control-plane black box: the journal learns this rank's
            # incarnation + clock table, the engine learns where
            # barrier/death events land and how to answer journal pulls
            jr.attach_comm(ce)
        fr = getattr(context, "_flightrec", None)
        if fr is not None:
            fr.attach_comm(self)
        rec = getattr(context, "recovery", None)
        if rec is not None:
            # recovery plane (core/recovery.py): wires the TAG_REJOIN
            # validator and lets the transport accept reconnections
            # from dead ranks pending that handshake
            rec.attach_comm(self)
        if self.funnelled:
            self._progress = None
            ce.add_periodic(self._purge_stale_handles, 5.0)
            ce.add_periodic(self._retry_rendezvous,
                            max(0.25, self._rdv_retry / 2.0))
            if self._clock_on:
                ce.add_periodic(ce.probe_clocks, self._clock_period)
                ce.post(ce.probe_clocks)   # first round at attach
            if self._hb_on:
                ce.add_periodic(ce.heartbeat_tick, self._hb_period)
                ce.add_periodic(ce.check_peer_timeouts, self._hb_period)
            if self._flush_window > 0:
                ce.add_periodic(self._drain_flush_window,
                                max(self._flush_window * 5e-4, 0.001))
        else:
            self._progress = threading.Thread(
                target=self._progress_loop,
                name=f"parsec-comm-{self.rank}", daemon=True)
            self._progress.start()
            if self._clock_on:
                # attach-time first round, like the funnelled path's
                # ce.post above: a run shorter than the first timer
                # tick must still feed clock tables + the frame-RTT
                # histogram.  Safe off the progress thread: SocketCE's
                # probe_clocks pings ESTABLISHED peers only (never
                # parks in _connect); peers still dialing in are
                # covered by the progress loop's fast first-round retry
                try:
                    ce.probe_clocks()
                except OSError:
                    pass

    # ------------------------------------------------------------------
    # funnelled comm progress (reference: remote_dep_dequeue_main)
    # ------------------------------------------------------------------
    def _enq_cb(self, kind: str):
        def cb(src: int, msg: Any) -> None:
            self._cmdq.put(("recv", kind, src, msg))
        return cb

    # lint: on-loop (AM handler)
    def _batch_cb(self, src: int, msgs: List) -> None:
        """Unpack an aggregated frame into individual commands."""
        for tag, payload in msgs:
            self.ce.recv_msgs += 1   # each inner message counts
            self.ce._dispatch(tag, src, payload)

    def send_user_trigger(self, tp_id: int) -> None:
        """Broadcast a user-declared termination to every peer
        (reference: the user_trigger termdet's own AM tag)."""
        for r in range(self.nranks):
            if r != self.rank:
                try:
                    self.ce.send_am(TAG_UTRIG, r, {"tp": tp_id})
                except OSError:
                    pass   # dead peer; its loss is already routed

    # lint: on-loop (AM handler)
    def _utrig_cb(self, src: int, msg: dict) -> None:
        tp = self.context.taskpools.get(msg["tp"])
        if tp is None or tp.termdet is None:
            # raced registration: retry until the SPMD peer reaches
            # add_taskpool (bounded — a missing pool is a program error)
            tries = msg.get("_tries", 0)
            if tries >= 2400:   # ~2 minutes: the pool will never appear
                self.context.record_error(RuntimeError(
                    f"rank {self.rank}: user-trigger for taskpool "
                    f"{msg['tp']} which never registered (mismatched "
                    "SPMD insertion?)"), None)
                return
            if tries and tries % 200 == 0:   # ~every 10s of waiting
                warning("rank %d: user-trigger still waiting for "
                        "taskpool %s to register", self.rank, msg["tp"])
            t = threading.Timer(0.05, self._utrig_cb,
                                args=(src, {**msg, "_tries": tries + 1}))
            t.daemon = True
            t.start()
            return
        tp.termdet.trigger(tp, propagate=False)

    def memcpy_shift(self, dst_copy, src_copy) -> None:
        """Thread-shift a local payload copy onto the comm-progress
        thread (reference: parsec_remote_dep_memcpy's short-circuit,
        remote_dep_mpi.c:557 — local reshape copies ride the comm thread
        so workers never block on memcpy)."""
        if self.funnelled:
            self.ce.post(self._do_memcpy, dst_copy, src_copy)
        else:
            self._cmdq.put(("memcpy", dst_copy, src_copy))

    @staticmethod
    # lint: on-loop (posted onto the comm loop by memcpy_shift)
    def _do_memcpy(dst_copy, src_copy) -> None:
        np.copyto(np.asarray(dst_copy.payload), np.asarray(src_copy.payload))

    # lint: on-loop (periodic hook on the evloop thread)
    def _purge_stale_handles(self) -> None:
        """GC rendezvous handles no receiver ever pulled (reference gap
        closed: refcounted handles with no timeout would leak if a rank
        in the bcast tree dies or the eager race skips its GET).  Fully
        served handles linger for a short grace (dead_at) so a
        retransmitted GET_REQ can be re-served idempotently."""
        ttl = float(params.get("comm_handle_timeout", 600.0))
        now = time.monotonic()
        stale = []
        with self._hlock:
            for h, handle in list(self._handles.items()):
                if handle.dead_at is not None:
                    if now - handle.dead_at > _HANDLE_GRACE_S:
                        del self._handles[h]   # served; silent drop
                elif now - handle.born > ttl:
                    stale.append(h)
                    del self._handles[h]
        for h in stale:
            warning("rank %d: dropping unclaimed rendezvous handle %d "
                    "after %.0fs", self.rank, h, ttl)
        # the DTD's serve-once regions share the same abandonment GC
        self.ce.purge_once_regions(ttl)

    def _progress_loop(self) -> None:
        next_purge = time.monotonic() + 5.0
        # first clock round right after attach, like the funnelled
        # transport's attach-time post — safe now that SocketCE's
        # probe_clocks pings ESTABLISHED peers only (a short run must
        # still feed the frame-RTT histogram at least one round);
        # then every probe period for drift
        next_clock = time.monotonic() + 0.05 if self._clock_on \
            else float("inf")
        next_hb = time.monotonic() + self._hb_period if self._hb_on \
            else float("inf")
        next_rdv = time.monotonic() + self._rdv_retry
        while not self._stop:
            if time.monotonic() > next_purge:
                self._purge_stale_handles()
                next_purge = time.monotonic() + 5.0
            if time.monotonic() > next_clock:
                probed = 0
                try:
                    probed = self.ce.probe_clocks()
                except OSError:
                    pass
                # a round that reached nobody (peers still dialing in)
                # retries fast: short runs must still get their first
                # accepted sample into the frame-RTT histogram
                next_clock = time.monotonic() + \
                    (self._clock_period if probed else 0.1)
            if time.monotonic() > next_hb:
                try:
                    self.ce.heartbeat_tick()
                except OSError:
                    pass
                self.ce.check_peer_timeouts()
                next_hb = time.monotonic() + self._hb_period
            if time.monotonic() > next_rdv:
                self._retry_rendezvous()
                next_rdv = time.monotonic() + max(0.25,
                                                  self._rdv_retry / 2.0)
            self._drain_flush_window()
            try:
                cmd = self._cmdq.get(timeout=0.05)
            except queue_mod.Empty:
                continue
            batch = [cmd]
            while True:
                try:
                    batch.append(self._cmdq.get_nowait())
                except queue_mod.Empty:
                    break
            #: per-destination send aggregation: consecutive outbound
            #: messages to one peer ride ONE wire frame
            sends: Dict[int, List[Tuple[int, Any]]] = {}
            for cmd in batch:
                try:
                    if cmd[0] == "send":
                        _, tag, dst, payload = cmd
                        sends.setdefault(dst, []).append((tag, payload))
                    elif cmd[0] == "recv":
                        _, kind, src, msg = cmd
                        self._recv_handlers[kind](src, msg)
                    elif cmd[0] == "memcpy":
                        _, dst_copy, src_copy = cmd
                        np.copyto(np.asarray(dst_copy.payload),
                                  np.asarray(src_copy.payload))
                except Exception as exc:
                    self._on_handler_error(exc)
            for dst, msgs in sends.items():
                try:
                    if dst in self.ce.dead_peers:
                        continue   # undeliverable; the death was routed
                    if len(msgs) == 1:
                        self.ce.send_am(msgs[0][0], dst, msgs[0][1])
                    else:
                        self.ce.send_am(TAG_BATCH, dst, msgs)
                        # the BATCH frame carried len(msgs) app messages
                        # in one send; the counters already accounted
                        # each at enqueue time
                except OSError:
                    # the lane died mid-send (EOF, dead-peer raise,
                    # SNDTIMEO): the transport's death path already
                    # routed a CONTAINED PeerFailedError into the
                    # touched pools — recording it again here would be
                    # context-GLOBAL and poison every pool on the rank
                    pass
                except Exception as exc:
                    self._on_handler_error(exc)

    def _on_handler_error(self, exc: Exception) -> None:
        self.context.record_error(exc, None)

    # ------------------------------------------------------------------
    # robustness: fault reconcile, dedup, rendezvous retry, containment
    # ------------------------------------------------------------------
    def _on_frame_fault(self, kind: str, tag: int, payload,
                        dst: int = -1) -> None:
        """Safra reconcile for injected frame faults: the counters must
        reflect what actually crossed the wire, or the token never sees
        a zero balance again (a permanent hang the PLAN did not ask
        for).  Only Safra-counted tags matter.  The per-destination
        twin moves WITH the global counter — recovery_reconcile
        subtracts a dead rank's contribution wholesale, and a drift
        between the two would push the post-recovery balance negative
        forever."""
        if tag == TAG_BATCH:
            n = len(payload) if isinstance(payload, list) else 1
        elif tag in (TAG_ACTIVATE, TAG_GET_REQ, TAG_GET_REP, TAG_DTD):
            n = 1
        else:
            return
        d = n if kind == "dup" else -n
        with self._term_lock:
            if dst >= 0 and dst in self._fence_epoch \
                    and dst not in self._sent_to:
                # the injector's delay timer outlived the peer AND its
                # recovery: recovery_reconcile already erased this
                # lane's whole count — subtracting the held frame again
                # would drive the survivor balance permanently negative
                # and the token would never see zero
                return
            self._app_sent += d
            if dst >= 0:
                self._sent_to[dst] = self._sent_to.get(dst, 0) + d

    def _is_dup(self, msg) -> bool:
        """Receiver-side dedup by wire id.  Called AFTER the Safra recv
        credit (the duplicate's send was also counted), bounded memory.
        Runs on the single comm/progress thread of either transport."""
        fid = msg.get("_fid") if isinstance(msg, dict) else None
        if fid is None:
            return False
        if fid in self._seen_fids:
            warning("rank %d: dropped duplicate app message %s",
                    self.rank, fid)
            return True
        self._seen_fids.add(fid)
        self._fid_order.append(fid)
        if len(self._fid_order) > 8192:
            self._seen_fids.discard(self._fid_order.popleft())
        return False

    # lint: on-loop (periodic hook)
    def _retry_rendezvous(self) -> None:
        """Bounded retry with exponential backoff for parked rendezvous
        pulls, and a terminal deadline: a GET whose source died or never
        answers fails ITS taskpool with a structured PeerFailedError
        instead of waiting forever (pre-r8 behavior: _pending_gets
        entries were immortal)."""
        if not self._pending_gets:
            return
        now = time.monotonic()
        for key, pend in list(self._pending_gets.items()):
            root, handle = key
            exc = None
            if root in self.ce.dead_peers:
                exc = PeerFailedError(
                    root, f"rank {self.rank}: rendezvous source rank "
                          f"{root} died (handle {handle})",
                    detector="rendezvous")
            elif now - pend["sent_at"] > self._rdv_timeout:
                exc = PeerFailedError(
                    root, f"rank {self.rank}: rendezvous pull of handle "
                          f"{handle} from rank {root} unanswered after "
                          f"{self._rdv_timeout:g}s "
                          f"({pend['attempts'] + 1} attempts)",
                    detector="rendezvous")
            if exc is not None:
                if self._pending_gets.pop(key, None) is not None:
                    self._contain_pool(pend["tp"], exc)
                continue
            if now >= pend["next_at"]:
                pend["attempts"] += 1
                pend["next_at"] = now + self._rdv_retry \
                    * (2 ** pend["attempts"])
                warning("rank %d: re-sending rendezvous GET %s to rank "
                        "%d (attempt %d)", self.rank, handle, root,
                        pend["attempts"] + 1)
                try:
                    self._send_app(TAG_GET_REQ, root,
                                   {"handle": handle, "from": self.rank})
                except (PeerFailedError, OSError):
                    # lint: contained (the next sweep sees dead_peers
                    # and fails the pull's pool with the terminal error)
                    pass

    def _on_peer_dead(self, rank: int, exc: Exception) -> None:
        """Containment — with a second exit: a dead peer's taskpools
        (parked rendezvous pulls rooted there, pools that exchanged
        traffic with it via Taskpool.peer_ranks) are first offered to
        the RECOVERY plane (core/recovery.py), which re-executes their
        lost lineage on the survivors; whatever recovery does not take
        fails through the per-pool error route (Context.record_pool_error
        -> error_sink for service jobs) exactly as before.  Only when
        nothing can be attributed AND no recovery excused the death does
        the failure land on the context globally (the pre-r8 behavior)."""
        pools: Dict[int, Any] = {}
        for key in [k for k in list(self._pending_gets) if k[0] == rank]:
            pend = self._pending_gets.pop(key, None)
            if pend is not None:
                pools[id(pend["tp"])] = pend["tp"]
        for tp in list(self.context.taskpools.values()):
            if rank in getattr(tp, "peer_ranks", ()):
                pools[id(tp)] = tp
        live = [tp for tp in pools.values()
                if not getattr(tp, "completed", False)
                and not getattr(tp, "cancelled", False)]
        handled = False
        rec = getattr(self.context, "recovery", None)
        if rec is not None:
            handled, live = rec.on_peer_dead(rank, exc, live)
        routed = False
        for tp in live:
            routed = True
            self.context.record_pool_error(tp, exc)
        if not routed and not handled:
            self.context.record_error(exc, None)

    def _contain_pool(self, tp, exc: Exception) -> None:
        """Pool-scoped containment with recovery awareness: secondary
        failures of a generation that is already being rebuilt (dead-
        child sends, parked pulls of the torn run) are swallowed — the
        restart owns that pool's fate — everything else routes through
        Context.record_pool_error as before."""
        rec = getattr(self.context, "recovery", None)
        if rec is not None and isinstance(exc, PeerFailedError) \
                and rec.recovering(tp) and rec.excused(exc.rank):
            return
        self.context.record_pool_error(tp, exc)

    # -- recovery reconcile (core/recovery.py) ---------------------------
    def peer_fence(self, src: int) -> int:
        with self._term_lock:
            return self._fence_epoch.get(src, 0)

    def note_peer_epoch(self, src: int, epoch: int) -> None:
        """A rejoined incarnation announced ``epoch``: frames at or
        above it pass the fence, its dead predecessor's stay out."""
        with self._term_lock:
            self._peer_epoch[src] = epoch
            self._fence_epoch.setdefault(src, 0)

    def recovery_reconcile(self, dead: int) -> None:
        """Subtract a dead rank's whole contribution from the Safra
        balance and fence its future stragglers, in ONE critical
        section — after this the token sees exactly the in-flight
        traffic among survivors, so termination detection converges
        once the re-inserted sub-DAG drains (the generalization of the
        on_frame_fault drop reconcile)."""
        with self._term_lock:
            fence = self._fence_epoch[dead] = \
                self._peer_epoch.get(dead, 0) + 1
            sent = self._sent_to.pop(dead, 0)
            recv = self._recv_from.pop(dead, 0)
            self._app_sent -= sent
            self._app_recv -= recv
        jr = getattr(self.context, "journal", None)
        if jr is not None:
            jr.emit("safra_reconcile", peer=dead, fence=fence,
                    sent=sent, recv=recv)

    def forget_pool(self, tp) -> None:
        """Drop every parked/queued protocol item of a pool's torn
        generation (recovery restart): delayed activations, outbox and
        flush-window frames, parked rendezvous pulls, DTD backlog and
        pending-pull counts.  Safra stays balanced: inbound items were
        credited at receive, outbound ones were counted only if they
        reached _send_app (queued-not-sent outbox entries were not)."""
        tpid = tp.taskpool_id
        with self._dlock:
            self._delayed = [(s, m) for (s, m) in self._delayed
                             if not (isinstance(m, dict)
                                     and m.get("tp") == tpid)]
            self._dtd_backlog.pop(tpid, None)
        with self._outbox_lock:
            for key in [k for k, edges in list(self._outbox.items())
                        if edges and edges[0][0].taskpool is tp]:
                self._outbox.pop(key, None)
        with self._flush_lock:
            for dst in list(self._flushbox):
                kept = [(t, m) for (t, m) in self._flushbox[dst]
                        if not (isinstance(m, dict)
                                and m.get("tp") == tpid)]
                if kept:
                    self._flushbox[dst] = kept
                else:
                    del self._flushbox[dst]
        for key, pend in list(self._pending_gets.items()):
            if pend.get("tp") is tp:
                self._pending_gets.pop(key, None)
        with self._term_lock:
            # every generation of this pool: the restart re-registers
            # what the new generation actually pulls
            for key in [k for k in self._dtd_refs_tp if k[0] == tpid]:
                self.dtd_refs_pending -= self._dtd_refs_tp.pop(key)

    def debug_state(self) -> Dict[str, Any]:
        """Protocol-state snapshot for the hang autopsy (Context.wait's
        soft deadline): termdet balance, parked work, per-peer liveness."""
        now = time.monotonic()
        with self._term_lock:
            out: Dict[str, Any] = {
                "app_sent": self._app_sent, "app_recv": self._app_recv,
                "balance": self._app_sent - self._app_recv,
                "color_black": self._color_black,
                "dyn_holds": len(self._dyn_holds),
                "dtd_refs_pending": self.dtd_refs_pending,
            }
        with self._dlock:
            out["delayed_activations"] = len(self._delayed)
            out["dtd_backlog"] = sum(len(v)
                                     for v in self._dtd_backlog.values())
        out["pending_gets"] = {
            f"{root}:{h}": {"attempts": p.get("attempts", 0),
                            "age_s": round(now - p.get("sent_at", now), 2)}
            for (root, h), p in list(self._pending_gets.items())}
        with self._hlock:
            out["serving_handles"] = len(self._handles)
        with self._flush_lock:
            out["flush_window_msgs"] = sum(len(v)
                                           for v in self._flushbox.values())
        out["dead_peers"] = sorted(self.ce.dead_peers)
        out["peers"] = self.ce.peer_debug()
        return out

    # ------------------------------------------------------------------
    # sender side
    # ------------------------------------------------------------------
    def remote_dep_activate(self, es, task, flow, dep, succ_tc, succ_locals,
                            copy) -> None:
        """Buffer one remote successor edge; flushed per task
        (reference: parsec_remote_dep_activate aggregating rank bits)."""
        from parsec_tpu.data.reshape import as_dtt, needs_reshape
        dst = succ_tc.rank_of(succ_locals)
        dtt = as_dtt(dep.dtt)
        if dtt is not None and needs_reshape(copy, dtt):
            # pre-send reshape: the converted payload is what travels
            # (reference: parsec_reshape.c remote pre-send path)
            copy = task.taskpool.reshape.get_copy(copy, dtt)
        with self._outbox_lock:
            self._outbox.setdefault(id(task), []).append(
                (task, flow, copy, dst, succ_tc.name, dict(succ_locals),
                 dep.end.flow))

    def flush_activations(self, es, task) -> None:
        """Group the task's buffered edges by flow payload and pack
        EVERY same-destination activation of the completing task into
        one framed batch (reference: remote_dep.c aggregating all of a
        task's output deps per rank into one activation message — one
        frame/syscall per dependency edge bundle, not per edge).  With
        ``comm_flush_window_ms`` > 0 the batch additionally holds for a
        short window so activations of OTHER tasks completing within it
        coalesce too."""
        with self._outbox_lock:
            edges = self._outbox.pop(id(task), None)
        if not edges:
            return
        byflow: Dict[Tuple, dict] = {}
        for (_t, flow, copy, dst, tc_name, locs, dflow) in edges:
            # group by (flow, payload copy): pre-send reshapes may split
            # one flow into several distinct payloads
            ent = byflow.setdefault((flow.name, id(copy)),
                                    {"copy": copy, "targets": {}})
            ent["targets"].setdefault(dst, []).append((tc_name, locs, dflow))
        tp = task.taskpool
        per_child: Dict[int, List[Tuple[int, dict]]] = {}
        for fname, ent in byflow.items():
            copy = ent["copy"]
            targets = ent["targets"]
            ranks = sorted(targets)
            msg = {
                "tp": tp.taskpool_id,
                "pe": tp.run_epoch,   # recovery generation fence
                "root": self.rank,
                "src_task": str(task),
                "deliveries": {r: targets[r] for r in ranks},
                "ranks": ranks,
            }
            tp.peer_ranks.update(ranks)   # containment attribution
            lin = tp._lineage
            if lin is not None:
                # recovery lineage: the recorded dests seed the
                # minimal-replay plan (tasks that fed a dead rank must
                # re-run so the re-executed partition is re-fed)
                lin.note_send(task, ranks)
            if self._sinks:
                # producer identity for the causal DAG: the same oid the
                # task_profiler's exec interval carries (forwarders keep
                # it, so tree hops still attribute to the producer)
                msg["_oid"] = hash(task.key)
            children = self._children(msg, self.rank)
            if copy is not None:
                payload = copy.payload
                if hasattr(payload, "addressable_shards") or \
                        not isinstance(payload, np.ndarray):
                    payload = np.asarray(payload)   # pull device data home
                buf, dt, shape = _encode(payload)
                nbytes = getattr(buf, "nbytes", len(buf))
                thr = min((self._peer_eager(c) for c in children),
                          default=self.eager)
                if nbytes <= thr:
                    msg["data"] = ("eager", buf, dt, shape)
                    with self._proto_lock:
                        self.proto["act_eager"] += 1
                        self.proto["eager_bytes"] += nbytes
                else:
                    h = next(_handle_seq)
                    with self._hlock:
                        self._handles[h] = _Handle((buf, dt, shape),
                                                   refs=len(ranks))
                    msg["data"] = ("get", h, dt, shape)
                    with self._proto_lock:
                        self.proto["act_rdv"] += 1
                        self.proto["rdv_bytes"] += nbytes
            else:
                msg["data"] = None
                with self._proto_lock:
                    self.proto["act_inline"] += 1
            for child in children:
                per_child.setdefault(child, []).append((TAG_ACTIVATE, msg))
        if not per_child:
            return
        window = self._flush_window
        if window > 0:
            with self._flush_lock:
                for child, items in per_child.items():
                    self._flushbox.setdefault(child, []).extend(items)
                if self._flush_deadline is None:
                    self._flush_deadline = time.monotonic() + window * 1e-3
            self._drain_flush_window()   # opportunistic: past-due drains
        else:
            for child, items in per_child.items():
                try:
                    self._send_batch(child, items)
                except PeerFailedError as exc:
                    # a dead child must not cut off its live siblings:
                    # route into the owning pool (the window>0 path's
                    # drain does the same per child)
                    self._contain_pool(tp, exc)

    # lint: on-loop (periodic hook + opportunistic worker calls)
    def _drain_flush_window(self, force: bool = False) -> None:
        """Ship the cross-task flush window once its deadline passed
        (driven by the transport's periodic hook / the progress loop)."""
        if not self._flushbox:
            return
        with self._flush_lock:
            if not self._flushbox:
                return
            if not force and self._flush_deadline is not None and \
                    time.monotonic() < self._flush_deadline:
                return
            box, self._flushbox = self._flushbox, {}
            self._flush_deadline = None
        for child, items in box.items():
            try:
                self._send_batch(child, items)
            except PeerFailedError as exc:
                # window drains run on the comm/progress thread, where
                # nothing catches for us: route into the owning pools
                for tpid in {p.get("tp") for _t, p in items
                             if isinstance(p, dict)}:
                    tp = self.context.taskpools.get(tpid)
                    if tp is not None:
                        self._contain_pool(tp, exc)

    # -- adaptive eager/rendezvous threshold (reference: the eager-limit
    # MCA of remote_dep_mpi.c, made per-peer and feedback-driven) --------
    def _peer_eager(self, dst: int) -> int:
        """Per-peer eager threshold: starts at ``comm_eager_limit``;
        observed backpressure (projected queue drain delay or frame
        latency above the budget) halves it — big payloads then ride
        rendezvous so receivers pull when ready — while a fast-draining
        pipe raises it toward the cap, skipping the GET round-trip."""
        base = self.eager
        if not params.get("comm_adaptive_eager", True):
            return base
        fb_fn = getattr(self.ce, "peer_feedback", None)
        fb = fb_fn(dst) if fb_fn is not None else None
        budget = self._bp_budget
        floor = min(self._eager_floor_cfg, base)
        cap = base * self._eager_cap_mult
        now = time.monotonic()
        # the read-modify-write below is shared across worker streams
        # completing tasks concurrently: a lost adjustment would leave a
        # congested peer's threshold half-lowered
        with self._proto_lock:
            st = self._proto_peer.get(dst)
            if st is None or st["base"] != base:
                # (re)base: tests and benches mutate self.eager mid-run
                st = self._proto_peer[dst] = {"eager": base, "base": base,
                                              "adj_at": 0.0}
            if fb is None:
                return st["eager"]
            # one adjustment per feedback window: a burst of queries
            # within one budget interval sees the SAME stale EWMA
            # sample — shifting once per query would multiply the step
            # and thrash the threshold instead of converging
            if now - st.get("adj_at", 0.0) < budget:
                return st["eager"]
            rate = fb.get("rate_ewma") or 0.0
            pending = fb.get("out_bytes") or 0
            delay = fb.get("delay_ewma") or 0.0
            if rate > 0:
                proj = pending / rate
            else:
                proj = 0.0 if pending < (64 << 10) else 2.0 * budget
            if proj > budget or delay > 4.0 * budget:
                if st["eager"] > floor:
                    st["eager"] = max(floor, st["eager"] // 2)
                    st["adj_at"] = now
                    self.proto["eager_downshift"] += 1
            elif proj < budget / 8.0 and delay < budget / 2.0 and \
                    st["eager"] < cap:
                st["eager"] = min(cap, st["eager"] * 2)
                st["adj_at"] = now
                self.proto["eager_upshift"] += 1
            return st["eager"]

    def stats(self) -> Dict[str, Any]:
        """Protocol + transport counters for the bench's bw/rtt modes
        and the prof gauges."""
        with self._proto_lock:
            out: Dict[str, Any] = dict(self.proto)
            out["peer_eager"] = {r: st["eager"]
                                 for r, st in self._proto_peer.items()}
        out.update(self.ce.stats.as_dict())
        out["msgs_sent"] = self.ce.sent_msgs
        out["msgs_recv"] = self.ce.recv_msgs
        out["transport"] = getattr(self.ce, "TRANSPORT",
                                   "evloop" if self.funnelled
                                   else "threads")
        extra = getattr(self.ce, "extra_stats", None)
        if extra is not None:
            # transport-specific counters (the shm ring exports
            # ring_full stalls + doorbell traffic through here)
            out.update(extra())
        # per-peer comm-delay estimate for the live attribution plane
        # (prof/liveattr.py), folded at SCRAPE time from state the
        # transport already maintains: the clock-probe min-RTT table
        # (one-way wire+dispatch ~ rtt/2) plus the queue->wire drain
        # EWMA of the adaptive protocol's feedback, where the
        # transport keeps one — zero new hot-path work
        delays: Dict[int, float] = {}
        try:
            for r, st in self.ce.clock_table().items():
                delays[r] = float(st.get("rtt", 0.0)) / 2.0
            fb_fn = getattr(self.ce, "peer_feedback", None)
            if fb_fn is not None:
                for r in list(delays):
                    fb = fb_fn(r)
                    if fb and fb.get("delay_ewma"):
                        delays[r] += float(fb["delay_ewma"])
        except Exception:   # a torn-down transport must not kill stats
            pass
        out["peer_comm_delay_s"] = delays
        return out

    # -- bcast topologies (reference: remote_dep.c:334-357, virtual
    # topologies re-rooted at the source) ----------------------------------
    def _children(self, msg: dict, me: int) -> List[int]:
        """My children in the tree over [root] + receiver ranks."""
        nodes = [msg["root"]] + list(msg["ranks"])
        i = nodes.index(me)
        n = len(nodes)
        if self.bcast == "star":
            return nodes[1:] if i == 0 else []
        if self.bcast == "chain":
            return [nodes[i + 1]] if i + 1 < n else []
        # binomial: children of position i are i + 2^j for 2^j < lsb(i)
        # (root's lsb is unbounded); parent(i) = i - lsb(i)
        kids = []
        lsb = i & -i if i else n
        m = 1
        while m < lsb and i + m < n:
            kids.append(nodes[i + m])
            m <<= 1
        return kids

    def _send_tree(self, msg: dict) -> None:
        """Forward down the bcast tree.  A dead child must not cut off
        its LIVE siblings: every child is attempted, and the first
        failure re-raises after the loop for the caller's pool
        routing."""
        first: Optional[PeerFailedError] = None
        for child in self._children(msg, self.rank):
            try:
                self._send_app(TAG_ACTIVATE, child, msg)
            except PeerFailedError as exc:
                if first is None:
                    first = exc
        if first is not None:
            raise first

    def _stamp_fid(self, payload) -> None:
        """Give an app message its wire id (origin rank, seq) — the
        receiver-side dedup key.  Stamped only at the ORIGINATOR (tree
        forwarders relay the id), so one logical message keeps one id
        across every hop and retransmit copies are recognizable."""
        if isinstance(payload, dict) and "_fid" not in payload:
            payload["_fid"] = (self.rank, next(self._fid_seq))

    def _stamp_ep(self, payload) -> None:
        """Incarnation mark, re-stamped PER HOP (unlike the fid): the
        receiver's fence is keyed by the rank it physically received
        the frame from, so ``_ep`` must name the LAST sender's
        incarnation — a rejoined rank relaying an epoch-0 originator's
        activation down the bcast tree must not have the relay fenced
        as its dead predecessor's straggler.  First incarnations
        (epoch 0) stamp nothing — a fence only ever exists for ranks
        that died, and a rejoiner is epoch >= 1 by construction."""
        if self._epoch and isinstance(payload, dict):
            payload["_ep"] = self._epoch

    def _dead_peer_guard(self, dst: int) -> None:
        if dst in self.ce.dead_peers:
            raise PeerFailedError(
                dst, f"rank {self.rank}: send to dead rank {dst}",
                detector="send")

    def _send_app(self, tag: int, dst: int, payload) -> None:
        """Application-message send: counted and blackening (Safra).
        On the event-loop transport the frame goes straight onto the
        loop's command ring; on the threaded transport it funnels
        through the comm-progress thread which aggregates per-peer
        (reference: remote_dep_dequeue_send).  A send to a DEAD rank
        raises a structured PeerFailedError instead of silently
        queueing — callers route it into the owning taskpool."""
        self._dead_peer_guard(dst)
        self._stamp_fid(payload)
        self._stamp_ep(payload)
        with self._term_lock:
            self._color_black = True
            self._app_sent += 1
            self._sent_to[dst] = self._sent_to.get(dst, 0) + 1
        if self._sinks:
            payload = self._traced(tag, dst, payload)
        self._post_send(tag, dst, payload)

    def _send_batch(self, dst: int, items: List[Tuple[int, Any]]) -> None:
        """Send several application messages to one destination as ONE
        wire frame (TAG_BATCH); each inner message stays individually
        counted for Safra (the receiver's _batch_cb mirrors this)."""
        self._dead_peer_guard(dst)
        for _tag, p in items:
            self._stamp_fid(p)
            self._stamp_ep(p)
        with self._term_lock:
            self._color_black = True
            self._app_sent += len(items)
            self._sent_to[dst] = self._sent_to.get(dst, 0) + len(items)
        if self._sinks:
            # per inner message: each gets its own correlation id; the
            # receiver's _batch_cb re-dispatches them individually, so
            # every flow edge survives coalescing
            items = [(tag, self._traced(tag, dst, p)) for tag, p in items]
        if len(items) == 1:
            self._post_send(items[0][0], dst, items[0][1])
            return
        with self._proto_lock:
            self.proto["coalesced_batches"] += 1
            self.proto["coalesced_msgs"] += len(items)
        self._post_send(TAG_BATCH, dst, list(items))

    # -- causal tracing (prof/causal.py) + flight recorder: every traced
    # app frame carries a send timestamp + (src_rank, event_seq)
    # correlation id; matched comm_send/comm_recv events become the
    # merged trace's flow edges.  The ``tracer``/``flightrec``
    # properties maintain ``_sinks`` so every site below fans out over
    # ONE tuple — adding a sink touches nothing here.
    @property
    def tracer(self):
        return self._tracer

    @tracer.setter
    def tracer(self, value) -> None:
        self._tracer = value
        self._resinks()

    @property
    def flightrec(self):
        return self._flightrec

    @flightrec.setter
    def flightrec(self, value) -> None:
        self._flightrec = value
        self._resinks()

    def _resinks(self) -> None:
        # tracer FIRST: its counter issues the correlation id when both
        # are live, so the ring's flow edges match the full trace's
        self._sinks = tuple(s for s in (self._tracer, self._flightrec)
                            if s is not None)

    def _traced(self, tag: int, dst: int, payload):
        sinks = self._sinks
        if not sinks or not isinstance(payload, dict):
            return payload
        corr = sinks[0].next_corr()
        now = time.perf_counter()
        # shallow copy: tree forwarding reuses one msg dict for several
        # children — each SEND is its own flow edge with its own id
        payload = dict(payload, _corr=corr, _sent_at=now)
        tp = payload.get("tp")
        root = payload.get("root")
        tpid = tp if isinstance(tp, int) else 0
        src_rank = root if isinstance(root, int) else None
        nbytes = _msg_nbytes(payload)
        for sink in sinks:
            sink.comm_send(tag, dst, corr, payload.get("_oid"),
                           nbytes, now, tpid=tpid, src_rank=src_rank)
        return payload

    def _trace_recv(self, tag: int, src: int, msg) -> None:
        sinks = self._sinks
        if not sinks or not isinstance(msg, dict):
            return
        corr = msg.get("_corr")
        if corr is None:
            return
        sent_at = msg.get("_sent_at")
        nbytes = _msg_nbytes(msg)
        for sink in sinks:
            sink.comm_recv(tag, src, corr, sent_at, nbytes)

    def _post_send(self, tag: int, dst: int, payload) -> None:
        if self.funnelled:
            self.ce.send_am(tag, dst, payload)
        else:
            self._cmdq.put(("send", tag, dst, payload))

    # ------------------------------------------------------------------
    # receiver side
    # ------------------------------------------------------------------
    def _on_app_recv(self, src: int, msg=None) -> bool:
        """Safra credit for one received app message; returns False —
        and credits NOTHING — when the frame is fenced: a straggler of
        a dead incarnation whose counters recovery_reconcile already
        subtracted (crediting it would push the survivor balance
        negative forever).  The fence check and the credit share one
        critical section so a concurrent reconcile can never see half
        of either."""
        with self._term_lock:
            fence = self._fence_epoch.get(src)
            if fence is not None:
                ep = msg.get("_ep", 0) if isinstance(msg, dict) else 0
                if ep < fence:
                    return False
            self._color_black = True   # Safra: receiving blackens
            self._app_recv += 1
            self._recv_from[src] = self._recv_from.get(src, 0) + 1
        return True

    # lint: on-loop (AM handler: runs in place on the evloop thread)
    def _activate_cb(self, src: int, msg: dict) -> None:
        self._trace_recv(TAG_ACTIVATE, src, msg)
        if not self._on_app_recv(src, msg):   # exactly once per message
            return            # fenced: stale incarnation straggler
        if self._is_dup(msg):
            return            # retransmit/injected dup: already acted on
        self._try_activation(src, msg)

    def _try_activation(self, src: int, msg: dict) -> None:
        from parsec_tpu.core.taskpool import TaskpoolState
        tp = self.context.taskpools.get(msg["tp"])
        if tp is not None and msg.get("pe", 0) < tp.run_epoch:
            # a torn recovery generation's activation (this pool already
            # restarted past it): the Safra credit landed, the delivery
            # is void — the restart re-enumerated every dependence
            return
        if tp is None or tp.state < TaskpoolState.RUNNING \
                or msg.get("pe", 0) > tp.run_epoch:
            # unknown taskpool, known but startup hasn't counted local
            # tasks yet, known but mid-recovery-restart (state rewound
            # below RUNNING parks EVERYTHING until the new generation's
            # structures exist), or a peer that finished ITS restart
            # before we even began ours (pe from the future): releasing
            # now would land in structures about to be torn down
            # (reference: delayed activations, remote_dep_mpi.c:1831).
            # One daemon timer at a time closes the race where the pool
            # became RUNNING and drained the queue between our state
            # check and the append.
            with self._dlock:
                self._delayed.append((src, msg))
                arm = not self._retry_pending
                if arm:
                    self._retry_pending = True
            if arm:
                t = threading.Timer(0.05, self.retry_delayed)
                t.daemon = True
                t.start()
            return
        self._process_activation(tp, msg)

    def retry_delayed(self) -> None:
        """Re-run activations that raced taskpool registration
        (reference: delayed activate queue, remote_dep_mpi.c:1831)."""
        with self._dlock:
            delayed, self._delayed = self._delayed, []
            self._retry_pending = False
        for src, msg in delayed:
            self._try_activation(src, msg)

    def _process_activation(self, tp, msg: dict) -> None:
        tp.peer_ranks.add(msg["root"])   # containment attribution
        # forward down the tree first (pipeline: data flows while we
        # work); a dead child fails THIS pool, not the whole context
        try:
            self._send_tree(msg)
        except PeerFailedError as exc:
            self._contain_pool(tp, exc)
        data = msg["data"]
        deliveries = msg["deliveries"].get(self.rank) or \
            msg["deliveries"].get(str(self.rank))
        if not deliveries:
            return
        corr = msg.get("_corr")
        if data is None:
            self._deliver(tp, deliveries, None, corr=corr)
        elif data[0] == "eager":
            _, buf, dt, shape = data
            self._deliver(tp, deliveries, _decode(buf, dt, shape),
                          corr=corr)
        else:   # rendezvous: pull the payload from the root
            _, handle, dt, shape = data
            key = (msg["root"], handle)
            now = time.monotonic()
            self._pending_gets[key] = {"tp": tp, "deliveries": deliveries,
                                       "corr": corr, "sent_at": now,
                                       "attempts": 0,
                                       "next_at": now + self._rdv_retry}
            try:
                self._send_app(TAG_GET_REQ, msg["root"],
                               {"handle": handle, "from": self.rank})
            except PeerFailedError as exc:
                self._pending_gets.pop(key, None)
                self._contain_pool(tp, exc)

    # lint: on-loop (AM handler)
    def _get_req_cb(self, src: int, msg: dict) -> None:
        self._trace_recv(TAG_GET_REQ, src, msg)
        if not self._on_app_recv(src, msg):
            return
        if self._is_dup(msg):
            return
        h = msg["handle"]
        with self._hlock:
            handle = self._handles.get(h)
        if handle is None:
            # purged (TTL) or never existed: report the miss to the
            # rank that actually cannot proceed — the requester — rather
            # than crashing the serving rank (the requester is still
            # alive by definition; if it died, the send guard raises
            # into _safe_dispatch and the death was already routed)
            try:
                self._send_app(TAG_GET_REP, src,
                               {"handle": h, "miss": True,
                                "root": self.rank})
            except PeerFailedError:
                # lint: contained (the requester died; its death was
                # already routed into the pools that touch it)
                pass
            return
        buf, dt, shape = handle.data
        try:
            self._send_app(TAG_GET_REP, src,
                           {"handle": h, "buf": buf, "dtype": dt,
                            "shape": shape, "root": self.rank})
        except PeerFailedError:
            # lint: contained (requester died — its death was already
            # routed; keep the handle for live readers)
            return
        with handle.lock:
            # fully-served handles LINGER (dead_at) for a grace period
            # instead of dropping instantly: a retransmitted GET_REQ
            # (retry backoff, duplicated frame) re-serves idempotently —
            # and decrements refs only ONCE per requester, else a slow
            # requester's retry would consume a sibling's ref and start
            # the grace purge while that sibling's pull is still parked
            if src not in handle.served:
                handle.served.add(src)
                handle.refs -= 1
                if handle.refs <= 0 and handle.dead_at is None:
                    handle.dead_at = time.monotonic()

    # ------------------------------------------------------------------
    # distributed DTD traffic (reference: the DTD two-sided protocol —
    # remote deps tracked by (tile, rank) with delayed release,
    # remote_dep_mpi.c:519, insert_function.c:3014-3163)
    # ------------------------------------------------------------------
    def dtd_send(self, dst: int, msg: dict) -> None:
        """Counted application send for the DTD layer (Safra-visible).
        Raises PeerFailedError when ``dst`` is dead — callers on worker
        threads route it into the pool via record_error."""
        tp = self.context.taskpools.get(msg.get("tp"))
        if tp is not None:
            tp.peer_ranks.add(dst)
            # recovery generation: a survivor mid-restart parks frames
            # of its already-recovered peer instead of losing them
            msg.setdefault("pe", tp.run_epoch)
        self._send_app(TAG_DTD, dst, msg)

    def dtd_ref_done(self, ref_key=None) -> None:
        """One rendezvous pull completed (locked: the counter is shared
        between the progress thread and socket recv threads).
        ``ref_key`` is the (taskpool_id, pool-generation) the pull was
        credited under in _dtd_cb.  A pull whose pool a recovery
        already forgot (forget_pool subtracted that generation's whole
        share) must NOT decrement again — the double-count would drive
        the global below zero, and a truthy negative keeps _local_idle
        False forever; the generation in the key also stops a
        pre-restart pull's completion from eating the NEW generation's
        count."""
        with self._term_lock:
            if ref_key is not None:
                n = self._dtd_refs_tp.get(ref_key)
                if n is None:
                    return   # forgotten by a recovery restart
                if n <= 1:
                    self._dtd_refs_tp.pop(ref_key, None)
                else:
                    self._dtd_refs_tp[ref_key] = n - 1
            self.dtd_refs_pending -= 1

    # lint: on-loop (AM handler)
    def _dtd_cb(self, src: int, msg: dict) -> None:
        self._trace_recv(TAG_DTD, src, msg)
        # For rendezvous refs the pending-pull count must become visible
        # ATOMICALLY with the message credit: crediting first opens a
        # window where the Safra token sees an even balance and empty
        # queues while the pull hasn't been registered yet.  A duplicate
        # is credited (its send was counted too) but must NOT register a
        # second pull — the leaked pending count would hang termination
        from parsec_tpu.core.taskpool import TaskpoolState
        dup = self._is_dup(msg)
        tp = self.context.taskpools.get(msg.get("tp")) \
            if isinstance(msg, dict) else None
        pe = msg.get("pe", 0) if isinstance(msg, dict) else 0
        # a torn generation's message is credited (its sender is live
        # and counted) but takes NO side effects — in particular its
        # 'ref' must not register a pull nobody will ever complete
        stale = tp is not None and pe < tp.run_epoch
        with self._term_lock:
            fence = self._fence_epoch.get(src)
            if fence is not None and \
                    (msg.get("_ep", 0) if isinstance(msg, dict)
                     else 0) < fence:
                return   # stale incarnation: no credit, no delivery
            self._color_black = True
            self._app_recv += 1
            self._recv_from[src] = self._recv_from.get(src, 0) + 1
            if not dup and not stale and isinstance(msg, dict) \
                    and "ref" in msg:
                # keyed by (pool, generation): a restart's forget_pool
                # subtracts exactly the torn generation's share, and a
                # pre-restart pull completing late cannot eat the new
                # generation's count (dtd_ref_done misses its key)
                key = (msg.get("tp"), pe)
                self.dtd_refs_pending += 1
                self._dtd_refs_tp[key] = \
                    self._dtd_refs_tp.get(key, 0) + 1
        if dup or stale:
            return
        if tp is not None:
            tp.peer_ranks.add(src)
        incoming = getattr(tp, "_dtd_incoming", None)
        if incoming is not None and pe <= tp.run_epoch and \
                (tp.run_epoch == 0
                 or tp.state >= TaskpoolState.RUNNING):
            # past a restart (run_epoch > 0) frames additionally wait
            # for the rebuilt structures (state back at RUNNING) in the
            # backlog; the pristine-pool fast path is unchanged
            incoming(src, msg)
            return
        with self._dlock:   # pool not registered here yet: backlog
            self._dtd_backlog.setdefault(msg["tp"], []).append((src, msg))
        # re-check: the pool may have registered — and drained an empty
        # backlog — between the lookup above and the append (the drain
        # pops under _dlock, so a second drain cannot double-deliver).
        # The SAME deliverability gate as above applies: a pool parked
        # below RUNNING by a recovery restart must keep the frame in
        # the backlog, or the mid-restart parking would be a no-op
        # (immediate re-drain into the half-torn structures)
        tp = self.context.taskpools.get(msg["tp"])
        if tp is not None \
                and getattr(tp, "_dtd_incoming", None) is not None \
                and (tp.run_epoch == 0
                     or tp.state >= TaskpoolState.RUNNING):
            self.dtd_drain_backlog(tp)

    def dtd_drain_backlog(self, tp) -> None:
        """Deliver DTD messages that arrived before ``tp`` registered.
        Generation-aware: frames of a torn generation drop (their Safra
        credit already landed), frames from a generation we have not
        reached yet re-park for the next drain."""
        with self._dlock:
            backlog = self._dtd_backlog.pop(tp.taskpool_id, [])
        keep = []
        for src, msg in backlog:
            pe = msg.get("pe", 0) if isinstance(msg, dict) else 0
            if pe < tp.run_epoch:
                continue   # torn generation: credited, void
            if pe > tp.run_epoch:
                keep.append((src, msg))
                continue
            tp._dtd_incoming(src, msg)
        if keep:
            with self._dlock:
                self._dtd_backlog.setdefault(tp.taskpool_id,
                                             []).extend(keep)

    # lint: on-loop (AM handler)
    def _get_rep_cb(self, src: int, msg: dict) -> None:
        self._trace_recv(TAG_GET_REP, src, msg)
        if not self._on_app_recv(src, msg):
            return
        if self._is_dup(msg):
            return
        key = (msg["root"], msg["handle"])
        pend = self._pending_gets.pop(key, None)
        if pend is None:
            return
        if msg.get("miss"):
            # contained: the pull's OWNING pool fails, not the context
            # (the handle expired server-side — TTL or a grace window
            # the retry backoff outlived)
            self._contain_pool(pend["tp"], PeerFailedError(
                src, f"rank {self.rank}: rendezvous payload "
                     f"{msg['handle']} from rank {src} expired before "
                     "our GET (comm_handle_timeout)",
                detector="rendezvous"))
            return
        arr = _decode(msg["buf"], msg["dtype"], msg["shape"])
        self._deliver(pend["tp"], pend["deliveries"], arr,
                      corr=pend.get("corr"))

    def _deliver(self, tp, deliveries, array: Optional[np.ndarray],
                 corr=None) -> None:
        """Release the incoming deps locally (reference:
        remote_dep_release_incoming, remote_dep.c:964).  ``corr`` is
        the activation frame's correlation id: each delivered successor
        gets a dep_deliver trace event binding the cross-rank flow edge
        to the consumer task."""
        from parsec_tpu.data.data import Coherency, Data
        ready = []
        copy = None
        if array is not None:
            # ONE shared copy for every local consumer of this payload —
            # exactly how local successors share the producer's copy
            # (_decode already returned a private array)
            datum = Data(nb_elts=array.nbytes)
            copy = datum.create_copy(0, payload=array,
                                     coherency=Coherency.SHARED, version=1)
        from parsec_tpu.data.reshape import as_dtt, needs_reshape
        sinks = self._sinks
        replay_filter = tp._replay_filter
        for tc_name, locs, dflow in deliveries:
            tc = tp.task_classes.get(tc_name)
            if tc is None:
                raise RuntimeError(f"unknown task class {tc_name!r}")
            if replay_filter is not None and \
                    tc.make_key(tc.complete_locals(locs)) \
                    not in replay_filter:
                # minimal-replay restart: a re-sending peer's activation
                # for a consumer whose output is already materialized
                # here — the Safra credit landed at receive; the
                # delivery itself is redundant and must not instantiate
                # an uncounted task into the restarted generation
                continue
            if sinks:
                try:
                    oid = hash(tc.make_key(locs))
                except Exception:
                    oid = None   # un-keyable locals: skip the trace
                if oid is not None:
                    for sink in sinks:
                        sink.dep_deliver(corr, oid, tpid=tp.taskpool_id)
            dcopy = copy
            if copy is not None:
                # receiver-side datatype resolution: the consumer's IN
                # dtt governs what it is handed (reference:
                # remote_dep_get_datatypes, remote_dep_mpi.c:832)
                fl = tc.flow(dflow)
                dep = fl.active_input(locs) if fl is not None else None
                dtt = as_dtt(dep.dtt) if dep is not None else None
                if dtt is not None and needs_reshape(copy, dtt):
                    dcopy = tp.reshape.get_copy(copy, dtt)
            t = deliver_dep(tp, tc, locs, dflow, dcopy, None)
            if t is not None:
                ready.append(t)
        if ready:
            scheduling.schedule(self.context.streams[0], ready)

    # ------------------------------------------------------------------
    # global quiescence: Safra's token (counterpart of termdet/fourcounter)
    # ------------------------------------------------------------------
    def _local_idle(self) -> bool:
        """Idle = no active pools AND no parked/unfinished protocol state;
        a delayed activation or pending GET is in-flight work the message
        balance alone does not capture."""
        ctx = self.context
        with self._dlock:
            if self._delayed or self._dtd_backlog:
                return False
        if self._pending_gets or self.dtd_refs_pending or \
                not self._cmdq.empty():
            return False
        if self._flushbox:
            self._drain_flush_window(force=True)
            return False
        rec = getattr(ctx, "recovery", None)
        if rec is not None and rec.busy():
            # a queued/active restart is about to rewind a pool: the
            # gang is NOT done, even if every counter reads zero right
            # now (the completed-pool-grace window)
            return False
        with ctx._lock:
            return ctx._active_taskpools == 0

    def _wait_recovery_idle(self, deadline) -> None:
        """Sole-survivor quiescence short-circuits must not outrun a
        queued recovery restart (the multi-rank rings are covered by
        the idle predicates; a lone rank has no ring to hold it)."""
        rec = getattr(self.context, "recovery", None)
        if rec is None:
            return
        while rec.busy():
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"rank {self.rank}: recovery still restarting "
                    "pools at the quiescence deadline")
            time.sleep(0.01)

    def _balance(self) -> int:
        with self._term_lock:
            return self._app_sent - self._app_recv

    def _live_peers(self) -> List[int]:
        """Peers still in the gang: not declared dead.  After a
        recovery excused a death, the Safra ring and the quiescence
        collectives run over exactly these ranks."""
        dead = self.ce.dead_peers
        return [r for r in range(self.nranks)
                if r != self.rank and r not in dead]

    def recovery_coordinator(self) -> int:
        """Lowest live rank — the deterministic coordinator of every
        TAG_RECOVER round (dead-set agreement, DTD skip agreement,
        the completed-pool retirement handshake).  Every survivor
        computes the same value from its dead set, so the rounds need
        no leader election."""
        return min([self.rank] + self._live_peers())

    def _next_live(self, r: int) -> Optional[int]:
        """The ring successor of ``r`` among live ranks (self counts as
        live); None when this rank is the only survivor."""
        dead = self.ce.dead_peers
        for i in range(1, self.nranks):
            cand = (r + i) % self.nranks
            if cand == self.rank or cand not in dead:
                return cand
        return None

    def _ring_root(self) -> int:
        """The Safra initiator: the LOWEST live rank (rank 0 unless its
        death was routed around by a recovery — a survivor ring must
        still have exactly one token source)."""
        dead = self.ce.dead_peers
        for r in range(self.nranks):
            if r == self.rank or r not in dead:
                return r
        return self.rank

    # lint: on-loop (AM handler)
    def _termdet_cb(self, src: int, msg: dict) -> None:
        if src in self.ce.dead_peers:
            # a stale token/terminate of a dead (possibly recovered-
            # around) rank must not steer the survivor ring
            return
        kind = msg.get("kind")
        if kind == "terminate":
            root = self._ring_root()
            if self.rank != root:
                nxt = self._next_live(self.rank)
                if nxt is not None and nxt != root:
                    try:
                        self.ce.send_am(TAG_TERMDET, nxt,
                                        {"kind": "terminate"})
                    except OSError:
                        pass   # dead next rank; its waiters fail fast
            self._terminated.set()
            return
        if kind == "dyn_release":
            root = self._ring_root()
            if self.rank != root:
                nxt = self._next_live(self.rank)
                if nxt is not None and nxt != root:
                    try:
                        self.ce.send_am(TAG_TERMDET, nxt,
                                        {"kind": "dyn_release"})
                    except OSError:
                        pass
            self._release_dyn_holds()
            return
        # token: wait until locally idle, then forward
        threading.Thread(target=self._forward_token,
                         args=(msg, kind == "dyn_token"),
                         daemon=True).start()

    def _forward_token(self, token: dict, dyn: bool = False) -> None:
        try:
            self._forward_token_inner(token, dyn)
        except OSError:
            # the next rank in the ring died mid-forward: quiescence
            # waiters fail fast through dead_peers; don't kill the
            # daemon thread with a loose traceback
            pass

    def _forward_token_inner(self, token: dict, dyn: bool) -> None:
        idle = self._dyn_idle if dyn else self._local_idle
        done_evt = self._dyn_released if dyn else self._terminated
        kind = "dyn_token" if dyn else "token"
        while not idle():
            if done_evt.wait(0.01):
                return
        with self._term_lock:
            my_black = self._color_black
            self._color_black = False
        root = self._ring_root()
        if self.rank == root:
            # token returned home: token.balance sums the live ring's
            # other ranks; the initiator's own balance joins only HERE
            # (adding it at send time too would double-count it and
            # never reach zero)
            clean = (not token["black"]) and not my_black and \
                token["balance"] + self._balance() == 0 and \
                token["rounds"] >= 1
            nxt = self._next_live(self.rank)
            if clean:
                if nxt is not None and nxt != root:
                    self.ce.send_am(
                        TAG_TERMDET, nxt,
                        {"kind": "dyn_release" if dyn else "terminate"})
                if dyn:
                    self._release_dyn_holds()
                else:
                    self._terminated.set()
            else:
                if nxt is None or nxt == root:
                    # the ring shrank to this rank mid-round (peers
                    # died and were excused): the waiter loop's re-kick
                    # handles it — a sole survivor short-circuits in
                    # wait_quiescence/resolve_dynamic_holds instead
                    return
                self.ce.send_am(TAG_TERMDET, nxt, {
                    "kind": kind, "black": False, "balance": 0,
                    "rounds": token["rounds"] + 1})
        else:
            nxt = self._next_live(self.rank)
            if nxt is None:
                return   # sole survivor mid-round; the waiter re-kicks
            self.ce.send_am(TAG_TERMDET, nxt, {
                "kind": kind,
                "black": token["black"] or my_black,
                "balance": token["balance"] + self._balance(),
                "rounds": token["rounds"]})

    # -- dynamic-pool termination (reference: the DISTRIBUTED termdet
    # behind ptgpp --dynamic-termdet; here a pool-scoped Safra round) ----
    def register_dynamic_hold(self, tp) -> None:
        """A DynamicTaskpool took a runtime-action hold at attach; it is
        released only when resolve_dynamic_holds proves global drain."""
        with self._term_lock:
            self._dyn_holds.append(tp)

    def rearm_dynamic_hold(self, tp) -> None:
        """Recovery restart of a DynamicTaskpool: the pool keeps (or
        regains) exactly one registration, so the restarted generation
        still resolves through the pool-scoped quiescence round —
        previously a kill with the hold outstanding stranded it across
        the restart."""
        with self._term_lock:
            if tp not in self._dyn_holds:
                self._dyn_holds.append(tp)

    def _dyn_idle(self) -> bool:
        """Locally drained MODULO the dynamic holds: every non-held pool
        done, every held pool at zero tasks with only its hold pending,
        and no parked protocol state (the Safra balance covers messages
        in flight)."""
        ctx = self.context
        with self._dlock:
            if self._delayed or self._dtd_backlog:
                return False
        if self._pending_gets or self.dtd_refs_pending or \
                not self._cmdq.empty():
            return False
        if self._flushbox:
            self._drain_flush_window(force=True)
            return False
        rec = getattr(ctx, "recovery", None)
        if rec is not None and rec.busy():
            return False   # a restart is rebuilding a held pool
        with self._term_lock:
            # a CONTAINED/cancelled dyn pool released its active-pool
            # slot but its hold entry lingers: counting it would wedge
            # the ring forever on a pool that can never drain — the
            # stranded-hold class recovery restarts now avoid, and
            # containment must not reintroduce
            holds = [tp for tp in self._dyn_holds
                     if getattr(tp, "_dyn_hold", False)
                     and not tp.cancelled and not tp.completed]
        with ctx._lock:
            if ctx._active_taskpools != len(holds):
                return False
        return all(tp.nb_tasks == 0 and tp.nb_pending_actions == 1
                   for tp in holds)

    def _release_dyn_holds(self) -> None:
        with self._term_lock:
            holds, self._dyn_holds = self._dyn_holds, []
        for tp in holds:
            if getattr(tp, "_dyn_hold", False):
                tp._dyn_hold = False
                tp.termdet.taskpool_addto_runtime_actions(tp, -1)
        self._dyn_released.set()

    def _drive_ring(self, idle_fn, done_evt, kind: str, on_done,
                    what: str, deadline: Optional[float]) -> None:
        """ONE Safra-ring driver for both quiescence flavors (the full
        context drain and the dynamic-hold round differ only in their
        idle predicate, completion event, token kind, and release
        action).  The ring root (lowest live rank — rank 0 unless its
        death was excused) launches the token once locally idle and
        RELAUNCHES it when an excused death shrinks the ring mid-round
        (the dead rank may have eaten the token; rounds restart, so a
        clean decision still needs one full white pass of the new
        ring); an UNEXCUSED death fails the waiter fast as before."""
        def kick():
            while not idle_fn():
                if done_evt.wait(0.01):
                    return
            with self._term_lock:
                self._color_black = False
            nxt = self._next_live(self.rank)
            if nxt is None or nxt == self.rank:
                on_done()
                return
            try:
                self.ce.send_am(TAG_TERMDET, nxt, {
                    "kind": kind, "black": False, "balance": 0,
                    "rounds": 0})
            except OSError:
                pass   # dead ring: the waiter below fails fast
        # kick is defined unconditionally: the mid-wait re-kick may run
        # on a rank that only became the ring root after rank 0's
        # excused death
        if self.rank == self._ring_root():
            threading.Thread(target=kick, daemon=True).start()
        seen_dead = set(self.ce.dead_peers)
        while not done_evt.wait(0.05):
            fatal = self.ce.dead_peers - self.ce.excused_peers
            if fatal:
                rec = getattr(self.context, "recovery", None)
                if rec is not None and rec.enabled:
                    # the excusal runs on the DECLARING comm thread a
                    # few instructions after the dead mark; this poll
                    # can land in that window when the GIL deschedules
                    # the declarer — give the excusal one bounded beat
                    # before calling the death fatal (recovery-off
                    # keeps the immediate containment)
                    grace = time.monotonic() + 0.5
                    while fatal and time.monotonic() < grace:
                        time.sleep(0.01)
                        fatal = self.ce.dead_peers - \
                            self.ce.excused_peers
            if fatal:
                dead = sorted(fatal)
                raise PeerFailedError(
                    dead[0], f"rank {self.rank}: {what} with dead "
                             f"peer(s) {dead}")
            if not self._live_peers():
                # sole survivor: local idle = global — once the
                # death's queued restart finished re-arming (the
                # completed-pool-grace race).  Checked EVERY iteration,
                # not only on a dead-set delta: a token sent to a peer
                # that died in the window between this ring starting
                # and seen_dead's snapshot is lost with no delta to
                # observe, and the ring would wait on it forever
                self._wait_recovery_idle(deadline)
                on_done()
                return
            if self.ce.dead_peers != seen_dead:
                seen_dead = set(self.ce.dead_peers)
                if self.rank == self._ring_root():
                    threading.Thread(target=kick, daemon=True).start()
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"rank {self.rank}: {what} not reached")

    def resolve_dynamic_holds(self, timeout: Optional[float] = None) -> None:
        """Block until every rank's dynamic pools drained with no
        discovery message in flight, then release their holds everywhere
        (called by Context.wait before the completion wait).  ``None``
        waits indefinitely — Context.wait(timeout=None) must not impose
        a spurious hard deadline on distributed dynamic pools."""
        with self._term_lock:
            if not self._dyn_holds:
                return
        if self.nranks == 1 or not self._live_peers():
            # single rank, or the sole survivor of a recovered gang:
            # local drain IS global drain — once no restart is queued
            # over the held pools
            self._wait_recovery_idle(
                None if timeout is None
                else time.monotonic() + timeout)
            self._release_dyn_holds()
            self._dyn_released.clear()
            return
        self._drive_ring(
            self._dyn_idle, self._dyn_released, "dyn_token",
            self._release_dyn_holds, "dynamic-pool termination",
            None if timeout is None else time.monotonic() + timeout)
        self._dyn_released.clear()

    def wait_quiescence(self, timeout: float = 120.0) -> None:
        """Block until every rank is idle and no message is in flight
        (called by Context.wait when distributed).  Runs over the LIVE
        ring: a recovery-excused death narrows the collective to the
        survivors; an unexcused one still fails fast."""
        if self.nranks == 1 or not self._live_peers():
            # sole survivor: local idle is global idle — but a queued
            # recovery restart must finish re-arming first, or the
            # caller retires/reads pools the restore is rewinding
            self._wait_recovery_idle(time.monotonic() + timeout)
            return
        self._drive_ring(
            self._local_idle, self._terminated, "token",
            self._terminated.set, "global termination",
            time.monotonic() + timeout)
        self._terminated.clear()

    def fini(self) -> None:
        self._stop = True
        self._drain_flush_window(force=True)
        if self._progress is not None:
            self._progress.join(timeout=5)
        self.ce.fini()
