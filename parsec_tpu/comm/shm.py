"""Shared-memory ring transport: same-host ranks over mmap SPSC rings.

The third ``make_ce`` transport (``PARSEC_MCA_COMM_TRANSPORT=shm``):
every directed peer pair owns one mmap-backed single-producer/
single-consumer byte ring under /dev/shm, carrying EXACTLY the frame
stream the TCP transports put on the wire (comm/frames.py parses it),
so the whole AM/one-sided/barrier/clock/heartbeat protocol stack rides
unchanged — a loopback-TCP hop pays two kernel socket copies plus
syscalls per chunk; the ring pays one userspace memcpy in and one out,
with ZERO syscalls on the data path.  Doorbells are abstract-namespace
unix datagrams, suppressed by a consumer-side ``waiting`` flag so a
busy consumer costs the producer nothing.

Topology/ownership: the RECEIVER creates (and at fini unlinks) its
inbound rings; senders attach lazily with a bounded retry, and a peer
whose ring never appears within 30s fails structurally
(``detector="connect"``).  The engine is FUNNELLED like EventLoopCE —
one loop thread owns every ring drain, AM dispatch, and send; worker
sends ride a command ring + self-doorbell.  The full failure-detection
contract holds: ``closed`` flag = EOF (hard kill / orderly shutdown),
heartbeat silence = hung peer, parser bound violation = corruption —
all routed through the shared ``declare_peer_dead`` sequence.

Index discipline: ``tail`` (producer) and ``head`` (consumer) are
monotonically increasing u64 byte counts at fixed 8-aligned offsets;
each is written by exactly ONE process and read by the other (aligned
8-byte copies — single stores on every supported platform).  The
``waiting``/``closed`` u32 flags are single-writer the same way.
"""

from __future__ import annotations

import mmap
import os
import select
import socket
import struct
import tempfile
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from parsec_tpu.comm.engine import TAG_HB, CommEngine, _frame_parts
from parsec_tpu.comm.frames import make_parser
from parsec_tpu.core.errors import PeerFailedError
from parsec_tpu.utils.debug_history import mark
from parsec_tpu.utils.mca import params
from parsec_tpu.utils.output import debug_verbose, warning

params.register("comm_shm_ring_mb", 8,
                "per-directed-peer-pair shared-memory ring capacity in "
                "MiB (shm transport); frames larger than the ring "
                "stream through it in chunks as the consumer drains")
params.register("comm_shm_dir", "",
                "directory for the shm transport's ring files (empty = "
                "/dev/shm when present, else the system tempdir)")

_MAGIC = 0x50534852            # "PSHR"
_VERSION = 1
_HDR = 64                      # data starts here
_OFF_MAGIC, _OFF_VER, _OFF_CAP = 0, 4, 8
_OFF_TAIL, _OFF_HEAD = 16, 24
_OFF_CLOSED, _OFF_WAITING = 32, 36
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")


def _ring_dir() -> str:
    d = str(params.get("comm_shm_dir", "") or "")
    if d:
        return d
    return "/dev/shm" if os.path.isdir("/dev/shm") else \
        tempfile.gettempdir()


def _ring_path(base: int, src: int, dst: int) -> str:
    return os.path.join(_ring_dir(),
                        f"parsec-shm-{base}-{src}to{dst}.ring")


class _Ring:
    """One mapped directed ring.  ``owner=True`` (the receiver side)
    creates/initializes the file and unlinks it at close."""

    __slots__ = ("path", "owner", "fd", "mm", "cap", "mask", "data")

    def __init__(self, path: str, owner: bool, cap: int):
        self.path = path
        self.owner = owner
        if owner:
            try:
                os.unlink(path)    # stale file from a crashed run
            except OSError:
                pass
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
            os.ftruncate(fd, _HDR + cap)
            self.fd = fd
            self.mm = mmap.mmap(fd, _HDR + cap)
            self.cap = cap
            # header: indices/flags first, MAGIC last — an attaching
            # sender accepts the ring only once it is fully initialized
            _U64.pack_into(self.mm, _OFF_CAP, cap)
            _U64.pack_into(self.mm, _OFF_TAIL, 0)
            _U64.pack_into(self.mm, _OFF_HEAD, 0)
            _U32.pack_into(self.mm, _OFF_CLOSED, 0)
            _U32.pack_into(self.mm, _OFF_WAITING, 0)
            _U32.pack_into(self.mm, _OFF_VER, _VERSION)
            _U32.pack_into(self.mm, _OFF_MAGIC, _MAGIC)
        else:
            fd = os.open(path, os.O_RDWR)
            size = os.fstat(fd).st_size
            self.fd = fd
            self.mm = mmap.mmap(fd, size)
            magic = _U32.unpack_from(self.mm, _OFF_MAGIC)[0]
            ver = _U32.unpack_from(self.mm, _OFF_VER)[0]
            if magic != _MAGIC or ver != _VERSION:
                self.close()
                raise OSError(f"{path}: bad ring magic/version "
                              f"({magic:#x}/{ver})")
            self.cap = _U64.unpack_from(self.mm, _OFF_CAP)[0]
            if _HDR + self.cap != size:
                self.close()
                raise OSError(f"{path}: ring size mismatch")
        self.mask = self.cap - 1
        self.data = memoryview(self.mm)[_HDR:]

    # single-writer fields (see the module docstring's discipline)
    def tail(self) -> int:
        return _U64.unpack_from(self.mm, _OFF_TAIL)[0]

    def set_tail(self, v: int) -> None:
        _U64.pack_into(self.mm, _OFF_TAIL, v)

    def head(self) -> int:
        return _U64.unpack_from(self.mm, _OFF_HEAD)[0]

    def set_head(self, v: int) -> None:
        _U64.pack_into(self.mm, _OFF_HEAD, v)

    def closed(self) -> bool:
        return bool(_U32.unpack_from(self.mm, _OFF_CLOSED)[0])

    def set_closed(self) -> None:
        _U32.pack_into(self.mm, _OFF_CLOSED, 1)

    def waiting(self) -> bool:
        return bool(_U32.unpack_from(self.mm, _OFF_WAITING)[0])

    def set_waiting(self, v: int) -> None:
        _U32.pack_into(self.mm, _OFF_WAITING, v)

    def close(self) -> None:
        try:
            if getattr(self, "data", None) is not None:
                self.data.release()
                self.data = None
            self.mm.close()
        except (BufferError, ValueError, OSError):
            pass
        try:
            os.close(self.fd)
        except OSError:
            pass
        if self.owner:
            try:
                os.unlink(self.path)
            except OSError:
                pass


class _ShmPeer:
    """Per-peer transport state, loop-thread-owned."""

    __slots__ = ("rank", "inbound", "outbound", "parser", "fp_native",
                 "pending", "pending_bytes", "born", "addr")

    def __init__(self, rank: int):
        self.rank = rank
        self.inbound: Optional[_Ring] = None    # peer -> us (we own)
        self.outbound: Optional[_Ring] = None   # us -> peer (attached)
        self.parser = None
        self.fp_native = False
        #: frames queued before the outbound ring attached
        self.pending: deque = deque()
        self.pending_bytes = 0
        self.born = time.monotonic()
        self.addr: Optional[bytes] = None       # doorbell sockaddr


class ShmCE(CommEngine):
    """Shared-memory ring active-message engine (same-host ranks)."""

    FUNNELLED = True   # callbacks + sends funnelled onto ONE thread
    CAP_MT = True      # send_am remains thread-safe (via the ring)
    TRANSPORT = "shm"

    def __init__(self, rank: int, nranks: int,
                 port_base: Optional[int] = None):
        super().__init__(rank, nranks)
        if port_base is None:
            port_base = int(params.get("comm_port_base", 0)) or \
                int(os.environ.get("PARSEC_COMM_PORT_BASE", 23500))
        self.port_base = port_base
        self._max_frame = int(params.get("comm_max_frame_mb", 4096)) << 20
        cap = max(64 << 10,
                  int(params.get("comm_shm_ring_mb", 8)) << 20)
        # power-of-two capacity (mask arithmetic)
        self._cap = 1 << (cap - 1).bit_length()
        self._stop = False
        self._ring: deque = deque()      # command ring (cross-thread)
        self._sleeping = False
        self._timers: List[list] = []
        #: re-entrancy latch: the ring-full stall path drains OUR
        #: inbound rings (deadlock breaker), and a handler dispatched
        #: there may SEND — a nested write would interleave bytes into
        #: the frame being written and rewind the published tail, so
        #: sends made while a write is in progress queue on the
        #: command ring instead (drained right after the write)
        self._writing = False
        #: stall deadline basis, cached off the per-frame path (the
        #: MCA registry get is a lock round-trip)
        pt = float(params.get("comm_peer_timeout_s", 15.0))
        self._stall_timeout = 2.0 * pt if pt > 0 else 3600.0
        # shm-specific counters (extra_stats; loop-thread-written,
        # scrape reads are tear-tolerant ints)
        self.ring_full_stalls = 0
        self.doorbells_sent = 0
        self.doorbells_recv = 0
        # doorbell: abstract-namespace unix datagram socket per rank —
        # the cross-process self-pipe (no filesystem residue)
        self._door = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        self._door.bind(self._door_addr(rank))
        self._door.setblocking(False)
        # a dedicated nonblocking sender socket (sendto from any
        # thread via _post's wake; loop-thread doorbells to peers)
        self._door_tx = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        self._door_tx.setblocking(False)
        # poll, not select.select: a resident service holds thousands
        # of fds and select dies (ValueError) at fd >= 1024
        self._poll = select.poll()
        self._poll.register(self._door.fileno(), select.POLLIN)
        #: rank -> _ShmPeer; created at init and mutated only on
        #: the loop thread thereafter (funnelled discipline — the
        #: ring indices inside each _Ring are single-writer per
        #: side, see the module docstring)
        self._peers: Dict[int, _ShmPeer] = {}
        for r in range(nranks):
            if r == rank:
                continue
            peer = _ShmPeer(r)
            peer.addr = self._door_addr(r)
            # our inbound ring (peer -> us): we own and initialize it
            peer.inbound = _Ring(_ring_path(port_base, r, rank),
                                 owner=True, cap=self._cap)
            peer.parser, peer.fp_native = make_parser(self._max_frame,
                                                      require=True)
            self._peers[r] = peer
        self._register_onesided()
        self._thread = threading.Thread(target=self._loop,
                                        name=f"ce-shm-{rank}",
                                        daemon=True)
        self._thread.start()
        self._post(("timer", self._check_unattached, 5.0))
        # frames parked before a peer's ring appeared flush from a fast
        # retry tick, not from the NEXT send (a barrier 'arrive' may be
        # the only frame this rank ever sends the peer)
        self._post(("timer", self._retry_pending, 0.02))
        # rejoin support: a restarted incarnation re-creates its ring
        # files — stale outbound mappings must drop so sends re-attach
        self._post(("timer", self._verify_outbound, 1.0))
        self._arm_kill()

    def _door_addr(self, r: int) -> bytes:
        # leading NUL = Linux abstract namespace
        return b"\0parsec-shm-%d-%d" % (self.port_base, r)

    # -- public loop hooks (the remote-dep layer's progress seam) -------
    def post(self, fn: Callable, *args) -> None:
        self._post(("call", fn, args))

    def add_periodic(self, fn: Callable[[], None], period: float) -> None:
        self._post(("timer", fn, float(period)))

    def extra_stats(self) -> Dict[str, int]:
        return {"shm_ring_full_stalls": self.ring_full_stalls,
                "shm_doorbells_sent": self.doorbells_sent,
                "shm_doorbells_recv": self.doorbells_recv}

    def peer_debug(self) -> Dict[int, Dict[str, Any]]:
        out = super().peer_debug()
        for r, peer in list(self._peers.items()):
            ent = out.setdefault(r, {})
            ent["attached"] = peer.outbound is not None
            ent["pending_bytes"] = peer.pending_bytes
            ob = peer.outbound
            if ob is not None:
                ent["out_bytes"] = ob.tail() - ob.head()
        return out

    # -- command ring ----------------------------------------------------
    def _post(self, cmd: tuple) -> None:
        self._ring.append(cmd)
        if self._sleeping:
            try:
                self._door_tx.sendto(b"\0", self._door_addr(self.rank))
                self.stats.wakeups += 1
            except (BlockingIOError, OSError):
                pass   # socket gone at teardown / buffer full = pending

    # lint: on-loop (command drain on the shm loop thread)
    def _drain_cmds(self) -> None:
        ring = self._ring
        while ring:
            try:
                cmd = ring.popleft()
            except IndexError:
                return
            op = cmd[0]
            try:
                if op == "send":
                    self._send_now(cmd[1], cmd[2], cmd[3])
                elif op == "call":
                    cmd[1](*cmd[2])
                elif op == "local":
                    self.recv_msgs += 1
                    self._safe_dispatch(cmd[1], self.rank, cmd[2])
                elif op == "timer":
                    self._timers.append(
                        [time.monotonic() + cmd[2], cmd[2], cmd[1]])
                elif op == "stop":
                    self._stop = True
            except Exception as exc:
                self._handler_error(exc)

    def _handler_error(self, exc: Exception) -> None:
        warning("rank %d: shm-loop command failed: %s", self.rank, exc)
        if self.on_error is not None:
            self.on_error(exc)

    # -- the loop --------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop:
            self._drain_cmds()
            if self._stop:
                break
            self._run_timers()
            progressed = self._drain_rings() if not self._muted else False
            if progressed or self._ring:
                continue
            # pre-sleep protocol: raise the waiting flags, then re-check
            # — a producer that wrote after our drain but before the
            # flag went up sees waiting=0 and skips the doorbell, so the
            # re-check below must (and does) observe its bytes
            for peer in self._peers.values():
                rg = peer.inbound
                if rg is not None:
                    rg.set_waiting(1)
            # count only rings a drain would actually consume: a muted
            # engine or a dead peer's residual bytes stay in the ring
            # forever and must not turn the loop into a busy-spin
            dirty = not self._muted and any(
                p.inbound is not None and
                (p.rank not in self.dead_peers or self.rejoin_allowed)
                and p.inbound.tail() != p.inbound.head()
                for p in self._peers.values())
            if dirty or self._ring:
                for peer in self._peers.values():
                    if peer.inbound is not None:
                        peer.inbound.set_waiting(0)
                continue
            self._sleeping = True
            if self._ring:
                self._sleeping = False
                continue
            try:
                r = self._poll.poll(self._next_timeout() * 1e3)
            except OSError:
                r = []
            self._sleeping = False
            for peer in self._peers.values():
                if peer.inbound is not None:
                    peer.inbound.set_waiting(0)
            if r:
                try:
                    while True:
                        self._door.recvfrom(64)
                        self.doorbells_recv += 1
                except (BlockingIOError, OSError):
                    pass
        self._shutdown_drain()

    def _next_timeout(self) -> float:
        if not self._timers:
            return 0.2
        due = min(t[0] for t in self._timers) - time.monotonic()
        return min(0.2, max(0.0, due))

    # lint: on-loop (periodic driver)
    def _run_timers(self) -> None:
        if not self._timers:
            return
        now = time.monotonic()
        for t in self._timers:
            if now >= t[0]:
                t[0] = now + t[1]
                try:
                    t[2]()
                except Exception as exc:
                    self._handler_error(exc)

    # lint: on-loop (periodic hook)
    def _retry_pending(self) -> None:
        for r, peer in list(self._peers.items()):
            if peer.pending and peer.outbound is None and \
                    r not in self.dead_peers:
                self._attach(peer)

    def _drop_stale_outbound(self, peer: _ShmPeer) -> None:
        """Rejoin freshness: a restarted incarnation re-created its
        inbound ring files, so an outbound mapping whose inode no
        longer matches the on-disk path is a write-only hole into the
        dead incarnation's anonymous inode — drop it and let the next
        send re-attach to the fresh ring."""
        rg = peer.outbound
        if rg is None:
            return
        try:
            fresh = os.stat(rg.path).st_ino == os.fstat(rg.fd).st_ino
        except OSError:
            fresh = False
        if not fresh:
            rg.close()              # owner=False: never unlinks
            peer.outbound = None

    # lint: on-loop (periodic hook)
    def _verify_outbound(self) -> None:
        """One inode stat per attached peer per tick, armed only when
        rejoin is enabled."""
        if not self.rejoin_allowed:
            return
        for peer in list(self._peers.values()):
            self._drop_stale_outbound(peer)

    # lint: on-loop (periodic hook)
    def _check_unattached(self) -> None:
        """A peer with queued frames whose inbound ring never appeared
        is a failure, not a silent stall (the TCP transports' 30s
        connect deadline)."""
        now = time.monotonic()
        for r, peer in list(self._peers.items()):
            if peer.outbound is None and peer.pending and \
                    now - peer.born > 30 and r not in self.dead_peers:
                peer.pending.clear()
                peer.pending_bytes = 0
                self.declare_peer_dead(r, PeerFailedError(
                    r, f"rank {self.rank}: rank {r}'s inbound ring "
                       "never appeared within 30s (frames queued)",
                    detector="connect"))

    # -- receive path ----------------------------------------------------
    # lint: on-loop (doorbell/ring drain handler)
    def _drain_rings(self) -> bool:
        progressed = False
        rejoinable = self.rejoin_allowed
        for peer in list(self._peers.values()):
            if peer.rank in self.dead_peers and not rejoinable:
                # with rejoin armed, a dead peer's ring was re-created
                # EMPTY at _drop_peer: draining it costs nothing until
                # a restarted incarnation writes its TAG_REJOIN frame —
                # the handshake that previously could not happen on shm
                continue
            rg = peer.inbound
            if rg is None:
                continue
            if self._drain_one(peer, rg):
                progressed = True
        return progressed

    def _drain_one(self, peer: _ShmPeer, rg: _Ring) -> bool:
        head = rg.head()
        tail = rg.tail()
        if tail == head:
            if rg.closed():
                self._ring_eof(peer)
            return False
        progressed = False
        mask, data = rg.mask, rg.data
        while tail != head:
            off = head & mask
            chunk = min(tail - head, rg.cap - off)
            try:
                frames = peer.parser.feed(data[off:off + chunk])
            except ValueError as exc:
                self.declare_peer_dead(peer.rank, PeerFailedError(
                    peer.rank, f"rank {self.rank}: protocol corruption "
                    f"from rank {peer.rank}: {exc}", detector="corrupt"))
                return True
            head += chunk
            rg.set_head(head)       # free space per chunk: the
            self.stats.bytes_recv += chunk   # producer unblocks ASAP
            # liveness per chunk, not per completed frame (a bulk
            # frame outlasting comm_peer_timeout_s must not get its
            # actively-streaming peer declared dead)
            self._note_heard(peer.rank)
            progressed = True
            if frames and not self._dispatch_frames(peer, frames):
                return True
            tail = rg.tail()
        return progressed

    def _dispatch_frames(self, peer: _ShmPeer, frames) -> bool:
        src = peer.rank
        return self._deliver_frames(
            frames, src, peer.fp_native,
            lambda why: self.declare_peer_dead(src, PeerFailedError(
                src, f"rank {self.rank}: protocol corruption from "
                f"rank {src}: {why}", detector="corrupt")),
            lambda: src not in self.dead_peers)

    def _deliver_held(self, tag: int, src: int, payload: Any) -> None:
        # funnelled contract: handlers run ONLY on the loop thread
        self._post(("call", self._safe_dispatch, (tag, src, payload)))

    def peer_rejoined(self, r: int, epoch: int) -> None:
        """TAG_REJOIN validated: beyond the base bookkeeping, make sure
        our outbound path re-attaches to the RESTARTED incarnation's
        ring (the _drop_peer at death usually closed it already; this
        covers an attach that raced the restart)."""
        super().peer_rejoined(r, epoch)

        def fresh():
            peer = self._peers.get(r)
            if peer is not None:
                self._drop_stale_outbound(peer)
        self.post(fresh)

    def _ring_eof(self, peer: _ShmPeer) -> None:
        """The producer set ``closed`` and every byte drained: EOF.
        Orderly shutdown is filtered by declare_peer_dead's _stop
        check, exactly like a TCP close."""
        if peer.rank in self.dead_peers:
            return
        if peer.parser is not None and not peer.parser.idle():
            why = f"rank {peer.rank} closed its ring mid-frame"
        else:
            why = None
        self.declare_peer_dead(peer.rank, PeerFailedError(
            peer.rank, f"rank {self.rank}: peer rank {peer.rank} "
            "closed its ring mid-run" + (f": {why}" if why else "")))

    # -- send path -------------------------------------------------------
    def send_am(self, tag: int, dst: int, payload: Any = None,
                _nofault: bool = False) -> None:
        mark("send_am tag=%d dst=%d", tag, dst)
        if self._muted and dst != self.rank:
            return   # injected silent hang swallows every outbound frame
        if self._fault is not None and not _nofault and dst != self.rank \
                and self._fault_frame(tag, dst, payload):
            return
        if dst == self.rank:
            self.sent_msgs += 1
            if threading.current_thread() is self._thread and \
                    not self._ring:
                self.recv_msgs += 1
                self._dispatch(tag, self.rank, payload)
            else:
                self._post(("local", tag, payload))
            return
        if threading.current_thread() is self._thread:
            # per-destination FIFO across threads (the evloop rule):
            # a loop-thread send must not overtake posted worker sends
            if self._ring:
                self._ring.append(("send", tag, dst, payload))
            else:
                self._send_now(tag, dst, payload)
        else:
            self._post(("send", tag, dst, payload))

    def _send_raw_parts(self, dst: int, parts: List[Any]) -> None:
        views = [memoryview(p) for p in parts if len(p)]

        def doit():
            peer = self._peers.get(dst)
            if peer is not None and dst not in self.dead_peers:
                self._write_views(peer, views, count_frame=False)
        self.post(doit)

    def _send_now(self, tag: int, dst: int, payload: Any) -> None:
        if self._writing:
            # nested send from a handler dispatched inside a stall's
            # drain: queue behind the in-progress write (FIFO holds —
            # the outer frame is earlier in program order)
            self._ring.append(("send", tag, dst, payload))
            return
        if dst in self.dead_peers:
            return        # undeliverable; the loss already surfaced
        peer = self._peers.get(dst)
        if peer is None:
            raise OSError(f"rank {self.rank}: no shm peer {dst}")
        parts = _frame_parts(tag, payload)
        views = [memoryview(p) for p in parts if len(p)]
        if peer.outbound is None and not self._attach(peer):
            # ring not up yet: park the frame; flushed at attach,
            # failed by _check_unattached past the deadline
            nbytes = sum(v.nbytes for v in views)
            peer.pending.append(views)
            peer.pending_bytes += nbytes
            return
        self._write_views(peer, views)

    def _attach(self, peer: _ShmPeer) -> bool:
        path = _ring_path(self.port_base, self.rank, peer.rank)
        try:
            peer.outbound = _Ring(path, owner=False, cap=0)
        except OSError:
            return False
        # flush frames parked while the peer was coming up
        while peer.pending:
            self._write_views(peer, peer.pending.popleft())
        peer.pending_bytes = 0
        return True

    def _write_views(self, peer: _ShmPeer, views: List,
                     count_frame: bool = True) -> None:
        """Producer side: copy the frame's parts into the outbound
        ring, streaming through it when the frame exceeds free space.
        A full ring means the consumer is behind: doorbell it, keep
        draining OUR inbound (two mutually-full rings must not
        deadlock), and give up through the shared death path after 2x
        the peer-timeout."""
        rg = peer.outbound
        if rg is None:
            return
        if count_frame:
            self.sent_msgs += 1
            self.stats.frames_sent += 1
        # re-entrancy latch: any send a stall-drained handler makes
        # queues on the command ring (_send_now) instead of writing —
        # a nested write would interleave bytes into THIS frame's
        # stream and rewind the published tail (frame loss)
        self._writing = True
        try:
            self._write_views_inner(peer, rg, views)
        finally:
            self._writing = False

    def _write_views_inner(self, peer: _ShmPeer, rg: _Ring,
                           views: List) -> None:
        deadline = None    # computed only if a stall actually happens
        mask, cap, data = rg.mask, rg.cap, rg.data
        tail = rg.tail()
        total = 0
        stall_ns = 5e-5
        for v in views:
            voff = 0
            n = v.nbytes
            while voff < n:
                free = cap - (tail - rg.head())
                if free == 0:
                    self.ring_full_stalls += 1
                    self._doorbell(peer)
                    if rg.closed() or peer.rank in self.dead_peers:
                        return
                    # service our own inbound while waiting (deadlock
                    # breaker when both directions are full; nested
                    # sends from handlers park on the command ring)
                    if threading.current_thread() is self._thread:
                        self._drain_rings()
                    now = time.monotonic()
                    if deadline is None:
                        deadline = now + self._stall_timeout
                    if now > deadline:
                        self.declare_peer_dead(peer.rank, PeerFailedError(
                            peer.rank, f"rank {self.rank}: rank "
                            f"{peer.rank} stopped draining its ring "
                            f"for {self._stall_timeout:.0f}s"))
                        return
                    time.sleep(min(stall_ns, 1e-3))  # lint: allow-blocking (backpressure wait)
                    stall_ns *= 2
                    continue
                stall_ns = 5e-5
                off = tail & mask
                chunk = min(n - voff, free, cap - off)
                data[off:off + chunk] = v[voff:voff + chunk]
                tail += chunk
                voff += chunk
                total += chunk
                rg.set_tail(tail)   # publish per chunk: the consumer
                # may start parsing while we stream the rest
        self.stats.bytes_sent += total
        if rg.waiting():
            self._doorbell(peer)

    def _doorbell(self, peer: _ShmPeer) -> None:
        try:
            self._door_tx.sendto(b"\0", peer.addr)
            self.doorbells_sent += 1
            self.stats.syscalls_send += 1
        except (BlockingIOError, OSError):
            pass   # peer not bound/draining: a lost doorbell only
            # defers the wake to the loop's bounded poll timeout

    # lint: on-loop (heartbeat periodic, via the base tick)
    def _hb_send(self, r: int) -> None:
        # NEVER block the loop on a heartbeat: skip when the ring is
        # unattached or lacks space — a hung peer's full ring would
        # wedge the thread that runs check_peer_timeouts (the SocketCE
        # discipline, ported to rings)
        peer = self._peers.get(r)
        if peer is None or peer.outbound is None or self._muted:
            return
        rg = peer.outbound
        parts = _frame_parts(TAG_HB, None)   # header-only frame
        need = sum(len(p) for p in parts)
        if rg.cap - (rg.tail() - rg.head()) < need:
            return   # full: beating it would block
        self.sent_msgs += 1
        self.stats.frames_sent += 1
        self._write_views(peer, [memoryview(p) for p in parts
                                 if len(p)], count_frame=False)

    # -- failure / teardown ---------------------------------------------
    def _drop_peer(self, r: int) -> None:
        if threading.current_thread() is not self._thread and \
                self._thread.is_alive():
            self._post(("call", self._drop_peer, (r,)))
            return
        peer = self._peers.get(r)
        if peer is not None:
            peer.pending.clear()
            peer.pending_bytes = 0
            # REJOIN SUPPORT: re-create the transport state the dead
            # incarnation poisoned.  The stale outbound mapping points
            # at the dead process's (possibly unlinked) inode — close
            # it so the next send attaches to the restarted
            # incarnation's fresh ring; our inbound ring re-creates
            # EMPTY with a fresh parser, so the rejoiner's TAG_REJOIN
            # frame lands on a clean stream instead of appending to a
            # torn one (and a never-returning peer's residual bytes
            # can no longer busy-spin the drain loop)
            if peer.outbound is not None:
                peer.outbound.close()
                peer.outbound = None
            if peer.inbound is not None and not self._stop \
                    and self.rejoin_allowed:
                try:
                    peer.inbound.close()   # owner: unlinks the old path
                    peer.inbound = _Ring(
                        _ring_path(self.port_base, r, self.rank),
                        owner=True, cap=self._cap)
                    peer.parser, peer.fp_native = make_parser(
                        self._max_frame, require=True)
                except OSError as exc:
                    warning("rank %d: could not re-create inbound ring "
                            "for dead rank %d: %s", self.rank, r, exc)
                    peer.inbound = None

    def _kill_close(self) -> None:
        """Injected hard death: close every outbound ring (peers see
        EOF) and surface each drop locally, mirroring the TCP kill."""
        def doit():
            for peer in list(self._peers.values()):
                if peer.outbound is not None:
                    peer.outbound.set_closed()
                self._doorbell(peer)
            for peer in list(self._peers.values()):
                if peer.rank not in self.dead_peers:
                    self.declare_peer_dead(peer.rank, PeerFailedError(
                        peer.rank, f"rank {self.rank}: fault_kill "
                        "(injected)"))
        self.post(doit)

    def _shutdown_drain(self, deadline: float = 5.0) -> None:
        """Orderly shutdown ships what is already queued (a barrier
        release POSTED just before the stop flag flipped must still be
        written — the evloop transport's contract), then waits
        (bounded) for consumers to drain it before marking our
        outbound rings closed: peers see a clean EOF, not silence."""
        if not self._muted:
            end = time.monotonic() + deadline
            while time.monotonic() < end:
                self._drain_cmds()
                busy = bool(self._ring)
                for peer in self._peers.values():
                    rg = peer.outbound
                    if rg is not None and not rg.closed() and \
                            peer.rank not in self.dead_peers and \
                            rg.tail() != rg.head():
                        busy = True
                if not busy:
                    break
                time.sleep(0.002)   # lint: allow-blocking (teardown drain)
        for peer in self._peers.values():
            if peer.outbound is not None:
                peer.outbound.set_closed()
                self._doorbell(peer)

    def fini(self) -> None:
        self._stop = True
        self._post(("stop",))
        if threading.current_thread() is not self._thread:
            self._thread.join(timeout=5)
        for peer in self._peers.values():
            if peer.outbound is not None:
                peer.outbound.close()
                peer.outbound = None
            if peer.inbound is not None:
                peer.inbound.close()
                peer.inbound = None
        for s in (self._door, self._door_tx):
            try:
                s.close()
            except OSError:
                pass
        debug_verbose(5, "rank %d shm CE down: sent=%d recv=%d %s",
                      self.rank, self.sent_msgs, self.recv_msgs,
                      self.extra_stats())
