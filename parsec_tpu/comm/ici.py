"""ICI transport: payload movement as XLA device collectives.

The second comm-engine module (reference seam: the transport-neutral
``parsec_comm_engine_t`` vtable, parsec/parsec_comm_engine.h:161-183, whose
only in-tree implementation is funnelled MPI, parsec_mpi_funnelled.c).  On
TPU the equivalent of registered-memory put/get between ranks is
device-to-device movement over the ICI mesh — so this module lowers
dataflow payload edges between the runtime's XLA devices to XLA
collective programs, keeping control (activation bookkeeping) on the
host:

- ``put``      — one point-to-point tile edge (DMA d2h-free device copy;
                 on a real slice this is an ICI transfer).
- ``bcast``    — one producer tile replicated to many devices in a single
                 XLA replication (the dataflow-broadcast primitive of
                 remote_dep.c:334-357, ridden on the interconnect instead
                 of N host round-trips).  The first customer is the GEMM
                 panel broadcast (apps/gemm.py RA/RB): release_deps calls
                 ``prebroadcast`` when one copy fans out to consumers on
                 several devices.
- ``permute``  — a batch of same-shaped tile edges executed as ONE
                 ``lax.ppermute`` (CollectivePermute) program over the
                 mesh — the per-wavefront batched schedule of SURVEY §5.8.
                 Non-permutation batches are split into permutation
                 rounds (each device sends/receives at most once per
                 round, matching CollectivePermute semantics).

Programs are shard_map computations over a 1D mesh of every attached XLA
device, cached per (shape, dtype, permutation).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from parsec_tpu.data.data import Coherency, DataCopy
from parsec_tpu.utils.mca import params
from parsec_tpu.utils.output import debug_verbose

params.register("comm_ici_enabled", 1,
                "lower multi-device payload edges to XLA collectives")
params.register("comm_ici_bcast_min", 2,
                "minimum distinct consumer devices to trigger a collective "
                "panel broadcast")
params.register("comm_ici_permute_window_ms", 2.0,
                "how long a deferred point-to-point placement may wait for "
                "same-wavefront siblings before an idle worker flushes the "
                "batch as CollectivePermute rounds")
params.register("comm_ici_permute_min", 2,
                "minimum batched edges to lower a flush to ppermute; "
                "smaller flushes fall back to per-edge puts")


class IciStats:
    __slots__ = ("puts", "put_bytes", "bcasts", "bcast_bytes",
                 "permutes", "permute_edges", "permute_bytes")

    def __init__(self):
        self.puts = 0
        self.put_bytes = 0
        self.bcasts = 0
        self.bcast_bytes = 0
        self.permutes = 0
        self.permute_edges = 0
        self.permute_bytes = 0

    def as_dict(self):
        return {k: getattr(self, k) for k in self.__slots__}


class IciEngine:
    """Collective payload transport over the local device mesh."""

    #: comm-engine capability flags (reference: parsec_comm_engine.h
    #: capabilities) — one-sided puts and collective broadcast, no
    #: two-sided AM (control rides the host/TCP engine)
    CAP_ONESIDED = True
    CAP_COLLECTIVE = True

    def __init__(self, registry):
        from parsec_tpu.devices.xla import XlaDevice
        self.registry = registry
        self.xla_devices = [d for d in registry.devices
                            if isinstance(d, XlaDevice) and d.enabled]
        self._space_to_pos: Dict[int, int] = {
            d.space: i for i, d in enumerate(self.xla_devices)}
        self._jdev = {d.space: d.jdev for d in self.xla_devices}
        self.stats = IciStats()
        self._mesh = None
        self._prog_cache: Dict[Tuple, Any] = {}
        self._lock = threading.Lock()
        #: serializes COLLECTIVE program launches: two multi-device
        #: programs dispatched concurrently from different worker
        #: threads (an idle-worker window flush racing a full-round
        #: defer_place flush) can interleave their per-device
        #: participant enqueues and deadlock the XLA rendezvous — the
        #: r8 repro's "two CollectivePermute run-ids stuck waiting for
        #: participants" wedge (the pre-existing dryrun >3min stall).
        #: One launch at a time gives every device queue the same
        #: program order.
        self._launch_lock = threading.Lock()
        #: deferred single-consumer placements awaiting same-wavefront
        #: siblings: (produced copy, destination space, enqueue time).
        #: Flushed as batched CollectivePermute rounds (SURVEY §5.8's
        #: "batched per DAG wavefront" schedule) when a full round
        #: accumulates or an idle worker drains the window.
        self._pending_edges: List[Tuple[DataCopy, int, float]] = []
        self._pending_lock = threading.Lock()
        #: when the last single-consumer edge was seen: a fresh edge after
        #: a quiet spell is treated as a chain hop (placed immediately),
        #: one arriving inside the window as a wavefront sibling (batched)
        self._last_edge = float("-inf")

    # ------------------------------------------------------------------
    @property
    def ndev(self) -> int:
        return len(self.xla_devices)

    def mesh(self):
        """Lazy 1D mesh over every attached XLA device."""
        if self._mesh is None:
            from jax.sharding import Mesh
            self._mesh = Mesh(
                np.array([d.jdev for d in self.xla_devices]), ("d",))
        return self._mesh

    # ------------------------------------------------------------------
    # point-to-point: the put of the CE vtable
    # ------------------------------------------------------------------
    def put(self, payload, dst_space: int):
        """Move one tile to ``dst_space``'s device, device-to-device
        (reference: CE put with registered memory,
        parsec_mpi_funnelled.c:793).  The placed copy must be PRIVATE:
        on the CPU client a plain device_put can alias the source
        buffer, which a later donation would corrupt (the r8 wrong-R
        root cause; see devices/xla.device_put_private)."""
        from parsec_tpu.devices.xla import device_put_private
        out = device_put_private(payload, self._jdev[dst_space])
        self.stats.puts += 1
        self.stats.put_bytes += getattr(payload, "nbytes", 0)
        return out

    # ------------------------------------------------------------------
    # broadcast: one producer tile -> many devices, one XLA replication
    # ------------------------------------------------------------------
    def bcast(self, payload, dst_spaces: Sequence[int]) -> Dict[int, Any]:
        """Replicate ``payload`` onto every device of the mesh in one XLA
        data movement; return {space: on-device array} for the requested
        targets (reference: the dataflow bcast trees, remote_dep.c:334-357
        — here the tree is the interconnect's native replication)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from parsec_tpu.devices.xla import device_put_replicated_private
        want = set(dst_spaces)
        sharding = NamedSharding(self.mesh(), P())   # fully replicated
        # the replicated "copies" must be PRIVATE: on the CPU client the
        # shard co-located with the host buffer can alias it (the same
        # r8 wrong-R hazard device_put_private closes for put/stage-in)
        # — a later in-place mutation or donation of the source would
        # corrupt every consumer's tile
        rep = device_put_replicated_private(payload, sharding)
        out: Dict[int, Any] = {}
        by_jdev = {jd: sp for sp, jd in self._jdev.items()}
        for shard in rep.addressable_shards:
            sp = by_jdev.get(shard.device)
            if sp in want:
                out[sp] = shard.data
        self.stats.bcasts += 1
        self.stats.bcast_bytes += getattr(payload, "nbytes", 0) * len(out)
        return out

    # ------------------------------------------------------------------
    # batched permute: one CollectivePermute program per wavefront round
    # ------------------------------------------------------------------
    def permute(self, edges: Iterable[Tuple[int, int, Any]]
                ) -> Dict[Tuple[int, int], Any]:
        """Execute a batch of (src_space, dst_space, payload) tile edges.
        Same-shaped edges forming a partial permutation ride ONE
        ``lax.ppermute`` launch; the batch is split into permutation
        rounds and (shape, dtype) groups as needed.  Returns
        {(src_space, dst_space): array-on-dst}."""
        groups: Dict[Tuple, List[Tuple[int, int, Any]]] = {}
        results: Dict[Tuple[int, int], Any] = {}
        for s, d, payload in edges:
            if s == d:
                results[(s, d)] = payload
                continue
            arr_shape = tuple(getattr(payload, "shape", ()))
            dt = str(getattr(payload, "dtype", "f4"))
            groups.setdefault((arr_shape, dt), []).append((s, d, payload))
        for (shape, dt), group in groups.items():
            for round_edges in self._rounds(group):
                results.update(self._permute_round(shape, round_edges))
        return results

    @staticmethod
    def _rounds(group: List[Tuple[int, int, Any]]
                ) -> List[List[Tuple[int, int, Any]]]:
        """Split edges into rounds where each device sends at most once
        and receives at most once (CollectivePermute is a partial
        permutation)."""
        rounds: List[List[Tuple[int, int, Any]]] = []
        for edge in group:
            for r in rounds:
                if all(edge[0] != e[0] and edge[1] != e[1] for e in r):
                    r.append(edge)
                    break
            else:
                rounds.append([edge])
        return rounds

    def _permute_round(self, shape, round_edges):
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self.mesh()
        n = self.ndev
        srcs: Dict[int, Any] = {}
        perm: List[Tuple[int, int]] = []
        for s, d, payload in round_edges:
            perm.append((self._space_to_pos[s], self._space_to_pos[d]))
            srcs[self._space_to_pos[s]] = payload
        perm.sort()
        dtype = None
        for a in srcs.values():
            dtype = a.dtype
            break
        from parsec_tpu.devices.xla import device_put_private
        shards = []
        for i, dev in enumerate(self.xla_devices):
            a = srcs.get(i)
            if a is None:
                a = jnp.zeros(shape, dtype)
            # PRIVATE stage-in: ``a`` is a producer's live tile — a
            # zero-copy device_put alias would let a concurrent donation
            # of the source corrupt the program's input mid-permute
            a = device_put_private(a, dev.jdev)
            shards.append(jnp.reshape(a, (1,) + shape))
        sharding = NamedSharding(mesh, P("d"))
        x = jax.make_array_from_single_device_arrays(
            (n,) + shape, sharding, shards)

        key = ("perm", shape, str(dtype), tuple(perm))
        with self._lock:
            prog = self._prog_cache.get(key)
            if prog is None:
                try:
                    from jax import shard_map
                except ImportError:
                    # jax 0.4.x ships it under experimental only (the
                    # top-level alias landed in 0.5); same callable
                    from jax.experimental.shard_map import shard_map

                def body(t):
                    return lax.ppermute(t, "d", perm)
                prog = jax.jit(shard_map(
                    body, mesh=mesh, in_specs=P("d"), out_specs=P("d")))
                self._prog_cache[key] = prog
        with self._launch_lock:
            # dispatch AND completion inside the lock: async dispatch
            # alone could still leave per-device enqueues of two
            # collectives interleaved (see _launch_lock)
            y = jax.block_until_ready(prog(x))
        pos_to_space = {v: k for k, v in self._space_to_pos.items()}
        recv = {d_pos: s_pos for s_pos, d_pos in perm}
        by_jdev = {jd: sp for sp, jd in self._jdev.items()}
        out: Dict[Tuple[int, int], Any] = {}
        for shard in y.addressable_shards:
            sp = by_jdev.get(shard.device)
            if sp is None:
                continue
            pos = self._space_to_pos[sp]
            if pos not in recv:
                continue
            out[(pos_to_space[recv[pos]], sp)] = shard.data[0]
        nbytes = int(np.prod(shape)) * np.dtype(dtype).itemsize \
            if shape else 0
        self.stats.permutes += 1
        self.stats.permute_edges += len(perm)
        self.stats.permute_bytes += nbytes * len(perm)
        return out

    # ------------------------------------------------------------------
    # runtime hook: collective panel broadcast on dataflow fan-out
    # ------------------------------------------------------------------
    def prebroadcast(self, copy: DataCopy, target_spaces: Sequence[int]
                     ) -> int:
        """Replicate a produced copy onto the consumer devices in one
        collective, attaching SHARED device copies to its datum so each
        consumer's stage-in finds the tile resident (zero further
        movement).  Returns the number of devices the tile landed on."""
        datum = copy.data
        if datum is None or copy.payload is None \
                or getattr(copy.payload, "parsec_deferred", False):
            # chain-held placeholder (devices/xla.py Deferred): the value
            # does not exist yet — consumers lazily stage (and force) it
            return 0
        spaces = sorted({s for s in target_spaces
                         if s in self._jdev})
        with datum._lock:
            missing = [s for s in spaces
                       if (c := datum.copy_on(s)) is None or
                       c.coherency == Coherency.INVALID or
                       c.version < copy.version]
        if len(missing) < int(params.get("comm_ici_bcast_min", 2)):
            return 0
        replicas = self.bcast(copy.payload, missing)
        attached = 0
        adopt = []
        with datum._lock:
            for sp, arr in replicas.items():
                existing = datum.copy_on(sp)
                if existing is None:
                    dc = DataCopy(datum, sp, payload=arr,
                                  coherency=Coherency.SHARED,
                                  version=copy.version)
                    datum.attach_copy(dc)
                    adopt.append((sp, dc))
                    attached += 1
                elif existing.coherency == Coherency.INVALID or \
                        existing.version < copy.version:
                    existing.payload = arr
                    existing.coherency = Coherency.SHARED
                    existing.version = copy.version
                    adopt.append((sp, existing))
                    attached += 1
        self._adopt(datum, adopt)
        debug_verbose(7, "ici prebroadcast: %d replicas of %s", attached,
                      datum)
        return attached

    def preplace(self, copy: DataCopy, space: int) -> bool:
        """Single-consumer counterpart of :meth:`prebroadcast`: move one
        produced device-resident tile onto the consumer's device NOW —
        overlapping the transfer with scheduling — instead of lazily
        inside the consumer's stage-in (reference: the CE put of a
        point-to-point dep edge, parsec_mpi_funnelled.c:793; on TPU a
        device-to-device ICI hop)."""
        datum = copy.data
        if datum is None or copy.payload is None or space not in self._jdev \
                or getattr(copy.payload, "parsec_deferred", False):
            return False
        if copy.device == space or copy.device not in self._jdev:
            return False      # host-resident payloads stage in normally
        with datum._lock:
            existing = datum.copy_on(space)
            if existing is not None and \
                    existing.coherency != Coherency.INVALID and \
                    existing.version >= copy.version:
                return False  # already resident
        arr = self.put(copy.payload, space)
        return self._attach_placed(copy, space, arr)

    def _attach_placed(self, copy: DataCopy, space: int, arr) -> bool:
        """Attach a freshly-moved replica to the datum as a SHARED copy on
        ``space`` (version-guarded: a consumer that already wrote a newer
        version wins) and register it with the device's HBM ledger."""
        datum = copy.data
        placed = None
        with datum._lock:
            existing = datum.copy_on(space)
            if existing is None:
                placed = DataCopy(datum, space, payload=arr,
                                  coherency=Coherency.SHARED,
                                  version=copy.version)
                datum.attach_copy(placed)
            elif existing.version <= copy.version:
                existing.payload = arr
                existing.coherency = Coherency.SHARED
                existing.version = copy.version
                placed = existing
        if placed is not None:
            self._adopt(datum, [(space, placed)])
        return True

    # ------------------------------------------------------------------
    # deferred placement: batch single-consumer edges per DAG wavefront
    # into CollectivePermute rounds (SURVEY §5.8; reference counterpart:
    # the per-peer aggregation of the comm thread, remote_dep_mpi.c —
    # here aggregation happens across DEVICE edges of one wavefront)
    # ------------------------------------------------------------------
    def defer_place(self, copy: DataCopy, space: int) -> bool:
        """Queue a device-resident single-consumer placement; when the
        batch completes a permutation round (every device sends/receives
        at most once) — or an idle worker drains the window
        (:meth:`flush_placements`) — the whole wavefront rides one
        ``lax.ppermute`` launch instead of N separate puts.  Placement is
        purely a prefetch: consumers that stage in before the flush win
        the version race and the late replica is dropped."""
        datum = copy.data
        if datum is None or not self.device_resident(copy) \
                or space not in self._jdev or copy.device == space \
                or self.ndev < 2:
            return False
        with datum._lock:
            existing = datum.copy_on(space)
            if existing is not None and \
                    existing.coherency != Coherency.INVALID and \
                    existing.version >= copy.version:
                return False  # already resident
        import time
        now = time.monotonic()
        window = float(params.get("comm_ici_permute_window_ms", 2.0)) / 1e3
        immediate = False
        flush_now = None
        with self._pending_lock:
            if not self._pending_edges and now - self._last_edge > window:
                # a lone edge after a quiet spell is a serialized chain
                # hop until proven otherwise: place it NOW so the
                # transfer overlaps scheduling (a deferred chain hop
                # always loses the race against its consumer's lazy
                # stage-in and the flush would be pure waste).  It also
                # opens the wave window: siblings arriving within it DO
                # defer, so a k-edge wavefront costs one put plus one
                # (k-1)-edge permute — within the "k edges ride <=2
                # launches" contract.
                immediate = True
            else:
                self._pending_edges.append((copy, space, now))
                # flush when the batch completes a permutation round —
                # OR when the oldest deferred edge has already outlived
                # the window (under load the gaps between wavefront
                # siblings stretch past it; without the age trigger the
                # batch would sit until an idle worker happens by,
                # losing every version race to lazy stage-in — the
                # "wavefront permute did not fire" flake, ~1/7 loaded)
                full_round = any(
                    e[0].device == copy.device or e[1] == space
                    for e in self._pending_edges[:-1]) \
                    or len(self._pending_edges) >= self.ndev - 1 \
                    or now - self._pending_edges[0][2] >= window
                if full_round:
                    flush_now, self._pending_edges = self._pending_edges, []
            self._last_edge = now
        if immediate:
            return self.preplace(copy, space)
        if flush_now:
            self._flush_edges(flush_now)
        return True

    def flush_placements(self, force: bool = False) -> int:
        """Drain deferred placements older than the batching window (all
        of them when ``force``).  Called from idle workers and quiescence
        points; failures are swallowed — placement is best-effort
        prefetch and consumers fall back to lazy stage-in."""
        if not self._pending_edges:
            return 0
        import time
        window = float(params.get("comm_ici_permute_window_ms", 2.0)) / 1e3
        take = None
        with self._pending_lock:
            if self._pending_edges and (
                    force or time.monotonic() - self._pending_edges[0][2]
                    >= window):
                take, self._pending_edges = self._pending_edges, []
        if not take:
            return 0
        try:
            self._flush_edges(take)
        except Exception as exc:
            debug_verbose(3, "ici flush_placements dropped %d edges: %s",
                          len(take), exc)
        return len(take)

    def _flush_edges(self, edges) -> None:
        live = []
        for copy, space, _t in edges:
            p = copy.payload
            if p is None or (hasattr(p, "is_deleted") and p.is_deleted()):
                continue     # evicted/donated since: consumer stages lazily
            datum = copy.data
            with datum._lock:
                existing = datum.copy_on(space)
                if existing is not None and \
                        existing.coherency != Coherency.INVALID and \
                        existing.version >= copy.version:
                    # the consumer staged in (or wrote) while the edge sat
                    # in the window: a collective for it would move bytes
                    # nobody reads
                    continue
            live.append((copy, space))
        if not live:
            return
        if len(live) < int(params.get("comm_ici_permute_min", 2)):
            for copy, space in live:
                self.preplace(copy, space)
            return
        # unique (src, dst) keys per permute() call: duplicate pairs would
        # collide in its result map, so they go in follow-up calls
        calls: List[List[Tuple[DataCopy, int]]] = []
        for item in live:
            key = (item[0].device, item[1])
            for c in calls:
                if all((e[0].device, e[1]) != key for e in c):
                    c.append(item)
                    break
            else:
                calls.append([item])
        for c in calls:
            try:
                results = self.permute(
                    [(copy.device, space, copy.payload)
                     for copy, space in c])
            except Exception as exc:
                debug_verbose(3, "ici permute batch failed (%s); "
                              "falling back to puts", exc)
                for copy, space in c:
                    try:
                        self.preplace(copy, space)
                    except Exception:
                        pass      # best-effort prefetch
                continue
            for copy, space in c:
                arr = results.get((copy.device, space))
                if arr is not None:
                    self._attach_placed(copy, space, arr)

    def device_resident(self, copy: DataCopy) -> bool:
        """Cheap hot-path gate: only device-resident produced copies are
        candidates for collective placement (chain-held placeholders —
        devices/xla.py Deferred — are not: the value does not exist)."""
        return copy.device in self._jdev and copy.payload is not None \
            and not getattr(copy.payload, "parsec_deferred", False)

    def _adopt(self, datum, placed) -> None:
        """Register externally-attached copies with their device's HBM
        ledger so eviction/budget accounting can see them."""
        by_space = {d.space: d for d in self.xla_devices}
        for sp, dc in placed:
            dev = by_space.get(sp)
            if dev is not None and hasattr(dev, "adopt"):
                dev.adopt(datum, dc)

    def consumer_spaces(self, taskpool, deliveries) -> List[int]:
        """Best-effort device targets for a list of local deliveries:
        each successor's affinity datum names its preferred/resident
        accelerator (reference: parsec_get_best_device's data-affinity
        rule, device.c:79-140)."""
        spaces: List[int] = []
        for succ_tc, succ_locals, _dflow in deliveries:
            if succ_tc.affinity is None:
                continue
            try:
                ref = succ_tc.affinity(succ_locals)
                datum = ref.resolve()
            except Exception:
                continue
            pref = datum.preferred_device
            if pref is not None and pref in self._jdev:
                spaces.append(pref)
                continue
            v = datum.newest_version()
            for sp, c in datum.copies().items():
                if sp in self._jdev and c.version == v \
                        and c.coherency != Coherency.INVALID:
                    spaces.append(sp)
                    break
        return spaces
