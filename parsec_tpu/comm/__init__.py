"""Distributed communication layer.

Rebuild of the reference's comm stack (reference: SURVEY.md §2.5/§5.8 —
parsec_comm_engine.h transport-neutral vtable, parsec_mpi_funnelled.c MPI
module, remote_dep.c dataflow protocol + bcast trees): an active-message
comm-engine seam (engine.py, socket transport standing in for the
reference's MPI and for DCN bootstrap on a pod), the remote-dependency
activation protocol with eager/rendezvous payloads and star/chain/binomial
broadcast propagation (remote_dep.py), Safra-token global quiescence (the
counterpart of the fourcounter termdet), and an mpiexec-style multiprocess
launcher for tests (launch.py).

On a TPU pod slice the *payload* edges additionally lower to XLA
collectives over ICI (parallel/spmd.py); this layer carries control
messages and host-side data movement, exactly the split the reference
makes between its AM layer and its one-sided put/get.
"""

from parsec_tpu.comm.engine import (CommEngine, EventLoopCE,  # noqa: F401
                                    SocketCE, make_ce)
from parsec_tpu.comm.remote_dep import RemoteDepEngine  # noqa: F401
from parsec_tpu.comm.launch import run_distributed  # noqa: F401
